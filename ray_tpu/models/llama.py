"""Llama-family decoder (RMSNorm / RoPE / SwiGLU / GQA) in pure JAX.

Second flagship model family next to GPT-2 (``models/gpt2.py``): the same
sharding-annotated, scan-over-layers, remat-able design — parameters are
a plain pytree with a parallel pytree of logical axis names; physical
shardings come from ``ray_tpu.parallel.sharding`` rules (Megatron TP on
head/ff/vocab dims, fsdp on embed, pp over the stacked layer dim).

Architecture differences from GPT-2, all modern-decoder standard:
* RMSNorm (no mean subtraction, no bias) instead of LayerNorm;
* rotary position embeddings applied to q/k per head (no learned wpe);
* SwiGLU MLP (gate ⊙ silu(up) with a 2/3·4d hidden, rounded to 128);
* grouped-query attention: ``n_kv_head <= n_head`` KV heads, each shared
  by ``n_head // n_kv_head`` query heads (KV cache/bandwidth saver);
* untied LM head.

Reference parity note: the reference has no model zoo of its own (torch
owns its compute path); this family exists because on TPU the framework
owns the compute path (SURVEY.md §5.7).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import causal_attention
from ray_tpu.parallel.sharding import logical_sharding, with_logical_constraint

Params = dict[str, Any]


def _round_to(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    n_layer: int = 16
    n_head: int = 16
    n_kv_head: int = 4
    d_model: int = 1024
    seq_len: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: Any = "dots"  # same semantics as GPT2Config.remat
    scan_layers: bool = True
    use_flash: bool | None = None
    attention_impl: str = "auto"  # "auto" | "ring" | "ulysses"
    # Fused Pallas RMSNorm kernels (ops/fused_norm.py — same kernel
    # family GPT2Config.fused_norm gates): forward saves only the fp32
    # rstd statistic, one backward kernel per row-block fuses
    # dx/dscale with the residual-add gradient. Odd shapes (D % 128)
    # fall back to the plain-XLA chain.
    fused_norm: bool = False
    mesh: Any = dataclasses.field(default=None, compare=False)

    def __post_init__(self):
        assert self.n_head % self.n_kv_head == 0, "GQA needs even groups"
        assert self.d_model % self.n_head == 0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @property
    def d_ff(self) -> int:
        # Llama's 2/3 * 4d SwiGLU hidden, rounded up for MXU tiling.
        return _round_to(int(8 * self.d_model / 3), 128)

    @property
    def n_params(self) -> int:
        d, hd = self.d_model, self.head_dim
        attn = d * (self.n_head * hd) + 2 * d * (self.n_kv_head * hd) \
            + (self.n_head * hd) * d
        mlp = 3 * d * self.d_ff
        per_layer = attn + mlp + 2 * d  # + the two RMSNorm scales
        return (self.vocab_size * d            # embed
                + self.n_layer * per_layer
                + d                            # final norm
                + d * self.vocab_size)         # untied head

    @classmethod
    def tiny(cls) -> "LlamaConfig":
        """CPU-test sized."""
        return cls(vocab_size=256, n_layer=2, n_head=4, n_kv_head=2,
                   d_model=64, seq_len=64)

    @classmethod
    def small(cls) -> "LlamaConfig":
        """~300M for single-chip benchmarking."""
        return cls(n_layer=16, n_head=16, n_kv_head=4, d_model=1024,
                   seq_len=2048)


def llama_param_axes(cfg: LlamaConfig) -> Params:
    return {
        "embed": ("vocab", "embed"),
        "blocks": {
            "attn_norm": ("layers", None),
            "wq": ("layers", "embed", "qkv"),
            "wk": ("layers", "embed", "qkv"),
            "wv": ("layers", "embed", "qkv"),
            "wo": ("layers", "qkv", "embed"),
            "mlp_norm": ("layers", None),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_norm": (None,),
        "lm_head": ("embed", "vocab"),
    }


def llama_shardings(cfg: LlamaConfig, mesh, rules=None) -> Params:
    return jax.tree.map(
        lambda axes: logical_sharding(mesh, axes, rules),
        llama_param_axes(cfg),
        is_leaf=lambda x: isinstance(x, tuple),
    )


def llama_init(rng: jax.Array, cfg: LlamaConfig) -> Params:
    d, l, v = cfg.d_model, cfg.n_layer, cfg.vocab_size
    hd, nh, nkv, ff = cfg.head_dim, cfg.n_head, cfg.n_kv_head, cfg.d_ff
    pd = cfg.param_dtype
    k = iter(jax.random.split(rng, 16))

    def norm(key, shape, stddev=0.02):
        return (jax.random.normal(key, shape, jnp.float32) * stddev).astype(pd)

    resid = 0.02 / (2 * l) ** 0.5
    return {
        "embed": norm(next(k), (v, d)),
        "blocks": {
            "attn_norm": jnp.ones((l, d), pd),
            "wq": norm(next(k), (l, d, nh * hd)),
            "wk": norm(next(k), (l, d, nkv * hd)),
            "wv": norm(next(k), (l, d, nkv * hd)),
            "wo": norm(next(k), (l, nh * hd, d), resid),
            "mlp_norm": jnp.ones((l, d), pd),
            "w_gate": norm(next(k), (l, d, ff)),
            "w_up": norm(next(k), (l, d, ff)),
            "w_down": norm(next(k), (l, ff, d), resid),
        },
        "final_norm": jnp.ones((d,), pd),
        "lm_head": norm(next(k), (d, v)),
    }


def _rms_norm(x: jax.Array, scale: jax.Array) -> jax.Array:
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return (y * scale).astype(x.dtype)


def _rope(x: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding over [B, T, H, D] (rotate pairs in the head dim)."""
    b, t, h, d = x.shape
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = jnp.arange(t, dtype=jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[None, :, None, :]  # [1, T, 1, half]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _norm_residual(x: jax.Array, scale: jax.Array,
                   cfg: LlamaConfig) -> tuple[jax.Array, jax.Array]:
    """(RMSNorm(x), residual-skip x); fused Pallas kernel when enabled —
    the skip's cotangent lands inside the one backward kernel."""
    if cfg.fused_norm:
        from ray_tpu.ops.fused_norm import fused_rms_norm_residual

        return fused_rms_norm_residual(x, scale)
    return _rms_norm(x, scale), x


def _block(x: jax.Array, p: Params, cfg: LlamaConfig) -> jax.Array:
    b, t, d = x.shape
    nh, nkv, hd = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    dt = cfg.dtype

    y, x_skip = _norm_residual(x, p["attn_norm"], cfg)
    q = (y @ p["wq"].astype(dt)).reshape(b, t, nh, hd)
    k = (y @ p["wk"].astype(dt)).reshape(b, t, nkv, hd)
    v = (y @ p["wv"].astype(dt)).reshape(b, t, nkv, hd)
    q = _rope(q, cfg.rope_theta)
    k = _rope(k, cfg.rope_theta)
    if nkv != nh:
        # GQA: each KV head serves n_head//n_kv_head query heads.
        rep = nh // nkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if cfg.attention_impl == "ring" and cfg.mesh is not None:
        from ray_tpu.ops.ring_attention import ring_causal_attention

        attn = ring_causal_attention(q, k, v, cfg.mesh, axis="sp")
    elif cfg.attention_impl == "ulysses" and cfg.mesh is not None:
        from ray_tpu.ops.ulysses import ulysses_attention

        attn = ulysses_attention(q, k, v, cfg.mesh, axis="sp")
    else:
        attn = causal_attention(q, k, v, use_flash=cfg.use_flash)
    x = x_skip + attn.reshape(b, t, nh * hd) @ p["wo"].astype(dt)
    x = with_logical_constraint(x, ("batch", "seq", None))

    y, x_skip = _norm_residual(x, p["mlp_norm"], cfg)
    gate = y @ p["w_gate"].astype(dt)
    up = y @ p["w_up"].astype(dt)
    h = jax.nn.silu(gate) * up
    h = with_logical_constraint(h, ("batch", "seq", "mlp"))
    x = x_skip + h @ p["w_down"].astype(dt)
    x = with_logical_constraint(x, ("batch", "seq", None))
    return x


def llama_forward(params: Params, tokens: jax.Array,
                  cfg: LlamaConfig) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, V] fp32."""
    dt = cfg.dtype
    x = params["embed"].astype(dt)[tokens]
    x = with_logical_constraint(x, ("batch", "seq", None))

    block_fn = lambda carry, p: (_block(carry, p, cfg), None)
    if cfg.remat == "dots":
        block_fn = jax.checkpoint(
            block_fn, prevent_cse=False,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
    elif cfg.remat:
        block_fn = jax.checkpoint(block_fn, prevent_cse=False)
    if cfg.scan_layers:
        x, _ = jax.lax.scan(block_fn, x, params["blocks"])
    else:
        for i in range(cfg.n_layer):
            x, _ = block_fn(x, jax.tree.map(lambda a: a[i], params["blocks"]))

    if cfg.fused_norm:
        from ray_tpu.ops.fused_norm import fused_rms_norm

        x = fused_rms_norm(x, params["final_norm"])
    else:
        x = _rms_norm(x, params["final_norm"])
    return jnp.einsum(
        "btd,dv->btv", x, params["lm_head"].astype(dt),
        preferred_element_type=jnp.float32,
    )


def llama_loss(params: Params, batch: dict[str, jax.Array],
               cfg: LlamaConfig) -> jax.Array:
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits = llama_forward(params, inputs, cfg)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - picked)


# -- autoregressive decoding (serving path) --------------------------------
#
# Same contract as the GPT-2 decode API (``models/gpt2.py``): one jitted
# decode step over a fixed slot batch + one jitted chunked-prefill lane,
# with a slot-indexed ring KV-cache. The cache rides the GQA layout —
# only ``n_kv_head`` heads are cached (``[n_layer, slots, cache_len,
# n_kv_head, head_dim]`` in the activation dtype, bf16 by default), and
# query-head groups re-read the shared KV at attention time, so the GQA
# bandwidth saving carries straight into serving HBM footprint.


def llama_init_cache(cfg: LlamaConfig, slots: int, cache_len: int) -> Params:  # decode-path
    shape = (cfg.n_layer, slots, cache_len, cfg.n_kv_head, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def _rope_at(x: jax.Array, pos: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding for ONE token per slot at an absolute position:
    x [S, H, D], pos [S] int32."""
    s, h, d = x.shape
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = pos.astype(jnp.float32)[:, None] * freqs[None, :]  # [S, half]
    cos = jnp.cos(angles)[:, None, :]  # [S, 1, half]
    sin = jnp.sin(angles)[:, None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


# jax-hot-path: traced into the engine's single compiled decode step
def llama_decode_step(params: Params, cache: Params, tokens: jax.Array,
                      pos: jax.Array, cfg: LlamaConfig
                      ) -> tuple[jax.Array, Params]:
    """One decode iteration for every slot: tokens [S] int32, pos [S]
    int32 -> (logits [S, V] fp32, new cache). See gpt2_decode_step for
    the ring-cursor/mask contract."""
    s = tokens.shape[0]
    nh, nkv, hd = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    cache_len = cache["k"].shape[2]
    dt = cfg.dtype
    cursor = jnp.mod(pos, cache_len)
    valid = jnp.minimum(pos + 1, cache_len)
    x = params["embed"].astype(dt)[tokens]  # [S, D]
    from ray_tpu.ops.attention import (cache_write_token,
                                       cached_decode_attention)

    def block(x, layer):
        p, k_cache, v_cache = layer
        y = _rms_norm(x, p["attn_norm"])
        q = _rope_at((y @ p["wq"].astype(dt)).reshape(s, nh, hd),
                     pos, cfg.rope_theta)
        k_new = _rope_at((y @ p["wk"].astype(dt)).reshape(s, nkv, hd),
                         pos, cfg.rope_theta)
        v_new = (y @ p["wv"].astype(dt)).reshape(s, nkv, hd)
        k_cache = cache_write_token(k_cache, k_new[:, None], cursor)
        v_cache = cache_write_token(v_cache, v_new[:, None], cursor)
        # GQA: expand the cached KV heads to the query heads at read
        # time (the cache itself stays n_kv_head wide).
        rep = nh // nkv
        attn = cached_decode_attention(
            q, jnp.repeat(k_cache, rep, axis=2),
            jnp.repeat(v_cache, rep, axis=2), valid, dt)
        x = x + attn.reshape(s, nh * hd) @ p["wo"].astype(dt)
        y = _rms_norm(x, p["mlp_norm"])
        gate = y @ p["w_gate"].astype(dt)
        up = y @ p["w_up"].astype(dt)
        x = x + (jax.nn.silu(gate) * up) @ p["w_down"].astype(dt)
        return x, (k_cache, v_cache)

    x, (k_all, v_all) = jax.lax.scan(
        block, x, (params["blocks"], cache["k"], cache["v"]))
    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum(
        "sd,dv->sv", x, params["lm_head"].astype(dt),
        preferred_element_type=jnp.float32)
    return logits, {"k": k_all, "v": v_all}


# jax-hot-path: traced into the engine's single compiled prefill lane
def llama_prefill(params: Params, cache: Params, tokens: jax.Array,
                  slots: jax.Array, lengths: jax.Array, cfg: LlamaConfig
                  ) -> tuple[jax.Array, Params]:
    """Chunked-prefill lane (fixed [R, P] shape): full causal forward
    over the padded prompts, K/V written into each row's target slot,
    logits at each prompt's last real token. Same pad-garbage contract
    as gpt2_prefill."""
    r, p_len = tokens.shape
    nh, nkv, hd = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    dt = cfg.dtype
    x = params["embed"].astype(dt)[tokens]
    from ray_tpu.ops.attention import cache_write_prompt

    def block(x, layer):
        p, k_cache, v_cache = layer
        y = _rms_norm(x, p["attn_norm"])
        q = _rope((y @ p["wq"].astype(dt)).reshape(r, p_len, nh, hd),
                  cfg.rope_theta)
        k_ = _rope((y @ p["wk"].astype(dt)).reshape(r, p_len, nkv, hd),
                   cfg.rope_theta)
        v_ = (y @ p["wv"].astype(dt)).reshape(r, p_len, nkv, hd)
        k_cache = cache_write_prompt(k_cache, k_, slots)
        v_cache = cache_write_prompt(v_cache, v_, slots)
        rep = nh // nkv
        attn = causal_attention(
            q, jnp.repeat(k_, rep, axis=2), jnp.repeat(v_, rep, axis=2),
            use_flash=False)
        x = x + attn.reshape(r, p_len, nh * hd) @ p["wo"].astype(dt)
        y = _rms_norm(x, p["mlp_norm"])
        gate = y @ p["w_gate"].astype(dt)
        up = y @ p["w_up"].astype(dt)
        x = x + (jax.nn.silu(gate) * up) @ p["w_down"].astype(dt)
        return x, (k_cache, v_cache)

    x, (k_all, v_all) = jax.lax.scan(
        block, x, (params["blocks"], cache["k"], cache["v"]))
    x = _rms_norm(x, params["final_norm"])
    last = x[jnp.arange(r), jnp.clip(lengths - 1, 0, p_len - 1)]
    logits = jnp.einsum(
        "rd,dv->rv", last, params["lm_head"].astype(dt),
        preferred_element_type=jnp.float32)
    return logits, {"k": k_all, "v": v_all}


def llama_flops_per_token(cfg: LlamaConfig,
                          seq_len: int | None = None) -> float:
    """6*N matmul FLOPs + causal attention score/value FLOPs."""
    t = seq_len or cfg.seq_len
    return 6 * cfg.n_params + 12 * cfg.n_layer * cfg.d_model * t // 2
