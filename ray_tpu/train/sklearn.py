"""SklearnTrainer: fit a scikit-learn estimator through the Train API
(reference ``python/ray/train/sklearn/sklearn_trainer.py``). sklearn has
no distributed-training story, so — exactly as the reference does — the
cluster's contribution is placement and PARALLEL CROSS-VALIDATION: the
single ``.fit`` runs in one remote task, and with ``cv`` set the k fold
fits fan out as independent tasks (the reference parallelizes folds via
its joblib backend; here they are plain ``ray_tpu`` tasks, same
substrate the joblib shim uses).

Result surface matches the reference: ``Result.metrics`` carries
``fit_time`` plus ``cv/test_score[_mean/_std]`` when ``cv`` is given,
and the checkpoint holds the fitted estimator under ``"estimator"``
(``Checkpoint.to_dict()["estimator"]``).
"""

from __future__ import annotations

import pickle
import time
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import Result

__all__ = ["SklearnTrainer"]


def _to_xy(ds, label_column: str):
    """Accept a Data dataset (rows of dicts / pandas) or an (X, y) tuple."""
    if isinstance(ds, tuple) and len(ds) == 2:
        return np.asarray(ds[0]), np.asarray(ds[1])
    if hasattr(ds, "to_pandas"):
        if label_column is None:
            raise ValueError(
                "label_column is required for dataset inputs "
                "(only (X, y) tuples can omit it)")
        df = ds.to_pandas()
        y = df[label_column].to_numpy()
        x = df.drop(columns=[label_column]).to_numpy()
        return x, y
    raise TypeError(f"unsupported dataset type {type(ds)!r}")


def _fit_task(est_bytes: bytes, x, y) -> bytes:
    est = pickle.loads(est_bytes)
    est.fit(x, y)
    return pickle.dumps(est)


def _cv_fold_task(est_bytes: bytes, x, y, train_idx, test_idx) -> float:
    est = pickle.loads(est_bytes)
    est.fit(x[train_idx], y[train_idx])
    return float(est.score(x[test_idx], y[test_idx]))


class SklearnTrainer:
    """``SklearnTrainer(estimator=..., label_column=..., datasets={"train":
    ds}, cv=5).fit()`` -> Result (reference surface, minus the joblib
    register indirection)."""

    def __init__(
        self,
        *,
        estimator,
        datasets: Dict[str, Any],
        label_column: Optional[str] = None,
        cv: Optional[int] = None,
        parallelize_cv: bool = True,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        if "train" not in datasets:
            raise ValueError('datasets must contain a "train" key')
        self.estimator = estimator
        self.datasets = datasets
        self.label_column = label_column
        self.cv = cv
        self.parallelize_cv = parallelize_cv
        self.scaling = scaling_config or ScalingConfig(num_workers=1)
        self.run_config = run_config or RunConfig()

    def fit(self) -> Result:
        x, y = _to_xy(self.datasets["train"], self.label_column)
        est_bytes = pickle.dumps(self.estimator)
        metrics: Dict[str, Any] = {}
        t0 = time.perf_counter()

        # One object-store copy shared by the fit and every CV fold —
        # passing arrays by value would ship (1 + cv) copies.
        x_ref, y_ref = ray_tpu.put(x), ray_tpu.put(y)
        fit_task = ray_tpu.remote(_fit_task)
        fitted_ref = fit_task.remote(est_bytes, x_ref, y_ref)

        fold_splits = []
        refs = []
        if self.cv:
            # Deterministic contiguous folds (sklearn KFold default).
            # Parallel folds are SUBMITTED before the fit is awaited (so
            # they overlap it), but fit_time below covers only the
            # estimator fit — not the CV gather (reference SklearnTrainer
            # semantics).
            n = len(y)
            folds = np.array_split(np.arange(n), self.cv)
            fold_task = ray_tpu.remote(_cv_fold_task)
            for i in range(self.cv):
                test_idx = folds[i]
                train_idx = np.concatenate(
                    [folds[j] for j in range(self.cv) if j != i])
                if self.parallelize_cv:
                    refs.append(fold_task.remote(
                        est_bytes, x_ref, y_ref, train_idx, test_idx))
                else:
                    fold_splits.append((train_idx, test_idx))

        fitted = pickle.loads(ray_tpu.get(fitted_ref, timeout=600))
        metrics["fit_time"] = time.perf_counter() - t0

        if self.cv:
            scores = ray_tpu.get(refs, timeout=600) \
                if self.parallelize_cv else [
                    _cv_fold_task(est_bytes, x, y, train_idx, test_idx)
                    for train_idx, test_idx in fold_splits]
            metrics["cv"] = {
                "test_score": list(scores),
                "test_score_mean": float(np.mean(scores)),
                "test_score_std": float(np.std(scores)),
            }

        for name, ds in self.datasets.items():
            if name == "train":
                continue
            vx, vy = _to_xy(ds, self.label_column)
            metrics[f"{name}_score"] = float(fitted.score(vx, vy))

        return Result(
            metrics=metrics,
            checkpoint=Checkpoint(data={"estimator": fitted}),
            metrics_history=[metrics],
        )
