"""pjit train-step factory: init + step compiled over an arbitrary mesh.

The whole inner loop — forward, backward, gradient reduction, AdamW — is ONE
jitted program; XLA inserts the dp/fsdp gradient collectives and the tp/sp
activation collectives from the sharding annotations (the "annotate shardings,
let XLA insert collectives" recipe). Contrast with the reference, where the
inner loop is torch DDP and the framework only carries control messages
(SURVEY.md §3.4 HOT LOOP note).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.sharding import logical_sharding
from ray_tpu.train.optim import AdamWConfig, adamw_init, adamw_update

Params = Any
TrainState = dict[str, Any]  # {'params', 'opt': {'mu','nu'}, 'step'}


def state_shardings(param_shardings: Params, mesh: Mesh) -> TrainState:
    """Optimizer state mirrors the param tree => shardings are shared."""
    return {
        "params": param_shardings,
        "opt": {"mu": param_shardings, "nu": param_shardings},
        "step": NamedSharding(mesh, P()),
    }


def make_init_fn(
    init_params: Callable[[jax.Array], Params],
    param_shardings: Params,
    mesh: Mesh,
):
    """Returns jitted rng -> TrainState, with params initialized *sharded*
    (no host-side full materialization — required for models > host RAM)."""

    def init(rng: jax.Array) -> TrainState:
        params = init_params(rng)
        return {
            "params": params,
            "opt": adamw_init(params),
            "step": jnp.zeros((), jnp.int32),
        }

    return jax.jit(init, out_shardings=state_shardings(param_shardings, mesh))


def make_train_step(
    loss_fn: Callable[[Params, Any], jax.Array],
    param_shardings: Params,
    mesh: Mesh,
    *,
    optimizer: AdamWConfig | None = None,
    batch_spec: Any = None,
    extra_metrics: Callable[[Params, Any], dict] | None = None,
):
    """Build the jitted (state, batch) -> (state, metrics) step.

    loss_fn(params, batch) -> scalar loss. batch_spec: pytree of
    PartitionSpec for the batch (default: first dim over ('dp','fsdp')).
    """
    opt_cfg = optimizer or AdamWConfig()
    st_shard = state_shardings(param_shardings, mesh)
    if batch_spec is None:
        batch_spec = P(("dp", "fsdp"))
    batch_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        batch_spec,
        is_leaf=lambda x: isinstance(x, P),
    )

    def step(state: TrainState, batch) -> tuple[TrainState, dict[str, jax.Array]]:
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], batch)
        new_params, new_opt, lr, gnorm = adamw_update(
            opt_cfg, grads, state["params"], state["opt"], state["step"]
        )
        new_state = {
            "params": new_params,
            "opt": new_opt,
            "step": state["step"] + 1,
        }
        metrics = {"loss": loss, "lr": lr, "grad_norm": gnorm}
        if extra_metrics is not None:
            metrics.update(extra_metrics(new_params, batch))
        return new_state, metrics

    return jax.jit(
        step,
        in_shardings=(st_shard, batch_shardings),
        out_shardings=(st_shard, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )


def batch_sharding(mesh: Mesh, spec: P | None = None) -> NamedSharding:
    return NamedSharding(mesh, spec if spec is not None else P(("dp", "fsdp")))
