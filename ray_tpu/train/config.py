"""Train/AIR config dataclasses.

Reference parity: ``python/ray/air/config.py`` — ``ScalingConfig:79``,
``FailureConfig:454``, ``CheckpointConfig:513``, ``RunConfig:642``.

TPU extension (SURVEY.md §7): ScalingConfig speaks topology — a worker is a
*host* owning its slice-local chips; ``use_tpu``/``topology`` replace
``use_gpu``; ``resources_per_worker`` defaults to the host's chip count.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    use_gpu: bool = False  # accepted for API parity; ignored on TPU builds
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    topology: Optional[str] = None  # e.g. "v4-64": 8 hosts x 8 chips
    # Elastic world size (Podracer-style preemptible fleets): when set,
    # the trainer keeps ONE placement group across attempts and rides
    # the head's bundle rescheduling — on bundle loss it re-forms the
    # collective at the surviving world size (>= min_workers) from the
    # latest checkpoint, and regrows to num_workers when the group
    # reports restored capacity. None = fixed gang (an attempt always
    # waits for all num_workers bundles).
    min_workers: Optional[int] = None

    def __post_init__(self):
        if self.min_workers is not None and not (
                1 <= self.min_workers <= self.num_workers):
            # Fail at construction: a floor above num_workers can never
            # be met (the gang has only num_workers bundles) and would
            # otherwise surface as an opaque 300s wait-for-live-bundles
            # timeout per attempt.
            raise ValueError(
                f"min_workers must be in [1, num_workers]; got "
                f"min_workers={self.min_workers} with "
                f"num_workers={self.num_workers}")

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        if "CPU" not in res:
            res["CPU"] = 1.0
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = float(self.chips_per_host())
        return res

    def chips_per_host(self) -> int:
        if self.topology:
            # "v4-64" => 64 chips total over num_workers hosts.
            total = int(self.topology.rsplit("-", 1)[1])
            return max(1, total // max(1, self.num_workers))
        return 4

    def as_placement_group_bundles(self) -> list[Dict[str, float]]:
        return [self.worker_resources() for _ in range(self.num_workers)]


@dataclass
class JaxConfig:
    """Per-worker JAX runtime setup (the ``TorchConfig`` analog,
    ``python/ray/train/torch/config.py:29``): whether/how workers join one
    ``jax.distributed`` process group so all hosts' chips form a single
    global mesh.

    ``platform``/``num_cpu_devices`` force the CPU simulation path (N
    virtual devices per worker process, Gloo cross-process collectives) —
    the test harness for multi-host behavior. On a real TPU pod leave both
    None: the TPU runtime discovers slice topology itself.
    """

    distributed: bool = True
    platform: Optional[str] = None  # e.g. "cpu" for the simulation path
    num_cpu_devices: Optional[int] = None  # virtual devices per worker
    init_timeout: float = 120.0


@dataclass
class FailureConfig:
    max_failures: int = 0  # 0 = no retries, -1 = infinite


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None  # None = keep all
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"  # or "min"

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None  # local dir (cloud sync is round-2)
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 1
    # Trial stop condition (tune): Stopper | {metric: threshold} | callable
    stop: Optional[object] = None
