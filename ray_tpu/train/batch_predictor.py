"""Batch inference over Datasets (reference: ``python/ray/train/
batch_predictor.py`` + the ``Predictor`` abstraction).

``BatchPredictor.from_checkpoint(ckpt, MyPredictor)`` fans a dataset's
blocks through a pool of predictor actors — each actor materializes the
model ONCE from the checkpoint, then scores batches as they stream in
(``map_batches`` with ``ActorPoolStrategy``).

TPU-native predictor: ``JaxPredictor`` holds a jitted apply function; a
replica per chip is the scaling unit, exactly like Serve replicas.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Type

from ray_tpu.train.checkpoint import Checkpoint


class Predictor:
    """Stateful scorer (reference ``ray.train.predictor.Predictor``)."""

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch):
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Predictor over a pure ``apply_fn(params, batch) -> predictions``.
    The checkpoint dict must hold ``params`` (the pytree) — the form
    ``JaxTrainer`` checkpoints produce."""

    def __init__(self, apply_fn: Callable, params: Any, jit: bool = True):
        import jax

        self._apply = jax.jit(apply_fn) if jit else apply_fn
        self._params = params

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *,
                        apply_fn: Optional[Callable] = None,
                        jit: bool = True) -> "JaxPredictor":
        if apply_fn is None:
            raise ValueError("JaxPredictor.from_checkpoint needs apply_fn=")
        data = checkpoint.to_dict()
        if "params" not in data:
            raise ValueError("checkpoint has no 'params' entry")
        return cls(apply_fn, data["params"], jit=jit)

    def predict(self, batch):
        import numpy as np

        out = self._apply(self._params, batch)
        import jax

        return jax.tree.map(np.asarray, out)


class BatchPredictor:
    """Scores datasets with a pool of predictor actors."""

    def __init__(self, checkpoint: Checkpoint,
                 predictor_cls: Type[Predictor], **predictor_kwargs):
        self._checkpoint = checkpoint
        self._predictor_cls = predictor_cls
        self._predictor_kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        predictor_cls: Type[Predictor],
                        **predictor_kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **predictor_kwargs)

    def predict(
        self,
        dataset,
        *,
        batch_size: int = 256,
        batch_format: str = "numpy",
        min_scoring_workers: int = 1,
        max_scoring_workers: int = 4,
    ):
        """Returns a Dataset of predictions. Each scoring actor builds
        its predictor once (first batch) and reuses it."""
        from ray_tpu.data.dataset import ActorPoolStrategy

        ckpt = self._checkpoint
        predictor_cls = self._predictor_cls
        predictor_kwargs = self._predictor_kwargs
        state: dict = {}  # per-actor after pickling: one predictor each

        def score(batch):
            p = state.get("predictor")
            if p is None:
                p = predictor_cls.from_checkpoint(ckpt, **predictor_kwargs)
                state["predictor"] = p
            out = p.predict(batch)
            # Normalize bare arrays into a column so the result is a
            # well-formed columnar block.
            if not isinstance(out, dict):
                out = {"predictions": out}
            return out

        return dataset.map_batches(
            score,
            batch_size=batch_size,
            batch_format=batch_format,
            compute=ActorPoolStrategy(
                min_size=min_scoring_workers, max_size=max_scoring_workers),
        )
