"""Train library: pjit train steps, sessions, worker groups, checkpoints.

TPU-native equivalent of the reference's Ray Train
(``python/ray/train/data_parallel_trainer.py:244``,
``python/ray/train/_internal/backend_executor.py:42``): the inner loop is a
single pjit-compiled step over a device mesh (XLA inserts the gradient
collectives on ICI); the framework's job is placement, session plumbing,
checkpoints and failure handling.
"""

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu("train")

from ray_tpu.train.train_step import TrainState, make_train_step, make_init_fn
from ray_tpu.train.optim import adamw_init, adamw_update
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    JaxConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.checkpoint import Checkpoint, load_sharded, save_sharded
from ray_tpu.train.trainer import DataParallelTrainer, JaxTrainer, Result
from ray_tpu.train import session
from ray_tpu.train import torch as torch_backend
from ray_tpu.train.torch import TorchConfig, TorchTrainer
from ray_tpu.train.huggingface import HuggingFaceTrainer
from ray_tpu.train.sklearn import SklearnTrainer
from ray_tpu.train.batch_predictor import BatchPredictor, JaxPredictor, Predictor

# Session API at package level too (reference exposes ray.air.session).
report = session.report
get_checkpoint = session.get_checkpoint
get_world_rank = session.get_world_rank
get_world_size = session.get_world_size
get_dataset_shard = session.get_dataset_shard

__all__ = [
    "TrainState",
    "make_train_step",
    "make_init_fn",
    "adamw_init",
    "adamw_update",
    "ScalingConfig",
    "RunConfig",
    "FailureConfig",
    "CheckpointConfig",
    "JaxConfig",
    "Checkpoint",
    "save_sharded",
    "load_sharded",
    "DataParallelTrainer",
    "JaxTrainer",
    "TorchConfig",
    "TorchTrainer",
    "HuggingFaceTrainer",
    "SklearnTrainer",
    "BatchPredictor",
    "JaxPredictor",
    "Predictor",
    "torch_backend",
    "Result",
    "session",
    "report",
    "get_checkpoint",
    "get_world_rank",
    "get_world_size",
    "get_dataset_shard",
]
