"""Train library: pjit train steps, sessions, worker groups, checkpoints.

TPU-native equivalent of the reference's Ray Train
(``python/ray/train/data_parallel_trainer.py:244``,
``python/ray/train/_internal/backend_executor.py:42``): the inner loop is a
single pjit-compiled step over a device mesh (XLA inserts the gradient
collectives on ICI); the framework's job is placement, session plumbing,
checkpoints and failure handling.
"""

from ray_tpu.train.train_step import TrainState, make_train_step, make_init_fn
from ray_tpu.train.optim import adamw_init, adamw_update

__all__ = [
    "TrainState",
    "make_train_step",
    "make_init_fn",
    "adamw_init",
    "adamw_update",
]
