"""Training goodput plane — the train-package name for the shared core.

The implementation lives in ``ray_tpu.util.goodput`` so the data layer
and the cluster plane (workerproc event flusher, node-agent replay) can
use it without importing the heavy ``ray_tpu.train`` package; this
module is the same objects under the train-side name (mirroring
``ray_tpu/serve/_observability.py`` for the serve plane). See that
module's docstring for the recording contract.
"""

from ray_tpu.util.goodput import (  # noqa: F401
    ANATOMY_PHASES,
    ITER_PHASES,
    STEP_PHASES,
    apply_events,
    data_stats,
    downtime_cause,
    drain_events,
    record_anatomy,
    record_downtime,
    record_iter_batch,
    record_stage,
    record_step,
    requeue_events,
    retract_gauges,
    retract_trial,
    scrape_text,
    stall_fraction_from,
    straggler_attribution,
    train_stats,
)
