"""Worker-facing session API: report / get_checkpoint / ranks / shards.

Reference parity: ``python/ray/air/session.py:41,94,220,345`` and the
per-worker ``_TrainSession`` (``python/ray/train/_internal/session.py:61``)
— results flow worker -> trainer through a queue; the trainer consumes them
in ``TrainingIterator`` order.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from ray_tpu.train.checkpoint import Checkpoint

_local = threading.local()


class _Session:
    def __init__(self, world_rank, world_size, local_rank, node_rank,
                 results_queue, checkpoint, dataset_shards, trial_info=None):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.node_rank = node_rank
        self.results_queue = results_queue
        self.checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        self.trial_info = trial_info
        self.iteration = 0

    def report(self, metrics: dict, checkpoint: Optional[Checkpoint] = None):
        self.iteration += 1
        payload = {
            "type": "report",
            "rank": self.world_rank,
            "iteration": self.iteration,
            "metrics": dict(metrics),
            "checkpoint": checkpoint,
            "trial_info": self.trial_info,
        }
        self.results_queue.put(payload)


def init_session(**kwargs) -> None:
    _local.session = _Session(**kwargs)


def shutdown_session() -> None:
    _local.session = None


def _session() -> _Session:
    s = getattr(_local, "session", None)
    if s is None:
        raise RuntimeError(
            "No train session active: this API must be called inside "
            "train_loop_per_worker."
        )
    return s


def report(metrics: dict, checkpoint: Optional[Checkpoint] = None) -> None:
    _session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return _session().checkpoint


def get_world_rank() -> int:
    return _session().world_rank


def get_world_size() -> int:
    return _session().world_size


def get_local_rank() -> int:
    return _session().local_rank


def get_node_rank() -> int:
    return _session().node_rank


def get_dataset_shard(name: str = "train"):
    return _session().dataset_shards.get(name)


def get_trial_info():
    return _session().trial_info


def in_session() -> bool:
    return getattr(_local, "session", None) is not None
