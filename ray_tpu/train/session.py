"""Worker-facing session API: report / get_checkpoint / ranks / shards.

Reference parity: ``python/ray/air/session.py:41,94,220,345`` and the
per-worker ``_TrainSession`` (``python/ray/train/_internal/session.py:61``)
— results flow worker -> trainer through a queue; the trainer consumes them
in ``TrainingIterator`` order.

Goodput accounting (the training telemetry plane): every ``report()``
closes one STEP and decomposes the wall time since the previous report
into phases — ``data_wait`` (accrued by the instrumented dataset
iterators via :func:`add_data_wait`), ``checkpoint_restore`` (time
spent materializing the session's start checkpoint, measured where
``to_dict``/``to_directory`` actually run), ``checkpoint_save`` /
``report`` (the synchronous hand-off inside ``report()`` itself), and
``step`` (the residual: the user's compute). Phases land two-sided via
``ray_tpu.util.goodput`` (local registry + worker-events replay), the
per-rank step time feeds the straggler gauge, and when tracing is
enabled each step is a ``cat="train"`` span in ``state.timeline()``.

Step anatomy (round 19): a train_fn that runs its step through
:func:`timed_step` (or accrues via :func:`add_step_anatomy`) gets each
report's step wall partitioned exactly into ``data_wait`` / ``host``
(dispatch until device launch) / ``compute`` (synced device wall) /
``sync`` (the residual: this rank's wait for the slowest rank), shipped
as per-rank ``ray_tpu_step_phase_seconds`` gauges; attach the compiled
HLO's cost via :func:`set_step_cost` and ``ray_tpu_mfu_percent`` is
exported too.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.util import goodput as _goodput
from ray_tpu.util import tracing as _tracing
from ray_tpu.util import xla_cost as _xla_cost

_local = threading.local()


def _instrument_restore(ckpt: Optional[Checkpoint]):
    """Time the checkpoint's materialization calls into the ACTIVE
    session's restore accumulator (resolved at call time, so a
    checkpoint shared across local-backend worker threads attributes
    each restore to the rank that performed it)."""
    if ckpt is None or getattr(ckpt, "_rt_restore_timed", False):
        return ckpt
    for name in ("to_dict", "to_directory"):
        orig = getattr(ckpt, name)

        def timed(*a, _orig=orig, **k):
            # Reentrancy guard: to_directory calls to_dict internally —
            # the restore must count once, not nested-twice.
            if getattr(_local, "_in_restore", False):
                return _orig(*a, **k)
            _local._in_restore = True
            s = getattr(_local, "session", None)
            sp = _tracing.start_span(
                "train.checkpoint_restore",
                {"trial": s.trial, "rank": s.world_rank}
                if s is not None else None,
                cat="train")
            t0 = time.perf_counter()
            try:
                return _orig(*a, **k)
            finally:
                _local._in_restore = False
                _tracing.finish_span(sp)
                s = getattr(_local, "session", None)
                if s is not None:
                    s._restore_s += time.perf_counter() - t0

        setattr(ckpt, name, timed)
    try:
        ckpt._rt_restore_timed = True
    except Exception:
        pass
    return ckpt


class _Session:
    def __init__(self, world_rank, world_size, local_rank, node_rank,
                 results_queue, checkpoint, dataset_shards, trial_info=None):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.node_rank = node_rank
        self.results_queue = results_queue
        self.checkpoint = _instrument_restore(checkpoint)
        self.dataset_shards = dataset_shards or {}
        self.trial_info = trial_info
        self.trial = (trial_info or {}).get("trial_id") or "train"
        self.iteration = 0
        self._phase_t0 = time.perf_counter()
        self._data_wait_s = 0.0
        self._restore_s = 0.0
        # Step-anatomy accruals (only populated by the instrumented
        # step path — timed_step / add_step_anatomy; a plain train_fn
        # keeps the classic data_wait/step residual accounting).
        self._host_s = 0.0
        self._compute_s = 0.0
        self._anat_steps = 0
        self._anat_recorded = False
        # Cost model attached via set_step_cost: per-step FLOPs for
        # this rank's shard, from the compiled HLO (util/xla_cost).
        self._step_flops = 0.0
        self._cost_kind: Optional[str] = None
        self._cost_devs = 1
        self._step_span = None
        self._open_step_span()

    def _open_step_span(self):
        self._step_span = _tracing.start_span(
            "train.step",
            {"trial": self.trial, "rank": self.world_rank,
             "iteration": self.iteration + 1},
            cat="train")

    def report(self, metrics: dict, checkpoint: Optional[Checkpoint] = None):
        now = time.perf_counter()
        self.iteration += 1
        interval = max(0.0, now - self._phase_t0)
        data_wait = min(self._data_wait_s, interval)
        self._data_wait_s = 0.0
        restore = min(self._restore_s, max(0.0, interval - data_wait))
        self._restore_s = 0.0
        step = max(0.0, interval - data_wait - restore)
        payload = {
            "type": "report",
            "rank": self.world_rank,
            "iteration": self.iteration,
            "metrics": dict(metrics),
            "checkpoint": checkpoint,
            "trial_info": self.trial_info,
            "ts": time.time(),
            "phases": {"data_wait": data_wait, "step": step,
                       "checkpoint_restore": restore},
        }
        ckpt_span = _tracing.start_span(
            "train.checkpoint_save",
            {"trial": self.trial, "rank": self.world_rank,
             "iteration": self.iteration},
            cat="train") if checkpoint is not None else None
        try:
            self.results_queue.put(payload)
        finally:
            _tracing.finish_span(ckpt_span)
        # The synchronous hand-off (checkpoint serialization rides the
        # queue put when one is attached).
        hand_off = max(0.0, time.perf_counter() - now)
        phases = {"step": step}
        if data_wait > 0:
            phases["data_wait"] = data_wait
        if restore > 0:
            phases["checkpoint_restore"] = restore
        if checkpoint is not None:
            phases["checkpoint_save"] = hand_off
        else:
            phases["report"] = hand_off
        try:
            _goodput.record_step(self.trial, self.world_rank, phases)
        except Exception:
            pass
        if self._anat_recorded:
            # Anatomy partition of the step wall (interval minus the
            # checkpoint-restore traffic): data_wait + host + compute
            # + sync == wall exactly — sync is the residual, i.e. the
            # wall time not attributable to this rank's own input/
            # dispatch/device work: its wait for the slowest rank.
            wall = max(0.0, interval - restore)
            host = min(self._host_s, max(0.0, wall - data_wait))
            compute = min(self._compute_s,
                          max(0.0, wall - data_wait - host))
            sync = max(0.0, wall - data_wait - host - compute)
            mfu = None
            if self._step_flops > 0 and compute > 0:
                mfu = _xla_cost.mfu_percent(
                    self._step_flops * max(1, self._anat_steps),
                    compute, device_kind=self._cost_kind,
                    n_devices=self._cost_devs)
            try:
                _goodput.record_anatomy(
                    self.trial, self.world_rank,
                    {"data_wait": data_wait, "host": host,
                     "compute": compute, "sync": sync}, mfu=mfu)
            except Exception:
                pass
        self._host_s = 0.0
        self._compute_s = 0.0
        self._anat_steps = 0
        self._anat_recorded = False
        _tracing.finish_span(self._step_span)
        self._open_step_span()
        self._phase_t0 = time.perf_counter()


def init_session(**kwargs) -> None:
    _local.session = _Session(**kwargs)


def shutdown_session() -> None:
    _local.session = None


def _session() -> _Session:
    s = getattr(_local, "session", None)
    if s is None:
        raise RuntimeError(
            "No train session active: this API must be called inside "
            "train_loop_per_worker."
        )
    return s


def report(metrics: dict, checkpoint: Optional[Checkpoint] = None) -> None:
    _session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return _session().checkpoint


def get_world_rank() -> int:
    return _session().world_rank


def get_world_size() -> int:
    return _session().world_size


def get_local_rank() -> int:
    return _session().local_rank


def get_node_rank() -> int:
    return _session().node_rank


def get_dataset_shard(name: str = "train"):
    return _session().dataset_shards.get(name)


def get_trial_info():
    return _session().trial_info


def in_session() -> bool:
    return getattr(_local, "session", None) is not None


def add_data_wait(seconds: float) -> None:
    """Accrue consumer data-wait seconds to the active session's current
    step (called by the instrumented dataset iterators; a no-op outside
    a train session)."""
    s = getattr(_local, "session", None)
    if s is not None and seconds > 0:
        s._data_wait_s += seconds


def _block_sync(out: Any) -> Any:
    """Force device completion of a step's outputs: the anatomy compute
    phase must end at a real sync, never at async dispatch. Degrades to
    a no-op off-jax (plain objects are already 'ready')."""
    if "jax" in sys.modules:
        try:
            import jax

            jax.block_until_ready(out)
        except Exception:
            pass
    return out


def add_step_anatomy(host_s: float, compute_s: float) -> None:
    """Accrue one instrumented step's host (dispatch until device
    launch) and compute (synced device wall) seconds to the active
    session's current report interval. ``report()`` then partitions
    the step wall into data_wait / host / compute / sync — sync is the
    residual, this rank's wait for the slowest rank. A no-op outside a
    train session."""
    s = getattr(_local, "session", None)
    if s is None:
        return
    s._host_s += max(0.0, float(host_s))
    s._compute_s += max(0.0, float(compute_s))
    s._anat_steps += 1
    s._anat_recorded = True


def timed_step(step_fn, *args: Any, **kwargs: Any):  # step-timed
    """Run one training-step call with anatomy timing: host = wall
    until the (async) dispatch returns, compute = wall until a real
    device sync completes. Returns the step's outputs (synced)."""
    t0 = time.perf_counter()
    out = step_fn(*args, **kwargs)
    host = time.perf_counter() - t0
    _block_sync(out)
    compute = time.perf_counter() - t0 - host
    add_step_anatomy(host, compute)
    return out


def set_step_cost(cost, device_kind: Optional[str] = None,
                  n_devices: int = 1) -> None:
    """Attach the per-step cost model for this rank's shard so
    ``report()`` can export MFU: ``cost`` is either FLOPs per step (a
    number) or the dict returned by ``xla_cost.step_cost`` on the
    compiled step function. A no-op outside a train session or when
    the cost dict is an off-jax stub."""
    s = getattr(_local, "session", None)
    if s is None:
        return
    if isinstance(cost, dict):
        if not cost.get("available"):
            return
        if device_kind is None:
            device_kind = cost.get("device_kind")
        cost = cost.get("flops", 0.0)
    s._step_flops = max(0.0, float(cost or 0.0))
    s._cost_kind = device_kind
    s._cost_devs = max(1, int(n_devices))
