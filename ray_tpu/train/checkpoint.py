"""Checkpoint: a unified training artifact, plus sharded jax state I/O.

Reference parity: ``python/ray/air/checkpoint.py:60`` — one artifact
interconvertible between dict / directory / object-ref forms, so the same
object flows worker -> trainer -> tune -> user.

TPU addition (SURVEY.md §5.4): ``save_sharded``/``load_sharded`` write a
jax pytree of (possibly sharded) arrays from each host and restore it onto
an arbitrary mesh/sharding layout — the "every host writes its shards"
model, not the reference's rank-0-uploads model. Layout: one ``.npy`` per
leaf + a pickled treedef manifest.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
from typing import Any, Optional

import numpy as np

import ray_tpu

_MANIFEST = "manifest.pkl"


class Checkpoint:
    """Exactly one of ``data`` (dict) / ``directory`` / ``ref`` is set."""

    def __init__(self, data: Optional[dict] = None,
                 directory: Optional[str] = None, ref=None):
        if sum(x is not None for x in (data, directory, ref)) != 1:
            raise ValueError("provide exactly one of data/directory/ref")
        self._data = data
        self._dir = directory
        self._ref = ref

    # -- constructors -----------------------------------------------------

    @classmethod
    def from_dict(cls, data: dict) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(directory=path)

    @classmethod
    def from_object_ref(cls, ref) -> "Checkpoint":
        return cls(ref=ref)

    # -- conversions ------------------------------------------------------

    def to_dict(self) -> dict:
        if self._data is not None:
            return dict(self._data)
        if self._ref is not None:
            return Checkpoint._materialize(self._ref).to_dict()
        out = {}
        for name in os.listdir(self._dir):
            p = os.path.join(self._dir, name)
            if name.endswith(".pkl"):
                with open(p, "rb") as f:
                    out[name[:-4]] = pickle.load(f)
            elif name.endswith(".npy"):
                out[name[:-4]] = np.load(p, allow_pickle=False)
        return out

    def to_directory(self, path: Optional[str] = None) -> str:
        path = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        if self._dir is not None:
            if os.path.abspath(self._dir) != os.path.abspath(path):
                shutil.copytree(self._dir, path, dirs_exist_ok=True)
            return path
        data = self.to_dict()
        for k, v in data.items():
            if isinstance(v, np.ndarray):
                np.save(os.path.join(path, k + ".npy"), v)
            else:
                with open(os.path.join(path, k + ".pkl"), "wb") as f:
                    pickle.dump(v, f)
        return path

    def to_object_ref(self):
        if self._ref is not None:
            return self._ref
        return ray_tpu.put(self)

    @staticmethod
    def _materialize(ref) -> "Checkpoint":
        value = ray_tpu.get(ref)
        if isinstance(value, Checkpoint):
            return value
        return Checkpoint.from_dict(value)

    def __reduce__(self):
        # Ship directory checkpoints by value (the dir may be node-local).
        if self._dir is not None:
            return (Checkpoint.from_dict, (self.to_dict(),))
        if self._data is not None:
            return (Checkpoint.from_dict, (self._data,))
        return (Checkpoint.from_object_ref, (self._ref,))


# -- sharded jax pytree checkpoints ---------------------------------------
#
# Truly sharded (SURVEY.md §5.4): every process writes ONLY its
# addressable shards — one .npy per unique shard index, exactly-once
# across hosts (the process holding the lowest-id device of a replica
# group writes it) — plus a global manifest mapping shard index -> file.
# No leaf is ever gathered whole, so models larger than host RAM
# checkpoint fine (the property rank-0-upload schemes lack). Restoring
# assembles each device's target region straight from the shard files
# (mmap'd), including RESHARDING onto a different mesh/layout.


def _bounds(index, shape) -> tuple:
    """Normalize an index (tuple of slices) to (starts, stops)."""
    starts, stops = [], []
    for sl, dim in zip(index, shape):
        starts.append(0 if sl.start is None else int(sl.start))
        stops.append(dim if sl.stop is None else int(sl.stop))
    return tuple(starts), tuple(stops)


def _shard_key(starts, stops) -> str:
    if not starts:
        return "full"
    return "_".join(f"{a}-{b}" for a, b in zip(starts, stops))


def _atomic_save(path: str, arr: np.ndarray) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.save(f, arr)
    os.replace(tmp, path)


def save_sharded(state: Any, path: str) -> None:
    """Write a pytree of (possibly sharded) jax arrays under ``path``.

    Multi-host: every process calls this with the same path on shared
    storage; each writes only the shards it holds (exactly once per
    unique shard across replicas), and process 0 writes the manifest.
    Callers should barrier after (the train session does) before
    treating the checkpoint as complete.
    """
    import jax

    os.makedirs(path, exist_ok=True)
    leaves, treedef = jax.tree_util.tree_flatten(state)
    manifest_leaves = []
    for i, leaf in enumerate(leaves):
        if not isinstance(leaf, jax.Array):
            # Small host-side values (python/np scalars): inline.
            manifest_leaves.append({"inline": leaf})
            continue
        shape = tuple(leaf.shape)
        # Global index map (every process knows the full layout).
        idx_map = leaf.sharding.devices_indices_map(shape)
        groups: dict = {}  # key -> (starts, stops, [devices])
        for dev, index in idx_map.items():
            starts, stops = _bounds(index, shape)
            key = _shard_key(starts, stops)
            groups.setdefault(key, (starts, stops, []))[2].append(dev)
        local = {s.device: s for s in leaf.addressable_shards}
        shards = []
        for key, (starts, stops, devs) in sorted(groups.items()):
            fname = f"leaf_{i}.{key}.npy"
            shards.append((starts, stops, fname))
            writer = min(devs, key=lambda d: d.id)
            if writer in local:  # exactly-once across replicas/hosts
                _atomic_save(
                    os.path.join(path, fname),
                    np.asarray(local[writer].data),
                )
        manifest_leaves.append(
            {"shape": shape, "dtype": str(leaf.dtype), "shards": shards}
        )
    if getattr(jax, "process_index", lambda: 0)() == 0:
        tmp = os.path.join(path, f"{_MANIFEST}.tmp.{os.getpid()}")
        with open(tmp, "wb") as f:
            pickle.dump(
                {"treedef": treedef, "leaves": manifest_leaves}, f
            )
        os.replace(tmp, os.path.join(path, _MANIFEST))


def _load_region(path: str, info: dict, starts, stops) -> np.ndarray:
    """Assemble the region [starts, stops) of a saved leaf from its shard
    files (mmap'd: only the bytes actually needed are read)."""
    dtype = np.dtype(info["dtype"])
    # Fast path: the region is exactly one saved shard.
    for s_starts, s_stops, fname in info["shards"]:
        if tuple(s_starts) == tuple(starts) and tuple(s_stops) == tuple(stops):
            return np.load(os.path.join(path, fname))
    out = np.empty([b - a for a, b in zip(starts, stops)], dtype)
    for s_starts, s_stops, fname in info["shards"]:
        lo = [max(a, c) for a, c in zip(starts, s_starts)]
        hi = [min(b, d) for b, d in zip(stops, s_stops)]
        if any(l >= h for l, h in zip(lo, hi)):
            continue
        src = np.load(os.path.join(path, fname), mmap_mode="r")
        src_sl = tuple(
            slice(l - c, h - c) for l, h, c in zip(lo, hi, s_starts)
        )
        dst_sl = tuple(
            slice(l - a, h - a) for l, h, a in zip(lo, hi, starts)
        )
        out[dst_sl] = src[src_sl]
    return out


def load_sharded(path: str, shardings: Any = None) -> Any:
    """Restore a pytree saved by ``save_sharded``.

    With ``shardings`` (a matching pytree of jax Shardings), each device's
    target region is assembled straight from the shard files — no full
    host-side copy of any leaf, and the saved layout may differ from the
    target layout (resharding on load). Without shardings, returns full
    numpy arrays.
    """
    import jax

    with open(os.path.join(path, _MANIFEST), "rb") as f:
        manifest = pickle.load(f)
    infos = manifest["leaves"]
    treedef = manifest["treedef"]

    if shardings is None:
        leaves = []
        for info in infos:
            if "inline" in info:
                leaves.append(info["inline"])
                continue
            shape = info["shape"]
            leaves.append(
                _load_region(path, info, (0,) * len(shape), tuple(shape))
            )
        return jax.tree_util.tree_unflatten(treedef, leaves)

    sh_leaves = treedef.flatten_up_to(shardings)
    leaves = []
    for info, sh in zip(infos, sh_leaves):
        if "inline" in info:
            value = info["inline"]
            if sh is not None and hasattr(sh, "device_set"):
                value = jax.device_put(value, sh)
            leaves.append(value)
            continue
        shape = tuple(info["shape"])

        def cb(index, _path=path, _info=info, _shape=shape):
            starts, stops = _bounds(index, _shape)
            return _load_region(_path, _info, starts, stops)

        leaves.append(jax.make_array_from_callback(shape, sh, cb))
    return jax.tree_util.tree_unflatten(treedef, leaves)
