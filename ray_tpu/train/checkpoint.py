"""Checkpoint: a unified training artifact, plus sharded jax state I/O.

Reference parity: ``python/ray/air/checkpoint.py:60`` — one artifact
interconvertible between dict / directory / object-ref forms, so the same
object flows worker -> trainer -> tune -> user.

TPU addition (SURVEY.md §5.4): ``save_sharded``/``load_sharded`` write a
jax pytree of (possibly sharded) arrays from each host and restore it onto
an arbitrary mesh/sharding layout — the "every host writes its shards"
model, not the reference's rank-0-uploads model. Layout: one ``.npy`` per
leaf + a pickled treedef manifest.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
from typing import Any, Optional

import numpy as np

import ray_tpu

_MANIFEST = "manifest.pkl"


class Checkpoint:
    """Exactly one of ``data`` (dict) / ``directory`` / ``ref`` is set."""

    def __init__(self, data: Optional[dict] = None,
                 directory: Optional[str] = None, ref=None):
        if sum(x is not None for x in (data, directory, ref)) != 1:
            raise ValueError("provide exactly one of data/directory/ref")
        self._data = data
        self._dir = directory
        self._ref = ref

    # -- constructors -----------------------------------------------------

    @classmethod
    def from_dict(cls, data: dict) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(directory=path)

    @classmethod
    def from_object_ref(cls, ref) -> "Checkpoint":
        return cls(ref=ref)

    # -- conversions ------------------------------------------------------

    def to_dict(self) -> dict:
        if self._data is not None:
            return dict(self._data)
        if self._ref is not None:
            return Checkpoint._materialize(self._ref).to_dict()
        out = {}
        for name in os.listdir(self._dir):
            p = os.path.join(self._dir, name)
            if name.endswith(".pkl"):
                with open(p, "rb") as f:
                    out[name[:-4]] = pickle.load(f)
            elif name.endswith(".npy"):
                out[name[:-4]] = np.load(p, allow_pickle=False)
        return out

    def to_directory(self, path: Optional[str] = None) -> str:
        path = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        if self._dir is not None:
            if os.path.abspath(self._dir) != os.path.abspath(path):
                shutil.copytree(self._dir, path, dirs_exist_ok=True)
            return path
        data = self.to_dict()
        for k, v in data.items():
            if isinstance(v, np.ndarray):
                np.save(os.path.join(path, k + ".npy"), v)
            else:
                with open(os.path.join(path, k + ".pkl"), "wb") as f:
                    pickle.dump(v, f)
        return path

    def to_object_ref(self):
        if self._ref is not None:
            return self._ref
        return ray_tpu.put(self)

    @staticmethod
    def _materialize(ref) -> "Checkpoint":
        value = ray_tpu.get(ref)
        if isinstance(value, Checkpoint):
            return value
        return Checkpoint.from_dict(value)

    def __reduce__(self):
        # Ship directory checkpoints by value (the dir may be node-local).
        if self._dir is not None:
            return (Checkpoint.from_dict, (self.to_dict(),))
        if self._data is not None:
            return (Checkpoint.from_dict, (self._data,))
        return (Checkpoint.from_object_ref, (self._ref,))


# -- sharded jax pytree checkpoints ---------------------------------------


def save_sharded(state: Any, path: str) -> None:
    """Write a pytree of jax/np arrays: one .npy per leaf + manifest.

    Each process writes only its addressable shards — on a multi-host mesh
    every host calls this with the same path on shared storage (or its own
    local dir), and ``load_sharded`` reassembles onto the target shardings.
    Single-host arrays are fully addressable, so the leaf is written whole.
    """
    import jax

    os.makedirs(path, exist_ok=True)
    leaves, treedef = jax.tree_util.tree_flatten(state)
    manifest = {"treedef": treedef, "n": len(leaves)}
    for i, leaf in enumerate(leaves):
        arr = np.asarray(jax.device_get(leaf))
        np.save(os.path.join(path, f"leaf_{i}.npy"), arr)
    with open(os.path.join(path, _MANIFEST), "wb") as f:
        pickle.dump(manifest, f)


def load_sharded(path: str, shardings: Any = None) -> Any:
    """Restore a pytree saved by ``save_sharded``; if ``shardings`` (a
    matching pytree of jax Shardings) is given, leaves are device_put
    directly onto their target layout (no full host-side copy per device)."""
    import jax

    with open(os.path.join(path, _MANIFEST), "rb") as f:
        manifest = pickle.load(f)
    leaves = [
        np.load(os.path.join(path, f"leaf_{i}.npy"))
        for i in range(manifest["n"])
    ]
    state = jax.tree_util.tree_unflatten(manifest["treedef"], leaves)
    if shardings is not None:
        state = jax.tree.map(
            lambda x, s: jax.device_put(x, s), state, shardings
        )
    return state
