"""DataParallelTrainer: worker group + training loop + fault tolerance.

Reference parity (SURVEY.md §3.4): ``BaseTrainer.fit``
(``train/base_trainer.py:339``) -> ``DataParallelTrainer``
(``data_parallel_trainer.py:244``) -> ``BackendExecutor.start``
(``_internal/backend_executor.py:93``) creates a ``WorkerGroup`` of actors
in the trial's placement group, initializes per-worker sessions, runs the
user loop, and consumes results through ``TrainingIterator._fetch_next_result``
(``trainer.py:155``). Worker failure => group restart from the latest
checkpoint within ``FailureConfig.max_failures`` (elastic restart).

TPU-native difference: a worker is a *host*; the inner loop is a jitted
step over the host's device mesh, so the framework never touches gradients —
placement, sessions, checkpoints, and failure handling only.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core import ids
from ray_tpu.core.object_ref import ActorError, TaskError
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    JaxConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train import session as session_mod
from ray_tpu.util import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    remove_placement_group,
)
from ray_tpu.util import goodput as _goodput
from ray_tpu.util.queue import Queue


@dataclass
class Result:
    metrics: Optional[dict]
    checkpoint: Optional[Checkpoint]
    error: Optional[BaseException] = None
    metrics_history: List[dict] = field(default_factory=list)
    # Downtime-ledger rollup for the whole fit(): wall_s, downtime_s,
    # by_cause (drain:<reason> / preemption / failure), restarts,
    # goodput_pct, per-rank last step seconds + skew.
    goodput: Optional[dict] = None


# The downtime ledger is shared with Tune trials (one accounting
# implementation): ray_tpu.util.goodput.GoodputLedger.
_GoodputLedger = _goodput.GoodputLedger


def _lost_to_drain(exc: BaseException) -> bool:
    """Did this failure come from the cluster's drain/preemption path?
    Matched against the HEAD-generated cause formats only ("node <id>
    died: drained: …" / "node <id> draining: …"), so an application
    error that merely mentions draining can never loop the trainer."""
    import re

    return re.search(
        r"node \S+ (died: drained:|draining:)", str(exc)) is not None


class TrainingWorkerPreempted(ActorError):
    """A node hosting training workers entered DRAINING (preemption
    notice / scale-down): the attempt restarts from the latest checkpoint
    PROACTIVELY — before the node dies — instead of waiting out a
    heartbeat timeout, and the restart does not consume
    ``FailureConfig.max_failures`` (the trainer-level analog of the
    task retry-budget preemption exemption)."""


class TrainingGroupResized(ActorError):
    """An elastic gang's placement group reports restored capacity
    (the head finished rescheduling lost bundles onto healthy nodes)
    while the current attempt runs at a SHRUNK world size: restart from
    the latest checkpoint at the larger size. A planned regrow, not a
    failure — exempt from ``FailureConfig.max_failures``; its downtime
    is attributed to the ``reschedule`` cause."""


class _TrainWorker:
    """Actor hosting one training worker (rank)."""

    def __init__(self, rank: int):
        self.rank = rank

    def node_id(self) -> str:
        """Which cluster node this worker landed on (for rank layout)."""
        import ray_tpu._private.worker as worker_mod

        return getattr(worker_mod.backend(), "node_id", "local")

    def setup_jax(
        self, group: str, rank: int, world_size: int,
        local_rank: int, local_world_size: int, jax_config,
    ) -> bool:
        """Join the group's jax.distributed runtime (Backend.on_start
        analog, ``train/torch/config.py:129-181``). Blocks until all
        ranks connect, so the trainer must call it on all workers
        concurrently."""
        import os

        from ray_tpu.parallel import distributed as dist

        os.environ["RAY_TPU_LOCAL_RANK"] = str(local_rank)
        dist.initialize(
            group, rank, world_size,
            platform=jax_config.platform,
            num_cpu_devices=jax_config.num_cpu_devices,
            timeout=jax_config.init_timeout,
        )
        return True

    def setup_torch(self, group: str, rank: int, world_size: int,
                    local_rank: int, torch_config) -> bool:
        """Join the group's torch.distributed process group (TorchTrainer
        backend hook; ``train/torch/config.py:129-181`` analog). Rank 0
        publishes its master addr/port through the cluster KV — the same
        rendezvous channel the JAX runtime uses."""
        import datetime
        import os

        import torch.distributed as tdist

        from ray_tpu.parallel import distributed as rdz

        if rank == 0:
            addr = rdz.publish_coordinator(group)
        else:
            addr = rdz.wait_coordinator(group, torch_config.init_timeout)
        os.environ["RAY_TPU_LOCAL_RANK"] = str(local_rank)
        # Torch-ecosystem conventions (accelerate/transformers read these
        # even when the process group is already initialized).
        host, _, port = addr.rpartition(":")
        os.environ["MASTER_ADDR"] = host
        os.environ["MASTER_PORT"] = port
        os.environ["RANK"] = str(rank)
        os.environ["WORLD_SIZE"] = str(world_size)
        os.environ["LOCAL_RANK"] = str(local_rank)
        tdist.init_process_group(
            torch_config.backend,
            init_method=f"tcp://{addr}",
            rank=rank,
            world_size=world_size,
            timeout=datetime.timedelta(seconds=torch_config.init_timeout),
        )
        return True

    def run(self, train_fn, config, session_kwargs):
        session_mod.init_session(**session_kwargs)
        try:
            train_fn(config)
        finally:
            q = session_kwargs["results_queue"]
            q.put({"type": "finished", "rank": self.rank})
            session_mod.shutdown_session()
        return self.rank


class WorkerGroup:
    """N worker actors inside one placement group
    (``train/_internal/worker_group.py:92``).

    Default (fixed gang): owns a fresh group sized for the full
    ``scaling.num_workers``. Elastic: the trainer passes the ONE
    long-lived group it holds across attempts plus the bundle indices
    that currently have a live node — this attempt runs at that
    (possibly shrunk) world size while the head's reschedule
    coordinator migrates the lost bundles in the background."""

    def __init__(self, scaling: ScalingConfig,
                 num_workers: Optional[int] = None,
                 pg=None, bundle_indices: Optional[List[int]] = None):
        self.scaling = scaling
        self.owns_pg = pg is None
        self.num_workers = num_workers or scaling.num_workers
        if pg is None:
            bundles = scaling.as_placement_group_bundles()
            pg = placement_group(
                bundles, strategy=scaling.placement_strategy)
            ray_tpu.get(pg.ready(), timeout=120)
        self.pg = pg
        if bundle_indices is None:
            bundle_indices = list(range(self.num_workers))
        self.bundle_indices = list(bundle_indices)[: self.num_workers]
        worker_cls = ray_tpu.remote(_TrainWorker)
        self.workers = [
            worker_cls.options(
                num_cpus=0,
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self.pg,
                    placement_group_bundle_index=self.bundle_indices[i],
                ),
            ).remote(i)
            for i in range(self.num_workers)
        ]

    def run_all(self, train_fn, config, session_kwargs_per_worker) -> list:
        return [
            w.run.remote(train_fn, config, kw)
            for w, kw in zip(self.workers, session_kwargs_per_worker)
        ]

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        if self.owns_pg:
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass


class _CheckpointManager:
    """Track reported checkpoints, keep top-K (``CheckpointConfig``,
    ``tune/execution/checkpoint_manager.py`` analog)."""

    def __init__(self, config: CheckpointConfig):
        self.config = config
        self.checkpoints: List[tuple] = []  # (score, iteration, Checkpoint)
        self.latest: Optional[Checkpoint] = None

    def register(self, checkpoint: Checkpoint, metrics: dict, iteration: int):
        self.latest = checkpoint
        attr = self.config.checkpoint_score_attribute
        score = metrics.get(attr) if attr else iteration
        if score is None:
            score = iteration
        sign = 1 if self.config.checkpoint_score_order == "max" else -1
        self.checkpoints.append((sign * score, iteration, checkpoint))
        self.checkpoints.sort(key=lambda t: (-t[0], -t[1]))
        if self.config.num_to_keep is not None:
            del self.checkpoints[self.config.num_to_keep :]

    @property
    def best(self) -> Optional[Checkpoint]:
        return self.checkpoints[0][2] if self.checkpoints else self.latest


def _shard_dataset(ds, n: int, equal: bool = True):
    """Per-worker shards: Data datasets via split(); arrays/lists striped."""
    if ds is None:
        return [None] * n
    if hasattr(ds, "split"):
        return ds.split(n, equal=equal)
    try:
        return [ds[i::n] for i in range(n)]
    except TypeError:
        return [ds] * n


class DataParallelTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.train_fn = train_loop_per_worker
        self.config = dict(train_loop_config or {})
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_checkpoint = resume_from_checkpoint

    # -- one attempt ------------------------------------------------------

    def _run_attempt(
        self, ckpt_mgr: _CheckpointManager, metrics_history: List[dict],
        ledger: Optional["_GoodputLedger"] = None,
        pg=None,
    ) -> Optional[dict]:
        """Run the worker group to completion; returns last metrics.
        Raises on worker failure (caller handles elasticity). With
        ``pg`` (the elastic path's long-lived group) the attempt runs
        on the bundles that currently have a live node — shrunk world
        size while the head migrates the rest — and a regrow watcher
        interrupts it when the group's capacity is whole again."""
        n = self.scaling.num_workers
        bundle_indices: Optional[List[int]] = None
        if pg is not None:
            bundle_indices = self._wait_live_bundles(pg)[:n]
            n = len(bundle_indices)
        drain_stop = threading.Event()
        drained_nodes: set = set()
        # Subscribe to drain events BEFORE placing anything: a preemption
        # notice for a node hosting this group triggers a checkpoint-
        # restore restart while the node is still up, not after a
        # heartbeat timeout. (The watcher records every draining node;
        # the consume loop intersects with the group's nodes.)
        threading.Thread(
            target=self._watch_drains,
            args=(drained_nodes, drain_stop), daemon=True,
        ).start()
        regrow_evt: Optional[threading.Event] = None
        if pg is not None and n < self.scaling.num_workers:
            regrow_evt = threading.Event()
            threading.Thread(
                target=self._watch_regrow,
                args=(pg, n, regrow_evt, drain_stop), daemon=True,
            ).start()
        group = WorkerGroup(self.scaling, num_workers=n, pg=pg,
                            bundle_indices=bundle_indices)
        # Pinned to the driver's node: a results queue riding a node a
        # preemption takes would read as a budget-consuming trial
        # failure (see queue.driver_node_options).
        from ray_tpu.util.queue import driver_node_options

        queue = Queue(actor_options=driver_node_options())
        try:
            shards = {
                name: _shard_dataset(ds, n) for name, ds in self.datasets.items()
            }
            start_ckpt = ckpt_mgr.latest or self.resume_checkpoint
            node_ranks, local_ranks, node_ids = self._compute_ranks(group)
            self._on_group_start(group, node_ranks, local_ranks)
            session_kwargs = [
                {
                    "world_rank": i,
                    "world_size": n,
                    "local_rank": local_ranks[i],
                    "node_rank": node_ranks[i],
                    "results_queue": queue,
                    "checkpoint": start_ckpt,
                    "dataset_shards": {
                        name: sh[i] for name, sh in shards.items()
                    },
                }
                for i in range(n)
            ]
            run_refs = group.run_all(self.train_fn, self.config, session_kwargs)
            return self._consume_results(
                queue, run_refs, n, ckpt_mgr, metrics_history,
                drained_nodes=drained_nodes, group_nodes=set(node_ids),
                ledger=ledger, regrow_evt=regrow_evt,
            )
        finally:
            drain_stop.set()
            queue.shutdown()
            group.shutdown()

    def _compute_ranks(self, group: WorkerGroup) -> tuple[list, list, list]:
        """node_rank + local_rank (+ raw node id) per worker, from actual
        actor placement (``backend_executor.py:339-404`` init_session
        rank layout)."""
        node_ids = ray_tpu.get(
            [w.node_id.remote() for w in group.workers], timeout=60
        )
        node_order: list[str] = []
        counts: dict[str, int] = {}
        node_ranks, local_ranks = [], []
        for nid in node_ids:
            if nid not in counts:
                counts[nid] = 0
                node_order.append(nid)
            node_ranks.append(node_order.index(nid))
            local_ranks.append(counts[nid])
            counts[nid] += 1
        return node_ranks, local_ranks, node_ids

    def _watch_drains(self, drained_nodes: set,
                      stop_evt: threading.Event) -> None:
        """Long-poll the head's NODES pubsub feed and record every node
        that enters DRAINING (the local backend has no head/pubsub: the
        watcher is a no-op there)."""
        from ray_tpu._private import worker as worker_mod

        head = getattr(worker_mod.backend(), "head", None)
        if head is None:
            return
        sub_id = f"train-drain:{ids.new_task_id()[:12]}"
        try:
            head.call("pubsub_subscribe", sub_id, "NODES")
            while not stop_evt.is_set():
                try:
                    got = head.call("pubsub_poll", sub_id, 1.0,
                                    timeout=10.0)
                except Exception:
                    return  # backend shutting down / head gone
                if got is None:
                    # Head restarted / subscription TTL'd away: poll
                    # returns None instantly for an unknown sub, so
                    # re-subscribe (not re-poll) or this would hot-spin.
                    time.sleep(0.5)
                    try:
                        head.call("pubsub_subscribe", sub_id, "NODES")
                    except Exception:
                        return
                    continue
                for m in got[0]:
                    data = m.get("data") or {}
                    if data.get("state") == "DRAINING" and \
                            data.get("node_id"):
                        drained_nodes.add(data["node_id"])
        finally:
            try:
                head.call("pubsub_unsubscribe", sub_id)
            except Exception:
                pass

    @staticmethod
    def _pg_table(pg) -> dict:
        from ray_tpu.util.placement_group import placement_group_table

        return placement_group_table(pg) or {}

    def _live_bundles(self, pg) -> List[int]:
        """Bundle indices whose node is alive and schedulable right now
        (the head's table carries them; a backend without per-bundle
        liveness — the local backend — reports all bundles once the
        group is CREATED)."""
        table = self._pg_table(pg)
        live = table.get("live_bundles")
        if live is None:
            if table.get("state") == "CREATED":
                return list(range(len(table.get("bundles") or
                                      [None] * self.scaling.num_workers)))
            return []
        return list(live)

    def _wait_live_bundles(self, pg, timeout: float = 300.0) -> List[int]:
        """Block until at least ``min_workers`` bundles have live nodes
        (the elastic floor): a gang that lost everything waits for the
        head's reschedule coordinator to land replacements rather than
        burning an attempt on an unplaceable world."""
        floor = max(1, self.scaling.min_workers or self.scaling.num_workers)
        deadline = time.monotonic() + timeout
        while True:
            live = self._live_bundles(pg)
            if len(live) >= floor:
                return sorted(live)
            state = self._pg_table(pg).get("state")
            if state in ("REMOVED", "INFEASIBLE"):
                raise RuntimeError(
                    f"elastic gang placement group is {state}")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"elastic gang never reached min_workers={floor} "
                    f"live bundles within {timeout}s (live={live})")
            time.sleep(0.25)

    def _watch_regrow(self, pg, current_n: int,
                      regrow_evt: threading.Event,
                      stop_evt: threading.Event) -> None:
        """Poll the group's table while an attempt runs SHRUNK: the
        moment more bundles are live than the attempt is using (the
        head finished rescheduling onto a replacement node), signal the
        consume loop to restart at the larger world size."""
        while not stop_evt.is_set():
            try:
                if len(self._live_bundles(pg)) > current_n:
                    regrow_evt.set()
                    return
            except Exception:
                return  # backend shutting down
            stop_evt.wait(0.5)

    def _on_group_start(self, group, node_ranks, local_ranks) -> None:
        """Framework-backend hook run before the training loops start
        (``Backend.on_start`` analog). Default: nothing."""

    def _consume_results(
        self, queue, run_refs, n, ckpt_mgr, metrics_history,
        drained_nodes: Optional[set] = None,
        group_nodes: Optional[set] = None,
        ledger: Optional["_GoodputLedger"] = None,
        regrow_evt: Optional[threading.Event] = None,
    ) -> Optional[dict]:
        """TrainingIterator: drain worker reports; rank-0 metrics win
        (``train/trainer.py:155 _fetch_next_result``)."""
        finished: set[int] = set()
        last_metrics: Optional[dict] = None
        while len(finished) < n:
            if drained_nodes and group_nodes and \
                    (drained_nodes & group_nodes):
                # A worker's node is leaving (preemption/scale-down):
                # restart from the latest checkpoint NOW, while that
                # node still serves its objects, instead of discovering
                # the loss via heartbeat timeout mid-step.
                raise TrainingWorkerPreempted(
                    "a training worker's node is draining; restarting "
                    "the group from the latest checkpoint")
            if regrow_evt is not None and regrow_evt.is_set():
                # Capacity restored while running shrunk: re-form the
                # collective at the larger world size from the latest
                # checkpoint (planned, budget-exempt).
                raise TrainingGroupResized(
                    "gang capacity restored; regrowing the group from "
                    "the latest checkpoint")
            # Fail fast if a worker actor died (its queue would stay silent).
            ready, _ = ray_tpu.wait(run_refs, num_returns=n, timeout=0.0)
            for r in ready:
                ray_tpu.get(r)  # raises ActorError/TaskError on failure
            try:
                msg = queue.get(timeout=1.0)
            except Exception:
                continue
            if msg["type"] == "finished":
                finished.add(msg["rank"])
                continue
            if msg["type"] == "report":
                if ledger is not None:
                    ledger.observe_report(msg)
                if msg["checkpoint"] is not None and msg["rank"] == 0:
                    ckpt_mgr.register(
                        msg["checkpoint"], msg["metrics"], msg["iteration"]
                    )
                if msg["rank"] == 0:
                    last_metrics = msg["metrics"]
                    metrics_history.append(msg["metrics"])
        for r in run_refs:
            ray_tpu.get(r, timeout=60)
        return last_metrics

    # -- public -----------------------------------------------------------

    def fit(self) -> Result:
        ckpt_mgr = _CheckpointManager(self.run_config.checkpoint_config)
        metrics_history: List[dict] = []
        max_failures = self.run_config.failure_config.max_failures
        ledger = _GoodputLedger()
        attempt = 0
        elastic = self.scaling.min_workers is not None
        pg = None
        # Terminal snapshot of the elastic gang's PG table (state /
        # placement / reschedule count), captured before the group is
        # released — the chaos harness's "PG ends ALIVE" invariant
        # reads it off the finished trainer.
        self.final_pg_state: Optional[dict] = None
        if elastic:
            # ONE long-lived reservation for the whole fit(): bundle
            # loss moves it to RESCHEDULING (the head migrates bundles
            # to healthy nodes) instead of killing it — attempts shrink
            # to the live bundles and regrow when capacity returns.
            bundles = self.scaling.as_placement_group_bundles()
            pg = placement_group(
                bundles, strategy=self.scaling.placement_strategy)
            ray_tpu.get(pg.ready(), timeout=120)
        try:
            while True:
                resched_before = (
                    self._pg_table(pg).get("reschedules", 0)
                    if pg is not None else 0)
                try:
                    last_metrics = self._run_attempt(
                        ckpt_mgr, metrics_history, ledger, pg=pg)
                    return Result(
                        metrics=last_metrics,
                        checkpoint=ckpt_mgr.best,
                        metrics_history=metrics_history,
                        goodput=ledger.summary(),
                    )
                except TrainingWorkerPreempted as e:
                    # Preemption exemption: a planned node departure
                    # restarts the group (from the latest checkpoint)
                    # WITHOUT consuming the failure budget.
                    ledger.mark_down(_goodput.downtime_cause(e))
                    time.sleep(0.2)
                except TrainingGroupResized:
                    # Planned regrow to restored capacity: exempt, and
                    # the restart cost is the reschedule's to carry.
                    ledger.mark_down("reschedule")
                    time.sleep(0.2)
                except (ActorError, TaskError) as e:
                    if _lost_to_drain(e):
                        # A group actor (worker or results queue) died
                        # WITH a draining/preempted node before the
                        # drain watcher could classify it: same
                        # exemption, same restart.
                        ledger.mark_down(_goodput.downtime_cause(e))
                        time.sleep(0.2)
                        continue
                    if elastic and self._gang_migrating(pg, resched_before):
                        # A gang bundle's node died outright (hard spot
                        # preemption, no notice): the reservation is
                        # RESCHEDULING, not dead — on a preemptible
                        # fleet this is the normal case, not a failure.
                        # Restart shrunk from the latest checkpoint,
                        # budget intact.
                        ledger.mark_down("preemption")
                        time.sleep(0.2)
                        continue
                    ledger.mark_down("failure")
                    attempt += 1
                    if max_failures >= 0 and attempt > max_failures:
                        return Result(
                            metrics=metrics_history[-1]
                            if metrics_history else None,
                            checkpoint=ckpt_mgr.best,
                            error=e,
                            metrics_history=metrics_history,
                            goodput=ledger.summary(),
                        )
                    # Elastic restart: new group resumes from latest
                    # checkpoint.
                    time.sleep(0.2)
        finally:
            # Session stop: the trial's per-rank gauge series (step
            # time, MFU, anatomy phases) must not outlive the trial on
            # the scrape (LC001 discipline — the local backend's worker
            # threads never die to trigger the agent's sweep).
            try:
                _goodput.retract_trial(ledger.trial)
            except Exception:
                pass
            if pg is not None:
                try:
                    table = self._pg_table(pg)
                    if table.get("state") == "RESCHEDULING":
                        # The trial finished at shrunk world size while
                        # the head was still migrating the lost
                        # bundles: let the reservation settle (bounded)
                        # so the terminal snapshot — the "gang ended
                        # ALIVE on healthy nodes" evidence — reflects
                        # the migration's outcome, not its midpoint.
                        settle = time.monotonic() + 20.0
                        while time.monotonic() < settle and \
                                table.get("state") == "RESCHEDULING":
                            time.sleep(0.25)
                            table = self._pg_table(pg)
                    self.final_pg_state = table
                except Exception:
                    pass
                try:
                    remove_placement_group(pg)
                except Exception:
                    pass

    def _gang_migrating(self, pg, resched_before: int) -> bool:
        """Is this attempt's loss a gang-bundle node loss the head is
        already migrating (PG RESCHEDULING now, or a reschedule
        completed since the attempt started)?"""
        if pg is None:
            return False
        try:
            table = self._pg_table(pg)
        except Exception:
            return False
        return (table.get("state") == "RESCHEDULING"
                or table.get("reschedules", 0) > resched_before)


class JaxTrainer(DataParallelTrainer):
    """DataParallelTrainer whose workers drive jax on their local devices.

    The torch/TF/horovod backends of the reference
    (``train/torch/config.py:113``) become: before the loops start, every
    worker joins ONE ``jax.distributed`` process group — rank 0 publishes
    the coordinator address through the cluster KV
    (``ray_tpu.parallel.distributed``), all ranks call
    ``jax.distributed.initialize``, and ``jax.devices()`` then spans every
    worker host. Gradient communication happens inside the jitted step
    (XLA collectives on ICI/DCN); the framework only does placement,
    sessions, checkpoints, and failure handling.
    """

    def __init__(self, *args, jax_config: Optional[JaxConfig] = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.jax_config = jax_config or JaxConfig()

    def _on_group_start(self, group, node_ranks, local_ranks) -> None:
        if not self.jax_config.distributed:
            return
        # The in-process local backend runs worker "actors" as threads of
        # ONE process — jax.distributed (one runtime per OS process) can't
        # span them. Multi-host setup needs the cluster backend, where each
        # worker is its own process; on the local backend each worker just
        # uses the process-wide JAX runtime as-is.
        from ray_tpu._private import worker as worker_mod
        from ray_tpu.core.local_backend import LocalBackend

        if isinstance(worker_mod.backend(), LocalBackend):
            return
        from ray_tpu.parallel import distributed as dist

        group_name = f"train-{ids.new_task_id()[:12]}"
        local_world = {}
        for nr in node_ranks:
            local_world[nr] = local_world.get(nr, 0) + 1
        # All setup calls must be in flight together: initialize() blocks
        # until every rank has connected to the coordinator.
        refs = [
            w.setup_jax.remote(
                group_name, i, self.scaling.num_workers,
                local_ranks[i], local_world[node_ranks[i]], self.jax_config,
            )
            for i, w in enumerate(group.workers)
        ]
        try:
            ray_tpu.get(refs, timeout=self.jax_config.init_timeout + 60)
        finally:
            dist.clear_group(group_name)
