"""HuggingFaceTrainer: transformers.Trainer per worker over torch DDP.

Reference parity: ``python/ray/train/huggingface/huggingface_trainer.py``
— the user supplies ``trainer_init_per_worker(train_dataset,
eval_dataset, **config) -> transformers.Trainer``; each worker joins the
gloo process group first (TorchTrainer backend), and the HF Trainer's
accelerate integration detects the already-initialized process group, so
its inner loop runs DDP without further wiring. Results flow back
through the standard session.report channel.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.train import session
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.torch import TorchConfig, TorchTrainer


class HuggingFaceTrainer(TorchTrainer):
    def __init__(
        self,
        trainer_init_per_worker: Callable,
        *,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        torch_config: Optional[TorchConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        trainer_init_config: Optional[dict] = None,
    ):
        init_fn = trainer_init_per_worker

        def loop(config):
            # RANK/WORLD_SIZE/LOCAL_RANK/MASTER_ADDR/PORT are exported by
            # setup_torch before this loop runs; accelerate attaches to
            # the already-initialized gloo group from those.
            train_ds = session.get_dataset_shard("train")
            eval_ds = session.get_dataset_shard("evaluation")
            hf_trainer = init_fn(train_ds, eval_ds, **config)
            result = hf_trainer.train()
            metrics = dict(result.metrics or {})
            metrics.setdefault("training_loss",
                               getattr(result, "training_loss", None))
            session.report(metrics)

        super().__init__(
            loop,
            train_loop_config=trainer_init_config,
            scaling_config=scaling_config,
            run_config=run_config,
            torch_config=torch_config,
            datasets=datasets or {},
        )
