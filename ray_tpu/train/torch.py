"""TorchTrainer: torch-DDP data parallelism on the actor substrate.

Reference parity: ``python/ray/train/torch/`` — ``TorchConfig``/
``_TorchBackend`` pick a backend (gloo on CPU hosts), rank 0 fans out a
master addr/port, every worker calls ``dist.init_process_group``
(``torch/config.py:29,69,113,129-181``), and ``prepare_model`` /
``prepare_data_loader`` wrap DDP + DistributedSampler
(``torch/train_loop_utils.py``).

TPU-native positioning: the flagship training path here is ``JaxTrainer``
(XLA collectives inside the jitted step); TorchTrainer exists for the
reference's torch workloads — CPU-side torch models data-parallel over
the same WorkerGroup/session machinery, rendezvousing through the same
cluster-KV channel the JAX runtime uses (``parallel/distributed.py``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ray_tpu.train import session
from ray_tpu.train.trainer import DataParallelTrainer


@dataclass
class TorchConfig:
    """``python/ray/train/torch/config.py:29`` analog. ``backend``:
    process-group backend; gloo is the CPU default (nccl has no meaning
    on TPU hosts — device collectives belong to XLA/JaxTrainer)."""

    backend: str = "gloo"
    init_timeout: float = 120.0


class TorchTrainer(DataParallelTrainer):
    """DataParallelTrainer whose workers join one torch.distributed
    process group before the training loops start; inside the loop,
    ``prepare_model`` makes gradient averaging automatic via DDP."""

    def __init__(self, *args, torch_config: Optional[TorchConfig] = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.torch_config = torch_config or TorchConfig()

    def _on_group_start(self, group, node_ranks, local_ranks) -> None:
        # torch.distributed is one process group per OS process; the
        # local backend's thread-actors share a process, so the
        # distributed path needs the cluster backend (same constraint and
        # guard as JaxTrainer._on_group_start).
        from ray_tpu._private import worker as worker_mod
        from ray_tpu.core import ids
        from ray_tpu.core.local_backend import LocalBackend
        from ray_tpu.parallel import distributed as rdz

        if isinstance(worker_mod.backend(), LocalBackend):
            return
        if self.scaling.num_workers == 1:
            return
        group_name = f"torch-{ids.new_task_id()[:12]}"
        refs = [
            w.setup_torch.remote(
                group_name, i, self.scaling.num_workers,
                local_ranks[i], self.torch_config,
            )
            for i, w in enumerate(group.workers)
        ]
        try:
            import ray_tpu

            ray_tpu.get(refs, timeout=self.torch_config.init_timeout + 60)
        finally:
            rdz.clear_group(group_name)


def get_device():
    """Reference ``train.torch.get_device``: the device this worker's
    model should live on. CPU-host torch here (accelerators are JAX's)."""
    import torch

    return torch.device("cpu")


def prepare_model(model, *, wrap_ddp: bool = True):
    """Wrap the model for distributed training when a process group is
    active (``train/torch/train_loop_utils.py`` prepare_model): DDP makes
    backward() all-reduce gradients so every rank steps identically."""
    import torch.distributed as dist

    if (wrap_ddp and dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    return model


def prepare_data_loader(loader, *, add_dist_sampler: bool = True):
    """Re-wrap a DataLoader with a DistributedSampler over this worker's
    rank/world (``train_loop_utils.py`` prepare_data_loader): each rank
    iterates a disjoint 1/world shard per epoch."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader
    from torch.utils.data.distributed import DistributedSampler

    if not (add_dist_sampler and dist.is_available()
            and dist.is_initialized() and dist.get_world_size() > 1):
        return loader
    if isinstance(getattr(loader, "sampler", None), DistributedSampler):
        return loader
    sampler = DistributedSampler(
        loader.dataset,
        num_replicas=session.get_world_size(),
        rank=session.get_world_rank(),
    )
    return DataLoader(
        loader.dataset,
        batch_size=loader.batch_size,
        sampler=sampler,
        num_workers=0,
        collate_fn=loader.collate_fn,
        drop_last=loader.drop_last,
    )
