"""Minimal fused AdamW as pure pytree functions.

Written in-tree (rather than via optax) so the optimizer state is a pytree
that mirrors the parameter tree exactly — its shardings are then the param
shardings verbatim, which is what makes ZeRO-style optimizer-state sharding
"fall out of pjit" (SURVEY.md §2.4). optax remains available to user code.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

Params = Any


class AdamWConfig(NamedTuple):
    lr: float = 3e-4
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8
    weight_decay: float = 0.1
    grad_clip: float = 1.0
    warmup_steps: int = 0


def adamw_init(params: Params) -> dict[str, Params]:
    zeros = lambda p: jnp.zeros_like(p)
    return {"mu": jax.tree.map(zeros, params), "nu": jax.tree.map(zeros, params)}


def _schedule(cfg: AdamWConfig, step: jax.Array) -> jax.Array:
    lr = jnp.asarray(cfg.lr, jnp.float32)
    if cfg.warmup_steps > 0:
        warm = jnp.minimum(1.0, (step + 1) / cfg.warmup_steps)
        lr = lr * warm
    return lr


def adamw_update(
    cfg: AdamWConfig,
    grads: Params,
    params: Params,
    opt_state: dict[str, Params],
    step: jax.Array,
):
    """One AdamW step with global-norm clipping. Returns (params, opt_state, lr)."""
    gnorm = jnp.sqrt(
        sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree.leaves(grads))
    )
    scale = jnp.where(
        gnorm > cfg.grad_clip, cfg.grad_clip / jnp.maximum(gnorm, 1e-12), 1.0
    )
    lr = _schedule(cfg, step)
    t = (step + 1).astype(jnp.float32)
    bc1 = 1.0 - cfg.b1**t
    bc2 = 1.0 - cfg.b2**t

    def upd(p, g, mu, nu):
        g = g.astype(jnp.float32) * scale
        mu = cfg.b1 * mu + (1 - cfg.b1) * g
        nu = cfg.b2 * nu + (1 - cfg.b2) * jnp.square(g)
        step_ = (mu / bc1) / (jnp.sqrt(nu / bc2) + cfg.eps)
        p32 = p.astype(jnp.float32)
        p32 = p32 - lr * (step_ + cfg.weight_decay * p32)
        return p32.astype(p.dtype), mu, nu

    flat_p, treedef = jax.tree.flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_mu = treedef.flatten_up_to(opt_state["mu"])
    flat_nu = treedef.flatten_up_to(opt_state["nu"])
    out = [upd(p, g, m, n) for p, g, m, n in zip(flat_p, flat_g, flat_mu, flat_nu)]
    new_p = treedef.unflatten([o[0] for o in out])
    new_mu = treedef.unflatten([o[1] for o in out])
    new_nu = treedef.unflatten([o[2] for o in out])
    return new_p, {"mu": new_mu, "nu": new_nu}, lr, gnorm
