"""Control-plane scalability benchmark: many nodes, deep task queues,
actor fan-out, cluster-wide object broadcast.

Mirrors the reference's scalability envelope harness
(``release/benchmarks/README.md:8-31``: 250+ nodes, 10k+ tasks, 1M queued,
10k actors, 1 GiB broadcast to 50+ nodes) scaled to one machine: N raylet
processes on one host (the ``cluster_utils.Cluster`` trick the reference
uses for multi-node tests, ``python/ray/cluster_utils.py:99``).

Usage:
    python -m ray_tpu.scripts.scalebench [--nodes 16] [--cpus 2]
        [--tasks 2000] [--actors 200] [--broadcast-mb 256]
        [--out MICROBENCH.json]

With --out pointing at MICROBENCH.json the results merge under a
"scalability" key (the per-op numbers from microbench.py stay put).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def run(nodes: int = 16, cpus: int = 2, tasks: int = 2000,
        actors: int = 200, broadcast_mb: int = 256) -> dict:
    import numpy as np

    import ray_tpu
    from ray_tpu.cluster.cluster_utils import Cluster

    out: dict = {"nodes": nodes, "cpus_per_node": cpus}

    def record(name, value, unit):
        out[name] = {"value": round(value, 2), "unit": unit}
        print(f"{name}: {value:,.2f} {unit}", file=sys.stderr, flush=True)

    ray_tpu.shutdown()
    t0 = time.perf_counter()
    cluster = Cluster()
    for _ in range(nodes):
        cluster.add_node(num_cpus=cpus)
    cluster.wait_for_nodes(timeout=30.0 + 5.0 * nodes)
    record("cluster_boot_s", time.perf_counter() - t0, "s")
    ray_tpu.init(cluster.address)

    try:
        @ray_tpu.remote
        def noop():
            return os.environ.get("RAY_TPU_NODE_ID")

        # Warm pools everywhere (SPREAD defeats the prefer-local fast
        # path so every node forks its workers before timing starts).
        from ray_tpu.util.scheduling_strategies import (  # noqa: F401
            NodeAffinitySchedulingStrategy,
        )

        warm = [
            noop.options(scheduling_strategy="SPREAD").remote()
            for _ in range(nodes * cpus)
        ]
        ray_tpu.get(warm, timeout=600)

        # 1. Deep queue: submit `tasks` CPU:1 noops in one burst —
        # ~tasks/(nodes*cpus) deep per slot — and drain.
        t0 = time.perf_counter()
        refs = [noop.remote() for _ in range(tasks)]
        submit_dt = time.perf_counter() - t0
        where = ray_tpu.get(refs, timeout=1200)
        drain_dt = time.perf_counter() - t0
        record("burst_submit_per_s", tasks / submit_dt, "ops/s")
        record("burst_tasks_per_s", tasks / drain_dt, "ops/s")
        record("burst_nodes_used", float(len(set(where))), "nodes")

        # 2. Actor fan-out: create `actors` zero-CPU actors, call each
        # once (reference envelope: 10k+ actors cluster-wide).
        @ray_tpu.remote(num_cpus=0)
        class Probe:
            def pid(self):
                return os.getpid()

        # Waved creation (32 in flight): measures steady-state creation
        # rate; an unbounded 200-actor burst on a 1-core box starves new
        # workers' accept loops past any sane timeout (the reference's
        # envelope runs paced on real multi-core nodes).
        t0 = time.perf_counter()
        handles, pids = [], []
        for start in range(0, actors, 32):
            wave = [Probe.remote()
                    for _ in range(min(32, actors - start))]
            pids.extend(ray_tpu.get(
                [h.pid.remote() for h in wave], timeout=1200))
            handles.extend(wave)
        dt = time.perf_counter() - t0
        record("actor_create_call_per_s", actors / dt, "ops/s")
        record("actor_distinct_pids", float(len(set(pids))), "workers")
        for h in handles:
            ray_tpu.kill(h)

        # 3. Broadcast: one large object pulled by every node (reference:
        # 1 GiB broadcast to 50+ nodes via chunked node-to-node pulls).
        blob = np.random.default_rng(0).integers(
            0, 255, broadcast_mb * 1024 * 1024, dtype=np.uint8)
        ref = ray_tpu.put(blob)

        @ray_tpu.remote
        def touch(x):
            return int(x[-1]) + len(x) % 7

        # Per-RPC accounting for the ownership protocol (verdict r4 #3
        # "Done" criterion): during the broadcast, location waits resolve
        # at the OWNER (this driver's directory server), so the head's
        # wait_locations count must stay O(1)-ish instead of O(nodes x
        # poll rounds), and its handler time flat.
        stats0 = cluster.head._server.handler_stats()
        t0 = time.perf_counter()
        sums = ray_tpu.get(
            [
                touch.options(scheduling_strategy="SPREAD").remote(ref)
                for _ in range(nodes)
            ],
            timeout=1200,
        )
        dt = time.perf_counter() - t0
        stats1 = cluster.head._server.handler_stats()
        assert len(set(sums)) == 1
        gib = broadcast_mb / 1024.0
        record("broadcast_object_gib", gib, "GiB")
        record("broadcast_nodes_per_s", nodes / dt, "nodes/s")
        record("broadcast_agg_gib_per_s", gib * nodes / dt, "GiB/s")

        def delta(method, field="count"):
            return (stats1.get(method, {}).get(field, 0)
                    - stats0.get(method, {}).get(field, 0))

        record("broadcast_head_wait_locations", float(
            delta("wait_locations")), "rpcs")
        record("broadcast_head_handler_s", float(round(
            sum(stats1.get(m, {}).get("total_s", 0.0)
                for m in stats1)
            - sum(stats0.get(m, {}).get("total_s", 0.0)
                  for m in stats0), 4)), "s")
        out["head_rpc_counts"] = {
            m: stats1[m]["count"] for m in sorted(stats1)
        }
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=16)
    ap.add_argument("--cpus", type=int, default=2)
    ap.add_argument("--tasks", type=int, default=2000)
    ap.add_argument("--actors", type=int, default=200)
    ap.add_argument("--broadcast-mb", type=int, default=256)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    res = run(args.nodes, args.cpus, args.tasks, args.actors,
              args.broadcast_mb)
    print(json.dumps(res, indent=1))
    if args.out:
        merged = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                merged = json.load(f)
        merged["scalability"] = res
        with open(args.out, "w") as f:
            json.dump(merged, f, indent=1)
            f.write("\n")


if __name__ == "__main__":
    main()
