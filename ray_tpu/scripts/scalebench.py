"""Control-plane scalability benchmark: many nodes, deep task queues,
actor fan-out, cluster-wide object broadcast — plus a head-at-scale
section that drives the head's RPC surface at the reference envelope
shapes (``release/benchmarks/README.md:8-31``: 250+ nodes, 10k+ actors,
1M queued) without paying one OS process per node.

Two sections:

* **Real cluster** (``run``): N raylet processes on one host (the
  ``cluster_utils.Cluster`` trick the reference uses for multi-node
  tests, ``python/ray/cluster_utils.py:99``) executing real tasks/
  actors/broadcasts end-to-end. On a shared-core box the absolute rates
  measure the box, not the design — the machine-independent signals are
  the per-RPC counts. The ``--queued`` phase parks that many infeasible
  specs in the client ``_retry_heap`` and proves the submitter stays
  live under them (bounded steady-state head RPC rate from retry
  backoff, a feasible probe task completing promptly, clean shutdown).

* **Head at scale** (``run_head_scale``): a real ``HeadServer`` (real
  RPC plane, real write-behind persistence) driven by a synthetic
  client at the reference shapes — 64+ registered nodes heartbeating,
  100k+ queued schedule requests, 100k borrow registrations and
  location adds, 1k actor records with pubsub fan-out to slow
  subscribers, a span burst past the retention cap. Every number here
  is a head-side cost (per-RPC counts, handler seconds, RSS growth,
  drop/coalesce counters) and therefore comparable across machines.

Usage:
    python -m ray_tpu.scripts.scalebench [--nodes 16] [--cpus 2]
        [--tasks 2000] [--actors 200] [--broadcast-mb 256]
        [--queued 0] [--head-scale] [--head-nodes 64]
        [--head-queued 100000] [--head-actors 1000]
        [--out MICROBENCH.json]

With --out pointing at MICROBENCH.json the results merge under
"scalability" (real cluster) and "head_scale" keys (the per-op numbers
from microbench.py stay put), and ``bench_log.record_scalebench``
appends the evidence line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _rss_mb() -> float:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


def run(nodes: int = 16, cpus: int = 2, tasks: int = 2000,
        actors: int = 200, broadcast_mb: int = 256,
        queued: int = 0) -> dict:
    import numpy as np

    import ray_tpu
    from ray_tpu.cluster.cluster_utils import Cluster
    from ray_tpu.core.config import config

    out: dict = {"nodes": nodes, "cpus_per_node": cpus}

    def record(name, value, unit):
        out[name] = {"value": round(value, 2), "unit": unit}
        print(f"{name}: {value:,.2f} {unit}", file=sys.stderr, flush=True)

    ray_tpu.shutdown()
    t0 = time.perf_counter()
    cluster = Cluster()
    for _ in range(nodes):
        cluster.add_node(num_cpus=cpus)
    cluster.wait_for_nodes(timeout=30.0 + 5.0 * nodes)
    record("cluster_boot_s", time.perf_counter() - t0, "s")
    ray_tpu.init(cluster.address)

    try:
        @ray_tpu.remote
        def noop():
            return os.environ.get("RAY_TPU_NODE_ID")

        # Warm pools everywhere (SPREAD defeats the prefer-local fast
        # path so every node forks its workers before timing starts).
        from ray_tpu.util.scheduling_strategies import (  # noqa: F401
            NodeAffinitySchedulingStrategy,
        )

        warm = [
            noop.options(scheduling_strategy="SPREAD").remote()
            for _ in range(nodes * cpus)
        ]
        ray_tpu.get(warm, timeout=600)

        # 1. Deep queue: submit `tasks` CPU:1 noops in one burst —
        # ~tasks/(nodes*cpus) deep per slot — and drain.
        t0 = time.perf_counter()
        refs = [noop.remote() for _ in range(tasks)]
        submit_dt = time.perf_counter() - t0
        where = ray_tpu.get(refs, timeout=1200)
        drain_dt = time.perf_counter() - t0
        record("burst_submit_per_s", tasks / submit_dt, "ops/s")
        record("burst_tasks_per_s", tasks / drain_dt, "ops/s")
        record("burst_nodes_used", float(len(set(where))), "nodes")

        # 2. Actor fan-out: create `actors` zero-CPU actors, call each
        # once (reference envelope: 10k+ actors cluster-wide).
        @ray_tpu.remote(num_cpus=0)
        class Probe:
            def pid(self):
                return os.getpid()

        # Waved creation (32 in flight): measures steady-state creation
        # rate; an unbounded 200-actor burst on a 1-core box starves new
        # workers' accept loops past any sane timeout (the reference's
        # envelope runs paced on real multi-core nodes).
        t0 = time.perf_counter()
        handles, pids = [], []
        for start in range(0, actors, 32):
            wave = [Probe.remote()
                    for _ in range(min(32, actors - start))]
            pids.extend(ray_tpu.get(
                [h.pid.remote() for h in wave], timeout=1200))
            handles.extend(wave)
        dt = time.perf_counter() - t0
        record("actor_create_call_per_s", actors / dt, "ops/s")
        record("actor_distinct_pids", float(len(set(pids))), "workers")
        for h in handles:
            ray_tpu.kill(h)

        # 3. Broadcast: one large object pulled by every node (reference:
        # 1 GiB broadcast to 50+ nodes via chunked node-to-node pulls).
        blob = np.random.default_rng(0).integers(
            0, 255, broadcast_mb * 1024 * 1024, dtype=np.uint8)
        ref = ray_tpu.put(blob)

        @ray_tpu.remote
        def touch(x):
            return int(x[-1]) + len(x) % 7

        # Per-RPC accounting for the ownership protocol (verdict r4 #3
        # "Done" criterion): during the broadcast, location waits resolve
        # at the OWNER (this driver's directory server), so the head's
        # wait_locations count must stay O(1)-ish instead of O(nodes x
        # poll rounds), and its handler time flat.
        stats0 = cluster.head._server.handler_stats()
        t0 = time.perf_counter()
        sums = ray_tpu.get(
            [
                touch.options(scheduling_strategy="SPREAD").remote(ref)
                for _ in range(nodes)
            ],
            timeout=1200,
        )
        dt = time.perf_counter() - t0
        stats1 = cluster.head._server.handler_stats()
        assert len(set(sums)) == 1
        gib = broadcast_mb / 1024.0
        record("broadcast_object_gib", gib, "GiB")
        record("broadcast_nodes_per_s", nodes / dt, "nodes/s")
        record("broadcast_agg_gib_per_s", gib * nodes / dt, "GiB/s")

        def delta(method, field="count"):
            return (stats1.get(method, {}).get(field, 0)
                    - stats0.get(method, {}).get(field, 0))

        record("broadcast_head_wait_locations", float(
            delta("wait_locations")), "rpcs")
        record("broadcast_head_handler_s", float(round(
            sum(stats1.get(m, {}).get("total_s", 0.0)
                for m in stats1)
            - sum(stats0.get(m, {}).get("total_s", 0.0)
                  for m in stats0), 4)), "s")
        out["head_rpc_counts"] = {
            m: stats1[m]["count"] for m in sorted(stats1)
        }

        # 4. Parked-queue audit (--queued): `queued` specs whose demand
        # no node can EVER fit (cpus+1 on a homogeneous cpus-per-node
        # cluster) land in the client _retry_heap. The envelope claims:
        # the submitter keeps breathing under them (probe task latency),
        # retry backoff decays the standing backlog's head RPC rate to a
        # bounded trickle, and shutdown fails them out in bounded time.
        if queued:
            from ray_tpu._private import worker as worker_mod

            # Parked specs must not hit the pending-task timeout and
            # fail out mid-measurement.
            config.override("pending_task_timeout_s", 1e9)
            backend = worker_mod.backend()
            rss0 = _rss_mb()

            @ray_tpu.remote(num_cpus=cpus + 1)
            def parked():
                return None

            t0 = time.perf_counter()
            qrefs = [parked.remote() for _ in range(queued)]
            submit_dt = time.perf_counter() - t0
            record("queued_submit_per_s", queued / submit_dt, "ops/s")
            # Every spec is now client-pending: parked in the retry
            # heap, queued for (re)dispatch, or mid-dispatch — the
            # population circulates between the three at whatever rate
            # the box dispatches, so the heap alone is a fluctuating
            # snapshot; PENDING total is the invariant (nothing may
            # fail out or leak).
            time.sleep(2.0)
            with backend._submit_cv:
                n_pending = (len(backend._retry_heap)
                             + len(backend._submit_q)
                             + backend._dispatching)
                n_heap = len(backend._retry_heap)
            record("queued_pending", float(n_pending), "specs")
            record("queued_in_retry_heap", float(n_heap), "specs")
            # A mid-dispatch batch can transiently count twice (it is
            # both "dispatching" and re-parking into the heap); LOSING
            # specs is the failure mode under test.
            assert queued <= n_pending <= queued + config.submit_batch_max, (
                f"{queued - n_pending} specs failed out of the backlog")
            # Steady-state head RPC rate with the full backlog at max
            # retry backoff: ~ceil(queued/submit_batch_max) batches per
            # submit_retry_max_s, NOT a flat-timer re-batch storm.
            window = 6.0
            s0 = cluster.head._server.handler_stats()
            time.sleep(window)
            s1 = cluster.head._server.handler_stats()
            sched = (s1.get("schedule_batch", {}).get("count", 0)
                     - s0.get("schedule_batch", {}).get("count", 0))
            record("queued_sched_rpcs_per_s", sched / window, "rpcs/s")
            # Submitter liveness: a feasible task lands while the heap
            # holds the full backlog.
            t0 = time.perf_counter()
            assert ray_tpu.get(noop.remote(), timeout=300) is not None
            record("queued_probe_latency_s",
                   time.perf_counter() - t0, "s")
            record("queued_rss_growth_mb", _rss_mb() - rss0, "MB")
            # qrefs stay alive into the finally below: shutdown fails
            # the whole parked backlog into LIVE refs — the worst case.
    finally:
        t0 = time.perf_counter()
        ray_tpu.shutdown()
        shutdown_dt = time.perf_counter() - t0
        cluster.shutdown()
        if queued:
            config.reset("pending_task_timeout_s")
    if queued:
        # With --queued this includes failing the whole parked backlog
        # into its result refs — the "no stall at teardown" claim.
        record("queued_shutdown_s", shutdown_dt, "s")
    return out


def run_head_scale(nodes: int = 64, queued: int = 100_000,
                   actors: int = 1000, subscribers: int = 8,
                   spans: int = 120_000, heartbeat_rounds: int = 10,
                   batch: int = 256) -> dict:
    """Drive a real HeadServer over its real RPC plane at the reference
    envelope shapes. Single process: the 'nodes' are registered entries
    that heartbeat over RPC, not OS processes — so the numbers isolate
    the HEAD's data structures, locks, persistence, and pubsub from
    worker-fork noise, and the per-RPC counts are machine-independent."""
    import tempfile
    import threading

    from ray_tpu.cluster.head import HeadServer
    from ray_tpu.cluster.rpc import RpcClient, ensure_cluster_token
    from ray_tpu.core import ids

    out: dict = {"nodes": nodes, "queued": queued, "actors": actors,
                 "subscribers": subscribers, "spans": spans}

    def record(name, value, unit):
        out[name] = {"value": round(value, 3), "unit": unit}
        print(f"head_scale.{name}: {value:,.2f} {unit}",
              file=sys.stderr, flush=True)

    ensure_cluster_token()
    persist = tempfile.NamedTemporaryFile(
        prefix="scalebench_head_", suffix=".sqlite", delete=False)
    persist.close()
    head = HeadServer(persist_path=persist.name, metrics_port=None)
    client = RpcClient(head.address)
    rss0 = _rss_mb()
    try:
        # -- membership + heartbeats at N nodes ---------------------------
        node_ids = [ids.new_node_id() for _ in range(nodes)]
        t0 = time.perf_counter()
        for nid in node_ids:
            # 127.0.0.1:1 refuses instantly: fanout best-effort calls to
            # synthetic agents fail fast instead of hanging.
            client.call("register_node", nid, "127.0.0.1:1",
                        {"CPU": 2.0}, "/dev/null")
        record("register_per_s", nodes / (time.perf_counter() - t0),
               "ops/s")
        t0 = time.perf_counter()
        for _ in range(heartbeat_rounds):
            for nid in node_ids:
                client.call("heartbeat", nid, {"CPU": 2.0})
        hb = nodes * heartbeat_rounds
        record("heartbeats_per_s", hb / (time.perf_counter() - t0),
               "ops/s")
        # Background pump: keep the synthetic nodes heartbeating for the
        # rest of the bench so the monitor doesn't declare them dead
        # mid-phase (their liveness is load-bearing for wait_locations).
        pump_stop = threading.Event()

        def _pump():
            pump_client = RpcClient(head.address)
            while not pump_stop.wait(0.5):
                for nid in node_ids:
                    try:
                        pump_client.call("heartbeat", nid, {"CPU": 2.0})
                    except Exception:
                        return
            pump_client.close()

        pump = threading.Thread(target=_pump, daemon=True)
        pump.start()
        # Status polling is now O(1) against the cached totals.
        t0 = time.perf_counter()
        polls = 200
        for _ in range(polls):
            total = client.call("cluster_resources")
            avail = client.call("available_resources")
        record("status_polls_per_s",
               2 * polls / (time.perf_counter() - t0), "ops/s")
        assert total.get("CPU") == 2.0 * nodes, total
        assert avail.get("CPU") is not None

        # -- queued specs: schedule_batch at the envelope depth -----------
        # Feasible half: placements spread by optimistic debit.
        half = queued // 2
        t0 = time.perf_counter()
        placed = 0
        for start in range(0, half, batch):
            n = min(batch, half - start)
            reqs = [{"demand": {"CPU": 1.0},
                     "task_id": f"t{start + i:08x}"} for i in range(n)]
            placed += sum(
                1 for p in client.call("schedule_batch", reqs)
                if p is not None)
        record("sched_feasible_per_s",
               half / (time.perf_counter() - t0), "ops/s")
        record("sched_feasible_placed", float(placed), "tasks")
        # Infeasible half: every request records a demand miss (the
        # autoscaler signal) — the miss table must stay O(1) per miss
        # and bounded, not O(backlog) per miss.
        t0 = time.perf_counter()
        for start in range(0, queued - half, batch):
            n = min(batch, queued - half - start)
            reqs = [{"demand": {"CPU": 64.0},
                     "task_id": f"m{start + i:08x}"} for i in range(n)]
            client.call("schedule_batch", reqs)
        record("sched_infeasible_per_s",
               (queued - half) / (time.perf_counter() - t0), "ops/s")
        misses = client.call("pending_demands")
        record("demand_miss_table", float(len(misses)), "entries")

        # -- borrow registrations + object directory at depth -------------
        t0 = time.perf_counter()
        for start in range(0, queued, batch):
            n = min(batch, queued - start)
            entries = [(f"t{start + i:08x}", node_ids[0],
                        [f"{start + i:032x}00000001"], None)
                       for i in range(n)]
            client.call("ref_task_begin_batch", entries)
        record("ref_begin_per_s",
               queued / (time.perf_counter() - t0), "ops/s")
        t0 = time.perf_counter()
        for start in range(0, queued, batch):
            n = min(batch, queued - start)
            items = [(f"{start + i:032x}00000001",
                      node_ids[(start + i) % nodes], False, 64,
                      None, "", None) for i in range(n)]
            client.call("add_locations", items)
        record("add_location_per_s",
               queued / (time.perf_counter() - t0), "ops/s")
        t0 = time.perf_counter()
        lookups = 200
        for i in range(lookups):
            got = client.call(
                "wait_locations",
                [f"{i:032x}00000001"], 5.0)
            assert got, "directory lost a location"
        record("wait_locations_per_s",
               lookups / (time.perf_counter() - t0), "ops/s")

        # -- 1k actors with deep pubsub fan-out ---------------------------
        for s in range(subscribers):
            client.call("pubsub_subscribe", f"slow-{s}", "ACTORS")
        actor_ids = [ids.new_actor_id() for _ in range(actors)]
        t0 = time.perf_counter()
        for aid in actor_ids:
            client.call("create_actor_record", aid, 0, 0, {"spec": {}})
            client.call("register_actor", aid,
                        node_ids[hash(aid) % nodes], "127.0.0.1:1",
                        "Probe")
        record("actor_register_per_s",
               actors / (time.perf_counter() - t0), "ops/s")
        # FSM churn: 10 full update rounds over every actor key. The
        # slow subscribers never poll — coalescing must bound each
        # buffer at ~#keys (latest state per actor), not rounds x keys.
        rounds = 10
        t0 = time.perf_counter()
        for r in range(rounds):
            for aid in actor_ids:
                client.call("publish", "ACTORS", aid,
                            {"actor_id": aid, "state": "ALIVE",
                             "round": r})
        record("actor_updates_per_s",
               rounds * actors / (time.perf_counter() - t0), "ops/s")
        st = client.call("pubsub_stats")
        record("pubsub_coalesced", float(st.get("coalesced", 0)), "msgs")
        record("pubsub_buffered", float(st.get("buffered", 0)), "msgs")
        record("pubsub_dropped", float(st.get("dropped", 0)), "msgs")
        per_sub = st.get("buffered", 0) / max(1, subscribers)
        assert per_sub <= actors + nodes + 1, (
            f"coalescing failed: {per_sub} buffered per subscriber for "
            f"{actors} keys")

        # -- span burst past the retention cap ----------------------------
        span_batch = [
            {"trace_id": f"{i:016x}", "span_id": f"{i:016x}",
             "name": "exec", "t0": 0.0, "t1": 1.0}
            for i in range(1000)
        ]
        t0 = time.perf_counter()
        for _ in range(spans // 1000):
            client.call("report_spans", span_batch)
        record("span_report_per_s",
               spans / (time.perf_counter() - t0), "ops/s")
        pst = client.call("pubsub_stats")
        record("span_retained", float(pst["spans"]["retained"]), "spans")
        record("span_dropped", float(pst["spans"]["dropped"]), "spans")
        assert pst["spans"]["retained"] <= pst["spans"]["cap"]

        # -- persistence + RSS + per-RPC accounting -----------------------
        head._store.flush()
        persist_stats = head._store.stats()
        out["persist"] = persist_stats
        record("persist_coalesced",
               float(persist_stats["coalesced"]), "writes")
        record("persist_flushes", float(persist_stats["flushes"]), "txns")
        record("rss_growth_mb", _rss_mb() - rss0, "MB")
        stats = head._server.handler_stats()
        out["head_rpc_counts"] = {
            m: stats[m]["count"] for m in sorted(stats)}
        out["head_rpc_mean_ms"] = {
            m: stats[m]["mean_ms"] for m in sorted(stats)}
        record("head_handler_total_s", float(round(
            sum(e["total_s"] for e in stats.values()), 3)), "s")
        pump_stop.set()
    finally:
        head.stop()
        try:
            os.unlink(persist.name)
        except OSError:
            pass
    return out


def run_demand_burst(waves: int = 5, seed: int = 0,
                     max_workers: int = 8) -> dict:
    """Fleet autoscaling under seeded arrival waves: mixed
    serve/train/data demand bursts against a LocalNodeProvider-backed
    fleet with a heterogeneous (on-demand + spot) node-type catalog.
    Each wave starts from an empty fleet, so the numbers are clean:
    scale-up latency (submit -> demand served, capacity provisioned by
    the bin-packer en route), bin-pack efficiency (requested /
    provisioned CPUs), and the zero-goodput-loss scale-down section
    (every node drained ALIVE -> DRAINING -> DEAD before the provider
    terminate, every removal ``drain:*``-attributed in the head's
    terminate-ack ledger)."""
    import random

    import ray_tpu
    from ray_tpu.autoscaler import LocalNodeProvider, StandardAutoscaler
    from ray_tpu.cluster.cluster_utils import Cluster

    node_types = {
        "cpu_small": {"num_cpus": 2},
        "spot_big": {"num_cpus": 4, "spot": True},
        "cpu_big": {"num_cpus": 4},
    }
    shapes = {t: float(c["num_cpus"]) for t, c in node_types.items()}
    out: dict = {"waves": waves, "seed": seed,
                 "node_types": {t: dict(c) for t, c in node_types.items()}}
    rng = random.Random(seed)
    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=1)  # driver-only node; waves need > 1 CPU
    cluster.wait_for_nodes()
    ray_tpu.init(cluster.address)
    provider = LocalNodeProvider(cluster)
    autoscaler = StandardAutoscaler(
        cluster.address, provider,
        node_types=node_types,
        max_workers=max_workers,
        idle_timeout_s=0.4,
        launch_cooldown_s=0.5,
    )
    latencies_ms: list = []
    requested_cpus = 0.0
    provisioned_cpus = 0.0
    terminated: list = []
    terminated_causes: dict = {}
    try:
        # Mixed workload flavors: a wave interleaves all three.
        @ray_tpu.remote
        def serve_req():
            time.sleep(0.05)
            return "served"

        @ray_tpu.remote
        def train_step():
            time.sleep(0.2)
            return "stepped"

        @ray_tpu.remote
        def data_shard():
            time.sleep(0.1)
            return "mapped"

        flavors = [serve_req, train_step, data_shard]
        for wave in range(waves):
            # 2- and 4-CPU demands pack exactly into the 2/4-CPU
            # catalog; the committed-seed efficiency claim rides on it.
            sizes = [rng.choice([2, 2, 4]) for _ in range(rng.randint(3, 4))]
            requested_cpus += float(sum(sizes))
            t0 = time.perf_counter()
            refs = [
                flavors[i % len(flavors)].options(num_cpus=s).remote()
                for i, s in enumerate(sizes)
            ]
            wave_launched: list = []
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                report = autoscaler.update()
                terminated += report["terminated"]
                for nid in report["launched"]:
                    wave_launched.append(autoscaler._node_type_of[nid])
                snap = cluster.head.rpc_demand_snapshot(10.0)
                if not snap["tasks"] and not report["launched"]:
                    break
                time.sleep(0.2)
            ray_tpu.get(refs, timeout=120)
            latencies_ms.append((time.perf_counter() - t0) * 1e3)
            provisioned_cpus += sum(shapes[t] for t in wave_launched)
            # Zero-goodput-loss scale-down back to the empty fleet:
            # idle nodes drain (coldest first), terminate lands only
            # after the head reports them DEAD.
            empty_by = time.monotonic() + 60.0
            while provider.non_terminated_nodes() \
                    and time.monotonic() < empty_by:
                terminated += autoscaler.update()["terminated"]
                time.sleep(0.1)
            assert not provider.non_terminated_nodes(), (
                "fleet failed to scale down to empty between waves")
            print(f"wave {wave}: {sizes} -> {wave_launched}, "
                  f"{latencies_ms[-1]:.0f}ms", file=sys.stderr, flush=True)
        # The head's terminate-ack ledger, read back before teardown:
        # the autoscaler posted one ``drain:*`` ack per planned removal.
        with cluster.head._lock:
            terminated_causes = {
                nid: rec["cause"]
                for nid, rec in cluster.head._terminate_acks.items()}
    finally:
        autoscaler.stop()
        ray_tpu.shutdown()
        cluster.shutdown()

    ordered = sorted(latencies_ms)
    out["scale_up_ms"] = {
        "p50": round(ordered[len(ordered) // 2], 1),
        "p99": round(ordered[min(len(ordered) - 1,
                                 int(round(0.99 * (len(ordered) - 1))))], 1),
        "samples": [round(v, 1) for v in latencies_ms],
    }
    out["requested_cpus"] = requested_cpus
    out["provisioned_cpus"] = provisioned_cpus
    out["bin_pack_efficiency"] = round(
        requested_cpus / provisioned_cpus, 3) if provisioned_cpus else 0.0
    # The ledger: every terminated node must carry a planned drain
    # cause in the head's terminate-ack table — read back before
    # shutdown via the acks the autoscaler posted.
    causes: dict = {}
    for cause in terminated_causes.values():
        causes[cause] = causes.get(cause, 0) + 1
    unplanned = [nid for nid in terminated
                 if not str(terminated_causes.get(nid, "")).startswith(
                     "drain:")]
    out["scale_down"] = {
        "nodes": len(terminated),
        "drained_first": len(terminated) - len(unplanned),
        "unplanned": len(unplanned),
        "causes": causes,
    }
    assert not unplanned, f"unplanned terminations: {unplanned}"
    for name, val in (("scale_up_p50_ms", out["scale_up_ms"]["p50"]),
                      ("scale_up_p99_ms", out["scale_up_ms"]["p99"]),
                      ("bin_pack_efficiency", out["bin_pack_efficiency"])):
        print(f"fleet.{name}: {val}", file=sys.stderr, flush=True)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=16)
    ap.add_argument("--cpus", type=int, default=2)
    ap.add_argument("--tasks", type=int, default=2000)
    ap.add_argument("--actors", type=int, default=200)
    ap.add_argument("--broadcast-mb", type=int, default=256)
    ap.add_argument("--queued", type=int, default=0)
    ap.add_argument("--head-scale", action="store_true",
                    help="also run the synthetic head-at-scale section")
    ap.add_argument("--head-nodes", type=int, default=64)
    ap.add_argument("--head-queued", type=int, default=100_000)
    ap.add_argument("--head-actors", type=int, default=1000)
    ap.add_argument("--head-subs", type=int, default=8)
    ap.add_argument("--head-spans", type=int, default=120_000)
    ap.add_argument("--skip-cluster", action="store_true",
                    help="head-scale section only (no real cluster)")
    ap.add_argument("--demand-burst", action="store_true",
                    help="fleet autoscaling section: seeded arrival "
                         "waves against a provider-backed fake fleet")
    ap.add_argument("--burst-waves", type=int, default=5)
    ap.add_argument("--burst-seed", type=int, default=0)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    # Head-scale first: its RSS-growth number needs a process that has
    # not already ballooned through the real-cluster section.
    head_res = None
    if args.head_scale or args.skip_cluster:
        head_res = run_head_scale(
            args.head_nodes, args.head_queued, args.head_actors,
            args.head_subs, args.head_spans)
        print(json.dumps(head_res, indent=1))
    res = None
    if not args.skip_cluster and not args.demand_burst:
        res = run(args.nodes, args.cpus, args.tasks, args.actors,
                  args.broadcast_mb, queued=args.queued)
        print(json.dumps(res, indent=1))
    fleet_res = None
    if args.demand_burst:
        fleet_res = run_demand_burst(args.burst_waves, args.burst_seed)
        print(json.dumps(fleet_res, indent=1))
    if args.out:
        merged = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                merged = json.load(f)
        if res is not None:
            merged["scalability"] = res
        if head_res is not None:
            merged["head_scale"] = head_res
        if fleet_res is not None:
            merged["fleet_scaling"] = fleet_res
        with open(args.out, "w") as f:
            json.dump(merged, f, indent=1)
            f.write("\n")
    from ray_tpu.scripts import bench_log

    if res is not None or head_res is not None:
        entry = bench_log.record_scalebench(
            scalability=res, head_scale=head_res)
        print(json.dumps({"bench_log": entry.get("committed_to")}),
              file=sys.stderr)
    if fleet_res is not None:
        entry = bench_log.record_fleet_scaling(
            scale_up_ms={k: v for k, v in
                         fleet_res["scale_up_ms"].items()
                         if k in ("p50", "p99")},
            bin_pack_efficiency=fleet_res["bin_pack_efficiency"],
            scale_down=fleet_res["scale_down"],
            waves=fleet_res["waves"], seed=fleet_res["seed"],
            device=bench_log.device_kind())
        print(json.dumps({"bench_log": entry.get("committed_to")}),
              file=sys.stderr)


if __name__ == "__main__":
    main()
