"""Drain-vs-crash recovery latency + gang-recovery MTTR benchmarks.

At pod scale, recovery LATENCY — not just recovery correctness —
dominates (MLPerf TPU-pod studies, PAPERS.md): a heartbeat-timeout crash
detection burns ``node_death_timeout_s`` of dead time per preemption,
while a proactive drain reconstructs actors on surviving nodes before
the departing node exits. This script measures both paths on a local
multi-node ``Cluster`` and emits one ``drain_recovery_ms`` record:

    python -m ray_tpu.scripts.drain_bench

Round 12 adds the GANG half — the placement-group reservation is now a
first-class migration citizen (head ``RESCHEDULING`` state machine), so
the probe that matters for elastic fleets is ``pg_reschedule_ms``: wall
time from a gang bundle losing its node (drain initiated, or the node
killed outright) to the group's reservation being CREATED again on
healthy nodes. ``--gang`` runs it for both triggers, plus a seeded
preemption schedule against an elastic ``DataParallelTrainer``
(num_workers=2, min_workers=1) whose downtime ledger must attribute
every lost second to preemption/drain/reschedule — the committed
``goodput_pct`` envelope. ``--out`` merges a ``gang_recovery`` section
into a MICROBENCH-style artifact.

Records append to the committed ``BENCH_TPU_SESSIONS.jsonl`` evidence
trail only when run on a real accelerator cluster
(``bench_log.record_drain_recovery`` / ``record_gang_recovery`` gate on
device); elsewhere the JSON lines are just printed.
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time


def _device_kind() -> str:
    from ray_tpu.scripts.bench_log import device_kind

    return device_kind()


def _wait_actor_on_other_node(head, actor_id: str, avoid_node: str,
                              timeout: float = 60.0) -> float:
    """Seconds until the actor is ALIVE on a node other than
    ``avoid_node``."""
    t0 = time.monotonic()
    deadline = t0 + timeout
    while time.monotonic() < deadline:
        info = head.rpc_get_actor(actor_id, timeout=1.0)
        if info and info["state"] == "ALIVE" and \
                info["node_id"] != avoid_node:
            return time.monotonic() - t0
        time.sleep(0.01)
    raise TimeoutError(f"actor {actor_id} not recovered in {timeout}s")


def _one_round(proactive: bool) -> float:
    """Recovery latency (s) for one fresh cluster: actor pinned on a
    victim node, victim removed via drain (proactive) or SIGKILL-style
    crash (heartbeat-timeout detection)."""
    import ray_tpu
    from ray_tpu.cluster.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=2)  # survivor (hosts the driver store)
    victim = cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    ray_tpu.init(cluster.address)
    try:
        @ray_tpu.remote
        class Probe:
            def ping(self):
                return "pong"

        actor = Probe.options(
            max_restarts=-1,
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                victim.node_id),
        ).remote()
        assert ray_tpu.get(actor.ping.remote(), timeout=30) == "pong"
        if proactive:
            cluster.head.rpc_drain_node(
                victim.node_id, "bench", 30.0, wait=False)
        else:
            cluster.kill_node(victim)
        return _wait_actor_on_other_node(
            cluster.head, actor._actor_id, victim.node_id)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


# -- gang-recovery MTTR (placement-group reschedule latency) ---------------


def _wait_pg_restored(pg, avoid_node: str,
                      timeout: float = 90.0) -> float:
    """Seconds until the group is CREATED again with every bundle on an
    alive node other than ``avoid_node`` and at least one completed
    reschedule."""
    import ray_tpu
    from ray_tpu.util.placement_group import placement_group_table

    t0 = time.monotonic()
    deadline = t0 + timeout
    while time.monotonic() < deadline:
        table = placement_group_table(pg) or {}
        alive = {n["NodeID"] for n in ray_tpu.nodes() if n["Alive"]}
        placement = table.get("placement") or []
        if (table.get("state") == "CREATED"
                and table.get("reschedules", 0) >= 1
                and placement
                and all(nid in alive and nid != avoid_node
                        for nid, _bi in placement)):
            return time.monotonic() - t0
        time.sleep(0.02)
    raise TimeoutError(
        f"gang reservation not restored within {timeout}s "
        f"(state={placement_group_table(pg)!r})")


def _gang_round(trigger: str) -> dict:
    """``pg_reschedule_ms`` for one fresh cluster: a 2-bundle SPREAD
    gang loses a bundle's node to a drain (``trigger='drain'``) or a
    kill (``trigger='node_death'``); measured drain/kill ->
    reservation whole again on healthy nodes."""
    import ray_tpu
    from ray_tpu.cluster.cluster_utils import Cluster
    from ray_tpu.util.placement_group import (
        placement_group,
        placement_group_table,
        remove_placement_group,
    )

    ray_tpu.shutdown()
    cluster = Cluster()
    for _ in range(3):
        cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    ray_tpu.init(cluster.address)
    try:
        pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="SPREAD")
        ray_tpu.get(pg.ready(), timeout=60)
        table = placement_group_table(pg)
        victim_nid = table["bundle_nodes"][1]
        victim = next(n for n in cluster.nodes
                      if n.node_id == victim_nid)
        t0 = time.monotonic()
        if trigger == "drain":
            cluster.head.rpc_drain_node(
                victim_nid, "bench-gang", 30.0, wait=False)
        else:
            cluster.kill_node(victim)
        restored_s = _wait_pg_restored(pg, victim_nid)
        out = {
            "trigger": trigger,
            "pg_reschedule_ms": round(
                (time.monotonic() - t0) * 1e3, 1),
            "restored_wait_ms": round(restored_s * 1e3, 1),
            "bundles": 2,
            "bundles_lost": 1,
        }
        remove_placement_group(pg)
        return out
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def _gang_goodput(seed: int) -> dict:
    """Elastic-gang goodput envelope under a seeded preemption
    schedule: a 2-worker (min 1) checkpointing trainer survives one
    graceful drain and one hard node kill (replacement capacity delayed
    so the gang genuinely runs SHRUNK, then regrows); every lost second
    must land in the ledger under a preemption/drain/reschedule cause
    with ``FailureConfig.max_failures=0`` intact."""
    import random

    import ray_tpu
    from ray_tpu import train
    from ray_tpu.cluster.cluster_utils import Cluster
    from ray_tpu.train import session
    from ray_tpu.train.checkpoint import Checkpoint
    from ray_tpu.util.placement_group import placement_group_table

    rng = random.Random(f"{seed}:gang-goodput")
    ray_tpu.shutdown()
    cluster = Cluster()
    # Driver node too small for a gang bundle (CPU:2): bundles live
    # only on the 2-cpu worker nodes, so losing one with no spare
    # capacity forces a GENUINE shrunk-world window — the gang can't
    # quietly re-home onto the driver's node.
    cluster.add_node(num_cpus=1)  # driver node: survives
    for _ in range(2):
        cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    ray_tpu.init(cluster.address)

    def train_fn(config):
        start = 0
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_dict().get("step", -1) + 1
        for i in range(start, config["steps"]):
            time.sleep(0.25)
            session.report(
                {"step": i, "world": session.get_world_size()},
                checkpoint=Checkpoint.from_dict({"step": i}))

    trainer = train.DataParallelTrainer(
        train_fn,
        train_loop_config={"steps": 36},
        scaling_config=train.ScalingConfig(
            num_workers=2, min_workers=1, placement_strategy="SPREAD",
            resources_per_worker={"CPU": 2}),
        run_config=train.RunConfig(
            failure_config=train.FailureConfig(max_failures=0)),
    )
    faults = {"drain": 0, "kill": 0}

    def gang_victim(wait_s: float = 30.0):
        # Wait for the gang's reservation to exist before injecting: a
        # slow pg.ready() on a loaded box must delay the fault, not
        # skip it (a zero-fault run would commit a vacuous envelope).
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            pgs = placement_group_table() or {}
            gang = next((v for v in pgs.values()
                         if v["state"] in ("CREATED", "RESCHEDULING")),
                        None)
            if gang is not None:
                nids = {nid for nid, _bi in gang["placement"]}
                # Never the driver's node (cluster.nodes[0]):
                # preempting the node hosting the driver's own
                # agent/store measures harness collapse, not gang
                # recovery.
                victim = next((n for n in list(cluster.nodes)[1:]
                               if n.node_id in nids), None)
                if victim is not None:
                    return victim
            time.sleep(0.25)
        return None

    def schedule():
        # One graceful drain (preemption notice), then one hard kill
        # with DELAYED replacement — the shrink/regrow window. The kill
        # waits out the drain restart (so both faults land on separate
        # attempts), and the replacement lags past heartbeat death
        # detection + a few steps, so the gang genuinely RUNS at the
        # surviving world size before regrowing.
        time.sleep(rng.uniform(1.0, 2.0))
        victim = gang_victim()
        if victim is not None:
            cluster.head.rpc_drain_node(
                victim.node_id, "bench-preempt", 10.0, wait=False)
            faults["drain"] += 1
            cluster.add_node(num_cpus=2)
        time.sleep(rng.uniform(6.0, 8.0))
        victim = gang_victim()
        if victim is not None:
            cluster.kill_node(victim)
            faults["kill"] += 1
            time.sleep(rng.uniform(9.0, 11.0))  # shrunk-world window
            cluster.add_node(num_cpus=2)

    injector = threading.Thread(target=schedule, daemon=True)
    injector.start()
    try:
        from ray_tpu.util.goodput import attribution_ok

        result = trainer.fit()
        injector.join(timeout=60.0)
        gp = dict(result.goodput or {})
        attributed, sums = attribution_ok(gp)
        worlds = sorted({m.get("world") for m in result.metrics_history
                         if m.get("world") is not None})
        final_pg = trainer.final_pg_state or {}
        alive = {n["NodeID"] for n in ray_tpu.nodes() if n["Alive"]}
        pg_alive = (final_pg.get("state") == "CREATED" and all(
            nid in alive for nid, _bi in final_pg.get("placement", [])))
        return {
            "seed": seed,
            "faults": dict(faults),
            # A passing envelope must have actually been attacked: a
            # zero-fault run (injector raced a slow setup) proves
            # nothing and must not commit as preemption evidence.
            "faults_injected": faults["drain"] >= 1
            and faults["kill"] >= 1,
            "completed": result.error is None,
            "budget_intact": result.error is None,  # max_failures=0
            "goodput": gp,
            "goodput_pct": gp.get("goodput_pct"),
            "downtime_fully_attributed": attributed and sums,
            "worlds_seen": worlds,
            "pg_final_state": final_pg.get("state"),
            "pg_reschedules": final_pg.get("reschedules", 0),
            "pg_alive_on_healthy_nodes": pg_alive,
        }
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def run_gang(seed: int) -> dict:
    """The full gang-recovery section: MTTR for both triggers + the
    seeded elastic-goodput envelope."""
    rounds = {t: _gang_round(t) for t in ("drain", "node_death")}
    return {
        "mttr": rounds,
        "goodput_envelope": _gang_goodput(seed),
    }


def main(argv=None) -> dict:
    from ray_tpu.scripts import bench_log

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--gang", action="store_true",
                    help="also run the gang-recovery MTTR probe + the "
                         "seeded elastic-goodput envelope")
    ap.add_argument("--seed", type=int, default=12,
                    help="preemption-schedule seed for the gang "
                         "goodput envelope (committed with the "
                         "artifact so the run is replayable)")
    ap.add_argument("--out", default=None,
                    help="merge the gang_recovery section into this "
                         "MICROBENCH-style artifact")
    args = ap.parse_args(argv)

    device = _device_kind()
    drain_s = _one_round(proactive=True)
    crash_s = _one_round(proactive=False)
    entry = bench_log.record_drain_recovery(
        drain_s * 1000, crash_s * 1000, device=device)
    print(json.dumps(entry))
    if not args.gang:
        return entry

    gang = run_gang(args.seed)
    for trigger, rnd in gang["mttr"].items():
        line = bench_log.record_gang_recovery(
            rnd["pg_reschedule_ms"], trigger=trigger,
            bundles=rnd["bundles"], bundles_lost=rnd["bundles_lost"],
            device=device, script="drain_bench")
        print(json.dumps(line))
    env = gang["goodput_envelope"]
    if env.get("goodput_pct") is not None:
        bench_log.record_goodput(
            trial="gang", goodput_pct=env["goodput_pct"],
            wall_s=env["goodput"].get("wall_s") or 0.0,
            downtime_s=env["goodput"].get("downtime_s") or 0.0,
            by_cause=env["goodput"].get("by_cause") or {},
            device=device, script="drain_bench", seed=args.seed)
    if args.out:
        # Merge-preserve: every perfsuite stage owns one section.
        payload = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                try:
                    payload = json.load(f)
                except ValueError:
                    payload = {}
        payload["gang_recovery"] = gang
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
    print(json.dumps(gang, default=str))
    ok = (env["completed"] and env["faults_injected"]
          and env["downtime_fully_attributed"]
          and env["pg_alive_on_healthy_nodes"])
    if not ok:
        raise SystemExit(
            f"gang probe FAILED (replay with --seed {args.seed}): "
            f"{env}")
    return gang


if __name__ == "__main__":
    main()
