"""Drain-vs-crash actor recovery latency benchmark.

At pod scale, recovery LATENCY — not just recovery correctness —
dominates (MLPerf TPU-pod studies, PAPERS.md): a heartbeat-timeout crash
detection burns ``node_death_timeout_s`` of dead time per preemption,
while a proactive drain reconstructs actors on surviving nodes before
the departing node exits. This script measures both paths on a local
multi-node ``Cluster`` and emits one ``drain_recovery_ms`` record:

    python -m ray_tpu.scripts.drain_bench

The record is appended to the committed ``BENCH_TPU_SESSIONS.jsonl``
evidence trail only when run on a real accelerator cluster
(``bench_log.record_drain_recovery`` gates on device); elsewhere the
JSON line is just printed.
"""

from __future__ import annotations

import json
import time


def _device_kind() -> str:
    from ray_tpu.scripts.bench_log import device_kind

    return device_kind()


def _wait_actor_on_other_node(head, actor_id: str, avoid_node: str,
                              timeout: float = 60.0) -> float:
    """Seconds until the actor is ALIVE on a node other than
    ``avoid_node``."""
    t0 = time.monotonic()
    deadline = t0 + timeout
    while time.monotonic() < deadline:
        info = head.rpc_get_actor(actor_id, timeout=1.0)
        if info and info["state"] == "ALIVE" and \
                info["node_id"] != avoid_node:
            return time.monotonic() - t0
        time.sleep(0.01)
    raise TimeoutError(f"actor {actor_id} not recovered in {timeout}s")


def _one_round(proactive: bool) -> float:
    """Recovery latency (s) for one fresh cluster: actor pinned on a
    victim node, victim removed via drain (proactive) or SIGKILL-style
    crash (heartbeat-timeout detection)."""
    import ray_tpu
    from ray_tpu.cluster.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=2)  # survivor (hosts the driver store)
    victim = cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    ray_tpu.init(cluster.address)
    try:
        @ray_tpu.remote
        class Probe:
            def ping(self):
                return "pong"

        actor = Probe.options(
            max_restarts=-1,
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                victim.node_id),
        ).remote()
        assert ray_tpu.get(actor.ping.remote(), timeout=30) == "pong"
        if proactive:
            cluster.head.rpc_drain_node(
                victim.node_id, "bench", 30.0, wait=False)
        else:
            cluster.kill_node(victim)
        return _wait_actor_on_other_node(
            cluster.head, actor._actor_id, victim.node_id)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def main() -> dict:
    from ray_tpu.scripts import bench_log

    drain_s = _one_round(proactive=True)
    crash_s = _one_round(proactive=False)
    entry = bench_log.record_drain_recovery(
        drain_s * 1000, crash_s * 1000, device=_device_kind())
    print(json.dumps(entry))
    return entry


if __name__ == "__main__":
    main()
