"""Signal-plane harness: history-vs-client agreement, bounded ring
memory, and a seeded SLO burn — the three claims the signal plane
stands on, each measured, none asserted.

Sections (all in one run, merged into MICROBENCH.json under
``signal_plane`` with ``--out``):

* **agreement** — drive seeded serve-shaped traffic through the real
  recorder -> head-scrape -> ring path, then ask the windowed query
  engine for the same numbers the client ledger knows: the counter
  delta must be count-exact, the windowed TTFT p50 must match the
  client-side percentile within the histogram's bucket resolution at
  that value, and the windowed QPS must match the paced rate. The
  query path's p50 latency is measured and must be far below the query
  window — a sleeping implementation (the old double-scrape) cannot
  pass this.
* **ring** — a 64-node-shaped synthetic scrape ingested far past the
  retention window and over the series cap: traced memory must plateau
  after warmup (bounded, not merely slow-growing) and every eviction
  must be counted by reason (series_cap / dead_node / stale) — never a
  silent cap.
* **slo** — a seeded TTFT-SLO burn: fast traffic (ok) -> slow traffic
  (burning) -> fast traffic (recovered), with the pubsub SLO channel
  subscribed the whole time. Exactly one burning event and one
  recovery event must arrive, and `ray-tpu slo` must show the same
  story.

Run: python -m ray_tpu.scripts.signal_bench [--out MICROBENCH.json]
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import random
import sys
import time
import tracemalloc

SCRAPE_S = 0.05
EVAL_S = 0.05
BURN_EVALS = 3
DEP = "bench"


def _percentile(values, q):
    s = sorted(values)
    if not s:
        return None
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


def _drive(obs, duration_s: float, rate_hz: float, ttft_values,
           ledger=None):
    """Paced serve-shaped traffic through the real recorder (the
    producer may sleep — the zero-sleeps claim is about the QUERY
    path)."""
    interval = 1.0 / rate_hz
    end = time.time() + duration_s
    i = 0
    while time.time() < end:
        val = ttft_values[i % len(ttft_values)]
        obs.record_status(DEP, "ok")
        obs.record_ttft(DEP, val)
        if ledger is not None:
            ledger.append(val)
        i += 1
        time.sleep(interval)
    return i


def _section_agreement(state, serve, obs):
    """Windowed queries vs the client ledger, on the live scrape path."""
    rng = random.Random(20260807)
    # Warm the counter series into the ring at a known value BEFORE the
    # timed run: windowed deltas subtract the first in-window sample,
    # so the ring must have seen the series at its starting value for
    # the delta to be count-exact.
    obs.record_status(DEP, "ok")
    obs.record_ttft(DEP, 0.02)
    time.sleep(SCRAPE_S * 6)

    ttft_pool = [rng.uniform(0.01, 0.2) for _ in range(64)]
    ledger: list = []
    rate_hz = 200.0
    t0 = time.time()
    n_sent = _drive(obs, 2.0, rate_hz, ttft_pool, ledger)
    elapsed_client = time.time() - t0
    # Mid-steady-state QPS check happens below with a window inside the
    # run; first let the tail land in the ring.
    time.sleep(SCRAPE_S * 6)

    # Count-exact delta: the window's FIRST ring sample is the warmed
    # counter at 1, so last - first is exactly the timed requests.
    big = state.query_metrics({
        "op": "delta", "name": "ray_tpu_serve_requests_total",
        "window_s": 300.0, "match": {"deployment": DEP}})
    ring_count = big.get("value") or 0

    # Windowed QPS: a window matching the run length, anchored at the
    # ring's latest ingest (a short idle tail and an equally short
    # clipped head make this approximate, hence the tolerance).
    qps_res = state.query_metrics({
        "op": "rate", "name": "ray_tpu_serve_requests_total",
        "window_s": elapsed_client, "match": {"deployment": DEP}})
    ring_qps = qps_res.get("value") or 0.0
    client_qps = n_sent / elapsed_client

    # Windowed TTFT p50 from bucket deltas vs the ledger percentile.
    q_res = state.query_metrics({
        "op": "quantile", "name": "ray_tpu_serve_decode_ttft_seconds",
        "q": 0.5, "window_s": 300.0, "match": {"deployment": DEP}})
    ring_p50 = q_res.get("value")
    resolution = q_res.get("resolution_s") or 0.0
    client_p50 = _percentile(ledger, 0.5)

    # serve.stats history path (satellite: no sleeps by construction).
    t_stats = time.time()
    stats = serve.stats(window_s=5.0, allow_sleep=False)
    stats_wall = time.time() - t_stats
    stats_qps = (stats.get("deployments", {}).get(DEP) or {}).get("qps")

    # Query-path latency: measured, not asserted. A sleep-based
    # implementation takes >= the window (5000ms here); the ring
    # answers from memory.
    lat_ms = []
    for _ in range(40):
        q0 = time.perf_counter()
        state.query_metrics({
            "op": "quantile",
            "name": "ray_tpu_serve_decode_ttft_seconds",
            "q": 0.5, "window_s": 60.0, "match": {"deployment": DEP}})
        lat_ms.append((time.perf_counter() - q0) * 1e3)
    query_p50_ms = round(_percentile(lat_ms, 0.5), 3)

    count_exact = int(ring_count) == n_sent
    ttft_ok = (ring_p50 is not None and client_p50 is not None
               and abs(ring_p50 - client_p50) <= resolution + 1e-9)
    qps_ok = client_qps > 0 and \
        abs(ring_qps - client_qps) / client_qps < 0.25
    no_sleep = query_p50_ms < 100.0 and stats_wall < 1.0
    return {
        "n_sent": n_sent,
        "ring_count": int(ring_count),
        "count_exact": count_exact,
        "client_qps": round(client_qps, 2),
        "ring_qps": round(ring_qps, 2),
        "serve_stats_qps": stats_qps,
        "serve_stats_wall_ms": round(stats_wall * 1e3, 1),
        "client_ttft_p50_s": round(client_p50, 5),
        "ring_ttft_p50_s": round(ring_p50, 5)
        if ring_p50 is not None else None,
        "bucket_resolution_s": round(resolution, 5),
        "query_p50_ms": query_p50_ms,
        "query_p99_ms": round(_percentile(lat_ms, 0.99), 3),
        "ok": bool(count_exact and ttft_ok and qps_ok and no_sleep),
        "checks": {"count_exact": count_exact, "ttft_p50": ttft_ok,
                   "qps": qps_ok, "no_sleep": no_sleep},
    }


def _section_ring():
    """64-node-shaped synthetic scrape: bounded memory + counted
    evictions, in-process against a standalone ring."""
    from ray_tpu.cluster.signals import MetricsRing

    nodes, per_node, max_series = 64, 80, 4000
    ring = MetricsRing(history_s=10.0, max_series=max_series,
                       scrape_interval_s=0.5)

    def exposition(snap: int) -> str:
        lines = []
        for n in range(nodes):
            for s in range(per_node):
                # 5% of series churn their label value each snapshot
                # (restarting workers) — the stale-eviction source.
                gen = snap if s % 20 == 0 else 0
                lines.append(
                    f'ray_tpu_worker_cpu_percent{{node_id="n{n:02d}",'
                    f'worker_id="w{s}g{gen}"}} {float(snap + s)}')
        return "\n".join(lines)

    tracemalloc.start()
    ts = 1_000_000.0
    warm_bytes = 0
    for snap in range(120):
        ts += 0.5
        ring.ingest_text(ts, exposition(snap))
        if snap == 40:
            warm_bytes = tracemalloc.get_traced_memory()[0]
    end_bytes = tracemalloc.get_traced_memory()[0]
    tracemalloc.stop()
    dead_dropped = ring.age_out_node("n00")
    bounded = (ring.series_count() <= max_series
               and end_bytes < warm_bytes * 1.5)
    # Stale aging is proven at unit level (tests/test_signal_plane.py):
    # under cap pressure the churned series are LRU-evicted as
    # series_cap before they can turn stale, so it isn't required here.
    return {
        "nodes": nodes,
        "series_offered": nodes * per_node,
        "max_series": max_series,
        "series_final": ring.series_count(),
        "warm_bytes": warm_bytes,
        "end_bytes": end_bytes,
        "growth_ratio": round(end_bytes / max(1, warm_bytes), 3),
        "evictions": dict(ring.evictions),
        "dead_node_series_dropped": dead_dropped,
        "ok": bool(bounded and ring.evictions["series_cap"] > 0
                   and dead_dropped > 0),
    }


def _section_slo(state, obs, cluster_address: str):
    """Seeded TTFT-SLO burn: ok -> burning -> ok with the pubsub SLO
    channel subscribed on both edges."""
    from ray_tpu.cluster.gcs_client import GcsClient

    gcs = GcsClient(cluster_address)
    gcs.pubsub.subscribe("signal_bench", "SLO")
    reg = state.register_slo(
        "bench-ttft", f'ttft_p50{{deployment="{DEP}"}} < 50ms over 2s')
    if not reg.get("ok"):
        return {"ok": False, "error": reg.get("error")}

    events: list = []

    def drain(deadline_s: float, until_state=None):
        end = time.time() + deadline_s
        while time.time() < end:
            res = gcs.pubsub.poll("signal_bench", timeout=0.5)
            for msg in (res[0] if res else []):  # poll -> (msgs, dropped)
                ev = msg.get("data") or {}
                if ev.get("slo") == "bench-ttft":
                    events.append(ev)
            if until_state and any(
                    e["state"] == until_state for e in events):
                return True
            time.sleep(EVAL_S)
        return False

    # Phase 1: fast traffic — the SLO must settle at ok, no events.
    _drive(obs, 1.0, 200.0, [0.005])
    drain(0.5)
    # Phase 2: slow traffic — windowed p50 climbs over threshold,
    # hysteresis counts BURN_EVALS breaches, ONE burning event fires.
    _drive(obs, 2.5, 100.0, [0.5])
    burned = drain(10.0, until_state="burning")
    # Phase 3: fast traffic flushes the slow samples out of the 2s
    # window; BURN_EVALS clean evals recover it — ONE recovery event.
    recover_end = time.time() + 20.0
    recovered = False
    while time.time() < recover_end and not recovered:
        _drive(obs, 0.5, 400.0, [0.005])
        recovered = drain(0.5, until_state="ok")
    status = state.slo_status()
    slo_now = (status.get("slos") or {}).get("bench-ttft") or {}

    # `ray-tpu slo` must tell the same story (in-process CLI call —
    # same head, same ring).
    from ray_tpu.scripts import cli

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        cli.main(["--address", cluster_address, "slo", "--json"])
    cli_view = json.loads(buf.getvalue())
    cli_state = ((cli_view.get("slos") or {})
                 .get("bench-ttft") or {}).get("state")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        cli.main(["--address", cluster_address, "top", "--json"])
    top_view = json.loads(buf.getvalue())

    burning_events = [e for e in events if e["state"] == "burning"]
    recovery_events = [e for e in events if e["state"] == "ok"]
    return {
        "burned": burned,
        "recovered": recovered,
        "burning_events": len(burning_events),
        "recovery_events": len(recovery_events),
        "final_state": slo_now.get("state"),
        "cli_state": cli_state,
        "cli_top_series": top_view.get("series"),
        "transitions": slo_now.get("transitions"),
        "events": events,
        "ok": bool(len(burning_events) == 1
                   and len(recovery_events) == 1
                   and slo_now.get("state") == "ok"
                   and cli_state == "ok"),
    }


def run() -> dict:
    from ray_tpu.core.config import config

    config.override("signal_scrape_interval_s", SCRAPE_S)
    config.override("slo_eval_interval_s", EVAL_S)
    config.override("slo_burn_evals", BURN_EVALS)
    config.override("signal_history_s", 600.0)

    import ray_tpu
    from ray_tpu import serve, state
    from ray_tpu.cluster.cluster_utils import Cluster
    from ray_tpu.serve import _observability as obs

    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    ray_tpu.init(cluster.address)
    try:
        agreement = _section_agreement(state, serve, obs)
        ring = _section_ring()
        slo = _section_slo(state, obs, cluster.address)
        status = state.slo_status()
        return {
            "scrape_interval_s": SCRAPE_S,
            "agreement": agreement,
            "ring": ring,
            "slo": slo,
            "head_series": status.get("series"),
            "head_evictions": status.get("evictions"),
            "ok": bool(agreement["ok"] and ring["ok"] and slo["ok"]),
        }
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
        for knob in ("signal_scrape_interval_s", "slo_eval_interval_s",
                     "slo_burn_evals", "signal_history_s"):
            config.reset(knob)


def main() -> None:
    ap = argparse.ArgumentParser(
        description="Signal-plane harness: windowed-query agreement, "
                    "bounded ring memory, seeded SLO burn")
    ap.add_argument("--out", default=None,
                    help="merge the signal_plane section into this "
                         "MICROBENCH-style artifact")
    args = ap.parse_args()

    res = run()

    from ray_tpu.scripts import bench_log

    entry = bench_log.record_signal_plane(
        agreement={"ok": res["agreement"]["ok"],
                   **res["agreement"]["checks"]},
        query_p50_ms=res["agreement"]["query_p50_ms"],
        series=res["head_series"] or 0,
        ring={k: res["ring"][k] for k in
              ("series_final", "growth_ratio", "evictions", "ok")},
        slo={k: res["slo"][k] for k in
             ("burning_events", "recovery_events", "final_state", "ok")
             if k in res["slo"]},
        device=bench_log.device_kind(), script="signal_bench")
    res["evidence"] = {"committed_to": entry.get("committed_to")}

    if args.out:
        # Merge-preserve: every perfsuite stage owns one section.
        payload = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                try:
                    payload = json.load(f)
                except ValueError:
                    payload = {}
        payload["signal_plane"] = res
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
    print(json.dumps(res, indent=1, default=str))
    if not res["ok"]:
        print("signal_bench: FAILED — see 'agreement'/'ring'/'slo' "
              "(either the windowed queries disagree with the client "
              "ledger, the ring memory is unbounded, or the seeded SLO "
              "burn did not fire exactly one burning + one recovery "
              "event)", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
