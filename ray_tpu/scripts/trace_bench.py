"""Trace-plane harness: TTFT decomposition truth, bounded assembly
memory, and the tracing hot-path overhead — the three claims the
flight recorder stands on, each measured, none asserted.

Sections (all in one run, merged into MICROBENCH.json under
``trace_plane`` with ``--out``):

* **decomposition** — a traced LLM serve slice (real deployment, real
  ``handle.stream`` transport, queue contention by construction): the
  flight recorder's windowed TTFT p50 must match the client-measured
  first-chunk p50 within 5%, the per-phase p50s must sum to the
  recorder's TTFT p50 within 5% (the partition claim, aggregated), and
  the decomposition must NAME the dominant phase. A decomposition that
  disagrees with the stopwatch is worse than none.
* **store** — synthetic trace churn far past every bound: traced
  memory must plateau after warmup and every bounded decision must be
  counted by cause (sampled / evicted / span_cap) — never a silent
  cap.
* **overhead** — engine tok/s three ways: tracing disabled, tracing
  enabled but the request NOT carrying a context (the guard idiom:
  sampling is the caller's decision, an untraced request must ride the
  span-free hot path), and fully traced. The untraced ratio is the
  regression gate; the traced ratio is reported.

Run: python -m ray_tpu.scripts.trace_bench [--out MICROBENCH.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import tracemalloc

STREAMS = 24
MAX_NEW = 4
DEP = "llm"


def _percentile(values, q):
    s = sorted(values)
    if not s:
        return None
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


def _engine_kwargs():
    import dataclasses

    import jax.numpy as jnp

    from ray_tpu.models import gpt2

    return dict(model="gpt2",
                config=dataclasses.replace(gpt2.GPT2Config.tiny(),
                                           dtype=jnp.float32),
                max_batch=2, prefill_rows=2, cache_len=64,
                max_prompt_len=8, max_new_tokens=MAX_NEW)


def _section_decomposition(state, serve):
    """Traced serve slice: recorder TTFT vs the client stopwatch."""
    from ray_tpu.serve.llm_engine import LLMEngine
    from ray_tpu.util import tracing

    dep = serve.deployment(name=DEP, max_concurrent_queries=64,
                           route_prefix="/llm")(LLMEngine)
    handle = serve.run(dep.bind(**_engine_kwargs()))
    # Untraced warmup: compile the prefill/decode kernels outside the
    # measured (and traced) window.
    import ray_tpu

    ray_tpu.get(handle.remote({"tokens": [5, 9, 2], "max_tokens": 2}),
                timeout=300)

    tracing.enable()
    tracing.drain()
    ttfts: list = []
    errors: list = []
    lock = threading.Lock()

    def one(i):
        prompt = [5 + (i % 7), 9, 2]
        try:
            with tracing.span("request", {"i": i}):
                t0 = time.perf_counter()
                first = None
                # Drain the whole stream (the slot must recycle); the
                # stopwatch stops at the FIRST chunk.
                for _chunk in handle.stream(prompt, MAX_NEW):
                    if first is None:
                        first = time.perf_counter() - t0
            if first is not None:
                with lock:
                    ttfts.append(first)
        except Exception as e:  # noqa: BLE001 — bench records, not raises
            with lock:
                errors.append(repr(e))

    threads = [threading.Thread(target=one, args=(i,))
               for i in range(STREAMS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    d = state.ttft_decomposition()
    client_p50 = _percentile(ttfts, 0.5)
    ring_p50 = d.get("ttft_p50_s")
    phase_sum = d.get("phase_sum_p50_s") or 0.0
    agree_client = (client_p50 and ring_p50
                    and abs(ring_p50 - client_p50) / client_p50 <= 0.05)
    agree_partition = (ring_p50
                       and abs(phase_sum - ring_p50) / ring_p50 <= 0.05)
    # An exemplar must resolve end to end: list -> get -> critical path
    # partitioning the root interval exactly.
    traces = state.list_traces(limit=5)
    resolved = None
    if traces:
        tr = state.get_trace(traces[0]["trace_id"])
        if tr is not None:
            path_s = sum(seg["self_s"] for seg in tr["critical_path"])
            resolved = {
                "trace_id": tr["trace_id"],
                "spans": len(tr["spans"]),
                "critical_path_s": round(path_s, 6),
                "duration_s": round(tr["duration_s"], 6),
                "partition_exact": abs(path_s - tr["duration_s"]) < 1e-6,
            }
    ok = bool(agree_client and agree_partition and d.get("dominant")
              and not errors and resolved
              and resolved["partition_exact"])
    return {
        "streams": STREAMS,
        "errors": errors[:3],
        "client_ttft_p50_s": round(client_p50, 5) if client_p50 else None,
        "recorder_ttft_p50_s": round(ring_p50, 5) if ring_p50 else None,
        "phase_sum_p50_s": round(phase_sum, 5),
        "phases": {k: round(v["p50_s"], 5)
                   for k, v in (d.get("phases") or {}).items()},
        "dominant": d.get("dominant"),
        "traces": d.get("traces"),
        "exemplar": resolved,
        "ok": ok,
        "checks": {"client_agreement": bool(agree_client),
                   "partition": bool(agree_partition),
                   "dominant_named": bool(d.get("dominant"))},
    }


def _section_store():
    """Synthetic churn through the bounded assembly store."""
    from ray_tpu.cluster.traces import TraceStore

    max_traces, n_traces = 256, 4000
    store = TraceStore(max_traces=max_traces, sample_rate=0.2,
                       slow_threshold_s=9999.0, quiet_s=0.0,
                       max_spans_per_trace=64)

    def tid(i: int) -> str:
        # Knuth-hash the index into the first 8 hex chars so the
        # deterministic sampler sees a spread of buckets.
        return f"{(i * 2654435761) % (1 << 32):08x}" + "d" * 24

    def spans(i: int):
        t = tid(i)
        base = i * 1_000_000
        return [
            {"trace_id": t, "span_id": f"r{i}", "parent_id": None,
             "name": "serve.stream:bench", "start_ns": base,
             "end_ns": base + 50_000_000, "status": "OK",
             "attributes": {"deployment": "bench"}, "pid": 1},
            {"trace_id": t, "span_id": f"p{i}", "parent_id": f"r{i}",
             "name": "llm.prefill:bench", "start_ns": base + 5_000_000,
             "end_ns": base + 30_000_000, "status": "OK",
             "attributes": {}, "pid": 1},
            {"trace_id": t, "span_id": f"d{i}", "parent_id": f"r{i}",
             "name": "llm.decode:bench", "start_ns": base + 30_000_000,
             "end_ns": base + 50_000_000, "status": "OK",
             "attributes": {}, "pid": 1},
        ]

    tracemalloc.start()
    warm_bytes = 0
    for i in range(n_traces):
        store.add_spans(spans(i))
        store.finalize_quiet(force=True)
        if i == n_traces // 3:
            warm_bytes = tracemalloc.get_traced_memory()[0]
    # One pathological trace over the span cap: clipped AND counted.
    fat = [dict(s, span_id=f"fat{j}") for j in range(100)
           for s in [spans(n_traces)[0]]]
    store.add_spans(fat)
    store.finalize_quiet(force=True)
    end_bytes = tracemalloc.get_traced_memory()[0]
    tracemalloc.stop()

    st = store.stats()
    dropped = st["dropped"]
    bounded = (st["kept"] <= max_traces
               and end_bytes < max(warm_bytes, 1) * 1.5)
    accounted = (dropped.get("sampled", 0) > 0
                 and dropped.get("evicted", 0) > 0
                 and dropped.get("span_cap", 0) > 0)
    return {
        "traces_offered": n_traces + 1,
        "max_traces": max_traces,
        "kept": st["kept"],
        "assembled_total": st["assembled_total"],
        "warm_bytes": warm_bytes,
        "end_bytes": end_bytes,
        "growth_ratio": round(end_bytes / max(1, warm_bytes), 3),
        "dropped": dict(dropped),
        "ok": bool(bounded and accounted),
    }


def _section_overhead():
    """Engine tok/s: tracing disabled vs enabled-untraced vs traced."""
    from ray_tpu.serve import _observability as obs
    from ray_tpu.serve.llm_engine import LLMEngine
    from ray_tpu.util import tracing

    tracing.disable()
    kw = _engine_kwargs()
    kw.update(max_new_tokens=16, cache_len=64, deployment="bench")
    eng = LLMEngine(**kw)
    prompt = [5, 9, 2]

    def tok_s(n: int, scope_ctx=None) -> float:
        toks = 0
        t0 = time.perf_counter()
        for _ in range(n):
            if scope_ctx is not None:
                with tracing.span("request") as root:
                    ctx = {"trace_id": root["trace_id"],
                           "span_id": root["span_id"]}
                    with obs.request_scope("bench", None, trace_ctx=ctx):
                        toks += len(eng.generate(prompt, 16))
            else:
                toks += len(eng.generate(prompt, 16))
        return toks / (time.perf_counter() - t0)

    try:
        tok_s(3)  # compile + warm
        off = tok_s(10)
        tracing.enable()
        tracing.drain()
        untraced = tok_s(10)          # enabled, no carried context
        traced = tok_s(10, scope_ctx=True)  # worst case: every request
        spans_recorded = len(tracing.collect(clear=True))
    finally:
        tracing.disable()
        tracing.drain()
        eng.shutdown_engine()

    untraced_ratio = untraced / off if off else 0.0
    traced_ratio = traced / off if off else 0.0
    return {
        "tok_s_off": round(off, 1),
        "tok_s_enabled_untraced": round(untraced, 1),
        "tok_s_traced": round(traced, 1),
        "untraced_ratio": round(untraced_ratio, 3),
        "traced_ratio": round(traced_ratio, 3),
        "spans_recorded": spans_recorded,
        # Within noise: an untraced request on a tracing-enabled
        # process must not pay for the flight recorder.
        "ok": bool(untraced_ratio >= 0.85 and spans_recorded > 0),
    }


def run() -> dict:
    import ray_tpu
    from ray_tpu import serve, state
    from ray_tpu.util import tracing

    # Overhead first: its baseline needs tracing untouched.
    overhead = _section_overhead()
    store = _section_store()

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    try:
        decomposition = _section_decomposition(state, serve)
    finally:
        tracing.disable()
        tracing.drain()
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
    return {
        "decomposition": decomposition,
        "store": store,
        "overhead": overhead,
        "ok": bool(decomposition["ok"] and store["ok"]
                   and overhead["ok"]),
    }


def main() -> None:
    ap = argparse.ArgumentParser(
        description="Trace-plane harness: TTFT decomposition vs the "
                    "client stopwatch, bounded assembly store, tracing "
                    "hot-path overhead")
    ap.add_argument("--out", default=None,
                    help="merge the trace_plane section into this "
                         "MICROBENCH-style artifact")
    args = ap.parse_args()

    res = run()

    from ray_tpu.scripts import bench_log

    entry = bench_log.record_trace_plane(
        decomposition={"ok": res["decomposition"]["ok"],
                       **res["decomposition"]["checks"],
                       "dominant": res["decomposition"]["dominant"]},
        ttft_p50_ms=round(
            (res["decomposition"]["recorder_ttft_p50_s"] or 0.0) * 1e3,
            3),
        overhead={k: res["overhead"][k] for k in
                  ("untraced_ratio", "traced_ratio", "ok")},
        store={k: res["store"][k] for k in
               ("kept", "growth_ratio", "dropped", "ok")},
        device=bench_log.device_kind(), script="trace_bench")
    res["evidence"] = {"committed_to": entry.get("committed_to")}

    if args.out:
        # Merge-preserve: every perfsuite stage owns one section.
        payload = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                try:
                    payload = json.load(f)
                except ValueError:
                    payload = {}
        payload["trace_plane"] = res
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
    print(json.dumps(res, indent=1, default=str))
    if not res["ok"]:
        print("trace_bench: FAILED — see 'decomposition'/'store'/"
              "'overhead' (either the recorder's TTFT disagrees with "
              "the client stopwatch, the assembly store is unbounded "
              "or drops silently, or untraced requests pay a tracing "
              "tax)", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
