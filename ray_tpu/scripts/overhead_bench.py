"""Task-overhead microbench: submit→start and per-phase latencies.

Runs a burst of no-op tasks (and actor calls) against the current
backend, waits for their state-API records — which carry the
worker-side phase breakdown (get_args / execute / put_outputs wall-ns)
— and emits p50/p99 evidence through
``bench_log.record_task_overhead`` (committed to
``BENCH_TPU_SESSIONS.jsonl`` only when run on an accelerator).

    python -m ray_tpu.scripts.overhead_bench                # local backend
    python -m ray_tpu.scripts.overhead_bench --cluster -n 200
    python -m ray_tpu.scripts.overhead_bench --address <head host:port>
"""

from __future__ import annotations

import argparse
import json
import time


def run(n_tasks: int = 100, payload_bytes: int = 1024,
        actor_calls: int = 20, wait_s: float = 30.0) -> list:
    """Drive the workload; returns the phase-carrying task records."""
    import ray_tpu
    from ray_tpu import state

    payload = b"x" * payload_bytes

    @ray_tpu.remote
    def noop(blob):
        return len(blob)

    @ray_tpu.remote
    class Probe:
        def ping(self, blob):
            return len(blob)

    ray_tpu.get([noop.remote(payload) for _ in range(n_tasks)])
    if actor_calls > 0:
        probe = Probe.remote()
        ray_tpu.get([probe.ping.remote(payload)
                     for _ in range(actor_calls)])
    # Worker task events flush in batches: wait until the records (with
    # phases) land, bounded.
    want = n_tasks + max(0, actor_calls)
    deadline = time.time() + wait_s
    records: list = []
    while time.time() < deadline:
        records = [
            r for r in state.list_tasks(limit=100_000)
            if r["name"] in ("noop", "ping") and r.get("phases")
        ]
        if len(records) >= want:
            break
        time.sleep(0.25)
    return records


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--address", default=None,
                        help="existing cluster head (default: local)")
    parser.add_argument("--cluster", action="store_true",
                        help="spin up a throwaway 2-node local cluster")
    parser.add_argument("-n", "--num-tasks", type=int, default=100)
    parser.add_argument("--payload-bytes", type=int, default=1024)
    parser.add_argument("--actor-calls", type=int, default=20)
    parser.add_argument("--device", default="",
                        help="accelerator label for the evidence trail "
                             "(empty/cpu = print only, don't commit)")
    args = parser.parse_args(argv)

    import ray_tpu
    from ray_tpu.scripts import bench_log

    cluster = None
    if args.cluster and args.address is None:
        from ray_tpu.cluster.cluster_utils import Cluster

        cluster = Cluster()
        cluster.add_node()
        cluster.add_node()
        cluster.wait_for_nodes()
        ray_tpu.init(cluster.address)
    else:
        ray_tpu.init(args.address)

    try:
        records = run(args.num_tasks, args.payload_bytes,
                      args.actor_calls)
        entry = bench_log.record_task_overhead(
            records, device=args.device,
            backend="cluster" if (cluster or args.address) else "local",
            payload_bytes=args.payload_bytes)
        print(json.dumps(entry, indent=1))
    finally:
        ray_tpu.shutdown()
        if cluster is not None:
            cluster.shutdown()


if __name__ == "__main__":
    main()
