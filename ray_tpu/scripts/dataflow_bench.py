"""Streaming-dataflow harness: generation -> training at device speed
while the object store churns past capacity.

The MindSpeed-RL-shaped scenario (PAPERS.md): generation actors stream
rollout blocks into the object store, a map stage on an AUTOSCALING
actor pool post-processes them, and the consumer side — a driver-side
``iter_device_batches`` loop plus a ``DataParallelTrainer`` mesh —
drains the result, all against a store deliberately smaller than the
dataset so dynamic block splitting + spill-to-URI + restore are doing
real work the whole time. The Podracer framing applies: keeping the
accelerators fed is the only metric, so the headline is the consumer
STALL FRACTION — and per the serve_bench/input_bench discipline it is
measured twice:

* client-side: wall time starved inside ``next()`` vs total loop wall,
  measured outside the dataset code;
* metrics-side: the ``ray_tpu_data_iter_seconds`` wait/user histograms.

The two must agree (tolerance 0.10, exact batch counts) AND stay under
0.10 while the spill counters prove the store actually churned —
disagreement or an unchurned store exits non-zero. Machine-independent
shape results (counts, agreement booleans, spill/restore/split/pool
counts) merge into MICROBENCH.json under ``streaming_dataflow``
(perfsuite ``--dataflow`` stage); ``bench_log.record_streaming_dataflow``
commits the evidence line on-chip.

Run: python -m ray_tpu.scripts.dataflow_bench [--out MICROBENCH.json]
     [--store-mb 24] [--gen-actors 4] [--rounds 64] [--block-kb 512]
     [--target-kb 256] [--steps 4] [--workers 2]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time


def _device_kind() -> str:
    from ray_tpu.scripts.bench_log import device_kind

    return device_kind()


def _obs():
    from ray_tpu.serve import _observability as serve_obs
    from ray_tpu.train import _observability as train_obs

    return serve_obs, train_obs


def _poll_until(fn, deadline_s: float = 20.0, interval: float = 0.25):
    deadline = time.monotonic() + deadline_s
    val = fn()
    while not val and time.monotonic() < deadline:
        time.sleep(interval)
        val = fn()
    return val


class _GenActor:
    """One generation actor: produces fixed-size float32 rollout blocks
    (the LLM-generation stand-in — the data plane under test does not
    care what computed the tokens)."""

    def __init__(self, block_kb: int):
        self.rows = max(1, (block_kb << 10) // (64 * 4))

    def generate(self, seed: int):
        import numpy as np

        rng = np.random.default_rng(seed)
        return {"tokens": rng.random((self.rows, 64), dtype=np.float32)}


def run(store_mb: int = 24, gen_actors: int = 4, rounds: int = 64,
        block_kb: int = 512, target_kb: int = 256, steps: int = 4,
        workers: int = 2, batch_size: int = 256,
        consume_ms: float = 8.0) -> dict:
    import numpy as np

    import ray_tpu
    from ray_tpu import data, train
    from ray_tpu.cluster.cluster_utils import Cluster
    from ray_tpu.core.config import config
    from ray_tpu.train import _observability as tob
    from ray_tpu.train import session
    from ray_tpu.util import goodput

    serve_obs, _ = _obs()

    spill_dir = tempfile.mkdtemp(prefix="ray_tpu_dataflow_spill_")
    config.override("spill_uri", f"file://{spill_dir}")
    config.override("target_block_size_bytes", target_kb << 10)
    ray_tpu.shutdown()
    cluster = Cluster()
    node = cluster.add_node(num_cpus=8, store_capacity=store_mb << 20)
    cluster.wait_for_nodes()
    ray_tpu.init(cluster.address)
    try:
        # Warm jax before any timed loop: platform init is startup
        # cost, not input-pipeline stall.
        import jax

        jax.device_put(np.zeros(1)).block_until_ready()

        before = serve_obs.parse_prometheus(tob.scrape_text())

        # -- generation: actors stream rollout blocks into the store --
        actor_cls = ray_tpu.remote(_GenActor)
        actors = [actor_cls.remote(block_kb) for _ in range(gen_actors)]
        refs = []
        for r in range(rounds):
            refs.append(actors[r % gen_actors].generate.remote(r))
        ray_tpu.wait(refs, num_returns=len(refs), timeout=None)
        ds = data.Dataset(list(refs))
        dataset_bytes = rounds * (block_kb << 10)

        # -- task-path map: dynamic splitting does its work here ------
        # (generation blocks are 2x target size; the fused task stage
        # splits each output into store-friendly pieces).
        normalized = ds.map_batches(
            lambda b: {"tokens": b["tokens"] - 0.5})

        # -- map stage on the autoscaling pool ------------------------
        processed = normalized.map_batches(
            lambda b: {"tokens": b["tokens"] * 0.5},
            compute=data.ActorPoolStrategy(
                min_size=1, max_size=4, scale_up_queue_depth=2))
        pool_stage = next(
            (s for s in processed.stats().lineage()
             if s.name == "map_batches(actors)"), None)
        pool = dict(pool_stage.extra) if pool_stage is not None else {}

        # Background churn: generation keeps streaming while the
        # consumer drains — the store stays past capacity the whole
        # loop (held refs; the relief valve is spill, not eviction).
        churn_refs: list = []
        churn_stop = threading.Event()

        def churn():
            i = 0
            while not churn_stop.is_set():
                if len(churn_refs) > rounds:
                    churn_stop.wait(0.05)  # plateau: hold ~rounds extra
                    continue
                churn_refs.append(
                    actors[i % gen_actors].generate.remote(10_000 + i))
                i += 1

        churn_thread = threading.Thread(target=churn, daemon=True)
        churn_thread.start()

        # -- the consumer loop: device batches at train speed ---------
        waits: list = []
        rows_consumed = 0
        n_batches = 0
        t0 = time.perf_counter()
        it = iter(processed.iter_device_batches(
            batch_size=batch_size, drop_last=True))
        while True:
            t_req = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                break
            waits.append(time.perf_counter() - t_req)
            n_batches += 1
            rows_consumed += int(batch["tokens"].shape[0])
            time.sleep(consume_ms / 1e3)  # the "train step"
        loop_wall = time.perf_counter() - t0
        churn_stop.set()
        churn_thread.join(timeout=30.0)
        client_wait = sum(waits)
        client_stall = client_wait / loop_wall if loop_wall > 0 else 0.0
        rows_s = rows_consumed / loop_wall if loop_wall > 0 else 0.0

        # -- metrics-side view of the same loop -----------------------
        expected = n_batches

        def settled():
            parsed = serve_obs.parse_prometheus(tob.scrape_text())
            delta = serve_obs.diff_parsed(before, parsed)
            d = serve_obs.histogram_dist(
                delta, "ray_tpu_data_iter_seconds", phase="user")
            return delta if d and d["count"] >= expected else None

        delta = _poll_until(settled) or serve_obs.diff_parsed(
            before, serve_obs.parse_prometheus(tob.scrape_text()))
        wait_d = serve_obs.histogram_dist(
            delta, "ray_tpu_data_iter_seconds", phase="wait")
        user_d = serve_obs.histogram_dist(
            delta, "ray_tpu_data_iter_seconds", phase="user")
        xfer_d = serve_obs.histogram_dist(
            delta, "ray_tpu_data_iter_seconds", phase="transfer")
        server_stall = goodput.stall_fraction_from(delta)
        splits_metric = sum(serve_obs.sum_counter(
            delta, "ray_tpu_block_splits_total", "stage").values())

        # -- the trainer mesh drains a shard under the same pressure --
        def train_fn(cfg):
            shard = session.get_dataset_shard("train")
            it = iter(shard.iter_batches(batch_size=cfg["batch_size"])) \
                if shard is not None else None
            for i in range(cfg["steps"]):
                if it is not None:
                    try:
                        next(it)
                    except StopIteration:
                        it = None
                time.sleep(cfg["consume_ms"] / 1e3)
                session.report({"step": i})

        trainer = train.DataParallelTrainer(
            train_fn,
            train_loop_config={"steps": steps,
                               "batch_size": batch_size,
                               "consume_ms": consume_ms},
            scaling_config=train.ScalingConfig(num_workers=workers),
            datasets={"train": processed},
        )
        result = trainer.fit()
        trainer_ok = result.error is None

        # -- spill/restore/split proof --------------------------------
        store_stats = node.rpc_store_stats()
        spill = {
            "spilled_objects": int(store_stats.get("spilled_objects", 0)),
            "spilled_bytes": int(store_stats.get("spilled_bytes", 0)),
            "restores": int(store_stats.get("spill_restores", 0)),
            "spill_denied": int(store_stats.get("spill_denied", 0)),
        }
        head_spill_records = len(cluster.head.rpc_spilled_objects())

        counts = {
            "wait": int(wait_d["count"]) if wait_d else 0,
            "user": int(user_d["count"]) if user_d else 0,
            "transfer": int(xfer_d["count"]) if xfer_d else 0,
        }
        agreement = {
            "wait_count_exact": counts["wait"] == expected,
            "user_count_exact": counts["user"] == expected,
            "transfer_counted": counts["transfer"] >= expected,
            "stall_within_tol": (
                server_stall is not None
                and abs(client_stall - server_stall) <= 0.10),
            "server_not_exceeding": (
                wait_d is not None
                and wait_d["sum"] <= client_wait * 1.1 + 0.05),
            # The acceptance claim itself: stall stays bounded while
            # the store churns past capacity.
            "stall_bounded": (
                client_stall < 0.10
                and server_stall is not None and server_stall < 0.10),
            # Held bytes = generation + normalized + pool output copies
            # (plus the churn plateau): the store was provably
            # oversubscribed AND the relief valve actually fired.
            "store_churned": spill["spilled_objects"] > 0
            and 2 * dataset_bytes > (store_mb << 20),
            "restores_counted": spill["restores"] > 0,
            "blocks_split": splits_metric > 0,
            "pool_scaled": pool.get("pool_peak", 0) > 1
            and pool.get("pool_scale_downs", 0) > 0,
            "trainer_completed": trainer_ok,
        }
        agreement["ok"] = all(agreement.values())

        return {
            "backend": "cluster",
            "store_capacity_bytes": store_mb << 20,
            "dataset_bytes": dataset_bytes,
            "gen_actors": gen_actors,
            "rounds": rounds,
            "n_batches": expected,
            "batch_size": batch_size,
            "target_block_size_bytes": target_kb << 10,
            "splits": int(splits_metric),
            "pool": pool,
            "spill": spill,
            "head_spill_records": head_spill_records,
            "client": {
                "stall_fraction": round(client_stall, 4),
                "wait_s": round(client_wait, 4),
                "loop_wall_s": round(loop_wall, 4),
                "rows_s": round(rows_s, 1),
            },
            "server": {
                "stall_fraction": round(server_stall, 4)
                if server_stall is not None else None,
                "wait_s": round(wait_d["sum"], 4) if wait_d else None,
                "counts": counts,
            },
            "trainer": {
                "workers": workers,
                "steps": steps,
                "ok": trainer_ok,
                "reports": len(result.metrics_history),
                "error": None if trainer_ok else repr(result.error),
            },
            "agreement": agreement,
        }
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
        config.reset("spill_uri")
        config.reset("target_block_size_bytes")
        import shutil

        shutil.rmtree(spill_dir, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser(
        description="Streaming-dataflow harness: generation->training "
                    "past store capacity with client/metrics stall "
                    "cross-check")
    ap.add_argument("--out", default=None,
                    help="merge the streaming_dataflow section into "
                         "this MICROBENCH-style artifact")
    ap.add_argument("--store-mb", type=int, default=24)
    ap.add_argument("--gen-actors", type=int, default=4)
    ap.add_argument("--rounds", type=int, default=64)
    ap.add_argument("--block-kb", type=int, default=512)
    ap.add_argument("--target-kb", type=int, default=256)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=256)
    args = ap.parse_args()

    res = run(store_mb=args.store_mb, gen_actors=args.gen_actors,
              rounds=args.rounds, block_kb=args.block_kb,
              target_kb=args.target_kb, steps=args.steps,
              workers=args.workers, batch_size=args.batch_size)

    from ray_tpu.scripts import bench_log

    entry = bench_log.record_streaming_dataflow(
        client=res["client"], server=res["server"],
        agreement=res["agreement"], rows_s=res["client"]["rows_s"],
        spill=res["spill"], pool=res["pool"],
        device=_device_kind(), script="dataflow_bench")
    res["evidence"] = {"committed_to": entry.get("committed_to")}

    if args.out:
        # Merge-preserve: every perfsuite stage owns one section.
        payload = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                try:
                    payload = json.load(f)
                except ValueError:
                    payload = {}
        payload["streaming_dataflow"] = res
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
    print(json.dumps(res, indent=1, default=str))
    if not res["agreement"]["ok"]:
        print("dataflow_bench: FAILED — see 'agreement' (either the "
              "stall metrics disagree/are unbounded, or the store "
              "never actually churned)", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
