"""Step-anatomy harness: the proof behind the MFU/straggler plane
(round 19; commits its section into MICROBENCH.json as
``step_anatomy`` with ``--out``).

Four claims, each measured, none asserted:

* **cost_model** — the XLA cost-model FLOPs (``util/xla_cost`` on the
  compiled train step's HLO) agree with the analytic
  ``*_flops_per_token`` estimate on BOTH model families (GPT-2 and
  Llama), so the exported MFU denominator is not a typo'd formula;
* **partition** — the session's anatomy phases (data_wait / host /
  compute / sync) sum to the step wall EXACTLY, report by report,
  proven from the emitted goodput events — a decomposition that does
  not partition is a narrative, not an accounting;
* **straggler** — a seeded slow rank in a 2-worker gang is named by
  :func:`ray_tpu.util.goodput.straggler_attribution` with the seeded
  cause (compute-bound for a slow step body, input-bound for seeded
  data wait), and the trial's per-rank gauges are retracted when the
  session stops;
* **sentinel** — ``bench_log --regress`` exits 0 when the fresh
  artifact matches the committed one and 1 when a seeded slowdown
  (halved MFU, doubled step wall, flipped verdict) is injected.

Run: python -m ray_tpu.scripts.anatomy_bench [--out MICROBENCH.json]

The harness is TPU-ready: every number is stamped with the live device
kind, and the evidence line enters BENCH_TPU_SESSIONS.jsonl only when
run on a real accelerator.
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import tempfile
import time

TRIAL = "anatomy_bench"


def _timed_loop(step_fn, state, batch, steps: int) -> tuple:  # step-timed
    """Timed train-step loop -> (state, dt seconds). The device sync
    (``float`` of the loss) sits between the timer reads, so the wall
    covers real compute, not async dispatch."""
    t0 = time.perf_counter()
    metrics = None
    for _ in range(steps):
        state, metrics = step_fn(state, batch)
    float(metrics["loss"])
    dt = time.perf_counter() - t0
    return state, dt


def _cost_model_one(name: str, cfg, init, loss, shardings_fn,
                    flops_per_token, *, batch: int = 4,
                    steps: int = 8, warmup: int = 2) -> dict:
    """HLO-vs-analytic FLOPs agreement + measured MFU for one model
    family's compiled train step."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.parallel.mesh import MeshConfig, build_mesh
    from ray_tpu.train.train_step import make_init_fn, make_train_step
    from ray_tpu.util import xla_cost

    mesh = build_mesh(MeshConfig(fsdp=-1))
    shardings = shardings_fn(cfg, mesh)
    init_fn = make_init_fn(lambda r: init(r, cfg), shardings, mesh)
    state = init_fn(jax.random.key(0))
    step_fn = make_train_step(
        lambda p, b: loss(p, b, cfg), shardings, mesh)
    tokens = jax.random.randint(
        jax.random.key(1), (batch, cfg.seq_len + 1), 0, cfg.vocab_size,
        jnp.int32)
    batch_data = {"tokens": tokens}

    cost = xla_cost.step_cost(step_fn, state, batch_data)
    analytic = float(flops_per_token(cfg)) * batch * cfg.seq_len
    out: dict = {"model": name, "batch": batch,
                 "seq_len": cfg.seq_len,
                 "analytic_flops": analytic,
                 "available": bool(cost.get("available"))}
    if not cost.get("available"):
        out["reason"] = cost.get("reason", "")
        out["ok"] = False
        return out

    for _ in range(warmup):
        state, metrics = step_fn(state, batch_data)
    float(metrics["loss"])
    state, dt = _timed_loop(step_fn, state, batch_data, steps)
    step_s = dt / steps

    mfu = xla_cost.mfu_percent(cost["flops"], step_s,
                               device_kind=cost.get("device_kind"))
    ratio = cost["flops"] / max(analytic, 1.0)
    out.update({
        "hlo_flops": cost["flops"],
        "flops_ratio": round(ratio, 3),
        "intensity_flops_per_byte": round(
            cost.get("intensity_flops_per_byte") or 0.0, 2),
        "roofline": cost.get("roofline"),
        "step_ms": round(step_s * 1000, 3),
        "mfu": round(mfu, 4),
        # Generous band by design: the analytic 6N formula ignores
        # softmax/norm/optimizer FLOPs and the HLO counts every one of
        # them — agreement here means "same order, same model", which
        # is exactly what a fat-fingered denominator would break.
        "ok": 0.25 <= ratio <= 4.0,
    })
    return out


def _cost_model_section() -> dict:
    try:
        import jax  # noqa: F401
    except Exception as e:
        return {"skipped": f"jax unavailable: {e!r}", "ok": False}
    from ray_tpu.models.gpt2 import (
        GPT2Config,
        gpt2_flops_per_token,
        gpt2_init,
        gpt2_loss,
        gpt2_shardings,
    )
    from ray_tpu.models.llama import (
        LlamaConfig,
        llama_flops_per_token,
        llama_init,
        llama_loss,
        llama_shardings,
    )

    gpt2 = _cost_model_one(
        "gpt2",
        GPT2Config(vocab_size=256, n_layer=2, n_head=4, d_model=128,
                   seq_len=64, remat=False),
        gpt2_init, gpt2_loss, gpt2_shardings, gpt2_flops_per_token)
    llama = _cost_model_one(
        "llama",
        LlamaConfig(vocab_size=256, n_layer=2, n_head=4, n_kv_head=2,
                    d_model=128, seq_len=64, remat=False),
        llama_init, llama_loss, llama_shardings, llama_flops_per_token)
    ratios = [m["flops_ratio"] for m in (gpt2, llama)
              if "flops_ratio" in m]
    return {
        "gpt2": gpt2,
        "llama": llama,
        # Headline for the regression gate: the worst family's ratio.
        "flops_ratio": round(max(ratios), 3) if ratios else None,
        "ok": bool(gpt2.get("ok")) and bool(llama.get("ok")),
    }


def _partition_section(steps: int = 4) -> dict:
    """Exact-partition proof on a live in-process session: every
    report's emitted anatomy phases must sum to that report's step wall
    (data_wait + step from the classic accounting) to float precision."""
    from ray_tpu.train import session
    from ray_tpu.train import _observability as tob

    tob.drain_events()  # isolate: only this section's events below
    session.init_session(
        world_rank=0, world_size=1, local_rank=0, node_rank=0,
        results_queue=queue.Queue(), checkpoint=None,
        dataset_shards=None, trial_info={"trial_id": TRIAL})
    try:
        session.set_step_cost(1e6)  # exercise the MFU export path
        for _ in range(steps):
            session.add_data_wait(0.002)
            time.sleep(0.002)
            session.timed_step(time.sleep, 0.004)
            session.report({})
    finally:
        session.shutdown_session()
    events = tob.drain_events()
    walls = [ev["p"].get("data_wait", 0.0) + ev["p"]["step"]
             for ev in events if ev.get("k") == "step"
             and ev.get("t") == TRIAL]
    anat = [sum(ev["p"].values()) for ev in events
            if ev.get("k") == "anat" and ev.get("t") == TRIAL]
    mfu_exported = any(ev.get("m") is not None for ev in events
                       if ev.get("k") == "anat")
    errs = [abs(a - w) for a, w in zip(anat, walls)]
    phases = next((dict(ev["p"]) for ev in reversed(events)
                   if ev.get("k") == "anat"), {})
    try:
        tob.retract_trial(TRIAL)
    except Exception:
        pass
    return {
        "steps": steps,
        "reports": len(walls),
        "anatomy_reports": len(anat),
        "max_partition_err_s": max(errs) if errs else None,
        "mfu_exported": mfu_exported,
        "last_phases": {k: round(v, 6) for k, v in phases.items()},
        "ok": (len(anat) == steps and len(walls) == steps
               and mfu_exported
               and all(e < 1e-9 for e in errs)),
    }


def _run_gang(seed: str, steps: int = 3) -> dict | None:
    """2-worker local gang with rank 1 seeded slow — ``seed`` picks the
    slow phase ('compute': a slow step body; 'input': seeded data
    wait). Returns the straggler verdict from the emitted events."""
    from ray_tpu import train
    from ray_tpu.train import session
    from ray_tpu.train import _observability as tob

    def train_fn(config):
        rank = session.get_world_rank()
        for _ in range(config["steps"]):
            if config["seed"] == "input" and rank == 1:
                time.sleep(0.05)
                session.add_data_wait(0.05)
            slow = 0.05 if (config["seed"] == "compute"
                            and rank == 1) else 0.0
            session.timed_step(time.sleep, 0.01 + slow)
            session.report({})

    tob.drain_events()
    trainer = train.DataParallelTrainer(
        train_fn,
        train_loop_config={"steps": steps, "seed": seed},
        scaling_config=train.ScalingConfig(num_workers=2),
    )
    result = trainer.fit()
    if result.error is not None:
        return {"error": repr(result.error)}
    rank_phases: dict = {}
    for ev in tob.drain_events():
        if ev.get("k") != "anat":
            continue
        acc = rank_phases.setdefault(ev["r"], {})
        for p, s in ev["p"].items():
            acc[p] = acc.get(p, 0.0) + s
    verdict = tob.straggler_attribution(rank_phases)
    return {"rank_phases": {
        str(r): {p: round(s, 4) for p, s in ph.items()}
        for r, ph in rank_phases.items()},
        "verdict": verdict}


def _straggler_section() -> dict:
    from ray_tpu.serve import _observability as obs
    from ray_tpu.util import metrics

    compute = _run_gang("compute")
    inp = _run_gang("input")

    def check(res, cause):
        v = (res or {}).get("verdict") or {}
        return {**(res or {}),
                "ok": v.get("rank") == 1 and v.get("cause") == cause}

    compute = check(compute, "compute-bound")
    inp = check(inp, "input-bound")

    # Session-stop discipline (LC001): fit()'s finally retracts the
    # trial's per-rank gauges — nothing may survive on the scrape.
    parsed = obs.parse_prometheus(metrics.prometheus_text())
    leftover = [dict(lb) for fam in ("ray_tpu_step_phase_seconds",
                                     "ray_tpu_mfu_percent")
                for lb in (parsed.get(fam) or {})
                if dict(lb).get("trial") == "train"]
    return {
        "compute_seeded": compute,
        "input_seeded": inp,
        "retraction": {"leftover_series": len(leftover),
                       "ok": not leftover},
        "ok": (compute["ok"] and inp["ok"] and not leftover),
    }


def _sentinel_section() -> dict:
    """The regression sentinel trips on a seeded slowdown and stays
    quiet on identity — proven through the real CLI entrypoint (exit
    codes), not just the library call."""
    from ray_tpu.scripts import bench_log

    base = {"step_anatomy": {
        "mfu": 42.0, "step_wall_s": 0.5,
        "phases": {"data_wait": 0.1, "host": 0.05,
                   "compute": 0.3, "sync": 0.05},
        "cost_model": {"flops_ratio": 1.2, "ok": True},
        "agreement": {"ok": True},
    }}
    seeded = json.loads(json.dumps(base))
    seeded["step_anatomy"]["mfu"] = 21.0           # halved
    seeded["step_anatomy"]["step_wall_s"] = 1.0    # doubled
    seeded["step_anatomy"]["cost_model"]["ok"] = False

    identity_problems = bench_log.regress_check(
        json.loads(json.dumps(base)), base)
    seeded_problems = bench_log.regress_check(seeded, base)

    with tempfile.TemporaryDirectory() as td:
        bp = os.path.join(td, "base.json")
        fp = os.path.join(td, "fresh.json")
        sp = os.path.join(td, "seeded.json")
        for path, obj in ((bp, base), (fp, base), (sp, seeded)):
            with open(path, "w") as f:
                json.dump(obj, f)
        rc_identity = bench_log.main(
            ["--regress", fp, "--against", bp])
        rc_seeded = bench_log.main(
            ["--regress", sp, "--against", bp])
    return {
        "identity_problems": len(identity_problems),
        "seeded_problems": seeded_problems,
        "identity_rc": rc_identity,
        "seeded_rc": rc_seeded,
        "ok": (not identity_problems and len(seeded_problems) >= 3
               and rc_identity == 0 and rc_seeded == 1),
    }


def run() -> dict:
    import ray_tpu
    from ray_tpu.scripts import bench_log

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    try:
        cost_model = _cost_model_section()
        partition = _partition_section()
        straggler = _straggler_section()
        sentinel = _sentinel_section()
    finally:
        ray_tpu.shutdown()

    phases = partition.get("last_phases") or {}
    gpt2 = cost_model.get("gpt2") or {}
    res = {
        "device": bench_log.device_kind() or "cpu",
        # Headline numbers (the regression gates key on these): the
        # GPT-2 family's measured MFU and the live partition's phases.
        "mfu": gpt2.get("mfu", 0.0),
        "phases": phases,
        "step_wall_s": round(sum(phases.values()), 6),
        "cost_model": cost_model,
        "partition": partition,
        "straggler": straggler,
        "sentinel": sentinel,
        "agreement": {"ok": bool(cost_model.get("ok"))
                      and bool(partition.get("ok"))},
        "ok": all(bool(s.get("ok")) for s in
                  (cost_model, partition, straggler, sentinel)),
    }

    entry = bench_log.record_step_anatomy(
        mfu=res["mfu"], phases=res["phases"],
        step_wall_s=res["step_wall_s"], agreement=res["agreement"],
        straggler=(straggler.get("compute_seeded") or {}).get("verdict"),
        device=res["device"], script="anatomy_bench")
    res["evidence"] = {"committed_to": entry.get("committed_to")}
    return res


def main() -> None:
    ap = argparse.ArgumentParser(
        description="Step-anatomy harness: cost-model agreement, exact "
                    "phase partition, seeded-straggler attribution, "
                    "regression-sentinel trip")
    ap.add_argument("--out", default=None,
                    help="merge the step_anatomy section into this "
                         "MICROBENCH-style artifact")
    args = ap.parse_args()

    res = run()

    if args.out:
        # Merge-preserve: every perfsuite stage owns one section.
        payload = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                try:
                    payload = json.load(f)
                except ValueError:
                    payload = {}
        payload["step_anatomy"] = res
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
    print(json.dumps(res, indent=1, default=str))
    if not res["ok"]:
        print("anatomy_bench: FAILED — see 'cost_model'/'partition'/"
              "'straggler'/'sentinel' (either the HLO and analytic "
              "FLOPs disagree, the phases do not partition the step "
              "wall, the seeded straggler was not attributed, or the "
              "sentinel did not trip)")
        raise SystemExit(1)


if __name__ == "__main__":
    main()
