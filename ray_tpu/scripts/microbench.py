"""Core-API microbenchmarks against the cluster backend.

The control-plane counterpart of ``bench.py``: measures the task/actor/
object hot paths the way the reference's perf suite does
(``python/ray/_private/ray_perf.py:93-236``, driven nightly by
``release/microbenchmark/run_microbenchmark.py:14-31``) — tasks/s sync and
async, 1:1 and 1:n actor calls/s, put/get ops/s and GB/s — but against a
real multi-process ``cluster_utils.Cluster`` rather than a single-node
runtime, so every number includes the scheduler RPC, borrow-registration
RPCs, and worker dispatch.

Usage:  python -m ray_tpu.scripts.microbench [--out MICROBENCH.json]
Emits one JSON object: {metric: {"value": .., "unit": ..}, ...}.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _rate(n: int, dt: float) -> float:
    return n / dt if dt > 0 else float("inf")


def run_all(num_nodes: int = 2, cpus_per_node: int = 4) -> dict:
    import numpy as np

    import ray_tpu
    from ray_tpu.cluster.cluster_utils import Cluster

    results: dict = {}

    def record(name, value, unit):
        results[name] = {"value": round(value, 2), "unit": unit}
        print(f"{name}: {value:,.1f} {unit}", file=sys.stderr, flush=True)

    ray_tpu.shutdown()
    cluster = Cluster()
    for _ in range(num_nodes):
        cluster.add_node(num_cpus=cpus_per_node)
    cluster.wait_for_nodes()
    ray_tpu.init(cluster.address)

    try:
        @ray_tpu.remote
        def noop():
            return None

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        # Warm every node's worker pool so measurements exclude process
        # forks (SPREAD defeats the prefer-local fast path, which would
        # otherwise keep the warmup on the driver's node).
        ray_tpu.get(
            [
                noop.options(scheduling_strategy="SPREAD").remote()
                for _ in range(2 * cpus_per_node * num_nodes)
            ],
            timeout=120,
        )

        # 1. tasks, sync: submit one, wait, repeat.
        n = 200
        t0 = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(noop.remote(), timeout=30)
        record("tasks_sync_per_s", _rate(n, time.perf_counter() - t0), "ops/s")

        # 2. tasks, async: submit a burst, then drain.
        n = 500
        t0 = time.perf_counter()
        ray_tpu.get([noop.remote() for _ in range(n)], timeout=120)
        record("tasks_async_per_s", _rate(n, time.perf_counter() - t0), "ops/s")

        # 3. actor calls 1:1 sync.
        a = Counter.remote()
        ray_tpu.get(a.inc.remote(), timeout=30)
        n = 200
        t0 = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(a.inc.remote(), timeout=30)
        record("actor_calls_sync_per_s", _rate(n, time.perf_counter() - t0),
               "ops/s")

        # 4. actor calls 1:1 async (client-side pipelining).
        n = 500
        t0 = time.perf_counter()
        ray_tpu.get([a.inc.remote() for _ in range(n)], timeout=120)
        record("actor_calls_async_per_s", _rate(n, time.perf_counter() - t0),
               "ops/s")

        # 5. actor calls 1:n — one driver fanning out to 8 actors.
        pool = [Counter.remote() for _ in range(8)]
        ray_tpu.get([b.inc.remote() for b in pool], timeout=60)
        n_per = 60
        t0 = time.perf_counter()
        ray_tpu.get(
            [b.inc.remote() for _ in range(n_per) for b in pool], timeout=120)
        record("actor_calls_1_to_n_per_s",
               _rate(n_per * len(pool), time.perf_counter() - t0), "ops/s")

        # 6. put/get small objects.
        n = 300
        t0 = time.perf_counter()
        refs = [ray_tpu.put(i) for i in range(n)]
        record("put_small_per_s", _rate(n, time.perf_counter() - t0), "ops/s")
        t0 = time.perf_counter()
        ray_tpu.get(refs, timeout=60)
        record("get_small_per_s", _rate(n, time.perf_counter() - t0), "ops/s")

        # 7. put/get throughput on a 256 MiB array (zero-copy numpy path).
        big = np.zeros(256 * 1024 * 1024, dtype=np.uint8)
        gib = big.nbytes / (1024 ** 3)
        t0 = time.perf_counter()
        ref = ray_tpu.put(big)
        record("put_gib_per_s", gib / (time.perf_counter() - t0), "GiB/s")
        t0 = time.perf_counter()
        out = ray_tpu.get(ref, timeout=60)
        assert out.nbytes == big.nbytes
        record("get_gib_per_s", gib / (time.perf_counter() - t0), "GiB/s")
        del big, out, ref

        # 8. cross-node task arg: ship ~64 MiB to a forced-remote task.
        @ray_tpu.remote(num_cpus=cpus_per_node)  # can't co-locate w/ driver node's tasks
        def size_of(arr):
            return arr.nbytes

        payload = np.zeros(64 * 1024 * 1024, dtype=np.uint8)
        pref = ray_tpu.put(payload)
        t0 = time.perf_counter()
        nbytes = ray_tpu.get(size_of.remote(pref), timeout=120)
        dt = time.perf_counter() - t0
        assert nbytes == payload.nbytes
        record("task_arg_64mib_ms", dt * 1e3, "ms")
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
    return results


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="MICROBENCH.json")
    ap.add_argument("--nodes", type=int, default=2)
    ap.add_argument("--cpus", type=int, default=4)
    args = ap.parse_args()
    results = run_all(args.nodes, args.cpus)
    # Preserve sections other writers own (scalebench.py merges its
    # "scalability" results into the same file).
    extra = {}
    import os

    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                prior = json.load(f)
            extra = {k: v for k, v in prior.items()
                     if k not in ("cmd", "backend", "nodes",
                                  "cpus_per_node", "metrics")}
        except (OSError, ValueError):
            pass
    payload = {
        "cmd": " ".join(sys.argv),
        "backend": "cluster",
        "nodes": args.nodes,
        "cpus_per_node": args.cpus,
        "metrics": results,
        **extra,
    }
    with open(args.out, "w") as f:
        json.dump(payload, f, indent=2)
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
