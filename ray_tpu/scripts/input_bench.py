"""Input-pipeline / training-goodput harness with client/server cross-check.

Drives the full training ingest path — dataset -> ``iter_batches`` /
``iter_device_batches`` -> train-step loop — and REQUIRES the metrics
plane to agree with an independent client-side measurement (the
serve_bench discipline: the telemetry itself is under test, not just
the workload):

* **pipeline**: a consumer loop with a known per-batch cost measures
  its own stall fraction (time starved in ``next()`` vs total loop
  wall); the bench then derives the same number from the
  ``ray_tpu_data_iter_seconds`` histograms and requires exact batch
  counts and agreement within tolerance — disagreement exits non-zero.
* **train**: a real ``DataParallelTrainer`` run whose per-step phase
  histograms (``ray_tpu_train_step_phase_seconds``) must count exactly
  ``workers x steps`` steps, with data_wait / checkpoint phases
  observed.
* **goodput under drain** (``--drain``): a checkpointing trial on a
  multi-node cluster is gracefully drained mid-run (the drain_bench
  scenario composed with the goodput ledger); the trial must finish
  with no error, its goodput %% computed, and the downtime attributed
  to the drain/preemption cause — never unaccounted wall time.

Machine-independent shape results (counts, phase coverage, agreement
booleans, attribution) merge into MICROBENCH.json under
``input_pipeline`` (perfsuite ``--input-pipeline`` stage); latency and
stall numbers ride along for context. ``bench_log.record_input_pipeline``
/ ``record_goodput`` commit evidence lines on-chip.

Run: python -m ray_tpu.scripts.input_bench [--out MICROBENCH.json]
     [--device] [--drain] [--blocks 8] [--batch-size 64]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time


def _device_kind() -> str:
    from ray_tpu.scripts.bench_log import device_kind

    return device_kind()


def _obs():
    from ray_tpu.serve import _observability as serve_obs
    from ray_tpu.train import _observability as train_obs

    return serve_obs, train_obs


def _poll_until(fn, deadline_s: float = 20.0, interval: float = 0.25):
    """Re-evaluate ``fn`` until truthy or the deadline; returns the last
    value either way (cluster events ship on a 0.25s cadence)."""
    deadline = time.monotonic() + deadline_s
    val = fn()
    while not val and time.monotonic() < deadline:
        time.sleep(interval)
        val = fn()
    return val


# -- pipeline stage ---------------------------------------------------------


def run_pipeline(n_blocks: int = 8, rows_per_block: int = 256,
                 batch_size: int = 64, consume_ms: float = 3.0,
                 produce_ms: float = 1.0, device: bool = False) -> dict:
    """Dataset -> iterator consumer loop; cross-check the stall
    fraction. Requires an initialized runtime."""
    import numpy as np

    from ray_tpu import data
    from ray_tpu.train import _observability as tob

    serve_obs, _ = _obs()
    before = serve_obs.parse_prometheus(tob.scrape_text())

    n_rows = n_blocks * rows_per_block

    def slow_ident(batch):
        time.sleep(produce_ms / 1e3)
        return batch

    ds = data.from_numpy(
        np.arange(n_rows * 4, dtype=np.float32).reshape(n_rows, 4),
        parallelism=n_blocks,
    ).map_batches(slow_ident, batch_size=rows_per_block)
    # Execute the plan BEFORE the timed loop: stage execution is its
    # own instrument (ray_tpu_data_stage_seconds); the stall fraction
    # is about the steady-state consumer loop, and lumping plan
    # execution into the client's first next() would compare two
    # different quantities.
    ds.materialize()

    # Client-side measurement: wall time inside next() (starved) vs the
    # consumer's own time — measured OUTSIDE the dataset code, so it is
    # an independent view of the same loop the iterator instruments.
    if device:
        # Warm the jax backend BEFORE the timed loop: the first
        # device_put pays platform init, which is startup cost, not
        # input-pipeline stall.
        import jax

        jax.device_put(np.zeros(1)).block_until_ready()

    waits: list = []
    n_batches = 0
    t_loop0 = time.perf_counter()
    if device:
        it = iter(ds.iter_device_batches(batch_size=batch_size,
                                         drop_last=True))
    else:
        it = iter(ds.iter_batches(batch_size=batch_size, drop_last=True))
    while True:
        t0 = time.perf_counter()
        try:
            _batch = next(it)
        except StopIteration:
            waits.append(time.perf_counter() - t0)  # final starved probe
            break
        waits.append(time.perf_counter() - t0)
        n_batches += 1
        time.sleep(consume_ms / 1e3)  # the "train step"
    loop_wall = time.perf_counter() - t_loop0
    client_wait = sum(waits)
    client_stall = client_wait / loop_wall if loop_wall > 0 else 0.0

    expected = n_batches

    def settled():
        parsed = serve_obs.parse_prometheus(tob.scrape_text())
        delta = serve_obs.diff_parsed(before, parsed)
        d = serve_obs.histogram_dist(delta, "ray_tpu_data_iter_seconds",
                                     phase="user")
        return delta if d and d["count"] >= expected else None

    delta = _poll_until(settled) or serve_obs.diff_parsed(
        before, serve_obs.parse_prometheus(tob.scrape_text()))

    wait_d = serve_obs.histogram_dist(delta, "ray_tpu_data_iter_seconds",
                                      phase="wait")
    user_d = serve_obs.histogram_dist(delta, "ray_tpu_data_iter_seconds",
                                      phase="user")
    xfer_d = serve_obs.histogram_dist(delta, "ray_tpu_data_iter_seconds",
                                      phase="transfer")
    occ_d = serve_obs.histogram_dist(delta,
                                     "ray_tpu_data_prefetch_occupancy")
    stage_names = sorted(set(serve_obs.sum_counter(
        delta, "ray_tpu_data_stage_seconds_count", "stage")))
    server_stall = tob.stall_fraction_from(delta)

    # Quantile agreement (serve_bench discipline): the per-batch wait
    # p50 seen by the client must sit within the histogram's bucket
    # resolution of the server's estimate.
    from ray_tpu.util.metrics import percentile

    client_p50_ms = round(percentile(sorted(waits), 0.5) * 1e3, 3) \
        if waits else None
    server_p50 = serve_obs.quantile_from_buckets(wait_d, 0.50)
    server_p50_ms = round(server_p50 * 1e3, 3) \
        if server_p50 is not None else None
    p50_within = False
    if client_p50_ms is not None and server_p50_ms is not None:
        tol_ms = max(
            serve_obs.bucket_width_at(wait_d, client_p50_ms / 1e3) * 1e3,
            0.35 * client_p50_ms, 2.0)
        p50_within = abs(client_p50_ms - server_p50_ms) <= tol_ms

    counts = {
        "wait": int(wait_d["count"]) if wait_d else 0,
        "user": int(user_d["count"]) if user_d else 0,
        "transfer": int(xfer_d["count"]) if xfer_d else 0,
        "occupancy": int(occ_d["count"]) if occ_d else 0,
    }
    agreement = {
        # One extra wait sample is the final starved next() that raised
        # StopIteration client-side; the iterator records waits only for
        # yielded batches, so both views count exactly n_batches.
        "wait_count_exact": counts["wait"] == expected,
        "user_count_exact": counts["user"] == expected,
        "occupancy_sampled": counts["occupancy"] == expected,
        "transfer_count_exact": (not device
                                 or counts["transfer"] == expected),
        "stall_within_tol": (
            server_stall is not None
            and abs(client_stall - server_stall) <= 0.10),
        "server_not_exceeding": (
            wait_d is not None
            and wait_d["sum"] <= client_wait * 1.1 + 0.05),
        "p50_within_tol": p50_within,
        "stage_recorded": any("map_batches" in s for s in stage_names),
    }
    agreement["ok"] = all(agreement.values())
    return {
        "n_batches": expected,
        "batch_size": batch_size,
        "n_blocks": n_blocks,
        "device": device,
        "client": {
            "stall_fraction": round(client_stall, 4),
            "wait_s": round(client_wait, 4),
            "loop_wall_s": round(loop_wall, 4),
            "wait_p50_ms": client_p50_ms,
        },
        "server": {
            "stall_fraction": round(server_stall, 4)
            if server_stall is not None else None,
            "wait_s": round(wait_d["sum"], 4) if wait_d else None,
            "wait_p50_ms": server_p50_ms,
            "counts": counts,
        },
        "stages_recorded": stage_names,
        "agreement": agreement,
    }


# -- train stage ------------------------------------------------------------


def run_train(steps: int = 6, workers: int = 2,
              step_ms: float = 5.0) -> dict:
    """A real trainer run; the per-step phase histograms must count
    exactly workers x steps."""
    import numpy as np

    from ray_tpu import data, train
    from ray_tpu.train import _observability as tob
    from ray_tpu.train import session
    from ray_tpu.train.checkpoint import Checkpoint

    serve_obs, _ = _obs()
    before = serve_obs.parse_prometheus(tob.scrape_text())

    ds = data.from_numpy(
        np.arange(workers * steps * 32, dtype=np.float32).reshape(-1, 1),
        parallelism=workers * 2)

    sleep_s = step_ms / 1e3

    def train_fn(config):
        shard = session.get_dataset_shard("train")
        it = iter(shard.iter_batches(batch_size=16)) \
            if shard is not None else None
        for i in range(config["steps"]):
            if it is not None:
                try:
                    next(it)
                except StopIteration:
                    it = None
            time.sleep(sleep_s)
            ckpt = None
            if session.get_world_rank() == 0:
                ckpt = Checkpoint.from_dict({"step": i})
            session.report({"step": i, "loss": 1.0 / (i + 1)},
                           checkpoint=ckpt)

    trainer = train.DataParallelTrainer(
        train_fn,
        train_loop_config={"steps": steps},
        scaling_config=train.ScalingConfig(num_workers=workers),
        datasets={"train": ds},
    )
    result = trainer.fit()
    if result.error is not None:
        raise RuntimeError(f"train stage failed: {result.error!r}")

    expected = workers * steps

    def settled():
        parsed = serve_obs.parse_prometheus(tob.scrape_text())
        delta = serve_obs.diff_parsed(before, parsed)
        d = serve_obs.histogram_dist(
            delta, "ray_tpu_train_step_phase_seconds",
            trial="train", phase="step")
        return delta if d and d["count"] >= expected else None

    delta = _poll_until(settled) or serve_obs.diff_parsed(
        before, serve_obs.parse_prometheus(tob.scrape_text()))

    phase_counts = {}
    for phase in ("data_wait", "step", "report", "checkpoint_save",
                  "checkpoint_restore"):
        d = serve_obs.histogram_dist(
            delta, "ray_tpu_train_step_phase_seconds",
            trial="train", phase=phase)
        if d:
            phase_counts[phase] = int(d["count"])
    reports = sum(serve_obs.sum_counter(
        delta, "ray_tpu_train_reports_total", "trial",
        trial="train").values())
    agreement = {
        "step_count_exact": phase_counts.get("step") == expected,
        "reports_exact": int(reports) == expected,
        # Every step consumed the shard iterator -> a data_wait sample
        # per step; rank 0 attached a checkpoint per step.
        "data_wait_observed": phase_counts.get("data_wait", 0) > 0,
        "checkpoint_save_counted":
            phase_counts.get("checkpoint_save") == steps,
    }
    agreement["ok"] = all(agreement.values())
    return {
        "workers": workers,
        "steps": steps,
        "phase_counts": phase_counts,
        "phases_observed": sorted(phase_counts),
        "reports": int(reports),
        "client_reports": len(result.metrics_history),
        "goodput": result.goodput,
        "agreement": agreement,
    }


# -- goodput-under-drain stage (drain_bench composed with the ledger) ------


def run_goodput_drain(steps: int = 12, step_ms: float = 250.0) -> dict:
    """Checkpointing trial on a real cluster, gracefully drained
    mid-run: the trial must complete, and every second of downtime must
    be attributed to the drain/preemption cause."""
    import ray_tpu
    from ray_tpu import train
    from ray_tpu.cluster.cluster_utils import Cluster
    from ray_tpu.train import session
    from ray_tpu.train.checkpoint import Checkpoint

    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=1)   # driver node: survives
    victim = cluster.add_node(num_cpus=4)  # the trial's capacity
    cluster.wait_for_nodes()
    ray_tpu.init(cluster.address)
    sleep_s = step_ms / 1e3

    def train_fn(config):
        start = 0
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_dict().get("step", -1) + 1
        for i in range(start, config["steps"]):
            time.sleep(sleep_s)
            session.report(
                {"step": i},
                checkpoint=Checkpoint.from_dict({"step": i})
                if session.get_world_rank() == 0 else None)

    try:
        trainer = train.DataParallelTrainer(
            train_fn,
            train_loop_config={"steps": steps},
            scaling_config=train.ScalingConfig(num_workers=2),
            run_config=train.RunConfig(
                failure_config=train.FailureConfig(max_failures=3)),
        )

        drained = threading.Event()

        def drain_mid_trial():
            # Let a few steps land, then gracefully drain the node the
            # workers run on (the drain_bench scenario) and add
            # replacement capacity for the elastic restart.
            time.sleep(steps * sleep_s / 3.0)
            try:
                cluster.head.rpc_drain_node(
                    victim.node_id, "input_bench-drain", 5.0)
                if victim in cluster.nodes:
                    cluster.nodes.remove(victim)
                cluster.add_node(num_cpus=4)
                drained.set()
            except Exception:
                pass

        t = threading.Thread(target=drain_mid_trial, daemon=True)
        t.start()
        result = trainer.fit()
        t.join(timeout=60.0)

        goodput = result.goodput or {}
        by_cause = goodput.get("by_cause") or {}
        attributed = sum(by_cause.values())
        downtime = goodput.get("downtime_s", 0.0)
        planned = {c: s for c, s in by_cause.items()
                   if c.startswith(("drain", "preemption"))}
        agreement = {
            "completed_without_error": result.error is None,
            "all_steps_reported": bool(
                result.metrics and
                result.metrics.get("step") == steps - 1),
            "drain_injected": drained.is_set(),
            "downtime_recorded": downtime > 0,
            # Attribution closes the books: the ledger's by_cause sums
            # to the downtime it reports (nothing unaccounted), and the
            # cause is the injected drain, not a generic failure.
            "downtime_fully_attributed":
                abs(attributed - downtime) < 1e-6,
            "attributed_to_drain":
                sum(planned.values()) >= downtime * 0.99 > 0,
        }
        agreement["ok"] = all(agreement.values())
        return {
            "steps": steps,
            "goodput_pct": goodput.get("goodput_pct"),
            "wall_s": goodput.get("wall_s"),
            "downtime_s": downtime,
            "by_cause": by_cause,
            "restarts": goodput.get("restarts"),
            "agreement": agreement,
        }
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


# -- driver -----------------------------------------------------------------


def run(blocks: int = 8, batch_size: int = 64, device: bool = False,
        drain: bool = False, steps: int = 6, workers: int = 2,
        cluster: bool = False) -> dict:
    import ray_tpu

    ray_tpu.shutdown()
    cluster_obj = None
    if cluster:
        from ray_tpu.cluster.cluster_utils import Cluster

        cluster_obj = Cluster()
        cluster_obj.add_node(num_cpus=8)
        cluster_obj.wait_for_nodes()
        ray_tpu.init(cluster_obj.address)
    else:
        ray_tpu.init(num_cpus=8)
    try:
        pipeline = run_pipeline(n_blocks=blocks, batch_size=batch_size,
                                device=device)
        train_res = run_train(steps=steps, workers=workers)
    finally:
        ray_tpu.shutdown()
        if cluster_obj is not None:
            cluster_obj.shutdown()

    result = {
        "backend": "cluster" if cluster else "local",
        "pipeline": pipeline,
        "train": train_res,
    }
    if drain:
        result["goodput_drain"] = run_goodput_drain()
    result["agreement"] = {
        "pipeline_ok": pipeline["agreement"]["ok"],
        "train_ok": train_res["agreement"]["ok"],
        "goodput_ok": (not drain
                       or result["goodput_drain"]["agreement"]["ok"]),
    }
    result["agreement"]["ok"] = all(result["agreement"].values())
    return result


def main() -> None:
    ap = argparse.ArgumentParser(
        description="Input-pipeline / training-goodput harness with "
                    "client/server stall-fraction cross-check")
    ap.add_argument("--out", default=None,
                    help="merge the input_pipeline section into this "
                         "MICROBENCH-style artifact")
    ap.add_argument("--blocks", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--device", action="store_true",
                    help="drive iter_device_batches (requires jax; "
                         "JAX_PLATFORMS=cpu works)")
    ap.add_argument("--drain", action="store_true",
                    help="add the goodput-under-drain probe (multi-node "
                         "cluster, graceful drain mid-trial)")
    ap.add_argument("--cluster", action="store_true",
                    help="run pipeline+train against a real "
                         "multiprocess cluster backend")
    args = ap.parse_args()

    res = run(blocks=args.blocks, batch_size=args.batch_size,
              device=args.device, drain=args.drain, steps=args.steps,
              workers=args.workers, cluster=args.cluster)

    from ray_tpu.scripts import bench_log

    device = _device_kind()
    entry = bench_log.record_input_pipeline(
        client=res["pipeline"]["client"],
        server=res["pipeline"]["server"],
        agreement=res["pipeline"]["agreement"],
        n_batches=res["pipeline"]["n_batches"],
        device=device, script="input_bench")
    res["evidence"] = {"committed_to": entry.get("committed_to")}
    gp = (res.get("goodput_drain") or {})
    if gp.get("goodput_pct") is not None:
        bench_log.record_goodput(
            trial="train", goodput_pct=gp["goodput_pct"],
            wall_s=gp.get("wall_s") or 0.0,
            downtime_s=gp.get("downtime_s") or 0.0,
            by_cause=gp.get("by_cause") or {},
            device=device, script="input_bench")

    if args.out:
        # Merge-preserve: every perfsuite stage owns one section.
        payload = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                try:
                    payload = json.load(f)
                except ValueError:
                    payload = {}
        payload["input_pipeline"] = res
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
    print(json.dumps(res, indent=1, default=str))
    if not res["agreement"]["ok"]:
        print("input_bench: CLIENT/SERVER DISAGREE — the goodput "
              "metrics are lying; see 'agreement'", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
