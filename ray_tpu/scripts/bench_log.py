"""Persist on-chip benchmark evidence (VERDICT r5 next-round item 1a).

Every successful on-chip measurement from ``bench.py`` and
``scripts/tpu_sweep.py`` is appended as one JSON line to a committed
``BENCH_TPU_SESSIONS.jsonl`` at the repo root, so perf claims have a
timestamped, in-repo evidence trail instead of living only in session
logs. Override the destination with ``RAY_TPU_BENCH_LOG`` (tests point
it at a tmp file; CI containers without a writable checkout can point it
at /tmp or set it empty to disable).

Appending is best-effort by design: a benchmark must never fail because
the evidence file is unwritable.
"""

from __future__ import annotations

import json
import os
import time

ENV_VAR = "RAY_TPU_BENCH_LOG"
FILENAME = "BENCH_TPU_SESSIONS.jsonl"

# Named benches that append via the record_* helpers below (lines keyed
# by "bench" rather than "script"+"config").
KNOWN_BENCHES = frozenset({
    "task_overhead", "memory_pressure", "chaos_soak", "scalebench",
    "drain_recovery_ms", "serve_latency", "input_pipeline", "goodput",
    "analyze", "gang_recovery", "llm_serving", "streaming_dataflow",
    "signal_plane", "fleet_scaling", "trace_plane", "step_anatomy",
})


def device_kind() -> str:
    """Platform of the first visible accelerator ("" when jax is absent
    or broken) — the shared probe every bench stamps its evidence lines
    with, so 'device' can never disagree across harnesses."""
    try:
        import jax

        return jax.devices()[0].platform
    except Exception:
        return ""


def default_path() -> str:
    """Repo-root BENCH_TPU_SESSIONS.jsonl (this file lives in
    ray_tpu/scripts/, two levels below the root)."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, FILENAME)


def record(entry: dict, path: str | None = None) -> str | None:
    """Append one measurement line; returns the path written, or None if
    persistence was disabled/unwritable."""
    if path is None:
        path = os.environ.get(ENV_VAR)
        if path == "":
            return None  # explicitly disabled
        if path is None:
            path = default_path()
    line = dict(entry)
    line.setdefault("ts", round(time.time(), 3))
    line.setdefault(
        "iso", time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
    try:
        with open(path, "a") as f:
            f.write(json.dumps(line, default=str) + "\n")
    except OSError:
        return None
    return path


def record_if_on_chip(entry: dict, path: str | None = None) -> str | None:
    """Record only measurements taken on an accelerator: a CPU fallback
    number is not TPU perf evidence and must not pollute the trail."""
    device = str(entry.get("device", "")).lower()
    if not device or device == "cpu":
        return None
    return record(entry, path)


def record_task_overhead(task_records: list, *, device: str = "",
                         path: str | None = None, **extra) -> dict:
    """Framework task-overhead evidence (``scripts/overhead_bench.py``):
    p50/p99 submit→start latency and per-phase (get_args / execute /
    put_outputs) wall time, computed from state-API task records that
    carry the worker-side phase breakdown. Committed to the evidence
    trail only on an accelerator; returns the entry (with
    ``committed_to``) either way."""
    from ray_tpu.util.metrics import latency_dist_ms

    submit_ms = []
    phase_samples: dict[str, list] = {}
    n = 0
    for rec in task_records:
        if rec.get("start_time") is None:
            continue
        n += 1
        if rec.get("submitted_at") is not None:
            submit_ms.append(
                max(0.0, (rec["start_time"] - rec["submitted_at"]) * 1e3))
        for phase, ns in (rec.get("phases") or {}).items():
            phase_samples.setdefault(phase, []).append(ns / 1e6)
    entry: dict = {"bench": "task_overhead", "device": device, "n_tasks": n}
    if submit_ms:
        entry["submit_to_start"] = latency_dist_ms(submit_ms)
    if phase_samples:
        entry["phases"] = {
            phase: latency_dist_ms(vals)
            for phase, vals in phase_samples.items()
        }
    entry.update(extra)
    entry["committed_to"] = record_if_on_chip(dict(entry), path)
    return entry


def record_memory_pressure(samples: list, *, device: str = "",
                           path: str | None = None, **extra) -> dict:
    """Object-store pressure evidence (``scripts/memory_bench.py``):
    peak/mean occupancy, evictions, and spill denials over a churn
    workload, computed from per-round ``stats()`` samples (dicts with
    used/capacity/num_evictions[/spill_denied]). Committed to the
    evidence trail only on an accelerator; returns the entry (with
    ``committed_to``) either way."""
    entry: dict = {"bench": "memory_pressure", "device": device,
                   "n_samples": len(samples)}
    if samples:
        used = [int(s.get("used", 0)) for s in samples]
        capacity = max(int(s.get("capacity", 0)) for s in samples)
        evictions = [int(s.get("num_evictions", 0)) for s in samples]
        denied = [int(s.get("spill_denied", 0)) for s in samples]
        entry["capacity_bytes"] = capacity
        entry["peak_used_bytes"] = max(used)
        entry["mean_used_bytes"] = round(sum(used) / len(used))
        if capacity:
            entry["peak_occupancy"] = round(max(used) / capacity, 4)
        entry["evictions"] = max(evictions) - min(evictions)
        if any("spill_denied" in s for s in samples):
            # Only samples that actually carry the stat (agent store
            # stats do; ad-hoc sample dicts may not) — a fabricated 0
            # would misreport a pressure run as denial-free.
            entry["spill_denied"] = max(denied) - min(denied)
    entry.update(extra)
    entry["committed_to"] = record_if_on_chip(dict(entry), path)
    return entry


def record_chaos_soak(*, seed, duration_s: float, faults: dict,
                      violations: list, mttr_ms: list,
                      tasks_ok: int, actor_calls_ok: int, puts_ok: int,
                      device: str = "", path: str | None = None,
                      **extra) -> dict:
    """Chaos-soak evidence (``scripts/chaos_soak.py``): the seeded fault
    schedule's class counts, invariant violations (must be [] for a
    passing soak), and per-fault MTTR (fault injection -> first
    successful probe round-trip). Committed to the evidence trail only
    on an accelerator; returns the entry (with ``committed_to``) either
    way. The seed makes any line replayable:
    ``RAY_TPU_CHAOS_SEED=<seed> python -m ray_tpu.scripts.chaos_soak``."""
    entry: dict = {
        "bench": "chaos_soak",
        "device": device,
        "seed": seed,
        "duration_s": round(float(duration_s), 1),
        "faults": dict(faults),
        "faults_injected": sum(faults.values()),
        "violations": list(violations),
        "n_violations": len(violations),
        "tasks_ok": tasks_ok,
        "actor_calls_ok": actor_calls_ok,
        "puts_ok": puts_ok,
    }
    if mttr_ms:
        entry["mttr_ms"] = {
            "mean": round(sum(mttr_ms) / len(mttr_ms), 1),
            "max": round(max(mttr_ms), 1),
            "n": len(mttr_ms),
        }
    entry.update(extra)
    entry["committed_to"] = record_if_on_chip(dict(entry), path)
    return entry


def record_serve_latency(*, client: dict, server: dict, agreement: dict,
                         mode: str = "http", connections: int = 0,
                         n_requests: int = 0, device: str = "",
                         path: str | None = None, **extra) -> dict:
    """Serve SLO latency evidence (``scripts/serve_bench.py``):
    client-side p50/p99/QPS over N concurrent streams, the server-side
    histogram view of the same requests, and the agreement verdict
    between them (the two must match or the serve metrics are lying).
    Committed to the evidence trail only on an accelerator; returns the
    entry (with ``committed_to``) either way."""
    entry: dict = {
        "bench": "serve_latency",
        "device": device,
        "mode": mode,
        "connections": int(connections),
        "n_requests": int(n_requests),
        "client": dict(client),
        "server": dict(server),
        "agreement": dict(agreement),
    }
    entry.update(extra)
    entry["committed_to"] = record_if_on_chip(dict(entry), path)
    return entry


def record_llm_serving(*, client: dict, server: dict, agreement: dict,
                       streams: int, tokens_s: float, device: str = "",
                       path: str | None = None, **extra) -> dict:
    """Continuous-batching LLM serving evidence (``serve_bench --llm``):
    client-measured TTFT p50/p99 + aggregate tokens/s over N concurrent
    token streams, the engine-side metric view of the same streams, and
    the agreement verdict (count-exact TTFT/token totals, quantile
    agreement, the single-compiled-shape assertion) — a one-sided
    throughput claim is exactly what this bench exists to prevent.
    Committed to the evidence trail only on an accelerator; returns the
    entry (with ``committed_to``) either way."""
    entry: dict = {
        "bench": "llm_serving",
        "device": device,
        "streams": int(streams),
        "tokens_s": float(tokens_s),
        "client": dict(client),
        "server": dict(server),
        "agreement": dict(agreement),
    }
    entry.update(extra)
    entry["committed_to"] = record_if_on_chip(dict(entry), path)
    return entry


def record_input_pipeline(*, client: dict, server: dict,
                          agreement: dict, n_batches: int = 0,
                          device: str = "", path: str | None = None,
                          **extra) -> dict:
    """Input-pipeline stall evidence (``scripts/input_bench.py``): the
    client-measured stall fraction of a dataset->iterator->train-step
    loop, the metrics-derived view of the same loop, and the agreement
    verdict between them (count-exact per phase, stall within
    tolerance — disagreement means the goodput metrics are lying).
    Committed to the evidence trail only on an accelerator; returns the
    entry (with ``committed_to``) either way."""
    entry: dict = {
        "bench": "input_pipeline",
        "device": device,
        "n_batches": int(n_batches),
        "client": dict(client),
        "server": dict(server),
        "agreement": dict(agreement),
    }
    entry.update(extra)
    entry["committed_to"] = record_if_on_chip(dict(entry), path)
    return entry


def record_streaming_dataflow(*, client: dict, server: dict,
                              agreement: dict, rows_s: float,
                              spill: dict, pool: dict,
                              device: str = "", path: str | None = None,
                              **extra) -> dict:
    """Streaming-dataflow evidence (``scripts/dataflow_bench.py``): a
    generation->training pipeline driven past store capacity — the
    client-measured consumer stall fraction, the metrics-derived view
    of the same loop, the agreement verdict, the throughput headline
    (rows/s through the consumer), the spill/restore counts that prove
    the store actually churned, and the actor-pool scale events. A
    stall claim without the spill counts is just a small-data run.
    Committed to the evidence trail only on an accelerator; returns the
    entry (with ``committed_to``) either way."""
    entry: dict = {
        "bench": "streaming_dataflow",
        "device": device,
        "rows_s": float(rows_s),
        "client": dict(client),
        "server": dict(server),
        "agreement": dict(agreement),
        "spill": dict(spill),
        "pool": dict(pool),
    }
    entry.update(extra)
    entry["committed_to"] = record_if_on_chip(dict(entry), path)
    return entry


def record_signal_plane(*, agreement: dict, query_p50_ms: float,
                        series: int, ring: dict | None = None,
                        slo: dict | None = None,
                        device: str = "", path: str | None = None,
                        **extra) -> dict:
    """Signal-plane evidence (``scripts/signal_bench.py``): the
    windowed-query-vs-client agreement verdict (history-derived QPS and
    TTFT p50 must match client-side measurement within bucket
    resolution — a query engine that disagrees with the traffic it
    summarizes is worse than none), the query path's p50 latency (the
    zero-sleeps claim, measured), the ring's series count, the
    bounded-memory section (64-node-shaped scrape: growth + eviction
    counts), and the seeded SLO burn section (exactly one burning and
    one recovery event). Committed to the evidence trail only on an
    accelerator; returns the entry (with ``committed_to``) either
    way."""
    entry: dict = {
        "bench": "signal_plane",
        "device": device,
        "agreement": dict(agreement),
        "query_p50_ms": float(query_p50_ms),
        "series": int(series),
    }
    if ring is not None:
        entry["ring"] = dict(ring)
    if slo is not None:
        entry["slo"] = dict(slo)
    entry.update(extra)
    entry["committed_to"] = record_if_on_chip(dict(entry), path)
    return entry


def record_trace_plane(*, decomposition: dict, ttft_p50_ms: float,
                       overhead: dict, store: dict | None = None,
                       device: str = "", path: str | None = None,
                       **extra) -> dict:
    """Trace-plane evidence (``scripts/trace_bench.py``): the TTFT
    decomposition agreement verdict (the flight recorder's windowed
    TTFT p50 must match the client stopwatch within 5%, the per-phase
    p50s must sum to it, and the dominant phase must be NAMED — a
    decomposition that disagrees with the stopwatch is worse than
    none), the recorder's TTFT p50, the tracing hot-path overhead
    ratios (untraced requests on a tracing-enabled process must run at
    baseline speed), and the bounded-store section (churn growth +
    per-cause drop counts). Committed to the evidence trail only on an
    accelerator; returns the entry (with ``committed_to``) either
    way."""
    entry: dict = {
        "bench": "trace_plane",
        "device": device,
        "decomposition": dict(decomposition),
        "ttft_p50_ms": float(ttft_p50_ms),
        "overhead": dict(overhead),
    }
    if store is not None:
        entry["store"] = dict(store)
    entry.update(extra)
    entry["committed_to"] = record_if_on_chip(dict(entry), path)
    return entry


def record_fleet_scaling(*, scale_up_ms: dict, bin_pack_efficiency: float,
                         scale_down: dict, waves: int, seed: int,
                         device: str = "", path: str | None = None,
                         **extra) -> dict:
    """Fleet autoscaling evidence (``scalebench --demand-burst``): the
    seeded arrival-wave envelope — scale-up latency p50/p99 (submit to
    demand-served, capacity provisioned by the bin-packer), bin-pack
    efficiency (requested / provisioned resources; launching a node per
    demand would read as waste here), and the zero-goodput-loss
    scale-down section (every terminated node drained first, every
    removal cause-attributed ``drain:*`` — an unplanned termination is
    exactly the goodput loss this bench exists to rule out). Committed
    to the evidence trail only on an accelerator; returns the entry
    (with ``committed_to``) either way."""
    entry: dict = {
        "bench": "fleet_scaling",
        "device": device,
        "waves": int(waves),
        "seed": int(seed),
        "scale_up_ms": dict(scale_up_ms),
        "bin_pack_efficiency": float(bin_pack_efficiency),
        "scale_down": dict(scale_down),
    }
    entry.update(extra)
    entry["committed_to"] = record_if_on_chip(dict(entry), path)
    return entry


def record_goodput(*, trial: str, goodput_pct: float, wall_s: float,
                   downtime_s: float, by_cause: dict,
                   device: str = "", path: str | None = None,
                   **extra) -> dict:
    """Training goodput evidence (``scripts/input_bench.py --drain``,
    chaos soak train probe): a trial's goodput %% with its downtime
    ledger — every non-productive second must carry a cause
    (drain:<reason> / preemption / failure), never unaccounted wall
    time. Committed to the evidence trail only on an accelerator;
    returns the entry (with ``committed_to``) either way."""
    entry: dict = {
        "bench": "goodput",
        "device": device,
        "trial": str(trial),
        "goodput_pct": float(goodput_pct),
        "wall_s": round(float(wall_s), 3),
        "downtime_s": round(float(downtime_s), 3),
        "by_cause": dict(by_cause),
    }
    entry.update(extra)
    entry["committed_to"] = record_if_on_chip(dict(entry), path)
    return entry


def record_analyze(*, rule_counts: dict, new: int, baselined: int,
                   ok: bool, stale_baseline: int = 0,
                   passes: list | None = None,
                   device: str = "", path: str | None = None,
                   **extra) -> dict:
    """Static-analysis gate evidence (``scripts/analyze.py --out``, the
    perfsuite `analyze` stage): per-rule finding counts, how many are
    baselined vs NEW, and the gate verdict — so an on-chip perf session
    also records that its tree passed the concurrency/contract gate
    (rule-count trends live in MICROBENCH.json's `analyze` section;
    this line is the timestamped trail). Committed to the evidence
    trail only on an accelerator; returns the entry (with
    ``committed_to``) either way."""
    if passes is None:
        # Default to the live registry: the evidence line must say
        # WHICH pass families were active — "analyze ran" from a build
        # where half the passes didn't load is a weaker claim.
        try:
            from ray_tpu.util import analyze as _analyze

            passes = sorted(_analyze.PASSES)
        except Exception:
            passes = []
    entry: dict = {
        "bench": "analyze",
        "device": device,
        "rule_counts": dict(rule_counts),
        "new": int(new),
        "baselined": int(baselined),
        "stale_baseline": int(stale_baseline),
        "passes": list(passes),
        "ok": bool(ok),
    }
    entry.update(extra)
    entry["committed_to"] = record_if_on_chip(dict(entry), path)
    return entry


def record_scalebench(*, scalability: dict | None = None,
                      head_scale: dict | None = None,
                      device: str = "", path: str | None = None,
                      **extra) -> dict:
    """Control-plane envelope evidence (``scripts/scalebench.py``): the
    real-cluster section's rates and the head-at-scale section's
    machine-independent per-RPC accounting, flattened to the headline
    numbers (the full artifact lives in MICROBENCH.json — this line is
    the timestamped when/at-what-shape trail). Committed to
    BENCH_TPU_SESSIONS.jsonl only on an accelerator; returns the entry
    (with ``committed_to``) either way."""

    def headline(section: dict | None, keys: tuple) -> dict:
        if not section:
            return {}
        out = {}
        for k in keys:
            e = section.get(k)
            if isinstance(e, dict) and "value" in e:
                out[k] = e["value"]
            elif e is not None and not isinstance(e, dict):
                out[k] = e
        return out

    entry: dict = {"bench": "scalebench", "device": device}
    sc = headline(scalability, (
        "nodes", "cpus_per_node", "cluster_boot_s", "burst_tasks_per_s",
        "burst_submit_per_s", "actor_create_call_per_s",
        "broadcast_agg_gib_per_s", "queued_pending",
        "queued_sched_rpcs_per_s", "queued_probe_latency_s",
        "queued_shutdown_s", "queued_rss_growth_mb"))
    if sc:
        entry["scalability"] = sc
    hs = headline(head_scale, (
        "nodes", "queued", "actors", "subscribers", "spans",
        "heartbeats_per_s", "status_polls_per_s", "sched_feasible_per_s",
        "sched_infeasible_per_s", "ref_begin_per_s", "add_location_per_s",
        "actor_register_per_s", "actor_updates_per_s",
        "pubsub_coalesced", "pubsub_dropped", "span_dropped",
        "persist_coalesced", "rss_growth_mb", "head_handler_total_s"))
    if hs:
        entry["head_scale"] = hs
    entry.update(extra)
    entry["committed_to"] = record_if_on_chip(dict(entry), path)
    return entry


# --------------------------------------------------------------------------
# Evidence-gap lint (VERDICT r5 item 1, "the cheapest high-value fix"):
# every line of the committed trail must parse and carry the fields a
# later reader needs to reconstruct when/where/what was measured. Runs
# in tier-1 against the committed file and as
# ``python -m ray_tpu.scripts.bench_log --check [path]``.
# --------------------------------------------------------------------------


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def check_line(obj: object, *, allow_header: bool = False) -> list[str]:
    """Schema errors for one parsed JSONL entry ([] = valid).

    Three valid shapes:
    * header — ``{"schema": <str>, ...}``; ONLY the first line of the
      file (``allow_header=True``) may take this shape, so a 'schema'
      key on a data line can't smuggle it past validation;
    * throughput point — ``script`` (+``config``) lines from bench.py /
      tpu_sweep.py: need ts, a non-CPU device, tok/s and MFU numbers;
    * named bench — ``bench`` lines from the record_* helpers: need ts
      and a non-CPU device.
    """
    if not isinstance(obj, dict):
        return ["not a JSON object"]
    if "schema" in obj:
        if not allow_header:
            return ["'schema' header shape only valid on line 1"]
        return [] if isinstance(obj["schema"], str) else [
            "header 'schema' must be a string"]
    errs = []
    if not _is_num(obj.get("ts")):
        errs.append("missing/non-numeric 'ts'")
    iso = obj.get("iso")
    if iso is not None and not isinstance(iso, str):
        errs.append("'iso' must be a string")
    device = obj.get("device")
    if not isinstance(device, str) or not device:
        errs.append("missing/empty 'device'")
    elif device.lower() == "cpu":
        errs.append("'device' is cpu — CPU numbers must not enter the "
                    "on-chip evidence trail")
    # 'bench' takes precedence: the record_* helpers also stamp a
    # provenance 'script' key (chaos_soak, serve_bench), which must not
    # route their lines into the throughput-point schema.
    if "bench" in obj:
        if obj["bench"] not in KNOWN_BENCHES:
            errs.append(f"unknown bench {obj['bench']!r}")
        elif obj["bench"] == "input_pipeline":
            # The whole point of the line is the CROSS-CHECKED stall
            # fraction: client AND server views plus the agreement flag
            # — a one-sided stall number is exactly the unverified
            # claim this bench exists to prevent.
            client = obj.get("client")
            server = obj.get("server")
            if not (isinstance(client, dict)
                    and _is_num(client.get("stall_fraction"))):
                errs.append("input_pipeline line missing numeric "
                            "client.stall_fraction")
            if not (isinstance(server, dict)
                    and _is_num(server.get("stall_fraction"))):
                errs.append("input_pipeline line missing numeric "
                            "server.stall_fraction")
            agreement = obj.get("agreement")
            if not (isinstance(agreement, dict)
                    and isinstance(agreement.get("ok"), bool)):
                errs.append("input_pipeline line missing boolean "
                            "agreement.ok")
        elif obj["bench"] == "streaming_dataflow":
            # The claim is "stall stayed bounded WHILE the store
            # churned": both stall views, the agreement verdict, a
            # numeric throughput, and the spill/restore counts that
            # prove churn are all load-bearing — drop any one and the
            # line is an unverified (or unloaded) claim.
            if not any(_is_num(obj.get(k))
                       for k in ("rows_s", "tokens_s")):
                errs.append("streaming_dataflow line missing numeric "
                            "rows_s/tokens_s throughput")
            client = obj.get("client")
            server = obj.get("server")
            if not (isinstance(client, dict)
                    and _is_num(client.get("stall_fraction"))):
                errs.append("streaming_dataflow line missing numeric "
                            "client.stall_fraction")
            if not (isinstance(server, dict)
                    and _is_num(server.get("stall_fraction"))):
                errs.append("streaming_dataflow line missing numeric "
                            "server.stall_fraction")
            agreement = obj.get("agreement")
            if not (isinstance(agreement, dict)
                    and isinstance(agreement.get("ok"), bool)):
                errs.append("streaming_dataflow line missing boolean "
                            "agreement.ok")
            spill = obj.get("spill")
            if not (isinstance(spill, dict)
                    and _is_num(spill.get("spilled_objects"))
                    and _is_num(spill.get("restores"))):
                errs.append("streaming_dataflow line missing numeric "
                            "spill.spilled_objects/restores counts")
        elif obj["bench"] == "fleet_scaling":
            # The claim is "the fleet sizes itself and shrinks without
            # losing goodput": the latency percentiles, the packing
            # efficiency, and the fully cause-attributed scale-down
            # ledger are each load-bearing — a line without them is an
            # unverified autoscaling claim.
            su = obj.get("scale_up_ms")
            if not (isinstance(su, dict) and _is_num(su.get("p50"))
                    and _is_num(su.get("p99"))):
                errs.append("fleet_scaling line missing numeric "
                            "scale_up_ms.p50/p99")
            if not _is_num(obj.get("bin_pack_efficiency")):
                errs.append("fleet_scaling line missing numeric "
                            "bin_pack_efficiency")
            sd = obj.get("scale_down")
            if not (isinstance(sd, dict) and _is_num(sd.get("nodes"))
                    and isinstance(sd.get("causes"), dict)):
                errs.append("fleet_scaling line missing scale_down "
                            "dict with numeric 'nodes' + 'causes' "
                            "attribution")
        elif obj["bench"] == "goodput":
            if not _is_num(obj.get("goodput_pct")):
                errs.append("goodput line missing numeric goodput_pct")
            if not _is_num(obj.get("downtime_s")):
                errs.append("goodput line missing numeric downtime_s")
            if not isinstance(obj.get("by_cause"), dict):
                errs.append("goodput line missing by_cause attribution "
                            "dict")
        elif obj["bench"] == "analyze":
            # The gate line must carry the verdict AND the per-rule
            # breakdown: a bare "analyze ran" claim with no counts is
            # exactly the unreviewable evidence this lint exists to
            # prevent.
            if not isinstance(obj.get("rule_counts"), dict):
                errs.append("analyze line missing rule_counts dict")
            if not _is_num(obj.get("new")):
                errs.append("analyze line missing numeric 'new' "
                            "finding count")
            if not isinstance(obj.get("ok"), bool):
                errs.append("analyze line missing boolean 'ok' gate "
                            "verdict")
            required = {"lock-order", "blocking", "finalizer",
                        "async-lock", "contracts", "retry",
                        "daemon-loop", "timeout-order", "jax-hotpath",
                        "lifecycle"}
            passes = obj.get("passes")
            if not isinstance(passes, list) \
                    or not required <= set(passes):
                missing = sorted(required - set(passes or ()))
                errs.append(f"analyze line missing active pass "
                            f"families {missing} — the gate claim must "
                            f"name every family that ran")
        elif obj["bench"] == "gang_recovery":
            # The MTTR line IS the number: a gang-recovery claim with
            # no reschedule latency is unreviewable.
            if not _is_num(obj.get("pg_reschedule_ms")):
                errs.append("gang_recovery line missing numeric "
                            "pg_reschedule_ms")
            if not isinstance(obj.get("trigger"), str) \
                    or not obj.get("trigger"):
                errs.append("gang_recovery line missing 'trigger' "
                            "(drain | node_death)")
        elif obj["bench"] == "llm_serving":
            # The headline IS ttft + throughput, cross-checked: a line
            # without both views and the verdict is an unverified
            # serving claim.
            client = obj.get("client")
            if not (isinstance(client, dict)
                    and _is_num(client.get("ttft_p50_ms"))
                    and _is_num(client.get("ttft_p99_ms"))):
                errs.append("llm_serving line missing numeric "
                            "client.ttft_p50_ms/ttft_p99_ms")
            if not _is_num(obj.get("tokens_s")):
                errs.append("llm_serving line missing numeric tokens_s")
            if not isinstance(obj.get("server"), dict):
                errs.append("llm_serving line missing server dict")
            agreement = obj.get("agreement")
            if not (isinstance(agreement, dict)
                    and isinstance(agreement.get("ok"), bool)):
                errs.append("llm_serving line missing boolean "
                            "agreement.ok")
        elif obj["bench"] == "signal_plane":
            # The line's claim is "the history ring answers truthfully
            # and cheaply": the windowed-vs-client agreement verdict,
            # the measured query latency (zero-sleeps, proven not
            # asserted), and the series count are all load-bearing.
            agreement = obj.get("agreement")
            if not (isinstance(agreement, dict)
                    and isinstance(agreement.get("ok"), bool)):
                errs.append("signal_plane line missing boolean "
                            "agreement.ok")
            if not _is_num(obj.get("query_p50_ms")):
                errs.append("signal_plane line missing numeric "
                            "query_p50_ms")
            if not _is_num(obj.get("series")):
                errs.append("signal_plane line missing numeric "
                            "series count")
        elif obj["bench"] == "trace_plane":
            # The line's claim is "the flight recorder tells the
            # truth cheaply": the decomposition-vs-stopwatch verdict,
            # the recorder's own TTFT p50, and the untraced hot-path
            # ratio are all load-bearing.
            decomp = obj.get("decomposition")
            if not (isinstance(decomp, dict)
                    and isinstance(decomp.get("ok"), bool)):
                errs.append("trace_plane line missing boolean "
                            "decomposition.ok")
            if not _is_num(obj.get("ttft_p50_ms")):
                errs.append("trace_plane line missing numeric "
                            "ttft_p50_ms")
            overhead = obj.get("overhead")
            if not (isinstance(overhead, dict)
                    and _is_num(overhead.get("untraced_ratio"))):
                errs.append("trace_plane line missing numeric "
                            "overhead.untraced_ratio")
        elif obj["bench"] == "step_anatomy":
            # The line's claim is "we know where the step wall went and
            # how close to peak the chip ran": the MFU number, the
            # phase partition (which must actually SUM to the step
            # wall — a decomposition that doesn't partition is a
            # narrative, not an accounting), and the cost-model-vs-
            # measured agreement verdict are all load-bearing.
            if not _is_num(obj.get("mfu")):
                errs.append("step_anatomy line missing numeric mfu")
            wall = obj.get("step_wall_s")
            phases = obj.get("phases")
            if not _is_num(wall):
                errs.append("step_anatomy line missing numeric "
                            "step_wall_s")
            if not (isinstance(phases, dict) and phases
                    and all(_is_num(v) for v in phases.values())):
                errs.append("step_anatomy line missing numeric "
                            "phases dict")
            elif _is_num(wall):
                total = sum(phases.values())
                if abs(total - wall) > max(1e-6, 0.01 * wall):
                    errs.append(
                        f"step_anatomy phases sum to {total:.6f}s but "
                        f"step_wall_s is {wall:.6f}s — the phases must "
                        f"partition the step wall exactly")
            agreement = obj.get("agreement")
            if not (isinstance(agreement, dict)
                    and isinstance(agreement.get("ok"), bool)):
                errs.append("step_anatomy line missing boolean "
                            "agreement.ok")
        elif obj["bench"] == "serve_latency":
            # A serve latency line must carry both views AND the
            # agreement verdict — a client-only (or server-only) number
            # is exactly the uncross-checked claim this bench exists to
            # prevent.
            client = obj.get("client")
            server = obj.get("server")
            if not (isinstance(client, dict)
                    and _is_num(client.get("p50_ms"))
                    and _is_num(client.get("p99_ms"))):
                errs.append("serve_latency line missing numeric "
                            "client.p50_ms/p99_ms")
            if not (isinstance(server, dict)
                    and _is_num(server.get("count"))):
                errs.append("serve_latency line missing server.count")
            agreement = obj.get("agreement")
            if not (isinstance(agreement, dict)
                    and isinstance(agreement.get("ok"), bool)):
                errs.append("serve_latency line missing boolean "
                            "agreement.ok")
    elif "script" in obj:
        if obj["script"] not in ("bench", "tpu_sweep"):
            errs.append(f"unknown script {obj['script']!r}")
        if not isinstance(obj.get("config"), str):
            errs.append("script line missing 'config'")
        if not any(_is_num(obj.get(k))
                   for k in ("tok_s", "tokens_per_sec_per_chip")):
            errs.append("script line missing tok_s/"
                        "tokens_per_sec_per_chip")
        if not any(_is_num(obj.get(k)) for k in ("mfu", "value")):
            errs.append("script line missing mfu/value")
    else:
        errs.append("neither a header ('schema'), a throughput point "
                    "('script'), nor a named bench ('bench')")
    return errs


def check_file(path: str) -> list[str]:
    """All schema violations in an evidence file, as 'line N: why'
    strings ([] = the file passes)."""
    problems: list[str] = []
    with open(path) as f:
        for n, line in enumerate(f, 1):
            if not line.strip():
                problems.append(f"line {n}: blank line")
                continue
            try:
                obj = json.loads(line)
            except ValueError as e:
                problems.append(f"line {n}: invalid JSON ({e})")
                continue
            problems.extend(
                f"line {n}: {err}"
                for err in check_line(obj, allow_header=n == 1))
    return problems


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="On-chip benchmark evidence trail tools")
    ap.add_argument("--check", action="store_true",
                    help="validate every line of the evidence file "
                         "against the expected schema; exit 1 on any "
                         "malformed line")
    ap.add_argument("--regress", metavar="FRESH", default=None,
                    help="perf-regression sentinel: diff a fresh "
                         "perfsuite artifact (MICROBENCH-shaped JSON) "
                         "against the committed MICROBENCH.json; exit "
                         "1 on any gated metric moving past tolerance "
                         "or any committed-true 'ok' verdict going "
                         "false")
    ap.add_argument("--against", metavar="COMMITTED", default=None,
                    help="baseline artifact for --regress (default: "
                         "HEAD's MICROBENCH.json via git, falling back "
                         "to the working-tree file)")
    ap.add_argument("path", nargs="?", default=None,
                    help=f"evidence file (default: committed {FILENAME})")
    args = ap.parse_args(argv)
    if args.regress:
        try:
            with open(args.regress) as f:
                fresh = json.load(f)
        except (OSError, ValueError) as e:
            print(f"bench_log regress: cannot read fresh artifact "
                  f"{args.regress}: {e}")
            return 1
        if args.against:
            try:
                with open(args.against) as f:
                    committed = json.load(f)
            except (OSError, ValueError) as e:
                print(f"bench_log regress: cannot read baseline "
                      f"{args.against}: {e}")
                return 1
        else:
            committed = _committed_microbench()
            if committed is None:
                print("bench_log regress: no committed MICROBENCH.json "
                      "to diff against — nothing to gate")
                return 0
        problems = regress_check(fresh, committed)
        if problems:
            for p in problems:
                print(f"bench_log regress: {p}")
            print(f"bench_log regress: FAIL ({len(problems)} "
                  f"regression(s) vs committed artifact)")
            return 1
        print("bench_log regress: OK (no gated metric regressed, no "
              "committed verdict went false)")
        return 0
    if not args.check:
        ap.error("nothing to do (pass --check or --regress)")
    path = args.path or default_path()
    try:
        problems = check_file(path)
    except OSError as e:
        print(f"bench_log check: cannot read {path}: {e}")
        return 1
    if problems:
        for p in problems:
            print(f"bench_log check: {p}")
        print(f"bench_log check: FAIL ({len(problems)} problem(s) in "
              f"{path})")
        return 1
    with open(path) as f:
        n_lines = sum(1 for _ in f)
    print(f"bench_log check: OK ({n_lines} line(s) in {path})")
    return 0


def record_gang_recovery(pg_reschedule_ms: float, *,
                         trigger: str = "drain",
                         bundles: int = 0, bundles_lost: int = 0,
                         device: str = "", path: str | None = None,
                         **extra) -> dict:
    """Gang-recovery MTTR evidence (``scripts/drain_bench.py`` gang
    probe): wall milliseconds from a gang bundle losing its node (drain
    initiated / node killed) to the placement group's reservation being
    whole again on healthy nodes — the reschedule coordinator's
    end-to-end latency, the number the elastic-fleet goodput envelope
    stands on. Committed to the evidence trail only on a real
    (accelerator) cluster; returns the entry (with ``committed_to``)
    either way."""
    entry = {
        "bench": "gang_recovery",
        "device": device,
        "trigger": str(trigger),
        "pg_reschedule_ms": round(float(pg_reschedule_ms), 1),
        "bundles": int(bundles),
        "bundles_lost": int(bundles_lost),
    }
    entry.update(extra)
    entry["committed_to"] = record_if_on_chip(dict(entry), path)
    return entry


def record_drain_recovery(proactive_drain_ms: float,
                          crash_detection_ms: float, *,
                          device: str = "", path: str | None = None,
                          **extra) -> dict:
    """Drain-vs-crash actor recovery latency evidence
    (``scripts/drain_bench.py``): how long until an actor lost from a
    departing node is ALIVE on another node, proactive drain vs
    heartbeat-timeout crash detection. Committed to the evidence trail
    only when run on a real (accelerator) cluster; returns the entry
    (with ``committed_to``) either way so callers print the same record
    that lands in the trail."""
    entry = {
        "bench": "drain_recovery_ms",
        "device": device,
        "proactive_drain_ms": round(float(proactive_drain_ms), 1),
        "crash_detection_ms": round(float(crash_detection_ms), 1),
        "speedup": round(
            float(crash_detection_ms) / max(float(proactive_drain_ms),
                                            1e-9), 2),
    }
    entry.update(extra)
    entry["committed_to"] = record_if_on_chip(dict(entry), path)
    return entry


def record_step_anatomy(*, mfu: float, phases: dict, step_wall_s: float,
                        agreement: dict, straggler: dict | None = None,
                        device: str = "", path: str | None = None,
                        **extra) -> dict:
    """Step-anatomy evidence (``scripts/anatomy_bench.py``): the
    cost-model MFU, the exact phase partition of one step's wall
    (data_wait / host / compute / sync must sum to ``step_wall_s``),
    the cost-model-vs-measured agreement verdict, and — when a seeded
    straggler ran — the attribution verdict. Committed to the evidence
    trail only on a real accelerator; returns the entry (with
    ``committed_to``) either way."""
    entry: dict = {
        "bench": "step_anatomy",
        "device": device,
        "mfu": round(float(mfu), 2),
        "step_wall_s": float(step_wall_s),
        "phases": {k: float(v) for k, v in dict(phases).items()},
        "agreement": dict(agreement),
    }
    if straggler:
        entry["straggler"] = dict(straggler)
    entry.update(extra)
    entry["committed_to"] = record_if_on_chip(dict(entry), path)
    return entry


# --------------------------------------------------------------------------
# Perf-regression sentinel (round 19): diff a fresh perfsuite artifact
# against the committed MICROBENCH.json. A perf number nobody compares
# is a perf number that silently rots — this is the comparison, run as
# the last perfsuite stage and as
# ``python -m ray_tpu.scripts.bench_log --regress FRESH [--against OLD]``.
# --------------------------------------------------------------------------

# Numeric gates: dotted section path -> (direction, relative tolerance).
# direction "higher" = the committed value is a floor (fresh may not
# drop more than tol below it); "lower" = a ceiling (fresh may not rise
# more than tol above it). Tolerances are deliberately loose — the
# sentinel exists to catch the 2x cliff nobody noticed, not to flake on
# scheduler jitter.
REGRESS_GATES: dict[str, tuple[str, float]] = {
    "step_anatomy.mfu": ("higher", 0.25),
    "step_anatomy.step_wall_s": ("lower", 0.25),
    "step_anatomy.cost_model.flops_ratio": ("lower", 0.25),
    "goodput.goodput_pct": ("higher", 0.15),
    "serve_latency.client.p99_ms": ("lower", 0.50),
    "signal_plane.query_p50_ms": ("lower", 0.50),
    "trace_plane.ttft_p50_ms": ("lower", 0.50),
}


def _dig(obj, dotted: str):
    for part in dotted.split("."):
        if not isinstance(obj, dict) or part not in obj:
            return None
        obj = obj[part]
    return obj


def _ok_paths(obj, prefix: str = "") -> dict[str, bool]:
    """Every boolean-valued 'ok' key in a nested artifact, by dotted
    path — the generic invariant: a check that passed in the committed
    artifact must not start failing in a fresh run."""
    out: dict[str, bool] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            p = f"{prefix}.{k}" if prefix else str(k)
            if k == "ok" and isinstance(v, bool):
                out[p] = v
            else:
                out.update(_ok_paths(v, p))
    return out


def regress_check(fresh: dict, committed: dict) -> list[str]:
    """Regressions in a fresh perfsuite artifact relative to the
    committed one ([] = clean). Two rules: (1) numeric gates — a
    REGRESS_GATES metric present in BOTH artifacts must not move in the
    bad direction by more than its relative tolerance; (2) verdict
    preservation — any boolean 'ok' that is true in the committed
    artifact and present in the fresh one must still be true. Sections
    or metrics absent from either side are skipped (a fresh artifact
    that only ran one stage gates only that stage)."""
    problems: list[str] = []
    for dotted, (direction, tol) in REGRESS_GATES.items():
        old = _dig(committed, dotted)
        new = _dig(fresh, dotted)
        if not (_is_num(old) and _is_num(new)) or old == 0:
            continue
        if direction == "higher":
            floor = old * (1.0 - tol)
            if new < floor:
                problems.append(
                    f"{dotted}: {new:.4g} fell below committed "
                    f"{old:.4g} by more than {tol:.0%} "
                    f"(floor {floor:.4g})")
        else:
            ceil = old * (1.0 + tol)
            if new > ceil:
                problems.append(
                    f"{dotted}: {new:.4g} rose above committed "
                    f"{old:.4g} by more than {tol:.0%} "
                    f"(ceiling {ceil:.4g})")
    fresh_oks = _ok_paths(fresh)
    for path, was_ok in _ok_paths(committed).items():
        if was_ok and fresh_oks.get(path) is False:
            problems.append(
                f"{path}: was true in the committed artifact, false "
                f"in the fresh run")
    return problems


def _committed_microbench() -> dict | None:
    """The committed MICROBENCH.json — preferring HEAD's copy via git
    (so a fresh-run-overwritten working file still diffs against what
    was actually committed), falling back to the working tree."""
    import subprocess

    root = os.path.dirname(default_path())
    try:
        out = subprocess.run(
            ["git", "show", "HEAD:MICROBENCH.json"], cwd=root,
            capture_output=True, text=True, timeout=30)
        if out.returncode == 0 and out.stdout.strip():
            return json.loads(out.stdout)
    except Exception:
        pass
    try:
        with open(os.path.join(root, "MICROBENCH.json")) as f:
            return json.load(f)
    except Exception:
        return None


if __name__ == "__main__":
    import sys

    sys.exit(main())
