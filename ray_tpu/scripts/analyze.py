"""`ray-tpu analyze` driver: the concurrency & contract static gate.

Runs the ``ray_tpu.util.analyze`` passes over the package (or explicit
paths), applies the committed ``ANALYZE_BASELINE.json`` allowlist, and
exits non-zero on any NEW finding — the same contract as
``bench_log --check``: drift fails loud, at review time, not at 3am in
a chaos soak.

Usage:
    python -m ray_tpu.scripts.analyze [paths...]
        [--rule lock-order|blocking|finalizer|async-lock|contracts
               |retry|daemon-loop|timeout-order|jax-hotpath|lifecycle]...
        [--no-baseline] [--baseline-file F] [--json]
        [--diff REV]           # only findings on lines changed since REV
        [--write-baseline]     # re-emit the baseline from current findings
        [--out MICROBENCH.json]  # merge-preserve an `analyze` section
                                 # (the perfsuite stage)

Baseline workflow: a justified finding is allowlisted by adding its
stable key (printed with --json, or by --write-baseline) to
ANALYZE_BASELINE.json with a one-line justification as the value.
Stale keys (matching nothing) are reported so the allowlist only ever
shrinks.
"""

from __future__ import annotations

import argparse
import json
import sys

from ray_tpu.util import analyze
from ray_tpu.util.analyze import core as _core


def _write_baseline(result: dict, path: str,
                    existing: dict) -> None:
    entries = {}
    for f in result["findings"]:
        entries[f.key] = existing.get(
            f.key, "TODO: one-line justification")
    with open(path, "w") as fh:
        json.dump({
            "_comment": (
                "ray-tpu analyze allowlist: finding key -> one-line "
                "justification. Only findings ABSENT from this file "
                "fail the run; stale keys are reported so the list "
                "only shrinks. Justify every entry."),
            "entries": dict(sorted(entries.items())),
        }, fh, indent=1)
        fh.write("\n")


def _merge_out(result: dict, out_path: str) -> None:
    """Merge-preserve an `analyze` section into MICROBENCH.json (the
    perfsuite stage): rule counts are the trend the suite tracks —
    the gate itself is the exit code."""
    import os
    import time

    artifact = {}
    if os.path.exists(out_path):
        try:
            with open(out_path) as fh:
                artifact = json.load(fh)
        except ValueError:
            artifact = {}
    artifact["analyze"] = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "files_scanned": result["n_files"],
        "passes": sorted(analyze.PASSES),
        "rule_counts": result["rule_counts"],
        "new_rule_counts": result["new_rule_counts"],
        "baselined": len(result["allowed"]),
        "new": len(result["new"]),
        "stale_baseline": len(result["stale_baseline"]),
        "ok": result["ok"],
    }
    with open(out_path, "w") as fh:
        json.dump(artifact, fh, indent=1, sort_keys=True)
        fh.write("\n")
    # Timestamped trail line too (committed only on an accelerator —
    # the on-chip perf session records that its tree passed the gate).
    try:
        from ray_tpu.scripts import bench_log

        bench_log.record_analyze(
            rule_counts=result["rule_counts"],
            new=len(result["new"]),
            baselined=len(result["allowed"]),
            stale_baseline=len(result["stale_baseline"]),
            ok=result["ok"],
            device=bench_log.device_kind(),
        )
    except Exception:
        pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ray-tpu analyze",
        description="concurrency & contract static analysis")
    ap.add_argument("paths", nargs="*",
                    help="files to analyze (default: the ray_tpu "
                         "package)")
    ap.add_argument("--rule", action="append", dest="rules",
                    metavar="NAME",
                    help="run only this pass (repeatable); one of: "
                         + ", ".join(sorted(analyze.PASSES)))
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore ANALYZE_BASELINE.json (show "
                         "everything)")
    ap.add_argument("--baseline", action="store_true",
                    help="(default) apply the committed baseline "
                         "allowlist — kept as an explicit flag for "
                         "scripts")
    ap.add_argument("--baseline-file", default=None)
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings (with stable "
                         "baseline keys)")
    ap.add_argument("--diff", metavar="REV", default=None,
                    help="only findings on lines changed since REV "
                         "(git diff -U0 parse)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write ANALYZE_BASELINE.json from current "
                         "findings (preserves existing justifications)")
    ap.add_argument("--out", default=None, metavar="MICROBENCH",
                    help="merge-preserve an `analyze` rule-count "
                         "section into this artifact (perfsuite stage)")
    args = ap.parse_args(argv)

    if args.write_baseline and (args.paths or args.diff or args.rules):
        # A restricted run only sees a slice of the findings; writing
        # the baseline from it would silently DROP every allowlist
        # entry (and hand-written justification) outside the slice.
        print("analyze: --write-baseline requires a full repo-wide run "
              "(no explicit paths, no --diff, no --rule)",
              file=sys.stderr)
        return 2

    try:
        result = analyze.run(
            paths=args.paths or None,
            rules=args.rules,
            use_baseline=not args.no_baseline,
            baseline_file=args.baseline_file,
            diff_rev=args.diff,
        )
    except (ValueError, RuntimeError) as e:
        print(f"analyze: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        path = args.baseline_file or _core.baseline_path()
        existing = _core.load_baseline(path)
        _write_baseline(result, path, existing)
        print(f"analyze: wrote {len(result['findings'])} entries to "
              f"{path}")
        return 0

    if args.out:
        _merge_out(result, args.out)

    if args.as_json:
        print(json.dumps({
            "ok": result["ok"],
            "rule_counts": result["rule_counts"],
            "new": [f.to_dict() for f in result["new"]],
            "baselined": [f.to_dict() for f in result["allowed"]],
            "stale_baseline": result["stale_baseline"],
        }, indent=1))
    else:
        for f in result["new"]:
            print(f.format())
        for key in result["stale_baseline"]:
            print(f"stale baseline entry (matches nothing — remove "
                  f"it): {key}")
        n_new = len(result["new"])
        n_base = len(result["allowed"])
        scanned = "diff-restricted" if args.diff else "repo"
        verdict = "OK" if result["ok"] else "FAIL"
        print(f"analyze: {verdict} ({scanned}: {n_new} new finding(s), "
              f"{n_base} baselined, "
              f"{len(result['stale_baseline'])} stale baseline "
              f"key(s))")
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
