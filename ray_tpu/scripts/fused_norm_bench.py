"""Fused-norm kernel microbench point (perfsuite ``--fused-norm``).

Pins the ``ops/fused_norm.py`` kernels' shape coverage into
MICROBENCH.json machine-independently: per shape it records the chosen
row block, the number of Pallas kernel launches in a fwd+bwd trace
(trace-time counters — wall-clock-free), the fp32 bytes the fused path
keeps out of HBM per step (saved-statistics vs XLA's materialized fp32
recompute chain), and fwd/grad parity error vs the plain-XLA chain.
Kernel-only µs (CPU interpret vs the XLA fusion, jitted, best-of-N) ride
along for relative sanity only — interpret-mode wall time is NOT a TPU
perf claim; the on-chip numbers come from ``tpu_sweep``.

Run: python -m ray_tpu.scripts.fused_norm_bench [--out MICROBENCH.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# CPU-interpret benchmark by design: force the platform regardless of
# any site TPU plugin env (JAX_PLATFORMS=axon etc.), same as
# pipeline_bench — this stage pins shape coverage, not TPU speed.
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from ray_tpu.ops import fused_norm as fn  # noqa: E402

# (name, kind, rows, d): the GPT-2-small / Llama-small shapes the models
# feed the kernels, plus one deliberately untileable shape to pin the
# fallback contract.
SHAPES = [
    ("gpt2_ln_768", "ln", 256, 768),
    ("llama_rms_1024", "rms", 256, 1024),
    ("gpt2_gelu_3072", "gelu", 256, 3072),
    ("odd_d100_fallback", "ln", 64, 100),
]


def _time_us(f, *args, reps: int = 5) -> float:
    g = jax.jit(f)
    jax.block_until_ready(g(*args))  # compile outside the timed reps
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = g(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return round(best * 1e6, 1)


def bench_point(kind: str, rows: int, d: int) -> dict:
    ks = jax.random.split(jax.random.key(0), 3)
    x = jax.random.normal(ks[0], (rows, d), jnp.float32)
    scale = jax.random.normal(ks[1], (d,), jnp.float32) * 0.1 + 1.0
    bias = jax.random.normal(ks[2], (d,), jnp.float32) * 0.1

    if kind == "ln":
        fused = lambda a: jax.value_and_grad(  # noqa: E731
            lambda b: jnp.sum(fn.fused_layer_norm(b, scale, bias)))(a)
        ref = lambda a: jax.value_and_grad(  # noqa: E731
            lambda b: jnp.sum(fn.ref_layer_norm(b, scale, bias)))(a)
        stats_bytes_per_row = 8      # fp32 mu + rstd
    elif kind == "rms":
        fused = lambda a: jax.value_and_grad(  # noqa: E731
            lambda b: jnp.sum(fn.fused_rms_norm(b, scale)))(a)
        ref = lambda a: jax.value_and_grad(  # noqa: E731
            lambda b: jnp.sum(fn.ref_rms_norm(b, scale)))(a)
        stats_bytes_per_row = 4      # fp32 rstd
    else:
        fused = lambda a: jax.value_and_grad(  # noqa: E731
            lambda b: jnp.sum(fn.fused_gelu(b)))(a)
        ref = lambda a: jax.value_and_grad(  # noqa: E731
            lambda b: jnp.sum(fn.ref_gelu(b)))(a)
        stats_bytes_per_row = 0      # saves the pre-activation it gets

    block = fn._should_fuse(rows, d, jnp.float32)
    before = dict(fn.KERNEL_INVOCATIONS)
    loss_f, grad_f = fused(x)
    launches = sum(fn.KERNEL_INVOCATIONS.values()) \
        - sum(before.values())
    loss_r, grad_r = ref(x)

    entry = {
        "rows": rows,
        "d": d,
        "fused": block is not None,
        "row_block": block,
        "grid_cells": (rows // block) if block else 0,
        # One fwd+bwd trace's Pallas launches (0 == XLA fallback).
        "kernel_launches": launches,
        # fp32 bytes/step the fused path keeps out of HBM: XLA
        # materializes the fp32 recompute chain (x32 [R, D]) for
        # backward; the kernel saves only the per-row statistics.
        "fp32_roundtrip_saved_bytes": (rows * d * 4
                                       - rows * stats_bytes_per_row)
        if block else 0,
        "loss_abs_err": float(jnp.abs(loss_f - loss_r)),
        "grad_max_err": float(jnp.abs(grad_f - grad_r).max()),
        # CPU-interpret relative timing only — not a TPU perf claim.
        "interpret_us": {
            "fused_fwd_bwd": _time_us(fused, x),
            "xla_fwd_bwd": _time_us(ref, x),
        },
    }
    return entry


def run_all() -> dict:
    assert jax.default_backend() == "cpu", "microbench pins CPU interpret"
    return {name: bench_point(kind, rows, d)
            for name, kind, rows, d in SHAPES}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="MICROBENCH.json")
    args = ap.parse_args()
    results = run_all()
    # Merge-preserve: every perfsuite stage owns one section of the
    # artifact (same contract as microbench/scalebench).
    payload = {}
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            payload = {}
    payload["fused_norm"] = {
        "cmd": " ".join(sys.argv),
        "shapes": results,
    }
    with open(args.out, "w") as f:
        # Match perfsuite's final-dump format exactly (indent=1,
        # sorted): whichever tool runs last must not reflow the whole
        # committed artifact into an unreviewable whitespace diff.
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({"fused_norm": results}))


if __name__ == "__main__":
    main()
