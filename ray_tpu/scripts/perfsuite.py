"""Canonical control-plane perf artifact: ONE command, ONE file.

Round-4 verdict weak #1: committed perf numbers disagreed because
microbench and scalebench ran at different times and SCALING.md's table
was hand-copied. This driver runs microbench + scalebench +
pipeline_bench back-to-back in one invocation, stamps every section with
a shared timestamp + host config, writes the single merged
MICROBENCH.json, and REGENERATES the measured table inside SCALING.md
from that artifact (between the GENERATED markers) so the doc can never
drift from the data again.

Usage:
    python -m ray_tpu.scripts.perfsuite [--out MICROBENCH.json]
        [--scaling-md SCALING.md] [--nodes 16] [--cpus 2]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

BEGIN = "<!-- BEGIN GENERATED perf table (perfsuite.py) -->"
END = "<!-- END GENERATED perf table -->"


def _host_meta() -> dict:
    return {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "cpu_count": os.cpu_count(),
        "loadavg_1m": round(os.getloadavg()[0], 2),
    }


def _render_table(artifact: dict) -> str:
    """The measured-numbers table SCALING.md embeds, straight from the
    artifact (no hand-copied values)."""
    m = artifact.get("metrics", {})
    s = artifact.get("scalability", {})
    h = artifact.get("head_scale", {})
    p = artifact.get("pipeline", {})
    meta = artifact.get("meta", {})

    def mv(key):
        e = m.get(key)
        return f"{e['value']:,.1f} {e['unit']}" if e else "—"

    def sv(key):
        e = s.get(key)
        return f"{e['value']:,.1f} {e['unit']}" if e else "—"

    def hv(key):
        e = h.get(key)
        return f"{e['value']:,.1f} {e['unit']}" if e else "—"

    lines = [
        BEGIN,
        f"*Regenerated {meta.get('ts', '?')} on cpu_count="
        f"{meta.get('cpu_count', '?')}, load {meta.get('loadavg_1m', '?')}"
        f" — `python -m ray_tpu.scripts.perfsuite`.*",
        "",
        f"| Metric | 2 nodes (microbench) | "
        f"{s.get('nodes', '?')} nodes (scalebench) |",
        "|---|---|---|",
        f"| tasks sync | {mv('tasks_sync_per_s')} | — |",
        f"| tasks async burst | {mv('tasks_async_per_s')} | "
        f"{sv('burst_tasks_per_s')} (submit {sv('burst_submit_per_s')}) |",
        f"| actor calls sync | {mv('actor_calls_sync_per_s')} | — |",
        f"| actor calls async | {mv('actor_calls_async_per_s')} | — |",
        f"| actor 1:n | {mv('actor_calls_1_to_n_per_s')} | — |",
        f"| actor create+call | — | {sv('actor_create_call_per_s')} |",
        f"| put small | {mv('put_small_per_s')} | — |",
        f"| get small | {mv('get_small_per_s')} | — |",
        f"| put GiB/s | {mv('put_gib_per_s')} | — |",
        f"| get GiB/s | {mv('get_gib_per_s')} | — |",
        f"| 64 MiB arg pass | {mv('task_arg_64mib_ms')} | — |",
        f"| broadcast | — | {sv('broadcast_agg_gib_per_s')} aggregate "
        f"({sv('broadcast_object_gib')} object) |",
        f"| cluster boot | — | {sv('cluster_boot_s')} |",
    ]
    if s.get("queued_pending"):
        n_pending = s["queued_pending"].get("value", 0)
        lines += [
            "",
            f"| Parked-queue audit ({n_pending:,.0f} infeasible specs) | |",
            "|---|---|",
            f"| submit into client queue | {sv('queued_submit_per_s')} |",
            f"| steady-state head schedule RPCs | "
            f"{sv('queued_sched_rpcs_per_s')} |",
            f"| feasible probe latency under backlog | "
            f"{sv('queued_probe_latency_s')} |",
            f"| driver RSS growth | {sv('queued_rss_growth_mb')} |",
            f"| shutdown (fails whole backlog) | "
            f"{sv('queued_shutdown_s')} |",
        ]
    if h:
        lines += [
            "",
            f"| Head at scale ({h.get('nodes', 0)} nodes, "
            f"{h.get('queued', 0):,} queued, {h.get('actors', 0):,} "
            f"actors, {h.get('subscribers', 0)} slow subscribers) "
            f"| rate |",
            "|---|---|",
            f"| heartbeats | {hv('heartbeats_per_s')} |",
            f"| status polls (cached totals) | {hv('status_polls_per_s')} |",
            f"| schedule_batch, feasible | {hv('sched_feasible_per_s')} |",
            f"| schedule_batch, infeasible | "
            f"{hv('sched_infeasible_per_s')} |",
            f"| borrow registrations | {hv('ref_begin_per_s')} |",
            f"| location adds | {hv('add_location_per_s')} |",
            f"| actor register | {hv('actor_register_per_s')} |",
            f"| actor FSM updates (pubsub) | {hv('actor_updates_per_s')} |",
            f"| pubsub coalesced / dropped | {hv('pubsub_coalesced')} / "
            f"{hv('pubsub_dropped')} |",
            f"| spans dropped at cap | {hv('span_dropped')} |",
            f"| persist writes coalesced | {hv('persist_coalesced')} |",
            f"| head RSS growth | {hv('rss_growth_mb')} |",
            f"| head handler CPU total | {hv('head_handler_total_s')} |",
        ]
    if p:
        lines += [
            "",
            "| Pipeline (CPU, 8 virt devices) | step ms | ticks | "
            "bubble | XLA temp MiB |",
            "|---|---|---|---|---|",
        ]
        for key in sorted(p):
            e = p[key]
            lines.append(
                f"| {key} | {e['step_ms']} | {e['ticks']} | "
                f"{e['bubble_frac']} | {e['xla_temp_mb']} |")
    lines.append(END)
    return "\n".join(lines)


def _update_scaling_md(path: str, artifact: dict) -> None:
    table = _render_table(artifact)
    text = ""
    if os.path.exists(path):
        with open(path) as f:
            text = f.read()
    if BEGIN in text and END in text:
        pre, rest = text.split(BEGIN, 1)
        _, post = rest.split(END, 1)
        text = pre + table + post
    else:
        text = text.rstrip() + "\n\n## Measured (generated)\n\n" \
            + table + "\n"
    with open(path, "w") as f:
        f.write(text)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="MICROBENCH.json")
    ap.add_argument("--scaling-md", default="SCALING.md")
    ap.add_argument("--nodes", type=int, default=16)
    ap.add_argument("--cpus", type=int, default=2)
    ap.add_argument("--tasks", type=int, default=2000)
    ap.add_argument("--actors", type=int, default=200)
    ap.add_argument("--broadcast-mb", type=int, default=256)
    ap.add_argument("--queued", type=int, default=0,
                    help="parked-queue audit depth for scalebench")
    ap.add_argument("--skip-head-scale", action="store_true")
    ap.add_argument("--skip-pipeline", action="store_true")
    ap.add_argument("--skip-analyze", action="store_true",
                    help="skip the static-analysis gate stage (runs by "
                         "default: cheap, and a perf artifact from a "
                         "tree with unbaselined concurrency findings "
                         "is not evidence)")
    ap.add_argument("--fused-norm", action="store_true",
                    help="add the fused-norm kernel microbench point "
                         "(CPU interpret shape coverage + op counts)")
    ap.add_argument("--serve", action="store_true",
                    help="add the serve request-path point "
                         "(concurrent-stream harness + client/server "
                         "latency cross-check)")
    ap.add_argument("--llm", action="store_true",
                    help="add the continuous-batching LLM serving "
                         "point (concurrent token streams + TTFT "
                         "cross-check + single-compiled-shape "
                         "assertion; machine-independent step/churn/"
                         "shed counts)")
    ap.add_argument("--llm-streams", type=int, default=400,
                    help="stream count for the --llm stage (the full "
                         "10k envelope runs via serve_bench --llm "
                         "directly)")
    ap.add_argument("--input-pipeline", action="store_true",
                    dest="input_pipeline",
                    help="add the training-goodput point "
                         "(dataset->iterator->train-step harness + "
                         "client/server stall-fraction cross-check)")
    ap.add_argument("--signals", action="store_true",
                    help="add the signal-plane point (windowed-query "
                         "agreement vs client ledger + bounded-ring "
                         "memory proof + seeded SLO burn with exactly "
                         "one burning and one recovery pubsub event)")
    ap.add_argument("--traces", action="store_true",
                    help="add the trace-plane point (TTFT "
                         "decomposition vs the client stopwatch, "
                         "bounded assembly store, tracing hot-path "
                         "overhead ratios)")
    ap.add_argument("--anatomy", action="store_true",
                    help="add the step-anatomy point (cost-model-vs-"
                         "analytic FLOPs agreement on two model "
                         "families, exact phase partition, seeded-"
                         "straggler attribution) and run the perf-"
                         "regression sentinel against the committed "
                         "artifact as the final stage")
    ap.add_argument("--dataflow", action="store_true",
                    help="add the streaming-dataflow point "
                         "(generation->training pipeline past store "
                         "capacity: split/spill/restore/pool counts + "
                         "client/metrics stall cross-check)")
    args = ap.parse_args()

    # Each stage runs in its own subprocess: benchmark isolation (no
    # leaked cluster state between stages) and jax platform independence
    # (pipeline_bench forces cpu).
    env = dict(os.environ)
    steps = []
    if not args.skip_analyze:
        # Gate first: rule counts land in the artifact's `analyze`
        # section (merge-preserve), and an unbaselined finding fails
        # the whole suite before any bench burns time.
        steps.append([sys.executable, "-m", "ray_tpu.scripts.analyze",
                      "--out", args.out])
    steps += [
        [sys.executable, "-m", "ray_tpu.scripts.microbench",
         "--out", args.out],
        [sys.executable, "-m", "ray_tpu.scripts.scalebench",
         "--nodes", str(args.nodes), "--cpus", str(args.cpus),
         "--tasks", str(args.tasks), "--actors", str(args.actors),
         "--broadcast-mb", str(args.broadcast_mb),
         "--queued", str(args.queued), "--out", args.out]
        + ([] if args.skip_head_scale else ["--head-scale"]),
    ]
    if not args.skip_pipeline:
        steps.append([sys.executable, "-m",
                      "ray_tpu.scripts.pipeline_bench", "--out", args.out])
    if args.fused_norm:
        steps.append([sys.executable, "-m",
                      "ray_tpu.scripts.fused_norm_bench", "--out", args.out])
    if args.serve:
        steps.append([sys.executable, "-m",
                      "ray_tpu.scripts.serve_bench", "--out", args.out])
    if args.llm:
        steps.append([sys.executable, "-m",
                      "ray_tpu.scripts.serve_bench", "--llm",
                      "--streams", str(args.llm_streams),
                      "--out", args.out])
    if args.input_pipeline:
        steps.append([sys.executable, "-m",
                      "ray_tpu.scripts.input_bench", "--out", args.out])
    if args.dataflow:
        steps.append([sys.executable, "-m",
                      "ray_tpu.scripts.dataflow_bench", "--out", args.out])
    if args.signals:
        steps.append([sys.executable, "-m",
                      "ray_tpu.scripts.signal_bench", "--out", args.out])
    if args.traces:
        steps.append([sys.executable, "-m",
                      "ray_tpu.scripts.trace_bench", "--out", args.out])
    if args.anatomy:
        steps.append([sys.executable, "-m",
                      "ray_tpu.scripts.anatomy_bench", "--out", args.out])
        # Sentinel last: diff the fresh artifact (every section above
        # has landed in --out by now) against the committed
        # MICROBENCH.json; a regression fails the suite.
        steps.append([sys.executable, "-m",
                      "ray_tpu.scripts.bench_log", "--regress", args.out])
    for argv in steps:
        print(f"perfsuite: {' '.join(argv[2:])}", file=sys.stderr,
              flush=True)
        rc = subprocess.run(argv, env=env).returncode
        if rc != 0:
            print(f"perfsuite: stage failed rc={rc}", file=sys.stderr)
            sys.exit(rc)
    with open(args.out) as f:
        artifact = json.load(f)
    artifact["meta"] = {**artifact.get("meta", {}), **_host_meta(),
                        "cmd": "python -m ray_tpu.scripts.perfsuite"}
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    if args.scaling_md:
        _update_scaling_md(args.scaling_md, artifact)
        print(f"perfsuite: updated {args.scaling_md}", file=sys.stderr)
    print(json.dumps({"ok": True, "out": args.out,
                      **artifact.get("meta", {})}))


if __name__ == "__main__":
    main()
