"""Shared GPT-2 throughput-measurement harness.

ONE definition of the timed-step protocol (steps / warmup / sync /
tok-s / FLOPs accounting) used by both the headline ``bench.py`` and
the ablation ``scripts/tpu_sweep.py`` — previously each re-implemented
its own 20-step loop and they could silently drift. Also owns the
per-chip peak-FLOPs table (MFU denominators) and the error-JSON shape
(full traceback tail, not a 200-char repr) so every measurement error
in the evidence trail is debuggable after the tunnel window closes.
"""

from __future__ import annotations

import time
import traceback

# bf16 peak TFLOP/s per chip by device kind substring.
PEAK_TFLOPS = {
    "v5 lite": 197.0,
    "v5litepod": 197.0,
    "v5e": 197.0,
    "v4": 275.0,
    "v5p": 459.0,
    "v6 lite": 918.0,
    "v6e": 918.0,
    "cpu": 0.5,  # nominal, so the harness still runs off-TPU
}

DEFAULT_PEAK = 197.0e12  # unknown accelerator: assume v5e


def peak_flops_per_chip(device_kind: str) -> float:
    kind = device_kind.lower()
    for key, tf in PEAK_TFLOPS.items():
        if key in kind:
            return tf * 1e12
    return DEFAULT_PEAK


def measure_gpt2(cfg, batch: int, *, steps: int = 20, warmup: int = 3,
                 mesh=None) -> dict:  # step-timed
    """Timed GPT-2 train-step loop -> measurement dict.

    Builds the sharded state on ``mesh`` (default: fsdp over all local
    devices), runs ``warmup`` steps, forces a device->host sync (a
    ``float()`` of the loss — ``block_until_ready`` alone is not
    reliable on experimental backends), then times ``steps`` steps.

    Returns {tok_s, ms_step, loss, dt, steps, warmup, batch, mfu} where
    ``mfu`` is computed against this host's device peak (one chip's
    peak x device count).
    """
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.gpt2 import (
        gpt2_flops_per_token,
        gpt2_init,
        gpt2_loss,
        gpt2_shardings,
    )
    from ray_tpu.parallel.mesh import MeshConfig, build_mesh
    from ray_tpu.train.train_step import make_init_fn, make_train_step

    warmup = max(warmup, 1)  # >=1: the post-warmup sync reads metrics
    if mesh is None:
        mesh = build_mesh(MeshConfig(fsdp=-1))
    shardings = gpt2_shardings(cfg, mesh)
    init_fn = make_init_fn(lambda r: gpt2_init(r, cfg), shardings, mesh)
    state = init_fn(jax.random.key(0))
    step_fn = make_train_step(
        lambda p, b: gpt2_loss(p, b, cfg), shardings, mesh)
    tokens = jax.random.randint(
        jax.random.key(1), (batch, cfg.seq_len + 1), 0, cfg.vocab_size,
        jnp.int32,
    )
    batch_data = {"tokens": tokens}
    for _ in range(warmup):
        state, metrics = step_fn(state, batch_data)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, batch_data)
    loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    tok_s = batch * cfg.seq_len * steps / dt
    n_dev = jax.device_count()
    peak = peak_flops_per_chip(jax.devices()[0].device_kind) * n_dev
    mfu = tok_s * gpt2_flops_per_token(cfg) / peak * 100.0
    return {
        "tok_s": round(tok_s, 1),
        "mfu": round(mfu, 2),
        "ms_step": round(dt / steps * 1000, 2),
        "loss": round(loss, 3),
        "dt": dt,
        "steps": steps,
        "warmup": warmup,
        "batch": batch,
    }


def error_entry(exc: BaseException, *, tb_chars: int = 1500) -> dict:
    """Error fields for a failed measurement point: the repr AND the
    traceback tail, so a one-shot tunnel-window failure is diagnosable
    from the JSON alone."""
    tb = traceback.format_exc()
    if tb is None or tb.strip() in ("", "NoneType: None"):
        tb = "".join(traceback.format_exception(
            type(exc), exc, exc.__traceback__))
    return {
        "error": repr(exc)[:300],
        "traceback_tail": tb[-tb_chars:],
    }
