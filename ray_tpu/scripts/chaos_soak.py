"""Chaos soak harness: a mixed workload under a seeded fault schedule.

The standing adversarial test for the recovery machinery (drain
protocol, lineage re-execution, retry-budget exemption, actor
reconstruction, RPC reconnect windows): drive tasks, restartable
actors, and puts/gets on a multi-node ``Cluster`` while a seeded
scheduler injects faults from ≥4 classes —

  * **partition** — symmetric drop rules head↔victim
    (``Cluster.partition``), healed inside the heartbeat-death window;
  * **delay** — a delay-range rule on every RPC to a victim agent;
  * **sever** — sever-after-send on agent→head traffic (the
    ``maybe_executed`` ambiguity path) at p<1;
  * **kill** — ``Cluster.kill_node`` on a victim (heartbeat-timeout
    death; lineage re-execution + actor reconstruction), with a
    replacement node added so capacity survives;
  * **failpoints** — raise/delay arms at absorbed sites
    (event-batch upload, head snapshot, client ref flush);

plus exactly one graceful drain carrying a ``max_retries=0`` probe task
(the retry-budget-exemption invariant). Everything is derived from ONE
seed (``--seed`` / ``RAY_TPU_CHAOS_SEED``): the same seed replays the
same fault schedule, and the seed is printed on failure.

Invariants checked after the run settles:

  1. every driver-visible result is correct (tasks, actor calls, puts);
  2. the drain-exempt ``max_retries=0`` task completed (budgets burn
     only for non-exempt causes);
  3. ``state.memory_leaks()`` is empty;
  4. the federated ``/metrics/cluster`` body still scrapes;
  5. the head directory is consistent with the agent stores (no
     location on a dead node; per-node store reports join cleanly);
  6. the standing serve probe (a deployment serving throughout the
     soak) completed at least one request, and every probe either
     completed or shed/failed cleanly — a request that HANGS through a
     partition/kill is a lost request the latency plane never saw.

Usage::

    python -m ray_tpu.scripts.chaos_soak --seed 7 --duration 20

``bench_log.record_chaos_soak`` prints the evidence line (committed to
BENCH_TPU_SESSIONS.jsonl only on an accelerator).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import threading
import time


def _device_kind() -> str:
    from ray_tpu.scripts.bench_log import device_kind

    return device_kind()


class _Soak:
    def __init__(self, seed: int, duration_s: float, n_victims: int = 2):
        self.seed = seed
        self.duration_s = duration_s
        self.n_victims = n_victims
        self.rng = random.Random(f"{seed}:soak-schedule")
        self.faults: dict[str, int] = {}
        self.violations: list[str] = []
        self.mttr_ms: list[float] = []
        self.tasks_ok = 0
        self.actor_calls_ok = 0
        self.puts_ok = 0
        self.serve_ok = 0
        self.serve_shed = 0
        self.llm_ok = 0
        self.llm_shed = 0
        self.llm_failed_fast = 0
        self.train_reports = 0
        self.train_goodput: "dict | None" = None
        self.gang_goodput: "dict | None" = None
        self.gang_reschedules = 0
        self.dataflow_ok = 0
        self.dataflow_failed = 0
        self.dataflow_spilled = 0
        self.dataflow_restores = 0
        self.signal_queries_ok = 0
        self.signal_queries_failed = 0
        self.signal_slo_transitions = 0
        self.signal_missed_evals = 0
        self.autoscaler_rounds_ok = 0
        self.autoscaler_rounds_failed = 0
        self.autoscaler_launches = 0
        self.autoscaler_launch_failures = 0
        self.autoscaler_quarantines = 0
        self.autoscaler_scale_downs = 0
        self.autoscaler_preemptions = 0
        self._autoscaler = None
        self._as_provider = None
        self._as_cluster = None
        self._fleet_work = None
        self._stop = threading.Event()
        # The streaming-dataflow probe's small-store node: exempt from
        # kill/drain (its custom resource exists nowhere else, so losing
        # it would just park every later probe round — the harness
        # starving itself, not a system fault); partitions/delays still
        # hit it.
        self._dataflow_node = None
        # The graceful-drain victim: the fault injector must not kill or
        # partition the node the drain (and its retry-exemption probe)
        # is pinned to — that would be the harness racing itself, not a
        # system fault.
        self._drain_victim = None

    # -- fault injection ---------------------------------------------------

    def _probe_mttr(self, fault: str, t_fault: float,
                    victim_node_id: str | None = None) -> None:
        """Time from fault injection to the next successful round trip
        THROUGH the faulted path: pinned to the victim node while it
        lives (default scheduling would stay on the driver's node and
        measure nothing), SPREAD across the survivors after a kill."""
        import ray_tpu
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        @ray_tpu.remote(max_retries=3)
        def _probe():
            return "ok"

        def strategy():
            # Re-evaluated every round: the victim can die (a drain or
            # kill racing this probe) mid-wait, and pinning every
            # remaining round to a corpse would read as a violation.
            if victim_node_id is not None:
                try:
                    if any(n["NodeID"] == victim_node_id and n["Alive"]
                           for n in ray_tpu.nodes()):
                        return NodeAffinitySchedulingStrategy(
                            victim_node_id)
                except Exception:
                    pass
            return "SPREAD"

        # Generous deadline: on a saturated CI box, kill recovery is
        # death-detection (~5s) + worker cold-forks, which stretches
        # arbitrarily under load — a tight bound here reads as a fake
        # invariant violation.
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if self._stop.is_set():
                return  # soak is settling: don't probe a closing cluster
            try:
                ref = _probe.options(
                    scheduling_strategy=strategy()).remote()
                if ray_tpu.get(ref, timeout=10.0) == "ok":
                    self.mttr_ms.append(
                        (time.monotonic() - t_fault) * 1e3)
                    return
            except Exception:
                pass
            time.sleep(0.1)
        if not self._stop.is_set():
            self.violations.append(
                f"{fault}: no successful probe within 120s of injection")

    def _fault_loop(self, cluster) -> None:
        from ray_tpu.cluster.rpc import channel_chaos
        from ray_tpu.util import failpoints

        classes = ["partition", "delay", "sever", "kill", "failpoint"]
        # One kill max per soak (each kill spends a node + respawn);
        # everything else repeats on the seeded schedule.
        killed = False
        while not self._stop.is_set():
            time.sleep(self.rng.uniform(1.0, 2.5))
            if self._stop.is_set():
                return
            victims = [n for n in cluster.nodes[1:]  # node 0 = driver's
                       if n is not self._drain_victim]
            if not victims:
                continue
            victim = self.rng.choice(victims)
            fault = self.rng.choice(classes)
            if fault == "kill" and killed:
                fault = "partition"
            if fault == "kill" and victim is self._dataflow_node:
                fault = "partition"  # see _dataflow_node comment
            t0 = time.monotonic()
            try:
                if fault == "partition":
                    # Shorter than the heartbeat-death window: the cut
                    # must be invisible to the application.
                    cluster.partition([["head"], [victim]])
                    time.sleep(self.rng.uniform(0.5, 2.0))
                    cluster.heal()
                elif fault == "delay":
                    rid = channel_chaos.add_rule(
                        "delay", dst=[victim.address],
                        arg=(0.005, 0.05), label="soak")
                    time.sleep(self.rng.uniform(1.0, 3.0))
                    channel_chaos.remove(rid)
                elif fault == "sever":
                    rid = channel_chaos.add_rule(
                        "sever", src=[victim.address],
                        dst=[cluster.head.address],
                        prob=0.3, label="soak")
                    time.sleep(self.rng.uniform(1.0, 3.0))
                    channel_chaos.remove(rid)
                elif fault == "kill":
                    killed = True
                    cluster.kill_node(victim)
                    cluster.add_node(num_cpus=4)  # replacement capacity
                elif fault == "failpoint":
                    arm = self.rng.choice([
                        {"agent.worker_events.upload": "raise,p=0.3"},
                        {"head.snapshot.before_persist": "raise"},
                        {"client.flush_refs.before": "delay:0.02"},
                        {"agent.heartbeat": "delay:0.2"},
                        # LLM engine scheduler faults: a delayed decode
                        # step and a flaky admission — the engine must
                        # requeue/recover and every probe stream still
                        # finish, shed typed, or fail fast.
                        {"serve.llm.before_step": "delay:0.08"},
                        {"serve.llm.before_admit": "raise,p=0.5"},
                    ])
                    # Engine replicas are worker processes: serve.llm
                    # sites need the cluster-wide control-plane fanout;
                    # the head/agent/driver sites arm locally (the
                    # in-process cluster shares this failpoint table).
                    if any(s.startswith("serve.llm.") for s in arm):
                        from ray_tpu import state

                        setter = state.set_failpoints
                    else:
                        setter = failpoints.set_failpoints
                    setter(arm)
                    time.sleep(self.rng.uniform(1.0, 3.0))
                    setter({site: None for site in arm})
            except Exception as e:
                from ray_tpu.util import metrics as _metrics

                _metrics.count_loop_restart("soak.fault")
                self.violations.append(f"injecting {fault}: {e!r}")
                continue
            self.faults[fault] = self.faults.get(fault, 0) + 1
            self._probe_mttr(
                fault, t0,
                victim_node_id=None if fault == "kill"
                else victim.node_id)

    # -- workload ----------------------------------------------------------

    def _workload(self, cluster, deadline: float) -> None:
        import ray_tpu

        @ray_tpu.remote
        def work(i):
            time.sleep(0.02)
            return i * i

        @ray_tpu.remote
        class Tally:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return 1

        actors = [Tally.options(max_restarts=-1,
                                max_task_retries=-1).remote()
                  for _ in range(2)]
        rng = random.Random(f"{self.seed}:workload")
        batch = 0
        while time.monotonic() < deadline:
            batch += 1
            n = rng.randint(6, 12)
            # SPREAD so the tasks actually land on victim nodes (the
            # default hybrid policy would keep them on the driver's).
            refs = [work.options(scheduling_strategy="SPREAD").remote(i)
                    for i in range(n)]
            call_refs = [a.bump.remote() for a in actors]
            payload = os.urandom(rng.randint(1 << 10, 64 << 10))
            put_ref = ray_tpu.put(payload)
            try:
                results = ray_tpu.get(refs, timeout=120.0)
                if results != [i * i for i in range(n)]:
                    self.violations.append(
                        f"batch {batch}: wrong task results {results!r}")
                else:
                    self.tasks_ok += n
                for r in ray_tpu.get(call_refs, timeout=120.0):
                    if r != 1:
                        self.violations.append(
                            f"batch {batch}: actor call returned {r!r}")
                    else:
                        self.actor_calls_ok += 1
                back = ray_tpu.get(put_ref, timeout=60.0)
                if back != payload:
                    self.violations.append(
                        f"batch {batch}: put/get roundtrip corrupted")
                else:
                    self.puts_ok += 1
            except Exception as e:
                self.violations.append(
                    f"batch {batch}: driver-visible error {e!r}")
            del put_ref

    def _serve_probe_setup(self) -> "object | None":
        """Deploy the standing serve probe and verify one warm-up round
        trip BEFORE any fault is injected (so the invariant separates
        'serve broke under faults' from 'serve never worked')."""
        from ray_tpu import serve

        @serve.deployment(name="soak_probe", num_replicas=2)
        def probe_fn(x):
            return x

        handle = serve.run(probe_fn.bind())
        import ray_tpu

        if ray_tpu.get(handle.remote(41), timeout=60.0) != 41:
            raise RuntimeError("serve probe warm-up returned wrong value")
        self.serve_ok += 1
        return handle

    def _serve_probe_loop(self, handle, deadline: float) -> None:
        """Standing serve invariant under faults: every probe request
        must either complete or fail FAST and cleanly (a deadline shed,
        a replica error while the controller re-reconciles) — a request
        that HANGS past its budget means the request path lost a
        request without shedding it, which is the one behavior a
        latency SLO cannot absorb."""
        import ray_tpu

        while time.monotonic() < deadline and not self._stop.is_set():
            t0 = time.monotonic()
            try:
                r = ray_tpu.get(
                    handle.options(deadline_s=20.0).remote(7),
                    timeout=45.0)
                if r == 7:
                    self.serve_ok += 1
                else:
                    self.violations.append(
                        f"serve probe returned wrong value {r!r}")
            except Exception:  # noqa: BLE001 — classified by duration
                took = time.monotonic() - t0
                if self._stop.is_set():
                    return  # settling cluster: not a verdict
                if took > 40.0:
                    self.violations.append(
                        f"serve probe HUNG {took:.1f}s (neither "
                        f"completed nor shed cleanly)")
                else:
                    self.serve_shed += 1
            time.sleep(0.5)

    def _llm_probe_setup(self):
        """Deploy the standing streaming-LLM probe (a small always-on
        continuous-batching engine) and prove one full stream BEFORE any
        fault is injected."""
        from ray_tpu import serve
        from ray_tpu.serve.llm_engine import LLMEngine

        eng = serve.deployment(
            name="soak_llm", num_replicas=1,
            max_concurrent_queries=16)(LLMEngine)
        handle = serve.run(eng.bind(
            model="gpt2", max_batch=2, cache_len=32, max_prompt_len=8,
            max_new_tokens=4))
        toks = [t for ch in handle.stream([3, 1, 4], 4) for t in ch]
        if len(toks) != 4:
            raise RuntimeError(
                f"llm probe warm-up stream incomplete: {toks!r}")
        self.llm_ok += 1
        return handle

    def _llm_probe_loop(self, handle, deadline: float) -> None:
        """Standing mid-stream invariant under faults: every probe
        stream must FINISH (all tokens, in order), shed TYPED, or fail
        fast — a stream that hangs past 40s through a partition/kill
        lost tokens the decode plane never accounted for, which is the
        one behavior the never-hang contract cannot absorb."""
        from ray_tpu.serve._observability import RequestShedError

        while time.monotonic() < deadline and not self._stop.is_set():
            t0 = time.monotonic()
            try:
                toks = [t for ch in handle.options(
                    deadline_s=20.0).stream([7, 2, 9], 4) for t in ch]
                if len(toks) == 4:
                    self.llm_ok += 1
                else:
                    self.violations.append(
                        f"llm probe stream incomplete: {toks!r}")
            except RequestShedError:
                self.llm_shed += 1
            except Exception:  # noqa: BLE001 — classified by duration
                took = time.monotonic() - t0
                if self._stop.is_set():
                    return
                if took > 40.0:
                    self.violations.append(
                        f"llm probe stream HUNG {took:.1f}s (neither "
                        f"finished, shed, nor failed fast)")
                else:
                    self.llm_failed_fast += 1
            time.sleep(0.8)

    def _train_probe(self, deadline: float) -> None:
        """Standing train invariant under faults: a small checkpointing
        trial must keep reporting steps — or restart cleanly from its
        checkpoint — for the whole fault schedule, and its downtime
        ledger must attribute every non-productive second to a cause
        (a gap the ledger can't explain means the goodput plane lost
        track of the trial)."""
        from ray_tpu import train
        from ray_tpu.train import session
        from ray_tpu.train.checkpoint import Checkpoint

        steps = max(6, int(self.duration_s / 0.6))

        def train_fn(config):
            start = 0
            ckpt = session.get_checkpoint()
            if ckpt is not None:
                start = ckpt.to_dict().get("step", -1) + 1
            for i in range(start, config["steps"]):
                time.sleep(0.4)
                session.report(
                    {"step": i},
                    checkpoint=Checkpoint.from_dict({"step": i}))

        try:
            result = train.DataParallelTrainer(
                train_fn,
                train_loop_config={"steps": steps},
                scaling_config=train.ScalingConfig(num_workers=1),
                run_config=train.RunConfig(
                    failure_config=train.FailureConfig(max_failures=8)),
            ).fit()
        except Exception as e:  # noqa: BLE001
            if not self._stop.is_set():
                self.violations.append(f"train probe crashed: {e!r}")
            return
        if result.error is not None:
            self.violations.append(
                f"train probe ended in error: {result.error!r}")
            return
        self.train_reports = len(result.metrics_history)
        gp = result.goodput or {}
        self.train_goodput = gp
        if not result.metrics or result.metrics.get("step") != steps - 1:
            self.violations.append(
                f"train probe lost steps: last metrics "
                f"{result.metrics!r}")
        by_cause = gp.get("by_cause") or {}
        if abs(sum(by_cause.values())
               - (gp.get("downtime_s") or 0.0)) > 1e-6:
            self.violations.append(
                f"train probe downtime not fully attributed: "
                f"{gp!r}")
        if any(not c for c in by_cause):
            self.violations.append(
                f"train probe downtime with empty cause: {by_cause!r}")

    def _gang_probe(self) -> None:
        """Standing PG-migration invariant: an elastic gang trial
        (num_workers=2, min_workers=1, max_failures=0) holding a
        placement group through the whole seeded kill/drain schedule
        must COMPLETE — its reservation migrates (RESCHEDULING ->
        CREATED on healthy nodes) instead of dying, every lost second
        lands in the ledger under a preemption/drain/reschedule cause,
        and the failure budget stays untouched (completing with
        max_failures=0 proves it)."""
        from ray_tpu import train
        from ray_tpu.train import session
        from ray_tpu.train.checkpoint import Checkpoint

        steps = max(6, int(self.duration_s / 0.6))

        def train_fn(config):
            start = 0
            ckpt = session.get_checkpoint()
            if ckpt is not None:
                start = ckpt.to_dict().get("step", -1) + 1
            for i in range(start, config["steps"]):
                time.sleep(0.4)
                session.report(
                    {"step": i},
                    checkpoint=Checkpoint.from_dict({"step": i}))

        trainer = train.DataParallelTrainer(
            train_fn,
            train_loop_config={"steps": steps},
            scaling_config=train.ScalingConfig(
                num_workers=2, min_workers=1,
                placement_strategy="SPREAD",
                resources_per_worker={"CPU": 1}),
            run_config=train.RunConfig(
                failure_config=train.FailureConfig(max_failures=0)),
        )
        try:
            result = trainer.fit()
        except Exception as e:  # noqa: BLE001
            if not self._stop.is_set():
                self.violations.append(f"gang probe crashed: {e!r}")
            return
        if result.error is not None:
            self.violations.append(
                f"gang probe burned its failure budget "
                f"(max_failures=0): {result.error!r}")
            return
        if not result.metrics or result.metrics.get("step") != steps - 1:
            self.violations.append(
                f"gang probe lost steps: last metrics "
                f"{result.metrics!r}")
        from ray_tpu.util.goodput import attribution_ok

        gp = result.goodput or {}
        self.gang_goodput = gp
        planned, sums = attribution_ok(gp)
        if not sums:
            self.violations.append(
                f"gang probe downtime not fully attributed: {gp!r}")
        if not planned:
            self.violations.append(
                f"gang probe downtime with unplanned cause(s) "
                f"(every second must be preemption/drain/reschedule): "
                f"{gp.get('by_cause')!r}")
        final_pg = trainer.final_pg_state or {}
        self.gang_reschedules = final_pg.get("reschedules", 0)
        if final_pg.get("state") != "CREATED":
            self.violations.append(
                f"gang probe PG did not end ALIVE: "
                f"{final_pg.get('state')!r}")
        else:
            import ray_tpu

            try:
                alive = {n["NodeID"] for n in ray_tpu.nodes()
                         if n["Alive"]}
                stale = [nid for nid, _bi in
                         final_pg.get("placement", [])
                         if nid not in alive]
                if stale:
                    self.violations.append(
                        f"gang probe PG placed on dead node(s) "
                        f"{stale!r} at completion")
            except Exception:
                pass

    def _drain_once(self, cluster) -> None:
        """One graceful drain mid-soak with a budget-exemption probe: a
        max_retries=0 task pinned to the drained node must complete."""
        import ray_tpu
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        victims = [n for n in cluster.nodes[1:]
                   if n is not self._dataflow_node]
        if not victims:
            return
        victim = self.rng.choice(victims)
        self._drain_victim = victim  # injector steers clear of it

        @ray_tpu.remote(max_retries=0)
        def fragile():
            time.sleep(1.5)
            return "exempt-ok"

        ref = fragile.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                victim.node_id)).remote()
        time.sleep(0.4)  # in flight on the victim
        try:
            res = cluster.head.rpc_drain_node(
                victim.node_id, "soak-drain", 1.0)
            if not res.get("ok"):
                self.violations.append(f"drain refused: {res!r}")
            if victim in cluster.nodes:
                cluster.nodes.remove(victim)
                victim.stop()
            if ray_tpu.get(ref, timeout=120.0) != "exempt-ok":
                self.violations.append(
                    "drain-exempt task returned wrong value")
        except Exception as e:
            self.violations.append(
                f"retry-budget exemption violated (max_retries=0 task "
                f"lost to a drain did not complete): {e!r}")
        self.faults["drain"] = self.faults.get("drain", 0) + 1

    # -- streaming-dataflow probe ------------------------------------------

    def _dataflow_probe_setup(self, cluster):
        """Add the probe's dedicated SMALL-store node (12 MiB): every
        probe round pushes ~2x its capacity through it, so dynamic
        splitting + spill-to-URI + restore run continuously while the
        fault schedule rages. The whole soak cluster spills to the
        shared URI (config set before cluster boot)."""
        node = cluster.add_node(num_cpus=2, store_capacity=12 << 20,
                                resources={"dataflow_probe": 8})
        cluster.wait_for_nodes()
        self._dataflow_node = node
        return node

    def _dataflow_probe_loop(self, deadline: float) -> None:
        """Standing invariant: every round of the generation->map->
        consume pipeline under memory pressure either completes or
        fails typed within the round budget — a hang is a violation.
        At least one round must complete over the soak."""
        import numpy as np

        import ray_tpu
        from ray_tpu import data

        @ray_tpu.remote(resources={"dataflow_probe": 1}, max_retries=3)
        def gen(seed):
            rng = np.random.default_rng(seed)
            # ~1 MiB per block, 16 blocks/round = ~16 MiB through a
            # 12 MiB store (plus the map stage's output copy).
            return {"tokens": rng.random((4096, 64), dtype=np.float32)}

        rounds = 0
        while time.monotonic() < deadline and not self._stop.is_set():
            t0 = time.monotonic()
            try:
                # 90s: the box runs every standing probe (serve, llm,
                # train, gang, signal, autoscaler fleet) concurrently —
                # generation on the 2-CPU probe node is the round's
                # long pole, and the budget must absorb co-probe load
                # spikes while staying under the 150s hang threshold.
                refs = [gen.remote(rounds * 100 + i) for i in range(16)]
                done, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                       timeout=90.0)
                if len(done) < len(refs):
                    raise RuntimeError(
                        f"generation incomplete ({len(done)}/16)")
                ds = data.Dataset(list(refs)).map_batches(
                    lambda b: {"tokens": b["tokens"] * 2.0})
                n = 0
                for _batch in ds.iter_batches(batch_size=1024):
                    n += 1
                if n <= 0:
                    raise RuntimeError("pipeline yielded no batches")
                self.dataflow_ok += 1
            except Exception:
                # Typed failure under chaos is allowed (a partitioned
                # probe node parks generation); hanging is not.
                self.dataflow_failed += 1
            if self._stop.is_set():
                return  # settling cluster: not a verdict
            took = time.monotonic() - t0
            if took > 150.0:
                self.violations.append(
                    f"dataflow probe round HUNG {took:.1f}s (neither "
                    f"completing nor failing fast)")
                return
            # Peak spilled-object count on the shared target (frees
            # drain the target between rounds, so sample at the round
            # boundary where pressure is highest).
            try:
                st = self._dataflow_node.rpc_store_stats()
                self.dataflow_spilled = max(
                    self.dataflow_spilled,
                    int(st.get("spilled_objects", 0)))
            except Exception:
                pass
            rounds += 1

    def _signal_probe_setup(self) -> bool:
        """Register a sentinel SLO that can never legitimately burn:
        any burning/recovery transition over the soak is the evaluator
        flapping on scrape gaps, not a real breach."""
        from ray_tpu import state

        st = state.slo_status()
        if not st.get("ok", False):
            return False  # signal plane disabled: nothing to probe
        reg = state.register_slo("soak-sentinel",
                                 "qps < 1000000 over 10s")
        if not reg.get("ok"):
            return False
        # Prove one query round trip BEFORE faults start (the serve
        # probe's discipline): under the fault schedule a saturated box
        # can starve every later round, and "never completed a query"
        # must mean the plane broke, not that the probe never got a
        # healthy turn.
        if state.query_metrics({"op": "gauge_last",
                                "name": "ray_tpu_node_worker_count",
                                "window_s": 60.0}).get("ok"):
            self.signal_queries_ok += 1
        return True

    def _signal_probe_loop(self, deadline: float) -> None:
        """Standing invariant: the head's history ring keeps answering
        windowed queries while agents are partitioned/killed — the ring
        is head-local state, so a partition starves it of NEW samples
        but must never make a query stall or error. A stalled query is
        a violation; per-round results are counted for the evidence
        line."""
        from ray_tpu import state

        while time.monotonic() < deadline and not self._stop.is_set():
            t0 = time.monotonic()
            try:
                res = state.query_metrics({
                    "op": "gauge_last",
                    "name": "ray_tpu_node_worker_count",
                    "window_s": 60.0})
                if res.get("ok"):
                    self.signal_queries_ok += 1
                else:
                    self.signal_queries_failed += 1
            except Exception:
                self.signal_queries_failed += 1
            if self._stop.is_set():
                return  # settling cluster: not a verdict
            took = time.monotonic() - t0
            if took > 30.0:
                self.violations.append(
                    f"signal query STALLED {took:.1f}s under faults "
                    f"(the ring must answer from head-local history)")
                return
            time.sleep(0.5)

    # -- autoscaler probe --------------------------------------------------

    def _autoscaler_probe_setup(self, cluster) -> bool:
        """Stand up a ``LocalNodeProvider`` fleet the fault schedule
        rides: fleet demand uses a custom resource only autoscaler-
        launched nodes carry, so every probe round exercises the full
        scale-up path (bin-pack -> create_node -> boot -> schedule) and
        the teardown exercises drain-before-terminate scale-down. A
        provider terminate of a node the head still reports ALIVE is an
        instant violation (goodput-loss scale-down). One clean round
        runs here, BEFORE faults start; then ``create_node`` itself is
        put on the seeded fault schedule so the backoff/quarantine boot
        loop earns its keep."""
        import ray_tpu
        from ray_tpu.autoscaler import LocalNodeProvider, StandardAutoscaler

        provider = LocalNodeProvider(cluster)
        real_terminate = provider.terminate_node

        def checked_terminate(node_id):
            try:
                alive = any(n["NodeID"] == node_id and n["Alive"]
                            for n in cluster.head.rpc_nodes())
            except Exception:
                alive = False
            if alive:
                self.violations.append(
                    f"autoscaler terminated {node_id[:12]} while the "
                    f"head still reported it ALIVE (drain-before-"
                    f"terminate violated)")
            real_terminate(node_id)

        provider.terminate_node = checked_terminate
        self._as_provider = provider
        self._as_cluster = cluster
        self._autoscaler = StandardAutoscaler(
            cluster.address, provider,
            node_types={
                # Catalog order is the packer's preference order: spot
                # first (Podracer economics — preemptible is the normal
                # case), on-demand as the quarantine fall-through.
                "fleet_spot": {"num_cpus": 2,
                               "resources": {"fleet": 2}, "spot": True},
                "fleet_ondemand": {"num_cpus": 2,
                                   "resources": {"fleet": 2}},
            },
            max_workers=3,
            idle_timeout_s=1.5,
            launch_cooldown_s=0.2,
            backoff_base_s=0.2,
            backoff_max_s=1.0,
            quarantine_failures=3,
            quarantine_cooldown_s=3.0,
        )

        @ray_tpu.remote(num_cpus=1, resources={"fleet": 1}, max_retries=5)
        def fleet_work(i):
            time.sleep(0.05)
            return i

        self._fleet_work = fleet_work
        self._autoscaler_round(0, budget_s=30.0)
        self.autoscaler_rounds_ok += 1
        # From here on, launches fail on the seeded schedule: with two
        # feasible types, backoff + quarantine fall-through must keep
        # demand satisfiable anyway. (Settle's failpoints.reset()
        # disarms this before the end-state round.)
        from ray_tpu.util import failpoints

        failpoints.set_failpoints(
            {"autoscaler.before_create": "raise:chaos,p=0.25"})
        return True

    def _autoscaler_round(self, tag: int, budget_s: float,
                          heed_stop: bool = True) -> None:
        """One demand burst: submit fleet-only tasks (no standing node
        carries the resource), pump the reconcile loop until all land.
        Raises if the budget expires with demand unsatisfied.
        ``heed_stop`` aborts at soak teardown (mid-soak rounds only —
        the end-state round runs AFTER settle, with ``_stop`` set)."""
        import ray_tpu

        refs = [self._fleet_work.remote(tag * 10 + i) for i in range(4)]
        pending = list(refs)
        pump_deadline = time.monotonic() + budget_s
        while pending and time.monotonic() < pump_deadline:
            report = self._autoscaler.update()
            self.autoscaler_launches += len(report["launched"])
            self.autoscaler_launch_failures += len(
                report["launch_failures"])
            self.autoscaler_scale_downs += len(report["terminated"])
            _, pending = ray_tpu.wait(
                pending, num_returns=len(pending), timeout=1.0)
            if pending and heed_stop and self._stop.is_set():
                raise RuntimeError("soak stopping mid-round")
        if pending:
            raise RuntimeError(
                f"fleet demand unsatisfied ({len(pending)}/4 pending "
                f"after {budget_s:.0f}s)")
        ray_tpu.get(refs, timeout=10.0)

    def _autoscaler_preempt_drill(self) -> bool:
        """Simulate a provider preemption notice on one live spot fleet
        node: drain(reason="preemption"). The reconcile loop must
        reclaim the slot and close the ledger with cause
        ``preemption``."""
        a = self._autoscaler
        live = set(self._as_provider.non_terminated_nodes())
        spots = [nid for nid, t in a._node_type_of.items()
                 if t == "fleet_spot" and nid in live
                 and nid not in a._draining]
        if not spots:
            return False
        self._as_cluster.head.rpc_drain_node(
            spots[0], "preemption", 10.0, wait=False)
        self.autoscaler_preemptions += 1
        return True

    def _autoscaler_probe_loop(self, deadline: float) -> None:
        """Standing invariant: a fleet-only demand burst is satisfied
        through autoscaler scale-up within the round budget even while
        faults land on the launched nodes and ``create_node`` itself
        fails on the seeded schedule. A round may fail typed under
        chaos; hanging is a violation. One round rides a simulated spot
        preemption."""
        preempted = False
        tag = 1
        while time.monotonic() < deadline and not self._stop.is_set():
            t0 = time.monotonic()
            try:
                self._autoscaler_round(tag, budget_s=45.0)
                self.autoscaler_rounds_ok += 1
                if not preempted:
                    preempted = self._autoscaler_preempt_drill()
            except Exception:
                if self._stop.is_set():
                    return  # settling cluster: not a verdict
                self.autoscaler_rounds_failed += 1
            took = time.monotonic() - t0
            if took > 120.0:
                self.violations.append(
                    f"autoscaler probe round HUNG {took:.1f}s (fleet "
                    f"demand neither satisfied nor failing fast)")
                return
            tag += 1
            # Gentle cadence: the soak box runs every other standing
            # probe too, and this one spawns node agents.
            time.sleep(1.0)

    def _autoscaler_end_state(self, cluster) -> None:
        """Post-storm verdicts: demand still satisfiable (no stuck
        quarantine — the schedule is over and cooldowns expired), fleet
        scales to zero with every termination drained first, and the
        head's terminate ledger is fully cause-attributed."""
        a = self._autoscaler
        try:
            try:
                self._autoscaler_round(999, budget_s=30.0,
                                       heed_stop=False)
                self.autoscaler_rounds_ok += 1
            except Exception as e:  # noqa: BLE001
                self.violations.append(
                    f"autoscaler demand unsatisfied after soak (stuck "
                    f"quarantine/backoff?): {e!r}")
            self.autoscaler_quarantines = sum(
                1 for st in a._type_state.values()
                if st.quarantined_until > 0)
            # Zero-goodput-loss scale-down: idle the whole fleet out.
            # The provider hook asserts drained-first on every
            # terminate; the ledger check below does attribution.
            a.idle_timeout_s = 0.0
            sd_deadline = time.monotonic() + 30.0
            while (self._as_provider.non_terminated_nodes()
                   and time.monotonic() < sd_deadline):
                report = a.update()
                self.autoscaler_scale_downs += len(report["terminated"])
                time.sleep(0.1)
            if self._as_provider.non_terminated_nodes():
                self.violations.append(
                    "autoscaler fleet failed to scale to zero after "
                    "the soak")
            with cluster.head._lock:
                acks = {nid: rec["cause"] for nid, rec
                        in cluster.head._terminate_acks.items()}
            fleet_acks = {nid: c for nid, c in acks.items()
                          if nid in set(a.launched)}
            bad = {nid[:12]: c for nid, c in fleet_acks.items()
                   if not (c == "preemption" or c.startswith("drain:")
                           or c.startswith("failure:"))}
            if bad:
                self.violations.append(
                    f"unattributed fleet terminations in ledger: {bad}")
            if (self.autoscaler_preemptions
                    and "preemption" not in fleet_acks.values()):
                self.violations.append(
                    "spot preemption not attributed as 'preemption' "
                    "in the terminate ledger")
        finally:
            a.stop()

    # -- invariants --------------------------------------------------------

    def _check_invariants(self, cluster) -> None:
        from ray_tpu import state

        # Leak sweeper: nothing flagged after settle.
        try:
            leaks = state.memory_leaks()
            if leaks:
                self.violations.append(
                    f"memory_leaks non-empty after settle: "
                    f"{[r['object_id'][:16] for r in leaks]}")
        except Exception as e:
            self.violations.append(f"memory_leaks unreachable: {e!r}")
        # Federated scrape still serves the whole cluster.
        try:
            from ray_tpu.cluster.gcs_client import GcsClient

            gcs = GcsClient(cluster.address)
            try:
                body = gcs.metrics.cluster_text()
            finally:
                gcs.close()
            if "ray_tpu_" not in body:
                self.violations.append(
                    "federated /metrics/cluster body has no ray_tpu_ "
                    "series")
        except Exception as e:
            self.violations.append(f"/metrics/cluster scrape: {e!r}")
        # Head directory consistent with the agent stores: no location
        # pointing at a dead node, and the per-node store reports join.
        try:
            alive = {n["NodeID"] for n in state.nodes() if n["Alive"]}
            for rec in state.list_objects(limit=10_000):
                stale = set(rec.get("locations") or ()) - alive
                if stale:
                    self.violations.append(
                        f"directory entry {rec['object_id'][:16]} "
                        f"located on dead node(s) {sorted(stale)}")
            for rep in state.object_store_stats():
                if rep.get("node_id") not in alive:
                    self.violations.append(
                        f"store report from non-alive node "
                        f"{rep.get('node_id')!r}")
        except Exception as e:
            self.violations.append(f"directory/store check: {e!r}")
        # No leaked per-node bundle reservations: every reservation an
        # agent still holds must be explained by a live group's
        # placement on that node (a failed/rolled-back 2PC round or a
        # kill mid-2PC must never strand a carve-out). Settle-retried:
        # an in-flight reschedule's PREPARED bundles (or a post-remove
        # rollback still in the coordinator's hands) are a transient,
        # self-correcting state, not a leak — only a PERSISTENT orphan
        # is a violation.
        def _bundle_leaks() -> list:
            pgs = cluster.head.rpc_placement_group_table() or {}
            expected: set = set()
            pending_pgs = set()
            for pg_id, pg in pgs.items():
                if pg.get("state") in ("CREATED", "RESCHEDULING"):
                    for nid, bi in pg.get("placement", []):
                        expected.add((nid, f"{pg_id}:{bi}"))
                elif pg.get("state") == "PENDING":
                    # A queued group's reserve 2PC may legitimately
                    # hold PREPARED bundles with placement still [] —
                    # its prepares can block in pool.acquire for up to
                    # 60s, past the settle window below.
                    pending_pgs.add(pg_id)
            leaks = []
            for node in list(cluster.nodes):
                try:
                    held = node.rpc_bundle_table()
                except Exception:
                    continue  # node stopping: nothing held
                for key in held:
                    if key.rsplit(":", 1)[0] in pending_pgs:
                        continue
                    if (node.node_id, key) not in expected:
                        leaks.append(
                            f"leaked bundle reservation {key} on node "
                            f"{node.node_id[-12:]} (no live placement "
                            f"group explains it)")
            return leaks

        try:
            leak_deadline = time.monotonic() + 30.0
            leaks = _bundle_leaks()
            while leaks and time.monotonic() < leak_deadline:
                time.sleep(1.0)
                leaks = _bundle_leaks()
            self.violations.extend(leaks)
        except Exception as e:
            self.violations.append(f"bundle-leak check: {e!r}")

    # -- driver ------------------------------------------------------------

    def run(self) -> dict:
        import ray_tpu
        from ray_tpu.cluster.cluster_utils import Cluster
        from ray_tpu.core.config import config
        from ray_tpu.scripts import bench_log

        # One knob seeds every chaos RNG in this process AND (via env)
        # every process the cluster spawns; restored on exit so an
        # in-process caller doesn't inherit the soak's seed.
        prev_env_seed = os.environ.get("RAY_TPU_CHAOS_SEED")
        os.environ["RAY_TPU_CHAOS_SEED"] = str(self.seed)
        config.override("chaos_seed", self.seed)
        # The streaming-dataflow probe's relief valve: the whole soak
        # cluster spills to one shared URI (so a killed node's spilled
        # objects restore instead of recomputing), and a small split
        # target keeps the probe's ~1 MiB blocks splitting for real.
        import shutil
        import tempfile

        spill_dir = tempfile.mkdtemp(prefix="ray_tpu_soak_spill_")
        config.override("spill_uri", f"file://{spill_dir}")
        config.override("target_block_size_bytes", 256 << 10)
        try:
            return self._run_seeded(ray_tpu, Cluster, bench_log)
        finally:
            if prev_env_seed is None:
                os.environ.pop("RAY_TPU_CHAOS_SEED", None)
            else:
                os.environ["RAY_TPU_CHAOS_SEED"] = prev_env_seed
            config.reset("chaos_seed")
            config.reset("spill_uri")
            config.reset("target_block_size_bytes")
            shutil.rmtree(spill_dir, ignore_errors=True)

    def _run_seeded(self, ray_tpu, Cluster, bench_log) -> dict:
        ray_tpu.shutdown()
        cluster = Cluster()
        cluster.add_node(num_cpus=4)  # driver node: survives
        for _ in range(self.n_victims):
            cluster.add_node(num_cpus=4)
        cluster.wait_for_nodes()
        ray_tpu.init(cluster.address)
        deadline = time.monotonic() + self.duration_s
        # Serve probe deploys (and proves one round trip) BEFORE faults
        # start; under faults its standing invariant is complete-or-
        # shed-cleanly, never hang.
        serve_handle = None
        try:
            serve_handle = self._serve_probe_setup()
        except Exception as e:  # noqa: BLE001
            self.violations.append(f"serve probe deploy failed: {e!r}")
        llm_handle = None
        try:
            llm_handle = self._llm_probe_setup()
        except Exception as e:  # noqa: BLE001
            self.violations.append(f"llm probe deploy failed: {e!r}")
        dataflow_ready = False
        try:
            self._dataflow_probe_setup(cluster)
            dataflow_ready = True
        except Exception as e:  # noqa: BLE001
            self.violations.append(
                f"dataflow probe setup failed: {e!r}")
        signal_ready = False
        try:
            signal_ready = self._signal_probe_setup()
        except Exception as e:  # noqa: BLE001
            self.violations.append(f"signal probe setup failed: {e!r}")
        autoscaler_ready = False
        try:
            autoscaler_ready = self._autoscaler_probe_setup(cluster)
        except Exception as e:  # noqa: BLE001
            self.violations.append(
                f"autoscaler probe setup failed: {e!r}")
        injector = threading.Thread(
            target=self._fault_loop, args=(cluster,), daemon=True)
        injector.start()
        try:
            # First third: faults only; then one graceful drain rides
            # along; workload runs throughout.
            workload = threading.Thread(
                target=self._workload, args=(cluster, deadline),
                daemon=True)
            workload.start()
            train_probe = threading.Thread(
                target=self._train_probe, args=(deadline,), daemon=True)
            train_probe.start()
            gang_probe = threading.Thread(
                target=self._gang_probe, daemon=True)
            gang_probe.start()
            if serve_handle is not None:
                threading.Thread(
                    target=self._serve_probe_loop,
                    args=(serve_handle, deadline), daemon=True).start()
            if llm_handle is not None:
                threading.Thread(
                    target=self._llm_probe_loop,
                    args=(llm_handle, deadline), daemon=True).start()
            if dataflow_ready:
                threading.Thread(
                    target=self._dataflow_probe_loop,
                    args=(deadline,), daemon=True).start()
            if signal_ready:
                threading.Thread(
                    target=self._signal_probe_loop,
                    args=(deadline,), daemon=True).start()
            if autoscaler_ready:
                threading.Thread(
                    target=self._autoscaler_probe_loop,
                    args=(deadline,), daemon=True).start()
            time.sleep(min(self.duration_s / 3.0, 10.0))
            self._drain_once(cluster)
            workload.join(timeout=self.duration_s + 180.0)
            if workload.is_alive():
                self.violations.append("workload wedged past deadline")
            # The trial restarts from checkpoint under kills: give it
            # the same generous settle the workload gets before calling
            # a hang.
            train_probe.join(timeout=self.duration_s + 240.0)
            if train_probe.is_alive():
                self.violations.append(
                    "train probe wedged past deadline (neither "
                    "reporting nor restarting)")
            # The gang trial rides the same kill/drain schedule and may
            # spend windows SHRUNK waiting for bundle reschedules: give
            # it the train probe's settle budget too.
            gang_probe.join(timeout=self.duration_s + 240.0)
            if gang_probe.is_alive():
                self.violations.append(
                    "gang probe wedged past deadline (gang neither "
                    "completing, shrinking, nor regrowing)")
            # Fault quota: a soak that recovered slowly (MTTR probes
            # stretch the schedule on a loaded box) keeps injecting —
            # bounded — until at least 4 DISTINCT fault classes landed
            # (the drain rides along and doesn't count), so a short run
            # still earns its adversarial coverage instead of passing on
            # e.g. three delays and nothing else.
            quota_deadline = time.monotonic() + 2 * self.duration_s
            while (len(set(self.faults) - {"drain"}) < 4
                   and not self.violations
                   and time.monotonic() < quota_deadline):
                time.sleep(0.5)
        finally:
            self._stop.set()
            # The injector's MTTR probe can run up to 120s per fault;
            # the join must outlast it or an orphaned probe records
            # spurious violations into a settling cluster.
            injector.join(timeout=150.0)
        # Settle: heal everything, let frees/heartbeats drain.
        cluster.heal()
        from ray_tpu.cluster.rpc import channel_chaos
        from ray_tpu.util import failpoints

        channel_chaos.clear("soak")
        failpoints.reset()
        time.sleep(2.0)
        self._check_invariants(cluster)
        if serve_handle is not None and self.serve_ok < 1:
            self.violations.append(
                "serve probe never completed a request")
        if llm_handle is not None and self.llm_ok < 1:
            self.violations.append(
                "llm probe never completed a stream")
        if dataflow_ready:
            if self.dataflow_ok < 1:
                self.violations.append(
                    "dataflow probe never completed a round")
            # Restores are cumulative per agent and can land on any
            # live node (the head picks the restore target): sum the
            # survivors for the evidence line.
            for node in list(cluster.nodes):
                try:
                    self.dataflow_restores += int(
                        node.rpc_store_stats().get("spill_restores", 0))
                except Exception:
                    continue
        if signal_ready:
            from ray_tpu import state

            if self.signal_queries_ok < 1:
                self.violations.append(
                    "signal probe never completed a query")
            try:
                sent = (state.slo_status().get("slos") or {}).get(
                    "soak-sentinel") or {}
                # missed_evals counts held evaluations (scrape gaps
                # under partition) — evidence, not a fault. Any
                # transition on a can't-burn sentinel IS the evaluator
                # flapping on those gaps.
                self.signal_slo_transitions = int(
                    sent.get("transitions", 0))
                self.signal_missed_evals = int(
                    sent.get("missed_evals", 0))
                if self.signal_slo_transitions:
                    self.violations.append(
                        f"sentinel SLO flapped "
                        f"{self.signal_slo_transitions}x on scrape "
                        f"gaps (evaluator must hold state when the "
                        f"window has no samples)")
                state.remove_slo("soak-sentinel")
            except Exception as e:  # noqa: BLE001
                self.violations.append(
                    f"signal probe teardown: {e!r}")
        if autoscaler_ready:
            try:
                self._autoscaler_end_state(cluster)
            except Exception as e:  # noqa: BLE001
                self.violations.append(
                    f"autoscaler probe end-state: {e!r}")
        try:
            from ray_tpu import serve

            serve.shutdown()
        except Exception:
            pass
        entry = bench_log.record_chaos_soak(
            seed=self.seed,
            duration_s=self.duration_s,
            faults=self.faults,
            violations=self.violations,
            mttr_ms=self.mttr_ms,
            tasks_ok=self.tasks_ok,
            actor_calls_ok=self.actor_calls_ok,
            puts_ok=self.puts_ok,
            device=_device_kind(),
            script="chaos_soak",
            serve_ok=self.serve_ok,
            serve_shed=self.serve_shed,
            llm_ok=self.llm_ok,
            llm_shed=self.llm_shed,
            llm_failed_fast=self.llm_failed_fast,
            train_reports=self.train_reports,
            train_goodput=self.train_goodput,
            gang_goodput=self.gang_goodput,
            gang_reschedules=self.gang_reschedules,
            dataflow_ok=self.dataflow_ok,
            dataflow_failed=self.dataflow_failed,
            dataflow_spilled=self.dataflow_spilled,
            dataflow_restores=self.dataflow_restores,
            signal_queries_ok=self.signal_queries_ok,
            signal_queries_failed=self.signal_queries_failed,
            signal_slo_transitions=self.signal_slo_transitions,
            signal_missed_evals=self.signal_missed_evals,
            autoscaler_rounds_ok=self.autoscaler_rounds_ok,
            autoscaler_rounds_failed=self.autoscaler_rounds_failed,
            autoscaler_launches=self.autoscaler_launches,
            autoscaler_launch_failures=self.autoscaler_launch_failures,
            autoscaler_quarantines=self.autoscaler_quarantines,
            autoscaler_scale_downs=self.autoscaler_scale_downs,
            autoscaler_preemptions=self.autoscaler_preemptions,
        )
        ray_tpu.shutdown()
        cluster.shutdown()
        return entry


def run(seed: int, duration_s: float = 20.0, n_victims: int = 2) -> dict:
    return _Soak(seed, duration_s, n_victims).run()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int,
                        default=int(os.environ.get(
                            "RAY_TPU_CHAOS_SEED", "0")) or None,
                        help="chaos seed (default: RAY_TPU_CHAOS_SEED, "
                             "else random)")
    parser.add_argument("--duration", type=float, default=20.0)
    parser.add_argument("--victims", type=int, default=2)
    args = parser.parse_args(argv)
    seed = args.seed if args.seed is not None \
        else random.SystemRandom().randrange(1 << 31)
    entry = run(seed, args.duration, args.victims)
    print(json.dumps(entry, default=str))
    if entry["n_violations"]:
        print(f"CHAOS SOAK FAILED ({entry['n_violations']} violations); "
              f"replay with RAY_TPU_CHAOS_SEED={seed}", flush=True)
        return 1
    print(f"chaos soak passed: {entry['faults_injected']} faults "
          f"({entry['faults']}), seed={seed}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
