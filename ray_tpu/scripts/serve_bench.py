"""Serve load harness: N concurrent streams + client/server cross-check.

Drives ``--connections`` concurrent client streams (each a thread
issuing sequential requests) through a deployment — over the HTTP proxy
by default, or the handle path — and records client-side p50/p99/QPS.
Then it reads the server-side ``ray_tpu_serve_request_seconds``
histograms back from the metrics plane and REQUIRES the two views to
agree: exact request-count match, and p50/p99/mean agreement within the
histogram's bucket resolution. If client and server disagree, the
metrics are lying (a phase is unobserved, double-counted, or
mis-tagged) and the bench exits non-zero — the latency plane itself is
under test, not just the deployment.

Also exercised per run: deadline sheds (requests sent with an
already-expired budget must come back 503/shed and land in
``ray_tpu_serve_shed_total``) and — when tracing — one end-to-end
traced request whose ingress/route/replica spans must share a trace id.

Machine-independent shape results (counts, agreement booleans, phases
observed) merge into MICROBENCH.json under ``serve`` (perfsuite
``--serve`` stage); latency numbers ride along for context only.
``bench_log.record_serve_latency`` commits an evidence line on-chip.

Run: python -m ray_tpu.scripts.serve_bench [--out MICROBENCH.json]
     [--mode http|handle] [--connections 8] [--requests 25] [--cluster]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

DEPLOYMENT = "serve_bench_echo"


def _device_kind() -> str:
    from ray_tpu.scripts.bench_log import device_kind

    return device_kind()


class _Stream:
    """One persistent client connection (HTTP keep-alive — the shape of
    a real load client; a fresh TCP handshake per request would measure
    the OS, not the serving path). ``post`` returns (status, body) for
    ANY status — a 503 shed is data here, not an exception."""

    def __init__(self, port: int):
        import http.client

        self._conn = http.client.HTTPConnection(
            "127.0.0.1", port, timeout=60.0)

    def post(self, path: str, payload, headers=None):
        body = json.dumps(payload).encode()
        self._conn.request(
            "POST", path, body=body,
            headers={"Content-Type": "application/json",
                     **(headers or {})})
        resp = self._conn.getresponse()
        data = resp.read()
        return resp.status, (json.loads(data) if data else None)

    def close(self):
        try:
            self._conn.close()
        except Exception:
            pass


def _percentile_ms(vals_s, q):
    from ray_tpu.util.metrics import percentile

    return round(percentile(sorted(vals_s), q) * 1e3, 3)


def run(mode: str = "http", connections: int = 8,
        requests_per_conn: int = 25, sleep_ms: float = 2.0,
        batch: bool = False, shed_probes: int = 4,
        cluster: bool = False, trace_check: bool = True) -> dict:
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import _observability as obs
    from ray_tpu.util import tracing

    ray_tpu.shutdown()
    cluster_obj = None
    prev_trace_env = os.environ.get("RAY_TPU_TRACING_ENABLED")
    if trace_check:
        # Operator opt-in BEFORE the cluster spawns: worker processes
        # (proxy, routers, replicas) read the env at import — an
        # unauthenticated traceparent header alone no longer enables
        # tracing server-side.
        os.environ["RAY_TPU_TRACING_ENABLED"] = "1"
    if cluster:
        from ray_tpu.cluster.cluster_utils import Cluster

        cluster_obj = Cluster()
        cluster_obj.add_node(num_cpus=8)
        cluster_obj.wait_for_nodes()
        ray_tpu.init(cluster_obj.address)
    else:
        ray_tpu.init(num_cpus=max(8, connections))

    sleep_s = sleep_ms / 1e3

    if batch:
        @serve.deployment(name=DEPLOYMENT, num_replicas=2,
                          max_concurrent_queries=64,
                          route_prefix="/bench")
        class Echo:  # noqa: F811 — bench-local deployment
            @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.005)
            def handle(self, items):
                time.sleep(sleep_s)
                return [{"x": i.get("x") if isinstance(i, dict) else i}
                        for i in items]

            def __call__(self, payload):
                return self.handle(payload)
    else:
        @serve.deployment(name=DEPLOYMENT, num_replicas=2,
                          max_concurrent_queries=64,
                          route_prefix="/bench")
        class Echo:
            def __call__(self, payload):
                time.sleep(sleep_s)
                return {"x": payload.get("x")
                        if isinstance(payload, dict) else payload}

    try:
        handle = serve.run(Echo.bind())
        port = serve.start_http_proxy() if mode == "http" else None
        before = obs.parse_prometheus(obs.metrics_text())

        latencies: list = []
        errors: list = []
        lat_lock = threading.Lock()

        def stream(conn_id: int):
            conn = _Stream(port) if mode == "http" else None
            try:
                for i in range(requests_per_conn):
                    t0 = time.perf_counter()
                    try:
                        if mode == "http":
                            status, body = conn.post(
                                "/bench", {"x": conn_id * 1000 + i})
                            ok = (status == 200
                                  and body.get("x") == conn_id * 1000 + i)
                        else:
                            out = ray_tpu.get(
                                handle.remote({"x": conn_id * 1000 + i}),
                                timeout=60.0)
                            ok = out.get("x") == conn_id * 1000 + i
                        dt = time.perf_counter() - t0
                        with lat_lock:
                            if ok:
                                latencies.append(dt)
                            else:
                                errors.append("wrong result")
                    except Exception as e:  # noqa: BLE001
                        with lat_lock:
                            errors.append(repr(e))
            finally:
                if conn is not None:
                    conn.close()

        t_start = time.perf_counter()
        threads = [threading.Thread(target=stream, args=(c,))
                   for c in range(connections)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall_s = time.perf_counter() - t_start

        # Server side: wait for the stream observations to settle (the
        # cluster backend ships them over the 0.25s worker-event
        # cadence), then diff against the pre-run snapshot so ONLY the
        # streams' requests enter the cross-check — the shed and trace
        # probes below come after this window on purpose.
        n_ok = len(latencies)
        delta = None
        after = before
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            after = obs.parse_prometheus(obs.metrics_text())
            delta = obs.diff_parsed(before, after)
            dist = obs.histogram_dist(
                delta, "ray_tpu_serve_request_seconds",
                deployment=DEPLOYMENT, phase="total")
            if dist and dist["count"] >= n_ok:
                break
            time.sleep(0.25)

        dist = obs.histogram_dist(delta, "ray_tpu_serve_request_seconds",
                                  deployment=DEPLOYMENT, phase="total")
        statuses = obs.sum_counter(delta, "ray_tpu_serve_requests_total",
                                   "status", deployment=DEPLOYMENT)
        phases_observed = sorted(
            p for p in obs.PHASES
            if obs.histogram_dist(delta, "ray_tpu_serve_request_seconds",
                                  deployment=DEPLOYMENT, phase=p))

        # Deadline sheds: an already-expired budget must come back as a
        # clean 503/shed, never execute, and count in the shed family.
        shed_seen = 0
        probe_conn = _Stream(port) if mode == "http" else None
        for _ in range(shed_probes):
            try:
                if mode == "http":
                    status, body = probe_conn.post(
                        "/bench", {"x": 1},
                        headers={serve.DEADLINE_HEADER: "0"})
                    if status == 503:
                        shed_seen += 1
                else:
                    ray_tpu.get(
                        handle.options(deadline_s=0.0).remote({"x": 1}),
                        timeout=60.0)
            except Exception as e:  # noqa: BLE001 — handle path sheds
                if "RequestShedError" in repr(e) or "shed" in repr(e):
                    shed_seen += 1
        sheds = {}
        if shed_probes:
            shed_deadline = time.monotonic() + 20.0
            while time.monotonic() < shed_deadline:
                shed_delta = obs.diff_parsed(
                    after, obs.parse_prometheus(obs.metrics_text()))
                sheds = obs.sum_counter(
                    shed_delta, "ray_tpu_serve_shed_total", "reason",
                    deployment=DEPLOYMENT)
                if sum(sheds.values()) >= shed_seen:
                    break
                time.sleep(0.25)

        # One traced request: ingress -> route -> replica must share a
        # trace id (the end-to-end propagation claim, checked live).
        trace = {}
        if trace_check:
            tracing.enable()
            trace_id = None
            if mode == "http":
                want = "aa" * 16
                if probe_conn is not None:
                    probe_conn.post(
                        "/bench", {"x": 0},
                        headers={"traceparent":
                                 f"00-{want}-{'bb' * 8}-01"})
                trace_id = want
            else:
                with tracing.span("serve_bench.client") as s:
                    ray_tpu.get(handle.remote({"x": 0}), timeout=60.0)
                    trace_id = s["trace_id"]
            deadline = time.monotonic() + 15.0
            names: set = set()
            while time.monotonic() < deadline:
                spans = [s for s in _collect_spans(ray_tpu)
                         if s["trace_id"] == trace_id
                         and s.get("cat") == "serve"]
                names = {s["name"].split(":")[0] for s in spans}
                want_names = {"serve.route", "serve.replica"} | (
                    {"serve.http"} if mode == "http" else set())
                if want_names <= names:
                    break
                time.sleep(0.25)
            trace = {"trace_id": trace_id,
                     "span_kinds": sorted(names),
                     "one_trace": {"serve.route", "serve.replica"}
                     <= names}
        if probe_conn is not None:
            probe_conn.close()

        client = {
            "count": n_ok,
            "errors": len(errors),
            "p50_ms": _percentile_ms(latencies, 0.50) if latencies else None,
            "p99_ms": _percentile_ms(latencies, 0.99) if latencies else None,
            "mean_ms": round(sum(latencies) / n_ok * 1e3, 3)
            if n_ok else None,
            "qps": round((n_ok + len(errors)) / wall_s, 1),
        }
        server = {"count": int(dist["count"]) if dist else 0}
        if dist:
            server["mean_ms"] = round(dist["sum"] / dist["count"] * 1e3, 3)
            for q, key in ((0.50, "p50_ms"), (0.99, "p99_ms")):
                v = obs.quantile_from_buckets(dist, q)
                server[key] = round(v * 1e3, 3) if v is not None else None

        # Client latency = server-observed total + ingress overhead the
        # server cannot see (HTTP parse, event-loop scheduling, the
        # executor hop). That overhead is ~constant per request, so it
        # is measured from the means and subtracted before comparing
        # quantile SHAPES; the server claiming MORE time than the
        # client saw, or a count mismatch, is unconditionally lying.
        ingress_ms = 0.0
        if client["mean_ms"] is not None and "mean_ms" in server:
            ingress_ms = max(0.0, client["mean_ms"] - server["mean_ms"])

        def within(client_ms, server_ms):
            """Histogram agreement: a bucket estimate can only be as
            precise as the bucket the sample fell in."""
            if client_ms is None or server_ms is None or not dist:
                return False
            tol_ms = max(
                obs.bucket_width_at(dist, client_ms / 1e3) * 1e3,
                0.35 * client_ms, 5.0)
            return abs((client_ms - ingress_ms) - server_ms) <= tol_ms

        agreement = {
            "count_exact": server["count"] == n_ok,
            "p50_within_tol": within(client["p50_ms"],
                                     server.get("p50_ms")),
            "p99_within_tol": within(client["p99_ms"],
                                     server.get("p99_ms")),
            "server_not_exceeding": (
                "mean_ms" in server and client["mean_ms"] is not None
                and server["mean_ms"]
                <= client["mean_ms"] * 1.1 + 5.0),
            "status_ok_match": int(statuses.get("ok", 0)) == n_ok,
            "shed_counted": (shed_probes == 0
                             or sum(sheds.values()) >= shed_seen > 0),
        }
        agreement["ok"] = all(agreement.values())
        client["ingress_overhead_ms"] = round(ingress_ms, 3)

        result = {
            "mode": mode,
            "backend": "cluster" if cluster else "local",
            "connections": connections,
            "requests_per_conn": requests_per_conn,
            "batch": batch,
            "client": client,
            "server": server,
            "statuses": {k: int(v) for k, v in statuses.items()},
            "shed": {"probes": shed_probes, "client_seen": shed_seen,
                     "server_counted": {k: int(v)
                                        for k, v in sheds.items()}},
            "phases_observed": phases_observed,
            "agreement": agreement,
        }
        if trace:
            result["trace"] = trace
        return result
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
        if cluster_obj is not None:
            cluster_obj.shutdown()
        if trace_check:
            if prev_trace_env is None:
                os.environ.pop("RAY_TPU_TRACING_ENABLED", None)
            else:
                os.environ["RAY_TPU_TRACING_ENABLED"] = prev_trace_env


LLM_DEPLOYMENT = "serve_bench_llm"


def run_llm(streams: int = 10_000, max_new_tokens: int = 8,
            max_batch: int = 64, cache_len: int = 64,
            max_prompt_len: int = 16, prefill_rows: int = 8,
            cluster: bool = False, chaos: bool = False,
            chaos_streams: int = 2_000, stream_lanes: int = 8,
            shed_probes: int = 4, collectors: int = 8,
            deadline_s: float = 900.0) -> dict:
    """Continuous-batching serving harness: N concurrent token streams
    through one GPT-2 engine deployment.

    Every stream is submitted up front (all N are OPEN concurrently:
    slots decode, the rest queue in the engine's admission lane) and
    drained by collector threads batch-polling the engine — plus a few
    lanes through the REAL streaming transports (``handle.stream`` +
    chunked HTTP) to prove order/completeness on the user-facing path.
    Client-side TTFT/token counts cross-check against the engine-side
    ``ray_tpu_serve_decode_*`` histograms (count-exact, quantile
    agreement), and the engine must report EXACTLY one compiled decode
    shape and one prefill shape after the whole run — a per-request
    recompile anywhere fails the bench. ``chaos=True`` adds a second
    pass under a seeded partition schedule committing p99-TTFT-under-
    partition with zero hung streams."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import _observability as obs
    from ray_tpu.serve import _private as sp

    ray_tpu.shutdown()
    cluster_obj = None
    if cluster:
        from ray_tpu.cluster.cluster_utils import Cluster

        cluster_obj = Cluster()
        cluster_obj.add_node(num_cpus=4)
        cluster_obj.add_node(num_cpus=4)
        cluster_obj.wait_for_nodes()
        ray_tpu.init(cluster_obj.address)
    else:
        ray_tpu.init(num_cpus=8)

    from ray_tpu.serve.llm_engine import LLMEngine

    eng = serve.deployment(
        name=LLM_DEPLOYMENT, num_replicas=1, max_concurrent_queries=64,
        route_prefix="/llm")(LLMEngine)
    try:
        handle = serve.run(eng.bind(
            model="gpt2", max_batch=max_batch, cache_len=cache_len,
            max_prompt_len=max_prompt_len, prefill_rows=prefill_rows,
            max_new_tokens=max_new_tokens,
            max_queue=streams + chaos_streams + 1024,
            deployment=LLM_DEPLOYMENT))
        # Warm-up (compiles prefill + decode) BEFORE the metric
        # snapshot so the timed run measures serving, not compilation.
        warm = ray_tpu.get(
            handle.remote({"tokens": [3, 1, 4, 1, 5],
                           "max_tokens": max_new_tokens}), timeout=300)
        assert len(warm["tokens"]) == max_new_tokens
        backend = _llm_backend()
        port = serve.start_http_proxy()

        result = {
            "streams": streams,
            "max_batch": max_batch,
            "max_new_tokens": max_new_tokens,
            "backend": "cluster" if cluster else "local",
        }
        main_pass = _llm_drive(
            backend, sp, obs, handle, port, streams=streams,
            max_new_tokens=max_new_tokens, stream_lanes=stream_lanes,
            shed_probes=shed_probes, collectors=collectors,
            deadline_s=deadline_s)
        result.update(main_pass)
        stats = _llm_rpc(backend, sp, "llm_stats", ())
        result["engine"] = {
            k: stats[k] for k in (
                "steps", "admitted", "completed", "shed", "errors",
                "tokens_out", "mean_occupancy", "queue_peak",
                "ring_wraps", "compiles")}
        # THE single-compiled-shape assertion: after warm-up + N
        # streams + lanes + probes of assorted prompt/generation
        # lengths, the engine traced each jitted shape exactly once.
        result["one_compiled_shape"] = (
            stats["compiles"] == {"decode": 1, "prefill": 1})
        result["agreement"]["one_compiled_shape"] = \
            result["one_compiled_shape"]
        result["agreement"]["ok"] = all(result["agreement"].values())

        if chaos:
            chaos_pass = _llm_chaos_pass(
                backend, sp, obs, handle, port, cluster_obj,
                streams=chaos_streams, max_new_tokens=max_new_tokens,
                collectors=collectors)
            result["chaos"] = chaos_pass
            stats2 = _llm_rpc(backend, sp, "llm_stats", ())
            # Still one shape after the chaos pass rode the same engine.
            result["chaos"]["one_compiled_shape"] = (
                stats2["compiles"] == {"decode": 1, "prefill": 1})
        return result
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
        if cluster_obj is not None:
            cluster_obj.shutdown()


def _llm_backend():
    from ray_tpu._private import worker as _worker

    return _worker.backend()


def _llm_rpc(backend, sp, method: str, args: tuple, timeout: float = 60.0):
    """Bare engine call pinned to the (single) replica."""
    [aid] = sp._stream_replicas(backend, LLM_DEPLOYMENT, refresh=True)
    return sp._stream_rpc(backend, aid, method, args, {}, None,
                          timeout=timeout)


def _llm_drive(backend, sp, obs, handle, port, *, streams: int,
               max_new_tokens: int, stream_lanes: int, shed_probes: int,
               collectors: int, deadline_s: float,
               label: str = "main") -> dict:
    """One load pass: submit `streams` requests up front, drain with
    batch-polling collectors, run transport lanes + shed probes, then
    cross-check client vs engine-side metrics."""
    import random as _random

    from ray_tpu import serve
    from ray_tpu.serve._observability import RequestShedError

    rng = _random.Random(f"serve_bench_llm:{label}")
    # Quiesce before the baseline snapshot: on the cluster backend the
    # warm-up's (or the prior pass's) observations ship on the 0.25s
    # worker-event cadence — snapshotting mid-flight would leak their
    # tokens into this pass's delta and fail the exact cross-check.
    last = None
    quiesce_deadline = time.monotonic() + 15.0
    while time.monotonic() < quiesce_deadline:
        cur = sum(obs.sum_counter(
            obs.parse_prometheus(obs.metrics_text()),
            "ray_tpu_serve_decode_tokens_total", "deployment",
            deployment=LLM_DEPLOYMENT).values())
        if last is not None and cur == last:
            break
        last = cur
        time.sleep(0.4)
    before = obs.parse_prometheus(obs.metrics_text())
    [aid] = sp._stream_replicas(backend, LLM_DEPLOYMENT, refresh=True)

    # -- transport lanes FIRST: real handle.stream + chunked HTTP prove
    # order/completeness on the user-facing paths. They run before the
    # bulk load on purpose — at 10k queued streams a lane's TTFT is the
    # whole admission queue, which only measures the queue again while
    # starving the HTTP client's socket timeout.
    lock = threading.Lock()
    lane_results = {"handle_ok": 0, "http_ok": 0, "lane_errors": []}
    lane_tokens = [0]

    def lane(kind: str, idx: int):
        prompt = [idx + 1, 7, 11]
        try:
            if kind == "handle":
                toks = [t for ch in handle.stream(prompt, max_new_tokens)
                        for t in ch]
                assert len(toks) == max_new_tokens, toks
                with lock:
                    lane_results["handle_ok"] += 1
                    lane_tokens[0] += len(toks)
            else:
                conn = _Stream(port)
                conn._conn.timeout = 300.0
                try:
                    body = json.dumps({"tokens": prompt,
                                       "max_tokens": max_new_tokens})
                    conn._conn.request(
                        "POST", "/llm", body=body.encode(),
                        headers={"Content-Type": "application/json",
                                 serve.STREAM_HEADER: "1"})
                    resp = conn._conn.getresponse()
                    toks = []
                    tail = None
                    while True:
                        line = resp.readline()
                        if not line:
                            break
                        obj = json.loads(line)
                        toks.extend(obj.get("tokens") or ())
                        if obj.get("done"):
                            tail = obj
                    assert resp.status == 200 and tail \
                        and len(toks) == max_new_tokens, (
                            resp.status, tail, toks)
                    with lock:
                        lane_results["http_ok"] += 1
                        lane_tokens[0] += len(toks)
                finally:
                    conn.close()
        except Exception as e:  # noqa: BLE001
            with lock:
                lane_results["lane_errors"].append(f"{kind}: {e!r}")

    lane_threads = [
        threading.Thread(target=lane,
                         args=("handle" if i % 2 == 0 else "http", i))
        for i in range(stream_lanes)]
    for t in lane_threads:
        t.start()
    for t in lane_threads:
        t.join()

    # -- bulk submit: every stream is open before the first is drained.
    t0 = time.perf_counter()
    submit_ts: dict = {}
    rids: list = []
    batch_size = 250
    prompts = [[rng.randrange(1, 200) for _ in range(rng.randint(3, 8))]
               for _ in range(streams)]
    for lo in range(0, streams, batch_size):
        batch = [{"tokens": p, "max_tokens": max_new_tokens}
                 for p in prompts[lo:lo + batch_size]]
        got = sp._stream_rpc(backend, aid, "llm_submit_many", (batch,),
                             {}, None, timeout=120.0)
        now = time.perf_counter()
        for rid in got:
            submit_ts[rid] = now
            rids.append(rid)
    submit_wall = time.perf_counter() - t0

    # -- collectors: batch-poll until every stream terminates.
    ttft_s: dict = {}
    tokens_got: dict = {r: 0 for r in rids}
    done_rids: set = set()
    hung: list = []
    shard = max(1, (len(rids) + collectors - 1) // collectors)

    def collect(shard_rids):
        open_rids = list(shard_rids)
        deadline = time.monotonic() + deadline_s
        while open_rids and time.monotonic() < deadline:
            chunk_rids = open_rids[:256]
            rest = open_rids[256:]
            try:
                polled = sp._stream_rpc(
                    backend, aid, "llm_poll", (chunk_rids,), {}, None,
                    timeout=60.0)
            except Exception:
                time.sleep(0.2)  # partition window: retry
                continue
            now = time.perf_counter()
            still_open = []
            with lock:
                for rid in chunk_rids:
                    resp = polled.get(rid) or {}
                    got = sum(len(c) for c in resp.get("chunks") or ())
                    if got and rid not in ttft_s:
                        ttft_s[rid] = now - submit_ts[rid]
                    tokens_got[rid] += got
                    if resp.get("done"):
                        done_rids.add(rid)
                    else:
                        still_open.append(rid)
            # Rotate: the unpolled remainder goes first so every open
            # stream is polled fairly. The inter-round sleep matters:
            # collectors share the replica's GIL with the engine loop,
            # and a tight poll spin visibly slows the decode steps.
            open_rids = rest + still_open
            time.sleep(0.05)
        with lock:
            hung.extend(r for r in open_rids if r not in done_rids)

    threads = [threading.Thread(
        target=collect, args=(rids[i * shard:(i + 1) * shard],))
        for i in range(collectors)]
    for t in threads:
        t.start()

    # -- typed shed probes: an already-dead budget must shed, not run.
    shed_seen = 0
    for _ in range(shed_probes):
        try:
            list(handle.options(deadline_s=0.0).stream([1, 2, 3], 4))
        except RequestShedError:
            shed_seen += 1
        except Exception:  # noqa: BLE001 — anything else is not a shed
            pass

    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    client_tokens = sum(tokens_got.values()) + lane_tokens[0]
    n_done = len(done_rids)
    ttft_vals = sorted(ttft_s.values())
    client = {
        "streams_done": n_done,
        "ttft_count": len(ttft_vals),
        "ttft_p50_ms": _percentile_ms(ttft_vals, 0.50)
        if ttft_vals else None,
        "ttft_p99_ms": _percentile_ms(ttft_vals, 0.99)
        if ttft_vals else None,
        "tokens": client_tokens,
        "submit_wall_s": round(submit_wall, 3),
    }

    # -- engine-side view: settle, then diff against the pre-run scrape.
    expected_streams = streams + lane_results["handle_ok"] \
        + lane_results["http_ok"]
    delta = {}
    settle = time.monotonic() + 30.0
    ttft_dist = None
    while time.monotonic() < settle:
        delta = obs.diff_parsed(
            before, obs.parse_prometheus(obs.metrics_text()))
        ttft_dist = obs.histogram_dist(
            delta, "ray_tpu_serve_decode_ttft_seconds",
            deployment=LLM_DEPLOYMENT)
        toks_counted = sum(obs.sum_counter(
            delta, "ray_tpu_serve_decode_tokens_total", "deployment",
            deployment=LLM_DEPLOYMENT).values())
        if ttft_dist and ttft_dist["count"] >= expected_streams \
                and toks_counted >= client_tokens:
            break
        time.sleep(0.25)
    step_dist = obs.histogram_dist(
        delta, "ray_tpu_serve_decode_step_seconds",
        deployment=LLM_DEPLOYMENT)
    occ_dist = obs.histogram_dist(
        delta, "ray_tpu_serve_decode_batch_occupancy",
        deployment=LLM_DEPLOYMENT)
    sheds = obs.sum_counter(delta, "ray_tpu_serve_shed_total", "reason",
                            deployment=LLM_DEPLOYMENT)
    server_tokens = int(sum(obs.sum_counter(
        delta, "ray_tpu_serve_decode_tokens_total", "deployment",
        deployment=LLM_DEPLOYMENT).values()))
    server = {"ttft_count": int(ttft_dist["count"]) if ttft_dist else 0,
              "tokens": server_tokens,
              "steps": int(step_dist["count"]) if step_dist else 0,
              "mean_occupancy": round(occ_dist["sum"] / occ_dist["count"],
                                      3) if occ_dist else None,
              "shed": {k: int(v) for k, v in sheds.items()}}
    if ttft_dist:
        for q, key in ((0.50, "ttft_p50_ms"), (0.99, "ttft_p99_ms")):
            v = obs.quantile_from_buckets(ttft_dist, q)
            server[key] = round(v * 1e3, 3) if v is not None else None

    def within(client_ms, server_ms):
        if client_ms is None or server_ms is None or not ttft_dist:
            return False
        tol = max(obs.bucket_width_at(ttft_dist, client_ms / 1e3) * 1e3,
                  0.35 * client_ms, 50.0)
        return abs(client_ms - server_ms) <= tol

    agreement = {
        "all_streams_done": n_done == streams and not hung,
        "ttft_count_exact": (ttft_dist is not None
                             and int(ttft_dist["count"])
                             == expected_streams),
        "tokens_exact": server_tokens == client_tokens,
        "ttft_p50_within_tol": within(client["ttft_p50_ms"],
                                      server.get("ttft_p50_ms")),
        "ttft_p99_within_tol": within(client["ttft_p99_ms"],
                                      server.get("ttft_p99_ms")),
        # A dead-on-arrival budget sheds typed at the replica boundary
        # (reason=replica) or in the engine (reason=decode) — either
        # way it must land in the shed family, never execute.
        "sheds_typed": shed_probes == 0
        or sum(sheds.values()) >= shed_seen > 0,
        "lanes_ok": not lane_results["lane_errors"]
        and lane_results["handle_ok"] + lane_results["http_ok"]
        == stream_lanes,
    }
    agreement["ok"] = all(agreement.values())
    return {
        "client": client,
        "server": server,
        "agreement": agreement,
        "hung_streams": len(hung),
        "tokens_s": round(client_tokens / wall, 1),
        "wall_s": round(wall, 2),
        "lanes": lane_results,
        "shed_probes": {"sent": shed_probes, "shed_typed": shed_seen},
    }


def _llm_chaos_pass(backend, sp, obs, handle, port, cluster_obj, *,
                    streams: int, max_new_tokens: int,
                    collectors: int) -> dict:
    """The PR-5 partition schedule over a live stream load: seeded
    head<->node cuts (healed inside the reconnect window) while streams
    decode — p99 TTFT under partition is the committed number, and a
    single hung stream fails the pass."""
    from ray_tpu.util import failpoints

    rng = failpoints.seeded_rng("serve_bench_llm_chaos")
    stop = threading.Event()
    cuts = {"n": 0}

    def partition_loop():
        while not stop.is_set():
            time.sleep(rng.uniform(1.0, 2.0))
            if stop.is_set():
                return
            try:
                if cluster_obj is not None and len(cluster_obj.nodes) > 1:
                    victim = cluster_obj.nodes[-1]
                    cluster_obj.partition([["head"], [victim]])
                    time.sleep(rng.uniform(0.4, 1.0))
                    cluster_obj.heal()
                else:
                    # Local backend: no network to cut — delay the
                    # engine loop instead so the pass still runs under
                    # injected fault pressure.
                    failpoints.set_failpoints(
                        {"serve.llm.before_step": "delay:0.05"})
                    time.sleep(rng.uniform(0.4, 1.0))
                    failpoints.set_failpoints(
                        {"serve.llm.before_step": None})
                cuts["n"] += 1
            except Exception:
                return

    injector = threading.Thread(target=partition_loop, daemon=True)
    injector.start()
    try:
        # No shed probes under partition: a probe racing a cut can fail
        # with a connection error instead of the typed shed, which is
        # correct behavior but not this pass's claim — shed typing is
        # the MAIN pass's assertion; this pass asserts zero hangs.
        # Lanes are off too: a lane failing FAST mid-cut is correct
        # (fail fast, never hang) but leaves an engine-side stream the
        # client-side count can no longer match exactly.
        out = _llm_drive(
            backend, sp, obs, handle, port, streams=streams,
            max_new_tokens=max_new_tokens, stream_lanes=0,
            shed_probes=0, collectors=collectors, deadline_s=600.0,
            label="chaos")
    finally:
        stop.set()
        injector.join(timeout=30.0)
        if cluster_obj is not None:
            try:
                cluster_obj.heal()
            except Exception:
                pass
        failpoints.set_failpoints({"serve.llm.before_step": None})
    return {
        "streams": streams,
        "partitions": cuts["n"],
        "p99_under_partition_ms": out["client"]["ttft_p99_ms"],
        "hung_streams": out["hung_streams"],
        "tokens_s": out["tokens_s"],
        "agreement": out["agreement"],
        "zero_hung": out["hung_streams"] == 0,
    }


def _collect_spans(ray_tpu):
    """This process's spans + the backend's span store (cluster: spans
    ship over the worker-events plane to the head)."""
    from ray_tpu._private import worker as _worker
    from ray_tpu.util import tracing

    spans = {s["span_id"]: s for s in tracing.collect()}
    try:
        backend = _worker.backend()
        if hasattr(backend, "list_spans"):
            for s in backend.list_spans():
                spans.setdefault(s["span_id"], s)
    except Exception:
        pass
    return list(spans.values())


def main() -> None:
    ap = argparse.ArgumentParser(
        description="Serve concurrent-stream load harness with "
                    "client/server latency cross-check")
    ap.add_argument("--out", default=None,
                    help="merge the serve section into this "
                         "MICROBENCH-style artifact")
    ap.add_argument("--mode", choices=["http", "handle"], default="http")
    ap.add_argument("--connections", type=int, default=8)
    ap.add_argument("--requests", type=int, default=25)
    ap.add_argument("--sleep-ms", type=float, default=2.0)
    ap.add_argument("--batch", action="store_true",
                    help="serve through a @serve.batch deployment "
                         "(exercises the batch_wait phase + batch shed)")
    ap.add_argument("--cluster", action="store_true",
                    help="run against a real multiprocess cluster "
                         "backend (events ship over the worker plane)")
    ap.add_argument("--llm", action="store_true",
                    help="continuous-batching LLM mode: N concurrent "
                         "token streams through a GPT-2 engine "
                         "deployment, TTFT/tokens-s cross-check + the "
                         "single-compiled-shape assertion")
    ap.add_argument("--streams", type=int, default=10_000,
                    help="concurrent token streams for --llm")
    ap.add_argument("--max-batch", type=int, default=64,
                    help="engine decode slots for --llm")
    ap.add_argument("--max-new", type=int, default=8,
                    help="tokens generated per stream for --llm")
    ap.add_argument("--chaos", action="store_true",
                    help="with --llm: add a second pass under a seeded "
                         "partition schedule (commits p99 TTFT under "
                         "partition; any hung stream fails)")
    args = ap.parse_args()

    from ray_tpu.scripts import bench_log

    if args.llm:
        res = run_llm(streams=args.streams, max_batch=args.max_batch,
                      max_new_tokens=args.max_new, cluster=args.cluster,
                      chaos=args.chaos)
        if res["client"]["ttft_p50_ms"] is not None:
            entry = bench_log.record_llm_serving(
                client=res["client"], server=res["server"],
                agreement=res["agreement"], streams=res["streams"],
                tokens_s=res["tokens_s"], device=_device_kind(),
                script="serve_bench", engine=res["engine"],
                hung_streams=res["hung_streams"])
            res["evidence"] = {k: entry[k] for k in ("committed_to",)
                               if k in entry}
        if args.out:
            payload = {}
            if os.path.exists(args.out):
                with open(args.out) as f:
                    try:
                        payload = json.load(f)
                    except ValueError:
                        payload = {}
            payload["llm_serving"] = res
            with open(args.out, "w") as f:
                json.dump(payload, f, indent=1, sort_keys=True)
                f.write("\n")
        print(json.dumps(res, indent=1, default=str))
        bad = (not res["agreement"]["ok"] or res["hung_streams"]
               or (args.chaos and not (
                   res["chaos"]["zero_hung"]
                   and res["chaos"]["agreement"]["ok"]
                   and res["chaos"]["one_compiled_shape"])))
        if bad:
            print("serve_bench --llm: FAILED (disagreement or hung "
                  "streams); see 'agreement'", file=sys.stderr)
            sys.exit(1)
        return

    res = run(mode=args.mode, connections=args.connections,
              requests_per_conn=args.requests, sleep_ms=args.sleep_ms,
              batch=args.batch, cluster=args.cluster)

    # Only a lint-valid line may enter the committed trail: a
    # degenerate run (every stream request failed -> no client
    # latencies) must not poison BENCH_TPU_SESSIONS.jsonl with a line
    # tier-1's evidence check would reject forever after.
    if res["client"]["p50_ms"] is not None:
        entry = bench_log.record_serve_latency(
            client=res["client"], server=res["server"],
            agreement=res["agreement"], mode=res["mode"],
            connections=res["connections"],
            n_requests=res["client"]["count"], device=_device_kind(),
            script="serve_bench")
        res["evidence"] = {k: entry[k] for k in ("committed_to",)
                           if k in entry}

    if args.out:
        # Merge-preserve: every perfsuite stage owns one section.
        payload = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                try:
                    payload = json.load(f)
                except ValueError:
                    payload = {}
        payload["serve"] = res
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
    print(json.dumps(res, indent=1, default=str))
    if not res["agreement"]["ok"]:
        print("serve_bench: CLIENT/SERVER DISAGREE — the serve metrics "
              "are lying; see 'agreement'", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
