"""Serve load harness: N concurrent streams + client/server cross-check.

Drives ``--connections`` concurrent client streams (each a thread
issuing sequential requests) through a deployment — over the HTTP proxy
by default, or the handle path — and records client-side p50/p99/QPS.
Then it reads the server-side ``ray_tpu_serve_request_seconds``
histograms back from the metrics plane and REQUIRES the two views to
agree: exact request-count match, and p50/p99/mean agreement within the
histogram's bucket resolution. If client and server disagree, the
metrics are lying (a phase is unobserved, double-counted, or
mis-tagged) and the bench exits non-zero — the latency plane itself is
under test, not just the deployment.

Also exercised per run: deadline sheds (requests sent with an
already-expired budget must come back 503/shed and land in
``ray_tpu_serve_shed_total``) and — when tracing — one end-to-end
traced request whose ingress/route/replica spans must share a trace id.

Machine-independent shape results (counts, agreement booleans, phases
observed) merge into MICROBENCH.json under ``serve`` (perfsuite
``--serve`` stage); latency numbers ride along for context only.
``bench_log.record_serve_latency`` commits an evidence line on-chip.

Run: python -m ray_tpu.scripts.serve_bench [--out MICROBENCH.json]
     [--mode http|handle] [--connections 8] [--requests 25] [--cluster]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

DEPLOYMENT = "serve_bench_echo"


def _device_kind() -> str:
    from ray_tpu.scripts.bench_log import device_kind

    return device_kind()


class _Stream:
    """One persistent client connection (HTTP keep-alive — the shape of
    a real load client; a fresh TCP handshake per request would measure
    the OS, not the serving path). ``post`` returns (status, body) for
    ANY status — a 503 shed is data here, not an exception."""

    def __init__(self, port: int):
        import http.client

        self._conn = http.client.HTTPConnection(
            "127.0.0.1", port, timeout=60.0)

    def post(self, path: str, payload, headers=None):
        body = json.dumps(payload).encode()
        self._conn.request(
            "POST", path, body=body,
            headers={"Content-Type": "application/json",
                     **(headers or {})})
        resp = self._conn.getresponse()
        data = resp.read()
        return resp.status, (json.loads(data) if data else None)

    def close(self):
        try:
            self._conn.close()
        except Exception:
            pass


def _percentile_ms(vals_s, q):
    from ray_tpu.util.metrics import percentile

    return round(percentile(sorted(vals_s), q) * 1e3, 3)


def run(mode: str = "http", connections: int = 8,
        requests_per_conn: int = 25, sleep_ms: float = 2.0,
        batch: bool = False, shed_probes: int = 4,
        cluster: bool = False, trace_check: bool = True) -> dict:
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import _observability as obs
    from ray_tpu.util import tracing

    ray_tpu.shutdown()
    cluster_obj = None
    prev_trace_env = os.environ.get("RAY_TPU_TRACING_ENABLED")
    if trace_check:
        # Operator opt-in BEFORE the cluster spawns: worker processes
        # (proxy, routers, replicas) read the env at import — an
        # unauthenticated traceparent header alone no longer enables
        # tracing server-side.
        os.environ["RAY_TPU_TRACING_ENABLED"] = "1"
    if cluster:
        from ray_tpu.cluster.cluster_utils import Cluster

        cluster_obj = Cluster()
        cluster_obj.add_node(num_cpus=8)
        cluster_obj.wait_for_nodes()
        ray_tpu.init(cluster_obj.address)
    else:
        ray_tpu.init(num_cpus=max(8, connections))

    sleep_s = sleep_ms / 1e3

    if batch:
        @serve.deployment(name=DEPLOYMENT, num_replicas=2,
                          max_concurrent_queries=64,
                          route_prefix="/bench")
        class Echo:  # noqa: F811 — bench-local deployment
            @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.005)
            def handle(self, items):
                time.sleep(sleep_s)
                return [{"x": i.get("x") if isinstance(i, dict) else i}
                        for i in items]

            def __call__(self, payload):
                return self.handle(payload)
    else:
        @serve.deployment(name=DEPLOYMENT, num_replicas=2,
                          max_concurrent_queries=64,
                          route_prefix="/bench")
        class Echo:
            def __call__(self, payload):
                time.sleep(sleep_s)
                return {"x": payload.get("x")
                        if isinstance(payload, dict) else payload}

    try:
        handle = serve.run(Echo.bind())
        port = serve.start_http_proxy() if mode == "http" else None
        before = obs.parse_prometheus(obs.metrics_text())

        latencies: list = []
        errors: list = []
        lat_lock = threading.Lock()

        def stream(conn_id: int):
            conn = _Stream(port) if mode == "http" else None
            try:
                for i in range(requests_per_conn):
                    t0 = time.perf_counter()
                    try:
                        if mode == "http":
                            status, body = conn.post(
                                "/bench", {"x": conn_id * 1000 + i})
                            ok = (status == 200
                                  and body.get("x") == conn_id * 1000 + i)
                        else:
                            out = ray_tpu.get(
                                handle.remote({"x": conn_id * 1000 + i}),
                                timeout=60.0)
                            ok = out.get("x") == conn_id * 1000 + i
                        dt = time.perf_counter() - t0
                        with lat_lock:
                            if ok:
                                latencies.append(dt)
                            else:
                                errors.append("wrong result")
                    except Exception as e:  # noqa: BLE001
                        with lat_lock:
                            errors.append(repr(e))
            finally:
                if conn is not None:
                    conn.close()

        t_start = time.perf_counter()
        threads = [threading.Thread(target=stream, args=(c,))
                   for c in range(connections)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall_s = time.perf_counter() - t_start

        # Server side: wait for the stream observations to settle (the
        # cluster backend ships them over the 0.25s worker-event
        # cadence), then diff against the pre-run snapshot so ONLY the
        # streams' requests enter the cross-check — the shed and trace
        # probes below come after this window on purpose.
        n_ok = len(latencies)
        delta = None
        after = before
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            after = obs.parse_prometheus(obs.metrics_text())
            delta = obs.diff_parsed(before, after)
            dist = obs.histogram_dist(
                delta, "ray_tpu_serve_request_seconds",
                deployment=DEPLOYMENT, phase="total")
            if dist and dist["count"] >= n_ok:
                break
            time.sleep(0.25)

        dist = obs.histogram_dist(delta, "ray_tpu_serve_request_seconds",
                                  deployment=DEPLOYMENT, phase="total")
        statuses = obs.sum_counter(delta, "ray_tpu_serve_requests_total",
                                   "status", deployment=DEPLOYMENT)
        phases_observed = sorted(
            p for p in obs.PHASES
            if obs.histogram_dist(delta, "ray_tpu_serve_request_seconds",
                                  deployment=DEPLOYMENT, phase=p))

        # Deadline sheds: an already-expired budget must come back as a
        # clean 503/shed, never execute, and count in the shed family.
        shed_seen = 0
        probe_conn = _Stream(port) if mode == "http" else None
        for _ in range(shed_probes):
            try:
                if mode == "http":
                    status, body = probe_conn.post(
                        "/bench", {"x": 1},
                        headers={serve.DEADLINE_HEADER: "0"})
                    if status == 503:
                        shed_seen += 1
                else:
                    ray_tpu.get(
                        handle.options(deadline_s=0.0).remote({"x": 1}),
                        timeout=60.0)
            except Exception as e:  # noqa: BLE001 — handle path sheds
                if "RequestShedError" in repr(e) or "shed" in repr(e):
                    shed_seen += 1
        sheds = {}
        if shed_probes:
            shed_deadline = time.monotonic() + 20.0
            while time.monotonic() < shed_deadline:
                shed_delta = obs.diff_parsed(
                    after, obs.parse_prometheus(obs.metrics_text()))
                sheds = obs.sum_counter(
                    shed_delta, "ray_tpu_serve_shed_total", "reason",
                    deployment=DEPLOYMENT)
                if sum(sheds.values()) >= shed_seen:
                    break
                time.sleep(0.25)

        # One traced request: ingress -> route -> replica must share a
        # trace id (the end-to-end propagation claim, checked live).
        trace = {}
        if trace_check:
            tracing.enable()
            trace_id = None
            if mode == "http":
                want = "aa" * 16
                if probe_conn is not None:
                    probe_conn.post(
                        "/bench", {"x": 0},
                        headers={"traceparent":
                                 f"00-{want}-{'bb' * 8}-01"})
                trace_id = want
            else:
                with tracing.span("serve_bench.client") as s:
                    ray_tpu.get(handle.remote({"x": 0}), timeout=60.0)
                    trace_id = s["trace_id"]
            deadline = time.monotonic() + 15.0
            names: set = set()
            while time.monotonic() < deadline:
                spans = [s for s in _collect_spans(ray_tpu)
                         if s["trace_id"] == trace_id
                         and s.get("cat") == "serve"]
                names = {s["name"].split(":")[0] for s in spans}
                want_names = {"serve.route", "serve.replica"} | (
                    {"serve.http"} if mode == "http" else set())
                if want_names <= names:
                    break
                time.sleep(0.25)
            trace = {"trace_id": trace_id,
                     "span_kinds": sorted(names),
                     "one_trace": {"serve.route", "serve.replica"}
                     <= names}
        if probe_conn is not None:
            probe_conn.close()

        client = {
            "count": n_ok,
            "errors": len(errors),
            "p50_ms": _percentile_ms(latencies, 0.50) if latencies else None,
            "p99_ms": _percentile_ms(latencies, 0.99) if latencies else None,
            "mean_ms": round(sum(latencies) / n_ok * 1e3, 3)
            if n_ok else None,
            "qps": round((n_ok + len(errors)) / wall_s, 1),
        }
        server = {"count": int(dist["count"]) if dist else 0}
        if dist:
            server["mean_ms"] = round(dist["sum"] / dist["count"] * 1e3, 3)
            for q, key in ((0.50, "p50_ms"), (0.99, "p99_ms")):
                v = obs.quantile_from_buckets(dist, q)
                server[key] = round(v * 1e3, 3) if v is not None else None

        # Client latency = server-observed total + ingress overhead the
        # server cannot see (HTTP parse, event-loop scheduling, the
        # executor hop). That overhead is ~constant per request, so it
        # is measured from the means and subtracted before comparing
        # quantile SHAPES; the server claiming MORE time than the
        # client saw, or a count mismatch, is unconditionally lying.
        ingress_ms = 0.0
        if client["mean_ms"] is not None and "mean_ms" in server:
            ingress_ms = max(0.0, client["mean_ms"] - server["mean_ms"])

        def within(client_ms, server_ms):
            """Histogram agreement: a bucket estimate can only be as
            precise as the bucket the sample fell in."""
            if client_ms is None or server_ms is None or not dist:
                return False
            tol_ms = max(
                obs.bucket_width_at(dist, client_ms / 1e3) * 1e3,
                0.35 * client_ms, 5.0)
            return abs((client_ms - ingress_ms) - server_ms) <= tol_ms

        agreement = {
            "count_exact": server["count"] == n_ok,
            "p50_within_tol": within(client["p50_ms"],
                                     server.get("p50_ms")),
            "p99_within_tol": within(client["p99_ms"],
                                     server.get("p99_ms")),
            "server_not_exceeding": (
                "mean_ms" in server and client["mean_ms"] is not None
                and server["mean_ms"]
                <= client["mean_ms"] * 1.1 + 5.0),
            "status_ok_match": int(statuses.get("ok", 0)) == n_ok,
            "shed_counted": (shed_probes == 0
                             or sum(sheds.values()) >= shed_seen > 0),
        }
        agreement["ok"] = all(agreement.values())
        client["ingress_overhead_ms"] = round(ingress_ms, 3)

        result = {
            "mode": mode,
            "backend": "cluster" if cluster else "local",
            "connections": connections,
            "requests_per_conn": requests_per_conn,
            "batch": batch,
            "client": client,
            "server": server,
            "statuses": {k: int(v) for k, v in statuses.items()},
            "shed": {"probes": shed_probes, "client_seen": shed_seen,
                     "server_counted": {k: int(v)
                                        for k, v in sheds.items()}},
            "phases_observed": phases_observed,
            "agreement": agreement,
        }
        if trace:
            result["trace"] = trace
        return result
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
        if cluster_obj is not None:
            cluster_obj.shutdown()
        if trace_check:
            if prev_trace_env is None:
                os.environ.pop("RAY_TPU_TRACING_ENABLED", None)
            else:
                os.environ["RAY_TPU_TRACING_ENABLED"] = prev_trace_env


def _collect_spans(ray_tpu):
    """This process's spans + the backend's span store (cluster: spans
    ship over the worker-events plane to the head)."""
    from ray_tpu._private import worker as _worker
    from ray_tpu.util import tracing

    spans = {s["span_id"]: s for s in tracing.collect()}
    try:
        backend = _worker.backend()
        if hasattr(backend, "list_spans"):
            for s in backend.list_spans():
                spans.setdefault(s["span_id"], s)
    except Exception:
        pass
    return list(spans.values())


def main() -> None:
    ap = argparse.ArgumentParser(
        description="Serve concurrent-stream load harness with "
                    "client/server latency cross-check")
    ap.add_argument("--out", default=None,
                    help="merge the serve section into this "
                         "MICROBENCH-style artifact")
    ap.add_argument("--mode", choices=["http", "handle"], default="http")
    ap.add_argument("--connections", type=int, default=8)
    ap.add_argument("--requests", type=int, default=25)
    ap.add_argument("--sleep-ms", type=float, default=2.0)
    ap.add_argument("--batch", action="store_true",
                    help="serve through a @serve.batch deployment "
                         "(exercises the batch_wait phase + batch shed)")
    ap.add_argument("--cluster", action="store_true",
                    help="run against a real multiprocess cluster "
                         "backend (events ship over the worker plane)")
    args = ap.parse_args()

    res = run(mode=args.mode, connections=args.connections,
              requests_per_conn=args.requests, sleep_ms=args.sleep_ms,
              batch=args.batch, cluster=args.cluster)

    from ray_tpu.scripts import bench_log

    # Only a lint-valid line may enter the committed trail: a
    # degenerate run (every stream request failed -> no client
    # latencies) must not poison BENCH_TPU_SESSIONS.jsonl with a line
    # tier-1's evidence check would reject forever after.
    if res["client"]["p50_ms"] is not None:
        entry = bench_log.record_serve_latency(
            client=res["client"], server=res["server"],
            agreement=res["agreement"], mode=res["mode"],
            connections=res["connections"],
            n_requests=res["client"]["count"], device=_device_kind(),
            script="serve_bench")
        res["evidence"] = {k: entry[k] for k in ("committed_to",)
                           if k in entry}

    if args.out:
        # Merge-preserve: every perfsuite stage owns one section.
        payload = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                try:
                    payload = json.load(f)
                except ValueError:
                    payload = {}
        payload["serve"] = res
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
    print(json.dumps(res, indent=1, default=str))
    if not res["agreement"]["ok"]:
        print("serve_bench: CLIENT/SERVER DISAGREE — the serve metrics "
              "are lying; see 'agreement'", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
