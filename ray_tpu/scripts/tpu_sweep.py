"""One-off TPU config sweep for the GPT-2 headline bench.

Measures step time / MFU for a grid of (config, batch) points on whatever
device is attached, printing one JSON line per point. Used to pick the
shipped `bench.py` config; results are recorded in PROFILE.md.

Run: python -m ray_tpu.scripts.tpu_sweep '[["base",16],["lever",24],...]'
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time

import jax
import jax.numpy as jnp

from ray_tpu.models.gpt2 import (
    GPT2Config,
    gpt2_flops_per_token,
    gpt2_init,
    gpt2_loss,
    gpt2_shardings,
)
from ray_tpu.parallel.mesh import MeshConfig, build_mesh
from ray_tpu.train.train_step import make_init_fn, make_train_step

PEAK = 197.0e12  # v5e bf16


def measure(cfg: GPT2Config, batch: int, steps: int = 20, warmup: int = 3):
    warmup = max(warmup, 1)  # >=1: the post-warmup sync reads metrics
    mesh = build_mesh(MeshConfig(fsdp=-1))
    shardings = gpt2_shardings(cfg, mesh)
    init_fn = make_init_fn(lambda r: gpt2_init(r, cfg), shardings, mesh)
    state = init_fn(jax.random.key(0))
    step_fn = make_train_step(lambda p, b: gpt2_loss(p, b, cfg), shardings, mesh)
    tokens = jax.random.randint(
        jax.random.key(1), (batch, cfg.seq_len + 1), 0, cfg.vocab_size, jnp.int32)
    batch_data = {"tokens": tokens}
    for _ in range(warmup):
        state, metrics = step_fn(state, batch_data)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, batch_data)
    loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    tok_s = batch * cfg.seq_len * steps / dt
    mfu = tok_s * gpt2_flops_per_token(cfg) / PEAK * 100.0
    return {"tok_s": round(tok_s, 1), "mfu": round(mfu, 2),
            "ms_step": round(dt / steps * 1000, 2), "loss": round(loss, 3)}


def main() -> None:
    base = GPT2Config(use_flash=True, remat="dots", scan_layers=False)
    named = {
        "base": base,
        "lever": dataclasses.replace(
            base, logits_dtype=jnp.bfloat16, ce_vocab_chunks=3),
        "bf16_only": dataclasses.replace(base, logits_dtype=jnp.bfloat16),
        "chunk_only": dataclasses.replace(base, ce_vocab_chunks=3),
        "chunk6": dataclasses.replace(
            base, logits_dtype=jnp.bfloat16, ce_vocab_chunks=6),
    }
    points = json.loads(sys.argv[1]) if len(sys.argv) > 1 else [
        ["base", 16], ["lever", 24], ["lever", 32]]
    from ray_tpu.scripts.bench_log import record_if_on_chip

    device_kind = jax.devices()[0].device_kind
    n_dev = jax.device_count()
    for name, batch in points:
        try:
            r = measure(named[name], int(batch))
            print(json.dumps({"config": name, "batch": batch, **r}), flush=True)
            # Evidence trail (VERDICT r5 item 1a): every successful
            # on-chip point lands in BENCH_TPU_SESSIONS.jsonl.
            record_if_on_chip({
                "script": "tpu_sweep", "config": name, "batch": int(batch),
                "device": device_kind, "n_devices": n_dev, **r,
            })
        except Exception as e:  # noqa: BLE001 — sweep survives OOM points
            print(json.dumps({"config": name, "batch": batch,
                              "error": repr(e)[:200]}), flush=True)


if __name__ == "__main__":
    main()
