"""One-off TPU config sweep for the GPT-2 headline bench.

Measures step time / MFU for a grid of (config, batch) points on whatever
device is attached, printing one JSON line per point. Used to pick the
shipped `bench.py` config; results are recorded in PROFILE.md, and every
successful on-chip point auto-appends to BENCH_TPU_SESSIONS.jsonl.

The timed-step protocol (steps/warmup/sync/FLOPs accounting) is the
shared harness in ``scripts/measure.py`` — the same loop ``bench.py``
times, so sweep points and the headline number are directly comparable.
Failed points record the full traceback tail, not a truncated repr: a
one-shot tunnel-window failure must be diagnosable from the JSON alone.

Run: python -m ray_tpu.scripts.tpu_sweep '[["base",16],["fused_norm",16],...]'

Named configs: base (round-3 winner), lever (round-5: bf16 logits +
chunked CE), bf16_only, chunk_only, chunk6, fused_norm (round-7: lever +
fused Pallas norm/residual/GELU backward kernels), fused_only (base +
fused kernels, isolating the kernel effect from the round-5 lever).
The default point list is the round-7 before/after ablation —
base/lever vs fused_norm at batch 16 and 24 — ready to run unattended
in the next tunnel window.
"""

from __future__ import annotations

import dataclasses
import json
import sys

import jax
import jax.numpy as jnp

from ray_tpu.models.gpt2 import GPT2Config
from ray_tpu.scripts.measure import error_entry, measure_gpt2


def named_configs() -> dict[str, GPT2Config]:
    base = GPT2Config(use_flash=True, remat="dots", scan_layers=False)
    lever = dataclasses.replace(
        base, logits_dtype=jnp.bfloat16, ce_vocab_chunks=3)
    return {
        "base": base,
        "lever": lever,
        "bf16_only": dataclasses.replace(base, logits_dtype=jnp.bfloat16),
        "chunk_only": dataclasses.replace(base, ce_vocab_chunks=3),
        "chunk6": dataclasses.replace(
            base, logits_dtype=jnp.bfloat16, ce_vocab_chunks=6),
        "fused_norm": dataclasses.replace(lever, fused_norm=True),
        "fused_only": dataclasses.replace(base, fused_norm=True),
    }


# Round-7 ablation grid (PROFILE.md sink #3): before/after for the fused
# norm kernels at the shipped batch and the next size up.
DEFAULT_POINTS = [
    ["base", 16],
    ["lever", 16],
    ["fused_norm", 16],
    ["lever", 24],
    ["fused_norm", 24],
]


def main() -> None:
    named = named_configs()
    points = json.loads(sys.argv[1]) if len(sys.argv) > 1 else DEFAULT_POINTS
    from ray_tpu.scripts.bench_log import record_if_on_chip

    device_kind = jax.devices()[0].device_kind
    n_dev = jax.device_count()
    for name, batch in points:
        try:
            r = measure_gpt2(named[name], int(batch))
            r.pop("dt", None)
            print(json.dumps({"config": name, **r}), flush=True)
            # Evidence trail (VERDICT r5 item 1a): every successful
            # on-chip point lands in BENCH_TPU_SESSIONS.jsonl.
            record_if_on_chip({
                "script": "tpu_sweep", "config": name,
                "device": device_kind, "n_devices": n_dev, **r,
            })
        except Exception as e:  # noqa: BLE001 — sweep survives OOM points
            print(json.dumps({"config": name, "batch": batch,
                              **error_entry(e)}), flush=True)


if __name__ == "__main__":
    main()
