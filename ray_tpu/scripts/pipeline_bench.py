"""1F1B vs GPipe pipeline microbenchmark (CPU virtual devices).

Quantifies the two claims ``parallel/pipeline.py`` makes (the round-4
verdict asked for measurements, not assertions):

  * step time: both schedules share the bubble-fraction law
    (pp-1)/(n_micro+pp-1); 1F1B's interleaving shaves the flush tail
    (fewer ticks for the same work);
  * memory: 1F1B stashes O(pp) live activations per stage, GPipe
    O(n_micro) — read straight off XLA's compiled-buffer analysis.

Usage: python -m ray_tpu.scripts.pipeline_bench [--out MICROBENCH.json]
Writes/merges a "pipeline" section keyed by pp/n_micro/style.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def run_all() -> dict:
    # CPU-device benchmark by design: force the platform regardless of
    # any site TPU plugin env (JAX_PLATFORMS=axon etc.). A site hook may
    # have pre-imported jax, so set the config too (conftest.py fix).
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu._compat import set_num_cpu_devices

    set_num_cpu_devices(8)
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from ray_tpu.parallel.pipeline import (
        build_1f1b_schedule,
        pipeline_value_and_grad,
    )

    d_model, seq = 128, 64
    results: dict = {}
    for pp in (2, 4):
        n_micro = 4 * pp
        mb = 2
        batch = mb * n_micro
        mesh = Mesh(np.array(jax.devices()[:pp]).reshape(pp), ("pp",))
        rngs = jax.random.split(jax.random.key(0), pp)
        params = {
            "w1": jnp.stack([jax.random.normal(r, (d_model, 4 * d_model))
                             * 0.02 for r in rngs]),
            "w2": jnp.stack([jax.random.normal(r, (4 * d_model, d_model))
                             * 0.02 for r in rngs]),
        }
        x = jax.random.normal(jax.random.key(1), (batch, seq, d_model))
        y = jax.random.normal(jax.random.key(2), (batch, seq, d_model))

        def stage_fn(p, xx):
            return xx + jax.nn.gelu(xx @ p["w1"]) @ p["w2"]

        def loss_fn(o, yy):
            return jnp.mean((o - yy) ** 2)

        for style in ("1f1b", "gpipe"):
            def step(sp):
                return pipeline_value_and_grad(
                    sp, x, y, mesh, stage_fn=stage_fn, loss_fn=loss_fn,
                    n_micro=n_micro, style=style)

            jitted = jax.jit(step)
            compiled = jitted.lower(params).compile()
            mem = compiled.memory_analysis()
            temp_mb = getattr(mem, "temp_size_in_bytes", 0) / 2**20
            loss, grads = jitted(params)  # warm
            jax.block_until_ready(loss)
            t0 = time.perf_counter()
            steps = 10
            for _ in range(steps):
                loss, grads = jitted(params)
            jax.block_until_ready(loss)
            dt = (time.perf_counter() - t0) / steps
            ticks = len(build_1f1b_schedule(n_micro, pp, style)[0])
            # Every tick executes one (masked) fwd AND one (masked) bwd
            # slot, so a bubble-free schedule would need n_micro ticks;
            # the excess is warmup/drain slots that run masked work.
            ideal = n_micro
            key = f"pp{pp}_m{n_micro}_{style}"
            results[key] = {
                "step_ms": round(dt * 1000, 2),
                "ticks": ticks,
                "bubble_frac": round(1 - ideal / ticks, 4),
                "xla_temp_mb": round(temp_mb, 2),
            }
            print(f"{key}: {results[key]}", file=sys.stderr, flush=True)
    return results


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    results = run_all()
    if args.out:
        merged = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                merged = json.load(f)
        merged["pipeline"] = results
        merged.setdefault("meta", {})["pipeline_cmd"] = (
            "python -m ray_tpu.scripts.pipeline_bench")
        with open(args.out, "w") as f:
            json.dump(merged, f, indent=2, sort_keys=True)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
