"""CLI: ``python -m ray_tpu.scripts.cli <command>``.

Reference parity: ``python/ray/scripts/scripts.py`` (``ray start/stop/
status/list/summary/timeline/memory``) + the state CLI
(``experimental/state/state_cli.py``).
"""

from __future__ import annotations

import argparse
import json
import sys


def _connect(args):
    import ray_tpu

    ray_tpu.init(args.address)
    return ray_tpu


def cmd_start(args):
    """Start a head or worker node daemon (blocks until SIGTERM)."""
    if args.head:
        from ray_tpu.cluster.head import main as head_main

        sys.argv = ["head", "--port", str(args.port)]
        head_main()
    else:
        if not args.address:
            print("--address required for worker nodes", file=sys.stderr)
            sys.exit(2)
        from ray_tpu.cluster.node_agent import main as node_main

        sys.argv = ["node", "--head", args.address]
        if args.num_cpus is not None:
            sys.argv += ["--num-cpus", str(args.num_cpus)]
        node_main()


def cmd_status(args):
    ray_tpu = _connect(args)
    total = ray_tpu.cluster_resources()
    avail = ray_tpu.available_resources()
    nodes = ray_tpu.nodes()

    def node_state(n):
        return n.get("State") or ("ALIVE" if n["Alive"] else "DEAD")

    alive = sum(1 for n in nodes if node_state(n) == "ALIVE")
    draining = sum(1 for n in nodes if node_state(n) == "DRAINING")
    extra = f", {draining} draining" if draining else ""
    print(f"nodes: {alive} alive{extra} / {len(nodes)}")
    for n in nodes:
        state = node_state(n)
        why = n.get("DrainReason") if state == "DRAINING" \
            else n.get("DeathCause")
        labels = n.get("Labels") or {}
        kind = labels.get("node_type") or "-"
        if labels.get("spot"):
            kind += " (spot)"
        print(f"  {n['NodeID'][-12:]:<14} {state:<9} {kind:<16}"
              + (f" ({why})" if why else ""))
    for k in sorted(total):
        print(f"  {k}: {avail.get(k, 0.0):g} / {total[k]:g} available")
    from ray_tpu import state

    fleet = state.autoscaler_status() or {}
    if fleet.get("types"):
        print(f"autoscaler: max_workers {fleet.get('max_workers', '?')}"
              + (f", draining {len(fleet['draining'])}"
                 if fleet.get("draining") else "")
              + (f", SLO burns: {', '.join(fleet['slo_burns'])}"
                 if fleet.get("slo_burns") else ""))
        for name, t in sorted(fleet["types"].items()):
            flags = []
            if t.get("spot"):
                flags.append("spot")
            if t.get("quarantined"):
                flags.append(
                    f"QUARANTINED {t['quarantine_remaining_s']:g}s")
            elif t.get("backoff_remaining_s"):
                flags.append(f"backoff {t['backoff_remaining_s']:g}s")
            if t.get("failures"):
                flags.append(f"{t['failures']} boot failure(s)")
            suffix = f" [{', '.join(flags)}]" if flags else ""
            print(f"  {name:<16} nodes {t.get('nodes', 0)}{suffix}")

    pgs = state.placement_groups() or {}
    active = {pid: pg for pid, pg in pgs.items()
              if pg.get("state") not in ("REMOVED",)}
    if active:
        by_state: dict = {}
        for pg in active.values():
            by_state[pg["state"]] = by_state.get(pg["state"], 0) + 1
        states = ", ".join(f"{n} {s}" for s, n in sorted(by_state.items()))
        print(f"placement groups: {states}")
        for pid, pg in sorted(active.items()):
            n_live = len(pg.get("live_bundles", ()))
            n_all = len(pg.get("bundles", ()))
            extra = ""
            if pg.get("reschedules"):
                extra += f", {pg['reschedules']} reschedule(s)"
            if pg["state"] == "RESCHEDULING" and pg.get("reschedule_cause"):
                extra += f" ({pg['reschedule_cause']})"
            print(f"  {pid[-12:]:<14} {pg['state']:<12} "
                  f"bundles {n_live}/{n_all} live{extra}")

    snaps = [s for s in state.device_stats() if s.get("available")]
    if snaps:
        # One line per jax-loaded worker process: platform, device
        # count, HBM in use / limit where the backend reports it.
        for s in snaps:
            devs = s.get("devices") or []
            used = sum(d.get("bytes_in_use", 0) for d in devs)
            limit = sum(d.get("bytes_limit", 0) for d in devs)
            mem = (f" HBM {used / 2**30:.2f}/{limit / 2**30:.2f} GiB"
                   if limit else "")
            comp = (s.get("compile") or {}).get("backend_compiles", 0)
            print(f"  devices[{s.get('worker_id', '?')}]: "
                  f"{len(devs)}x {s.get('platform')}{mem}, "
                  f"{comp} compiles")
    else:
        print("  devices: none reported (no jax-loaded worker)")


def cmd_drain(args):
    """Gracefully drain a node: exclude it from scheduling, migrate
    restartable actors, let in-flight tasks finish to the deadline, then
    deregister (the ``ray drain-node`` analog)."""
    _connect(args)
    from ray_tpu._private import worker as worker_mod

    backend = worker_mod.backend()
    if not hasattr(backend, "head"):
        raise SystemExit("drain requires a cluster (--address <head>)")
    from ray_tpu.cluster.gcs_client import NodeInfoAccessor

    result = NodeInfoAccessor(backend.head).drain(
        args.node_id, reason=args.reason, deadline_s=args.deadline,
        wait=not args.no_wait)
    print(json.dumps(result, indent=2, default=str))


def cmd_list(args):
    from ray_tpu import state

    _connect(args)
    kind = args.kind
    rows = {
        "tasks": state.list_tasks,
        "actors": state.list_actors,
        "objects": state.list_objects,
    }[kind]()
    if getattr(rows, "truncated", False):
        # No silent caps: a clipped object listing says so.
        print(json.dumps({"truncated": True, "total": rows.total,
                          "objects": list(rows)}, indent=2, default=str))
        return
    print(json.dumps(list(rows), indent=2, default=str))


def cmd_summary(args):
    from ray_tpu import state

    _connect(args)
    print(json.dumps(
        {"tasks": state.summarize_tasks(), "actors": state.summarize_actors()},
        indent=2,
    ))


def cmd_timeline(args):
    from ray_tpu import state

    _connect(args)
    out = state.timeline(args.output)
    print(f"wrote chrome trace to {out}")


def _mib(n) -> str:
    return f"{(n or 0) / 1048576:.1f}"


def cmd_memory(args):
    """Object & memory observability (``ray memory`` analog): cluster
    totals + per-node shm occupancy + top objects with owner/task/
    callsite attribution; ``--group-by`` aggregates live bytes by
    creation site, ``--leaks`` prints the head sweeper's flags,
    ``--stats-only`` the raw per-node store stats."""
    from ray_tpu import state

    _connect(args)
    if args.stats_only:
        reports = state.object_store_stats(node_id=args.node,
                                           include_objects=False)
        print(json.dumps(reports, indent=2, default=str))
        return
    if args.leaks:
        leaks = state.memory_leaks()
        if not leaks:
            print("no leaked objects flagged")
            return
        print(f"{len(leaks)} leaked object(s) "
              f"(alive past the age threshold, unreachable):")
        for r in leaks:
            print(f"  {r['object_id'][:20]}…  {_mib(r.get('size'))} MiB  "
                  f"{r.get('kind')}  age {r.get('age_s')}s  "
                  f"task={r.get('task') or '?'}  "
                  f"owner={r.get('owner') or '?'}")
            if r.get("callsite"):
                print(f"    created at: {r['callsite']}")
        return
    summary = state.memory_summary(top_k=args.top,
                                   group_by=args.group_by or "callsite")
    t = summary["totals"]
    print(f"object store: {_mib(t['bytes_used'])}/"
          f"{_mib(t['bytes_capacity'])} MiB used across "
          f"{t['nodes']} node(s), {t['objects']} object(s), "
          f"{t['evictions']} eviction(s), "
          f"{_mib(t['spilled_bytes'])} MiB spilled, "
          f"{summary.get('leaks', 0)} leak(s)")
    for nid, n in sorted(summary["nodes"].items()):
        if args.node and nid != args.node:
            continue
        line = (f"  node {nid[-12:]:<14} {_mib(n['bytes_used'])}/"
                f"{_mib(n['bytes_capacity'])} MiB "
                f"({n['occupancy'] * 100:.0f}%)  "
                f"{n['objects']} obj  {n['evictions']} evict  "
                f"{_mib(n['spilled_bytes'])} MiB spilled")
        print(line)
        for path in n.get("oom_reports") or []:
            print(f"    oom report: {path}")
    top = summary.get("top_objects") or []
    if args.node:
        top = [r for r in top
               if args.node in (r.get("nodes") or [])]
    if top:
        print("top objects by size:")
        for r in top:
            # Holders (processes keeping the ref alive) over the shm
            # active-reader count: "who still references this" is the
            # question a full store asks.
            refs = r.get("ref_holders")
            if refs is None:
                refs = r.get("refcount", "?")
            print(f"  {r['object_id'][:20]}…  {_mib(r.get('size'))} MiB  "
                  f"refs={refs}  "
                  f"{'pinned' if r.get('pinned') else 'unpinned':<8}  "
                  f"task={r.get('task') or '?'}  "
                  f"age={r.get('age_s', '?')}s")
            if r.get("callsite"):
                print(f"    created at: {r['callsite']}")
    groups = summary.get("groups") or []
    if groups:
        print(f"by {summary.get('group_by', 'callsite')}:")
        for g in groups:
            print(f"  {_mib(g['bytes']):>9} MiB  {g['objects']:>5} obj  "
                  f"{g['key']}")


def cmd_serve(args):
    """Serve observability: ``ray-tpu serve stats`` prints the
    per-deployment SLO table (replicas, p50/p99, QPS over the sampling
    window, status/shed counts, live ongoing/queued gauges) from the
    request-path latency plane — the first stop before attributing
    serving latency to the model itself."""
    _connect(args)
    from ray_tpu import serve

    if args.action != "stats":
        raise SystemExit(f"unknown serve action {args.action!r}")
    stats = serve.stats(window_s=args.window)
    if args.json:
        print(json.dumps(stats, indent=2, default=str))
        return
    deployments = stats.get("deployments") or {}
    if not deployments:
        print("no deployments (or no serve traffic recorded yet)")
        return
    hdr = (f"{'deployment':<24} {'repl':>4} {'p50 ms':>8} {'p99 ms':>8} "
           f"{'qps':>7} {'ok':>8} {'err':>5} {'shed':>5} {'ongoing':>7} "
           f"{'queued':>6}")
    print(hdr)
    print("-" * len(hdr))
    for name, d in deployments.items():
        req = d.get("requests") or {}
        shed = sum((d.get("shed") or {}).values())
        qps = d.get("qps")
        print(f"{name:<24} {d.get('replicas', '?'):>4} "
              f"{d.get('p50_ms', '—'):>8} {d.get('p99_ms', '—'):>8} "
              f"{qps if qps is not None else '—':>7} "
              f"{req.get('ok', 0):>8} {req.get('error', 0):>5} "
              f"{shed:>5} {d.get('ongoing', 0):>7} "
              f"{d.get('queued', 0):>6}")
        phases = d.get("phases") or {}
        if args.phases and phases:
            for phase, ph in phases.items():
                print(f"    {phase:<12} p50 {ph.get('p50_ms', '—')} ms  "
                      f"mean {ph.get('mean_ms', '—')} ms  "
                      f"n={ph.get('count', 0)}")
        decode = d.get("decode") or {}
        if decode:
            print(f"    decode       streams {decode.get('streams', 0)}  "
                  f"ttft p50 {decode.get('ttft_p50_ms', '—')} ms  "
                  f"p99 {decode.get('ttft_p99_ms', '—')} ms  "
                  f"tokens {decode.get('tokens', 0)}  "
                  f"steps {decode.get('steps', 0)}  "
                  f"occ {decode.get('mean_occupancy', '—')}")
    if stats.get("reconcile_s") is not None:
        print(f"controller reconcile: {stats['reconcile_s'] * 1e3:.1f} ms")


def _print_top(top, window):
    slos = top.get("slos") or {}
    burning = sum(1 for s in slos.values() if s["state"] == "burning")
    print(f"window {window:g}s · {top.get('series', 0)} series"
          + (f" · {burning} SLO(s) BURNING" if burning else ""))
    nodes = top.get("nodes") or {}
    if nodes:
        hdr = (f"{'node':<16} {'cpu%':>6} {'rss MB':>8} {'store%':>7} "
               f"{'workers':>7}")
        print(hdr)
        print("-" * len(hdr))
        for nid, n in sorted(nodes.items()):
            occ = n.get("store_occupancy")
            print(f"{nid[-14:]:<16} {n.get('cpu_percent', 0):>6} "
                  f"{n.get('rss_bytes', 0) / 1e6:>8.1f} "
                  f"{(f'{occ:.1%}' if occ is not None else '—'):>7} "
                  f"{n.get('workers', 0):>7}")
    serve = top.get("serve") or {}
    if serve:
        hdr = (f"{'deployment':<24} {'qps':>7} {'shed%':>6} "
               f"{'ttft p50':>9} {'itl p50':>9} {'lat p50':>9}")
        print(hdr)
        print("-" * len(hdr))
        for dep, d in sorted(serve.items()):
            def ms(key):
                v = d.get(key)
                return f"{v * 1e3:.1f}ms" if v is not None else "—"
            shed = d.get("shed_ratio")
            print(f"{dep:<24} {d.get('qps', 0):>7} "
                  f"{(f'{shed:.1%}' if shed is not None else '—'):>6} "
                  f"{ms('ttft_p50_s'):>9} {ms('itl_p50_s'):>9} "
                  f"{ms('latency_p50_s'):>9}")
    fleet = top.get("fleet") or {}
    churn = fleet.get("types") or {}
    if churn:
        hdr = (f"{'node type':<16} {'launch':>7} {'fail':>6} "
               f"{'bench':>6} {'down':>6}")
        print(hdr)
        print("-" * len(hdr))
        for t, c in sorted(churn.items()):
            print(f"{t:<16} {c.get('launches', 0):>7} "
                  f"{c.get('launch_failures', 0):>6} "
                  f"{c.get('quarantines', 0):>6} "
                  f"{c.get('scale_downs', 0):>6}")
    pending = fleet.get("pending_demand") or {}
    if pending:
        print("pending demand: " + ", ".join(
            f"{k} {v}" for k, v in sorted(pending.items())))
    train = top.get("train") or {}
    for trial, t in sorted(train.items()):
        gp = t.get("goodput_pct")
        mfu = t.get("mfu_pct")
        strag = t.get("straggler")
        strag_s = ""
        if strag and strag.get("cause") != "balanced":
            strag_s = (f", straggler r{strag.get('rank')} "
                       f"{strag.get('cause')}")
        print(f"trial {trial}: {t.get('reports_per_s', 0)} reports/s"
              + (f", goodput {gp}%" if gp is not None else "")
              + (f", mfu {mfu:.1f}%" if mfu is not None else "")
              + strag_s)
    for name, s in sorted(slos.items()):
        v = s.get("value")
        print(f"slo {name:<20} {s['state']:<8} "
              f"{v if v is not None else '—'} "
              f"{s['op']} {s['threshold']}  ({s['expr']})")
        ex = s.get("exemplar_trace_ids")
        if ex:
            print("    exemplars: " + " ".join(ex)
                  + "  (ray-tpu trace <id>)")
    traces = top.get("traces") or {}
    if traces.get("assembled_total") or traces.get("pending"):
        drops = traces.get("dropped") or {}
        drop_s = ", ".join(f"{k} {v}" for k, v in sorted(drops.items())
                           if v) or "none"
        span_drops = (traces.get("head_spans_dropped", 0)
                      + traces.get("worker_spans_dropped", 0))
        print(f"traces: {traces.get('kept', 0)} kept / "
              f"{traces.get('assembled_total', 0)} assembled "
              f"({traces.get('pending', 0)} pending) · drops: {drop_s}"
              + (f" · SPANS DROPPED: {span_drops}" if span_drops else ""))


def cmd_top(args):
    """Live cluster view from the head's metrics history ring — every
    number a windowed ring query, zero sleeps in the request path (the
    --watch cadence is the terminal's, not the data path's)."""
    _connect(args)
    from ray_tpu import state

    def once():
        top = state.signal_top(args.window)
        if not top.get("ok"):
            raise SystemExit(f"signal plane unavailable: "
                             f"{top.get('error')}")
        if args.json:
            print(json.dumps(top, indent=2, default=str))
        else:
            _print_top(top, args.window)

    if not args.watch:
        once()
        return
    import time as _time

    try:
        while True:
            print("\x1b[2J\x1b[H", end="")
            once()
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        pass


def cmd_slo(args):
    """SLO registry: ``ray-tpu slo`` prints the burn-rate table;
    ``register <name> <expr>`` / ``remove <name>`` manage objectives
    (grammar: ``ttft_p50{deployment="d"} < 2s over 60s``,
    ``shed_ratio < 1% over 300s``, ``rate(family) < N over Ws``)."""
    _connect(args)
    from ray_tpu import state

    if args.op == "register":
        if not args.name or not args.expr:
            raise SystemExit("usage: ray-tpu slo register <name> <expr>")
        res = state.register_slo(args.name, " ".join(args.expr))
        if not res.get("ok"):
            raise SystemExit(f"register failed: {res.get('error')}")
        print(json.dumps(res["slo"], indent=2, default=str))
        return
    if args.op == "remove":
        if not args.name:
            raise SystemExit("usage: ray-tpu slo remove <name>")
        res = state.remove_slo(args.name)
        if not res.get("ok"):
            raise SystemExit(f"remove failed: {res.get('error')}")
        print("removed" if res.get("removed") else "not registered")
        return
    status = state.slo_status()
    if not status.get("ok"):
        raise SystemExit(f"signal plane unavailable: "
                         f"{status.get('error')}")
    if args.json:
        print(json.dumps(status, indent=2, default=str))
        return
    slos = status.get("slos") or {}
    if not slos:
        print("no SLOs registered "
              "(ray-tpu slo register <name> '<expr>')")
        return
    hdr = (f"{'name':<20} {'state':<8} {'value':>10} {'threshold':>10} "
           f"{'window':>7} {'breaches':>8}")
    print(hdr)
    print("-" * len(hdr))
    for name, s in sorted(slos.items()):
        v = s.get("value")
        print(f"{name:<20} {s['state']:<8} "
              f"{(round(v, 5) if v is not None else '—'):>10} "
              f"{s['op']}{s['threshold']:>9} "
              f"{s['window_s']:>6g}s {s['breach_streak']:>8}")
        print(f"    {s['expr']}")
        ex = s.get("exemplar_trace_ids")
        if ex:
            print("    exemplars: " + " ".join(ex)
                  + "  (ray-tpu trace <id>)")


def _print_ttft_decomp(out):
    n = out.get("traces", 0)
    if not n:
        print("no finalized traces in the window "
              "(is tracing enabled? RAY_TPU_TRACING_ENABLED=1)")
        return
    p50 = out.get("ttft_p50_s")
    p99 = out.get("ttft_p99_s")
    print(f"{n} trace(s) · ttft p50 "
          f"{p50 * 1e3:.1f}ms · p99 {p99 * 1e3:.1f}ms · dominant phase: "
          f"{out.get('dominant')}")
    hdr = f"{'phase':<12} {'p50':>10} {'p99':>10} {'mean':>10} {'n':>6}"
    print(hdr)
    print("-" * len(hdr))
    for phase, p in sorted((out.get("phases") or {}).items(),
                           key=lambda kv: -(kv[1].get("p50_s") or 0.0)):
        def ms(v):
            return f"{v * 1e3:.1f}ms" if v is not None else "—"
        print(f"{phase:<12} {ms(p.get('p50_s')):>10} "
              f"{ms(p.get('p99_s')):>10} {ms(p.get('mean_s')):>10} "
              f"{p.get('count', 0):>6}")
    ps = out.get("phase_sum_p50_s")
    if p50 and ps is not None:
        print(f"phase-sum p50 {ps * 1e3:.1f}ms "
              f"({ps / p50:.1%} of ttft p50)")


def cmd_trace(args):
    """Flight-recorder queries. ``ray-tpu trace`` lists kept traces;
    ``ray-tpu trace <id>`` renders the assembled cross-process span
    tree (``--chrome out.json`` exports Perfetto-loadable events,
    ``--path`` prints the critical-path segments); ``ray-tpu trace
    --ttft`` prints the windowed per-phase TTFT decomposition."""
    _connect(args)
    from ray_tpu import state

    if args.ttft:
        out = state.ttft_decomposition(
            window_s=args.window, deployment=args.deployment)
        if args.json:
            print(json.dumps(out, indent=2, default=str))
        else:
            _print_ttft_decomp(out)
        return
    if not args.trace_id:
        rows = state.list_traces(args.limit)
        if args.json:
            print(json.dumps(rows, indent=2, default=str))
            return
        if not rows:
            print("no traces kept (enable tracing and send traffic; "
                  "only errored/slow/sampled traces are retained)")
            return
        hdr = (f"{'trace_id':<34} {'root':<28} {'dur':>9} "
               f"{'spans':>5} {'kept':>10} {'dominant':>9}")
        print(hdr)
        print("-" * len(hdr))
        for r in rows:
            dur = r.get("duration_s") or 0.0
            mark = "!" if r.get("errored") else " "
            print(f"{r['trace_id']:<34} {(r.get('root') or '?')[:27]:<28} "
                  f"{dur * 1e3:>8.1f}ms{mark}{r.get('spans', 0):>5} "
                  f"{r.get('kept_because', ''):>10} "
                  f"{r.get('dominant') or '—':>9}")
        return
    tr = state.get_trace(args.trace_id)
    if tr is None:
        raise SystemExit(
            f"unknown trace {args.trace_id!r} — never reported, still "
            f"inside the assembly quiet window, or tail-sampled out "
            f"(kept: errored, >slow-threshold, or sampled-in)")
    if args.chrome:
        from ray_tpu.util import tracing

        n = tracing.export_chrome_trace(args.chrome, tr["spans"])
        print(f"wrote {n} span(s) to {args.chrome} "
              f"(load in Perfetto / chrome://tracing)")
        return
    if args.json:
        print(json.dumps(tr, indent=2, default=str))
        return
    from ray_tpu.cluster.traces import render_tree

    print(f"trace {tr['trace_id']}  "
          f"({tr['duration_s'] * 1e3:.1f}ms, kept: {tr['kept_because']}"
          + (f", deployment {tr['deployment']}" if tr.get("deployment")
             else "") + ")")
    print(render_tree(tr["spans"]))
    d = tr.get("decomposition")
    if d:
        parts = ", ".join(f"{k} {v * 1e3:.1f}ms"
                          for k, v in sorted(d["phases"].items(),
                                             key=lambda kv: -kv[1]))
        print(f"ttft {d['total_s'] * 1e3:.1f}ms = {parts} "
              f"(dominant: {d['dominant']})")
    if args.path:
        print("critical path:")
        for seg in tr.get("critical_path") or ():
            print(f"  {seg['self_s'] * 1e3:>8.1f}ms  {seg['phase']:<10} "
                  f"{seg['name']}")


def cmd_data(args):
    """Input-pipeline observability: ``ray-tpu data stats`` prints the
    per-stage execution rollup and the consumer-loop stall fraction —
    the input-pipeline gate in front of any kernel-level MFU work
    (a starved loop means the kernels are idle, not slow)."""
    _connect(args)
    from ray_tpu import state

    if args.action != "stats":
        raise SystemExit(f"unknown data action {args.action!r}")
    stats = state.data_stats()
    if args.json:
        print(json.dumps(stats, indent=2, default=str))
        return
    stages = stats.get("stages") or {}
    if stages:
        hdr = (f"{'stage':<28} {'execs':>5} {'blocks':>7} "
               f"{'rows':>10} {'wall ms':>9} {'MB/s':>8}")
        print(hdr)
        print("-" * len(hdr))
        for name, st in stages.items():
            mb_s = (st.get("bytes_per_s") or 0) / 1e6
            print(f"{name:<28} {st.get('executions', 0):>5} "
                  f"{st.get('blocks', '—'):>7} "
                  f"{st.get('rows_total', '—'):>10} "
                  f"{st.get('wall_ms', 0):>9} {mb_s:>8.1f}")
    else:
        print("no dataset stages recorded")
    it = stats.get("iterator") or {}
    for phase in ("wait", "user", "transfer"):
        d = it.get(phase)
        if d:
            print(f"iterator {phase:<9} n={d['count']:<7} "
                  f"p50 {d['p50_ms']} ms  mean {d['mean_ms']} ms")
    occ = it.get("occupancy")
    if occ:
        print(f"prefetch occupancy: mean {occ['mean']} "
              f"({occ['samples']} samples)")
    sf = stats.get("stall_fraction")
    if sf is not None:
        print(f"stall fraction: {sf:.1%} of consumer loop wall time "
              f"starved for data")
    else:
        print("stall fraction: — (no consumer loops recorded)")


def cmd_train(args):
    """Training goodput: ``ray-tpu train stats`` prints per-trial
    report counts, step-phase latencies, rank skew, and the downtime
    ledger's goodput %."""
    _connect(args)
    from ray_tpu import state

    if args.action != "stats":
        raise SystemExit(f"unknown train action {args.action!r}")
    stats = state.train_stats()
    if args.json:
        print(json.dumps(stats, indent=2, default=str))
        return
    trials = stats.get("trials") or {}
    if not trials:
        print("no train sessions recorded")
        return
    for name, t in trials.items():
        gp = t.get("goodput_pct")
        skew = t.get("rank_skew")
        print(f"trial {name}: {t.get('reports', 0)} reports"
              + (f", goodput {gp}%" if gp is not None else "")
              + (f", rank skew {skew}x" if skew is not None else ""))
        for phase, d in (t.get("phases") or {}).items():
            print(f"    {phase:<18} n={d['count']:<7} "
                  f"p50 {d['p50_ms']} ms  mean {d['mean_ms']} ms")
        ranks = t.get("rank_step_s")
        if ranks:
            line = "  ".join(f"r{r}={s * 1e3:.1f}ms"
                             for r, s in ranks.items())
            print(f"    rank step: {line}")
        anat = t.get("anatomy") or {}
        mfu = anat.get("mfu_pct") or {}
        if mfu:
            line = "  ".join(f"r{r}={v:.1f}%"
                             for r, v in sorted(mfu.items()))
            print(f"    mfu: {line}")
        for rank, phases in sorted((anat.get("ranks") or {}).items()):
            line = "  ".join(f"{p}={s * 1e3:.1f}ms"
                             for p, s in phases.items())
            print(f"    anatomy r{rank}: {line}")
        strag = anat.get("straggler")
        if strag:
            if strag.get("cause") == "balanced":
                print("    straggler: none (balanced gang)")
            else:
                print(f"    straggler: rank {strag.get('rank')} "
                      f"{strag.get('cause')} "
                      f"(+{strag.get('excess_s', 0) * 1e3:.1f}ms over "
                      f"median, phase {strag.get('phase')})")
        for cause, s in (t.get("downtime_s") or {}).items():
            print(f"    downtime [{cause}]: {s:.2f} s")


def cmd_logs(args):
    """List captured worker logs, or print (and follow) one worker's."""
    from ray_tpu import state

    _connect(args)
    if not args.worker_id:
        rows = state.list_logs()
        if not rows:
            print("no captured worker logs (local backend, or no "
                  "workers spawned yet)")
            return
        print(f"{'WORKER':<16} {'NODE':<10} {'PID':>7} {'ALIVE':<5} "
              f"{'ACTOR':<10} {'OUT':>9} {'ERR':>9}")
        for r in rows:
            print(f"{r['worker_id']:<16} {r['node_id'][-8:]:<10} "
                  f"{r['pid']:>7} {str(r['alive']):<5} "
                  f"{(r.get('actor_id') or '')[-8:]:<10} "
                  f"{r.get('stdout_bytes', 0):>9} "
                  f"{r.get('stderr_bytes', 0):>9}")
        return
    from ray_tpu._private import worker as worker_mod

    backend = worker_mod.backend()
    rec = backend.get_log(args.worker_id, args.stream,
                          tail_lines=args.tail)
    sys.stdout.write(rec["data"])
    sys.stdout.flush()
    if args.follow:
        for chunk in state.follow_log(
                args.worker_id, args.stream, offset=rec["offset"],
                idle_timeout_s=args.idle_timeout):
            sys.stdout.write(chunk["data"])
            sys.stdout.flush()


def cmd_stack(args):
    """Stack dump (or timed stack profile) of live workers
    (``ray stack`` / py-spy analog)."""
    import json as _json

    from ray_tpu import state

    _connect(args)
    if args.worker_id:
        targets = [args.worker_id]
    else:
        targets = [r["worker_id"] for r in state.list_logs()
                   if r.get("alive")]
        if not targets:
            from ray_tpu._private import worker as worker_mod

            if hasattr(worker_mod.backend(), "head"):
                # Cluster with no live workers: routing a None worker
                # would just produce a lookup traceback.
                print("no live workers to inspect")
                return
            targets = [None]  # local backend: dump this process
    outputs = []
    for wid in targets:
        if args.duration:
            out = state.profile_worker(
                wid, duration_s=args.duration, interval_s=args.interval,
                fmt=args.format)
        else:
            out = state.dump_stack(wid)
        outputs.append(out)
    if args.format == "chrome" and args.duration:
        events = [e for ev in outputs for e in ev]
        if args.output:
            with open(args.output, "w") as f:
                _json.dump(events, f)
            print(f"wrote chrome trace to {args.output}")
        else:
            print(_json.dumps(events))
        return
    for wid, out in zip(targets, outputs):
        if len(targets) > 1:
            print(f"==== worker {wid} ====")
        print(out if isinstance(out, str) else _json.dumps(out, indent=1))


def cmd_tprof(args):
    """Remote profiler capture (``jax.profiler.trace`` in the worker;
    stack-sampler fallback off-jax): trace files stream back and land
    in --output, TensorBoard/Perfetto-loadable."""
    from ray_tpu import state

    _connect(args)
    wid = args.worker_id
    if wid is None:
        from ray_tpu._private import worker as worker_mod

        if hasattr(worker_mod.backend(), "head"):
            live = [r["worker_id"] for r in state.list_logs()
                    if r.get("alive")]
            if not live:
                print("no live workers to profile")
                return
            wid = live[0]
    res = state.capture_profile(
        wid, duration_s=args.duration, interval_s=args.interval,
        out_dir=args.output)
    print(f"captured {res['kind']} profile of "
          f"{res.get('worker_id') or 'this process'} "
          f"({res['duration_s']:g}s) -> {res['dir']}")
    for path in res["files"]:
        print(f"  {path}")


def cmd_metrics(args):
    """Dump the federated Prometheus scrape (one body covering every
    alive agent), or write a file-SD targets document for
    scrape-config bootstrap."""
    from ray_tpu._private import worker as worker_mod

    _connect(args)
    backend = worker_mod.backend()
    if args.targets_json:
        import json as _json

        from ray_tpu.util.metrics import file_sd_targets

        ep = (backend.metrics_endpoint()
              if hasattr(backend, "metrics_endpoint") else None)
        if ep is None:
            raise SystemExit(
                "no metrics endpoint (local backend, or exposition "
                "disabled on the head)")
        doc = file_sd_targets(ep["address"], path=ep["cluster_path"])
        with open(args.targets_json, "w") as f:
            _json.dump(doc, f, indent=1)
        print(f"wrote prometheus file-SD targets to {args.targets_json}")
        return
    if not hasattr(backend, "cluster_metrics_text"):
        raise SystemExit("this backend exports no metrics")
    sys.stdout.write(backend.cluster_metrics_text())


def cmd_chaos(args):
    """Deterministic fault injection: arm/disarm failpoints cluster-wide
    and manage network-chaos partitions (``GcsClient.chaos``)."""
    if not args.address:
        raise SystemExit("chaos requires --address <head>")
    from ray_tpu.cluster.gcs_client import GcsClient

    gcs = GcsClient(args.address)
    try:
        op = args.op
        if op == "list":
            print(json.dumps({
                "failpoints": gcs.chaos.list(),
                "channel_chaos": gcs.chaos.list_channel_chaos(),
            }, indent=2, default=str))
        elif op == "arm":
            if not args.site or not args.spec:
                raise SystemExit("chaos arm <site> <spec>")
            print(json.dumps(gcs.chaos.arm(args.site, args.spec),
                             indent=2, default=str))
        elif op == "disarm":
            if args.all:
                sites = set()

                def walk(table):
                    # Armed tables nest per process ({"head": {...},
                    # node: {"agent": {...}, worker: {...}}}); a site's
                    # leaf record always carries its "spec".
                    for key, val in (table or {}).items():
                        if not isinstance(val, dict):
                            continue
                        if "spec" in val and "site" in val:
                            sites.add(key)
                        else:
                            walk(val)

                walk(gcs.chaos.list())
                print(json.dumps(gcs.chaos.set_failpoints(
                    {s: None for s in sites}), indent=2, default=str))
            elif args.site:
                print(json.dumps(gcs.chaos.disarm(args.site),
                                 indent=2, default=str))
            else:
                raise SystemExit("chaos disarm <site> (or --all)")
        elif op == "partition":
            # Groups arrive via --groups, but the first two also land in
            # the (site, spec) positional slots when given bare.
            raw = list(args.groups or ())
            if not raw:
                raw = [g for g in (args.site, args.spec) if g]
            if len(raw) < 2:
                raise SystemExit(
                    "chaos partition <group> <group> ... — each group a "
                    "comma-separated list of node ids (or 'head')")
            groups = [g.split(",") for g in raw]
            print(json.dumps(gcs.chaos.partition(groups),
                             indent=2, default=str))
        elif op == "heal":
            print(json.dumps(gcs.chaos.heal(), indent=2, default=str))
        else:
            raise SystemExit(f"unknown chaos op {op!r}")
    finally:
        gcs.close()


def cmd_submit(args):
    from ray_tpu.job_submission import JobSubmissionClient

    _connect(args)
    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint=" ".join(args.entrypoint))
    print(f"submitted {job_id}")
    if args.wait:
        status = client.wait_until_finished(job_id)
        print(f"{job_id}: {status}")
        print(client.get_job_logs(job_id))


def cmd_dashboard(args):
    from ray_tpu.dashboard import Dashboard

    if not args.address:
        raise SystemExit("dashboard requires --address <head host:port>")
    dash = Dashboard(args.address, host=args.host, port=args.port)
    print(f"dashboard at {dash.url} (head {args.address}); Ctrl-C to stop")
    try:
        import threading

        threading.Event().wait()
    except KeyboardInterrupt:
        dash.shutdown()


def cmd_client_server(args):
    from ray_tpu.util.client import ClientProxyServer

    if not args.address:
        raise SystemExit(
            "client-server requires --address <head host:port>")
    srv = ClientProxyServer(args.address, host=args.host, port=args.port)
    print(f"client proxy at ray://{srv.address} (head {args.address}); "
          f"Ctrl-C to stop")
    try:
        import threading

        threading.Event().wait()
    except KeyboardInterrupt:
        srv.shutdown()


def cmd_up(args):
    """Foreground cluster from YAML; Ctrl-C tears it down (``ray up``)."""
    import signal
    import threading as _threading

    from ray_tpu.autoscaler.launcher import create_or_update_cluster

    handle = create_or_update_cluster(args.config)
    print(f"cluster '{handle.config['cluster_name']}' up at "
          f"{handle.address} — Ctrl-C to tear down")
    done = _threading.Event()
    signal.signal(signal.SIGINT, lambda *a: done.set())
    signal.signal(signal.SIGTERM, lambda *a: done.set())
    done.wait()
    print("tearing down…")
    handle.teardown()


def main(argv=None):
    import sys as _sys

    argv = list(_sys.argv[1:]) if argv is None else list(argv)
    parser = argparse.ArgumentParser(prog="ray-tpu")
    parser.add_argument("--address", default=None,
                        help="cluster head host:port (default: local)")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("start", help="start a head/worker daemon")
    p.add_argument("--head", action="store_true")
    p.add_argument("--port", type=int, default=6380)
    p.add_argument("--num-cpus", type=float, default=None)
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("status", help="cluster resource status")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser(
        "drain",
        help="gracefully drain a node (migrate actors, finish tasks, "
             "then remove)")
    p.add_argument("node_id")
    p.add_argument("--reason", default="cli")
    p.add_argument("--deadline", type=float, default=None,
                   help="seconds in-flight tasks get before force-removal")
    p.add_argument("--no-wait", action="store_true",
                   help="initiate the drain and return immediately")
    p.set_defaults(fn=cmd_drain)

    p = sub.add_parser("list", help="list tasks/actors/objects")
    p.add_argument("kind", choices=["tasks", "actors", "objects"])
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("summary", help="task/actor state summary")
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("timeline", help="dump chrome trace")
    p.add_argument("--output", "-o", default="/tmp/ray_tpu_timeline.json")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser(
        "memory",
        help="object & memory observability (ray memory analog): "
             "occupancy, attribution, leaks, OOM reports")
    p.add_argument("--group-by", choices=["callsite", "task", "node",
                                          "owner"],
                   default=None,
                   help="aggregate live bytes by creation site "
                        "(default: callsite)")
    p.add_argument("--leaks", action="store_true",
                   help="print objects the leak sweeper flags")
    p.add_argument("--stats-only", action="store_true",
                   help="raw per-node store stats, no per-object join")
    p.add_argument("--node", default=None,
                   help="restrict to one node id (also surfaces its "
                        "OOM reports)")
    p.add_argument("--top", type=int, default=20,
                   help="how many top-by-size objects to show")
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser(
        "logs", help="list/print captured worker logs (ray logs analog)")
    p.add_argument("worker_id", nargs="?", default=None)
    p.add_argument("--stream", choices=["out", "err"], default="out")
    p.add_argument("--tail", type=int, default=200)
    p.add_argument("--follow", "-f", action="store_true",
                   help="stream the log as it grows")
    p.add_argument("--idle-timeout", type=float, default=10.0,
                   help="stop following after this long without growth")
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser(
        "stack", help="stack dump / profile of workers (ray stack analog)")
    p.add_argument("worker_id", nargs="?", default=None,
                   help="default: every live worker (local: this process)")
    p.add_argument("--duration", "-d", type=float, default=None,
                   help="time-sample for this many seconds instead of "
                        "an instantaneous dump")
    p.add_argument("--interval", type=float, default=0.01)
    p.add_argument("--format", choices=["text", "collapsed", "chrome"],
                   default="text")
    p.add_argument("--output", "-o", default=None,
                   help="write chrome-trace output here")
    p.set_defaults(fn=cmd_stack)

    p = sub.add_parser(
        "tprof",
        help="remote profiler capture (jax.profiler.trace / stack "
             "sampler fallback)")
    p.add_argument("worker_id", nargs="?", default=None,
                   help="default: first live worker (local: this process)")
    p.add_argument("--duration", "-d", type=float, default=2.0)
    p.add_argument("--interval", type=float, default=0.01,
                   help="stack-sampler fallback interval")
    p.add_argument("--output", "-o", default=None,
                   help="directory for the trace files (default: tmp)")
    p.set_defaults(fn=cmd_tprof)

    p = sub.add_parser(
        "metrics",
        help="dump the federated /metrics/cluster scrape body")
    p.add_argument("--targets-json", default=None,
                   help="instead write a prometheus file-SD targets "
                        "document here")
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser(
        "chaos",
        help="deterministic fault injection: failpoints + partitions")
    p.add_argument("op",
                   choices=["list", "arm", "disarm", "partition", "heal"])
    p.add_argument("site", nargs="?", default=None,
                   help="failpoint site (arm/disarm)")
    p.add_argument("spec", nargs="?", default=None,
                   help="failpoint spec, e.g. 'raise,once' / 'delay:0.2' "
                        "/ 'kill,p=0.1' (arm)")
    p.add_argument("--all", action="store_true",
                   help="disarm: clear every armed site")
    p.add_argument("--groups", nargs="*", default=None,
                   help="partition: comma-separated node ids per group "
                        "(use 'head' for the head), e.g. "
                        "--groups head,node-a node-b")
    p.set_defaults(fn=cmd_chaos)

    p = sub.add_parser(
        "serve",
        help="serve observability (per-deployment p50/p99/QPS/shed)")
    p.add_argument("action", choices=["stats"])
    p.add_argument("--window", type=float, default=1.0,
                   help="QPS sampling window seconds (0 = single scrape, "
                        "no QPS)")
    p.add_argument("--phases", action="store_true",
                   help="also print the per-phase latency breakdown")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser(
        "top",
        help="live cluster view from the head's metrics history "
             "(nodes, serve, train, SLOs — zero sleeps in the path)")
    p.add_argument("--window", type=float, default=60.0,
                   help="query window seconds")
    p.add_argument("--watch", action="store_true",
                   help="refresh continuously until ^C")
    p.add_argument("--interval", type=float, default=2.0,
                   help="--watch refresh cadence seconds")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser(
        "slo",
        help="SLO registry: burn-rate table / register / remove")
    p.add_argument("op", nargs="?", default="status",
                   choices=["status", "register", "remove"])
    p.add_argument("name", nargs="?", default=None)
    p.add_argument("expr", nargs="*",
                   help="SLO expression, e.g. "
                        "ttft_p50{deployment=\"d\"} < 2s over 60s")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_slo)

    p = sub.add_parser(
        "trace",
        help="flight recorder: list kept traces, render one "
             "cross-process tree, or the windowed TTFT decomposition")
    p.add_argument("trace_id", nargs="?", default=None)
    p.add_argument("--ttft", action="store_true",
                   help="windowed per-phase TTFT decomposition")
    p.add_argument("--window", type=float, default=None,
                   help="--ttft window seconds (default: all retained)")
    p.add_argument("--deployment", default=None,
                   help="--ttft filter by deployment")
    p.add_argument("--limit", type=int, default=30,
                   help="list mode: max traces shown")
    p.add_argument("--chrome", metavar="PATH", default=None,
                   help="export the trace as Chrome/Perfetto events")
    p.add_argument("--path", action="store_true",
                   help="print the critical-path segments")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser(
        "data",
        help="input-pipeline observability (stage rollup + stall "
             "fraction)")
    p.add_argument("action", choices=["stats"])
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_data)

    p = sub.add_parser(
        "train",
        help="training goodput (step phases, rank skew, downtime "
             "ledger)")
    p.add_argument("action", choices=["stats"])
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser("submit", help="submit a job entrypoint")
    p.add_argument("--wait", action="store_true")
    p.add_argument("entrypoint", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser(
        "up", help="launch a cluster from a YAML config (ray up analog)")
    p.add_argument("config", help="cluster YAML path")
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("dashboard", help="serve the REST dashboard")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8265)
    p.set_defaults(fn=cmd_dashboard)

    p = sub.add_parser(
        "client-server", help="serve a ray:// client proxy")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10001)
    p.set_defaults(fn=cmd_client_server)

    p = sub.add_parser(
        "analyze",
        help="concurrency & contract static analysis (lock order, "
             "blocking-under-lock, finalizer safety, async-holding-"
             "lock, failpoint/metric contract drift); exits 1 on any "
             "unbaselined finding")
    p.set_defaults(fn=None)

    # `analyze` forwards its whole tail verbatim to the analyzer's own
    # parser: parse_known_args lets the main parser consume the global
    # flags (wherever they sit) and leaves the analyzer's flags/paths
    # in `rest` — no hardcoded list of value-taking globals.
    args, rest = parser.parse_known_args(argv)
    if args.command == "analyze":
        from ray_tpu.scripts.analyze import main as analyze_main

        raise SystemExit(analyze_main(rest))
    if rest:
        parser.error(f"unrecognized arguments: {' '.join(rest)}")
    args.fn(args)


if __name__ == "__main__":
    main()
