"""Object-store pressure microbench: occupancy/evictions under churn.

Drives a put/get/drop churn workload sized against the node's store
capacity, samples per-node shm occupancy (``state.object_store_stats``)
every round, and emits peak/mean occupancy + eviction/spill-denial
deltas through ``bench_log.record_memory_pressure`` (committed to
``BENCH_TPU_SESSIONS.jsonl`` only when run on an accelerator — same
policy as ``record_task_overhead``).

    python -m ray_tpu.scripts.memory_bench --cluster
    python -m ray_tpu.scripts.memory_bench --address <head host:port> \
        --rounds 40 --object-mb 8 --window 6
"""

from __future__ import annotations

import argparse
import json


def run(rounds: int = 30, object_mb: float = 4.0,
        window: int = 8) -> list:
    """Churn: each round puts one ``object_mb`` array and drops refs
    beyond a ``window``-deep keep-alive set, so the store fills, the
    ref-counter frees, and (when capacity is tight) spill/eviction
    engage. Returns one summed-stats sample per round."""
    import numpy as np

    import ray_tpu
    from ray_tpu import state

    nbytes = int(object_mb * (1 << 20))
    keep: list = []
    samples: list = []
    for i in range(rounds):
        keep.append(ray_tpu.put(
            np.full(nbytes, i % 251, dtype=np.uint8)))
        if len(keep) > window:
            # Read-then-drop: the churn half of the workload.
            ray_tpu.get(keep.pop(0))
        reports = state.object_store_stats(include_objects=False)
        agg = {"used": 0, "capacity": 0, "num_evictions": 0,
               "num_objects": 0, "spilled_bytes": 0, "spill_denied": 0}
        for rep in reports:
            st = rep.get("stats") or {}
            for k in agg:
                agg[k] += int(st.get(k, 0))
        samples.append(agg)
    del keep
    return samples


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--address", default=None,
                        help="existing cluster head (default: local)")
    parser.add_argument("--cluster", action="store_true",
                        help="spin up a throwaway 2-node local cluster")
    parser.add_argument("--store-mb", type=int, default=96,
                        help="per-node store capacity for --cluster "
                             "(small = pressure engages)")
    parser.add_argument("--rounds", type=int, default=30)
    parser.add_argument("--object-mb", type=float, default=4.0)
    parser.add_argument("--window", type=int, default=8,
                        help="live refs kept during the churn")
    parser.add_argument("--device", default="",
                        help="accelerator label for the evidence trail "
                             "(empty/cpu = print only, don't commit)")
    args = parser.parse_args(argv)

    import ray_tpu
    from ray_tpu.scripts import bench_log

    cluster = None
    if args.cluster and args.address is None:
        from ray_tpu.cluster.cluster_utils import Cluster

        cluster = Cluster()
        cluster.add_node(store_capacity=args.store_mb << 20)
        cluster.add_node(store_capacity=args.store_mb << 20)
        cluster.wait_for_nodes()
        ray_tpu.init(cluster.address)
    else:
        ray_tpu.init(args.address)

    try:
        samples = run(args.rounds, args.object_mb, args.window)
        entry = bench_log.record_memory_pressure(
            samples, device=args.device,
            backend="cluster" if (cluster or args.address) else "local",
            rounds=args.rounds, object_mb=args.object_mb,
            window=args.window)
        print(json.dumps(entry, indent=1))
    finally:
        ray_tpu.shutdown()
        if cluster is not None:
            cluster.shutdown()


if __name__ == "__main__":
    main()
