"""QMIX: monotonic value-function factorization for cooperative
multi-agent Q-learning (reference ``rllib/algorithms/qmix/qmix.py``,
whose torch mixer lives in ``qmix/mixers.py``), with VDN (additive
mixing) as the degenerate ``mixer="vdn"`` point — the same pairing the
reference ships.

Per-agent utilities Q_i(o_i, a_i) come from ONE parameter-shared MLP fed
an agent-id one-hot (the reference shares weights across homogeneous
agents the same way); the mixer combines the chosen utilities into
Q_tot under a monotonicity constraint dQ_tot/dQ_i >= 0, enforced by
abs() on hypernetwork-generated weights — hypernets condition on the
GLOBAL state, which is what lets QMIX represent joint optima that
per-agent greedy argmax can still recover. Everything (epsilon-greedy
rollout, replay, TD update on Q_tot, target sync) is one jitted Anakin
program.

The canonical capability split is reproduced in ``TwoStepGame``
(the QMIX paper's §6.1 matrix game): VDN's additive factorization can
only represent the payoff-7 branch while QMIX reaches the optimal 8 —
``tests/test_rllib_qmix.py`` asserts exactly that separation.
"""

from __future__ import annotations

import time
from typing import Any, Dict, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithm import EpisodeStats
from ray_tpu.rllib.optim import adam_step as _adam
from ray_tpu.rllib.optim import linear_epsilon, periodic_target_sync
from ray_tpu.rllib.ppo import mlp_apply, mlp_init
from ray_tpu.rllib.replay import buffer_add, buffer_init, buffer_sample

__all__ = ["QMIX", "QMIXConfig", "TwoStepGame"]


class TwoStepState(NamedTuple):
    phase: jax.Array  # 0 = step one; 1/2 = which matrix game step two is


class TwoStepGame:
    """The QMIX paper's two-step cooperative matrix game. Agent 1's first
    action picks the branch (agent 2's is ignored); the branch-A payoff
    matrix is a flat 7, branch B is [[0, 1], [1, 8]] — the 8 requires
    coordinated (1, 1) and a NON-additive joint value to be representable.
    """

    n_agents = 2
    num_actions = 2
    state_size = 3      # one-hot phase (global state, fed to the mixer)
    observation_size = 3 + 2  # global one-hot + agent-id one-hot

    # Plain numpy so importing the module never touches a jax backend
    # (converted at trace time inside step()).
    PAYOFF_A = np.full((2, 2), 7.0)
    PAYOFF_B = np.array([[0.0, 1.0], [1.0, 8.0]])

    def reset(self, rng):
        return TwoStepState(jnp.zeros((), jnp.int32))

    def state(self, s: TwoStepState) -> jax.Array:
        return jax.nn.one_hot(s.phase, 3)

    def obs(self, s: TwoStepState) -> jax.Array:
        """[n_agents, obs_size] — shared state view + agent id."""
        g = jnp.tile(self.state(s), (2, 1))
        return jnp.concatenate([g, jnp.eye(2)], axis=1)

    def step(self, s: TwoStepState, actions: jax.Array, rng: jax.Array):
        in_step1 = s.phase == 0
        branch = jnp.where(actions[0] == 0, 1, 2).astype(jnp.int32)
        payoff = jnp.where(
            s.phase == 1,
            jnp.asarray(self.PAYOFF_A)[actions[0], actions[1]],
            jnp.asarray(self.PAYOFF_B)[actions[0], actions[1]])
        reward = jnp.where(in_step1, 0.0, payoff)
        done = ~in_step1
        nxt = TwoStepState(jnp.where(in_step1, branch, 0))
        rewards = jnp.full((2,), reward)
        return nxt, self.obs(nxt), rewards, done


class QMIXConfig:
    """Builder-style config (``QMIXConfig().training(mixer="vdn")``)."""

    def __init__(self):
        self.env = TwoStepGame()
        self.num_envs = 16
        self.steps_per_iter = 64
        self.buffer_size = 4_096
        self.batch_size = 128
        self.updates_per_iter = 64
        self.gamma = 0.99
        self.lr = 5e-3
        self.hidden_sizes = (32,)
        self.mixing_embed = 16
        self.mixer = "qmix"             # "qmix" | "vdn"
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 3_000
        self.target_update_every = 100
        self.learning_starts = 256
        self.seed = 0

    def environment(self, env=None) -> "QMIXConfig":
        if env is not None:
            self.env = env
        return self

    def training(self, **kwargs) -> "QMIXConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown QMIX option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "QMIXConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "QMIX":
        return QMIX(self)


def _mixer_init(rng, n_agents: int, state_size: int, embed: int):
    """Hypernetworks state -> mixing weights (abs'd at apply time)."""
    k1, k2, k3, k4 = jax.random.split(rng, 4)
    return {
        "hw1": mlp_init(k1, (state_size, n_agents * embed)),
        "hb1": mlp_init(k2, (state_size, embed)),
        "hw2": mlp_init(k3, (state_size, embed)),
        "hb2": mlp_init(k4, (state_size, embed, 1)),
    }


def _mixer_apply(mp, qs, state, n_agents: int, embed: int):
    """qs [B, n_agents], state [B, S] -> Q_tot [B]. Monotone in qs."""
    w1 = jnp.abs(mlp_apply(mp["hw1"], state)).reshape(-1, n_agents, embed)
    b1 = mlp_apply(mp["hb1"], state)
    h = jax.nn.elu(jnp.einsum("ba,bae->be", qs, w1) + b1)
    w2 = jnp.abs(mlp_apply(mp["hw2"], state))
    b2 = mlp_apply(mp["hb2"], state)[:, 0]
    return jnp.sum(h * w2, axis=1) + b2


def _make_train_iter(cfg: QMIXConfig):
    env = cfg.env
    n_ag, n_act = env.n_agents, env.num_actions
    embed = cfg.mixing_embed

    def vec(fn):
        return jax.vmap(fn)

    reset_fn = vec(env.reset)
    obs_fn = vec(env.obs)
    state_fn = vec(env.state)
    step_fn = vec(env.step)

    def agent_qs(params, obs):
        """obs [B, n_agents, O] -> [B, n_agents, A] via the shared net."""
        return mlp_apply(params, obs)

    def mix(mp, qs, state):
        if cfg.mixer == "vdn":
            return jnp.sum(qs, axis=1)
        return _mixer_apply(mp, qs, state, n_ag, embed)

    def epsilon_at(global_step):
        return linear_epsilon(global_step, cfg.epsilon_start,
                              cfg.epsilon_end, cfg.epsilon_decay_steps)

    def td_loss(p, tp, batch):
        qs = agent_qs(p["agent"], batch["obs"])           # [B, n, A]
        taken = jnp.take_along_axis(
            qs, batch["actions"][..., None], axis=2)[..., 0]  # [B, n]
        q_tot = mix(p["mixer"], taken, batch["state"])
        next_qs = agent_qs(tp["agent"], batch["next_obs"])
        next_best = jnp.max(next_qs, axis=2)              # [B, n]
        next_tot = mix(tp["mixer"], next_best, batch["next_state"])
        y = batch["rewards"] + cfg.gamma * (1.0 - batch["dones"]) * \
            jax.lax.stop_gradient(next_tot)
        err = q_tot - y
        return jnp.mean(err * err)

    @jax.jit
    def reset(rng):
        return reset_fn(jax.random.split(rng, cfg.num_envs))

    @jax.jit
    def train_iter(learner, states, rng):
        def env_step(carry, _):
            learner, states, rng = carry
            rng, k_rand, k_expl, k_step = jax.random.split(rng, 4)
            obs = obs_fn(states)                          # [E, n, O]
            gstate = state_fn(states)                     # [E, S]
            qs = agent_qs(learner["params"]["agent"], obs)
            greedy = jnp.argmax(qs, axis=2)               # [E, n]
            randa = jax.random.randint(
                k_rand, (cfg.num_envs, n_ag), 0, n_act)
            eps = epsilon_at(learner["env_steps"])
            explore = jax.random.uniform(
                k_expl, (cfg.num_envs, n_ag)) < eps
            actions = jnp.where(explore, randa, greedy)
            nstates, nobs, rewards, done = step_fn(
                states, actions, jax.random.split(k_step, cfg.num_envs))
            team_rew = jnp.mean(rewards, axis=1)          # cooperative
            learner = dict(
                learner,
                buffer=buffer_add(
                    learner["buffer"], cfg.buffer_size,
                    obs=obs, state=gstate, actions=actions,
                    rewards=team_rew, next_obs=nobs,
                    next_state=state_fn(nstates),
                    dones=done.astype(jnp.float32)),
                env_steps=learner["env_steps"] + cfg.num_envs,
                reward_sum=learner["reward_sum"] + jnp.sum(team_rew),
                done_count=learner["done_count"] + jnp.sum(done),
            )
            return (learner, nstates, rng), None

        (learner, states, rng), _ = jax.lax.scan(
            env_step, (learner, states, rng), None,
            length=cfg.steps_per_iter)

        def update(carry, _):
            learner, rng = carry
            rng, k = jax.random.split(rng)
            buf = learner["buffer"]
            batch = buffer_sample(
                buf, k, cfg.batch_size,
                ("obs", "state", "actions", "rewards", "next_obs",
                 "next_state", "dones"))
            loss, grads = jax.value_and_grad(td_loss)(
                learner["params"], learner["target_params"], batch)
            ready = (buf["size"] >= cfg.learning_starts).astype(jnp.float32)
            grads = jax.tree.map(lambda g: g * ready, grads)
            params, opt = _adam(learner["params"], learner["opt"], grads,
                                lr=cfg.lr)
            target = periodic_target_sync(
                learner["target_params"], params, opt["t"],
                cfg.target_update_every)
            learner = dict(learner, params=params, opt=opt,
                           target_params=target)
            return (learner, rng), loss * ready

        (learner, rng), losses = jax.lax.scan(
            update, (learner, rng), None, length=cfg.updates_per_iter)
        metrics = {
            "loss": jnp.mean(losses),
            "epsilon": epsilon_at(learner["env_steps"]),
        }
        return learner, states, rng, metrics

    return reset, train_iter


class QMIX(EpisodeStats):
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""

    def __init__(self, config: QMIXConfig):
        self.config = config
        env = config.env
        rng = jax.random.key(config.seed)
        k_agent, k_mix, k_env, self._rng = jax.random.split(rng, 4)
        agent = mlp_init(
            k_agent,
            (env.observation_size, *config.hidden_sizes, env.num_actions))
        params = {
            "agent": agent,
            "mixer": _mixer_init(k_mix, env.n_agents, env.state_size,
                                 config.mixing_embed),
        }
        n_ag, obs_s, st_s = env.n_agents, env.observation_size, \
            env.state_size
        self._learner = {
            "params": params,
            "target_params": jax.tree.map(jnp.copy, params),
            "opt": {"mu": jax.tree.map(jnp.zeros_like, params),
                    "nu": jax.tree.map(jnp.zeros_like, params),
                    "t": jnp.zeros((), jnp.int32)},
            "buffer": buffer_init(
                config.buffer_size,
                {"obs": (n_ag, obs_s), "state": (st_s,),
                 "actions": (n_ag,), "rewards": (),
                 "next_obs": (n_ag, obs_s), "next_state": (st_s,),
                 "dones": ()},
                dtypes={"actions": jnp.int32}),
            "env_steps": jnp.zeros((), jnp.int32),
            "reward_sum": jnp.zeros(()),
            "done_count": jnp.zeros((), jnp.int32),
        }
        self._reset, self._train_iter = _make_train_iter(config)
        self._states = self._reset(k_env)
        self._iteration = 0

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        snap = self._episode_snapshot()
        self._learner, self._states, self._rng, metrics = self._train_iter(
            self._learner, self._states, self._rng)
        self._iteration += 1
        reward_mean = self._episode_reward_mean(snap)
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter":
                self.config.num_envs * self.config.steps_per_iter,
            "episode_reward_mean": reward_mean,
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(v) for k, v in metrics.items()},
        }

    def greedy_actions(self, states) -> jax.Array:
        """Greedy joint action for a batch of env states (for tests)."""
        obs = jax.vmap(self.config.env.obs)(states)
        qs = mlp_apply(self._learner["params"]["agent"], obs)
        return jnp.argmax(qs, axis=2)
