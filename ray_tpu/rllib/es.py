"""Evolution Strategies (Salimans et al. 2017).

Reference parity: ``rllib/algorithms/es`` — antithetic gaussian
perturbations, centered-rank fitness shaping, seed-only communication
for distributed rollouts. TPU-native twist: the DEFAULT path evaluates
the entire population inside one jitted program — perturbation sampling,
P×E vectorized env rollouts, rank shaping, and the gradient estimate all
compile together (population is just another vmapped axis; the MXU eats
the [P, params] matmuls). The distributed path keeps the reference's
trick: workers receive (params, seeds), return only (seed, fitness)
pairs, and the learner regenerates the noise from seeds.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu.rllib.env import CartPole
from ray_tpu.rllib.ppo import mlp_apply, mlp_init


class ESConfig:
    def __init__(self):
        self.env = CartPole()
        self.population = 128        # perturbation PAIRS are population/2
        self.sigma = 0.05
        self.lr = 0.03
        self.l2_coeff = 0.005
        self.episode_length = 500
        self.hidden_sizes = (32, 32)
        self.num_rollout_workers = 0
        # Gradient estimator: "es" = rank-shaped average over the whole
        # population (Salimans); "ars" = top-k directions by
        # max(f+, f-), step scaled by the reward std of the survivors
        # (Mania et al. 2018, rllib/algorithms/ars).
        self.estimator = "es"
        self.top_k = 0  # 0 = population/4 (ARS default-ish)
        self.seed = 0

    def training(self, **kw) -> "ESConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown config key {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "ES":
        return ES(self)


def _flatten_params(params):
    leaves, treedef = jax.tree.flatten(params)
    sizes = [l.size for l in leaves]
    shapes = [l.shape for l in leaves]
    flat = jnp.concatenate([l.reshape(-1) for l in leaves])

    def unflatten(v):
        out, off = [], 0
        for size, shape in zip(sizes, shapes):
            out.append(v[off:off + size].reshape(shape))
            off += size
        return jax.tree.unflatten(treedef, out)

    return flat, unflatten


def _episode_fitness(env, apply_fn, T):
    """Fitness of ONE policy: single-episode return (reward stream
    masked once the first done fires — auto-reset must not inflate)."""

    def fitness(flat_params, unflatten, rng):
        params = unflatten(flat_params)
        state = env.reset(rng)

        def step_fn(carry, _):
            state, alive, rng = carry
            rng, k = jax.random.split(rng)
            logits = apply_fn(params, env.obs(state))
            action = jnp.argmax(logits, axis=-1)
            state, _, reward, done = env.step(state, action, k)
            out = reward * alive
            alive = alive * (1.0 - done.astype(jnp.float32))
            return (state, alive, rng), out

        (_, _, _), rewards = jax.lax.scan(
            step_fn, (state, jnp.ones(()), rng), None, length=T)
        return rewards.sum()

    return fitness


def _centered_ranks(fitness):
    """Fitness shaping: ranks scaled into [-0.5, 0.5] (ES paper §2)."""
    ranks = jnp.argsort(jnp.argsort(fitness))
    return ranks.astype(jnp.float32) / (fitness.shape[0] - 1) - 0.5


class ESWorker:
    """Distributed evaluator: regenerates noise from seeds so only
    (seeds, fitnesses) cross the wire (reference es.py seed protocol)."""

    def __init__(self, cfg_dict: dict):
        self.cfg = cfg_dict
        self._fit = None

    def evaluate(self, flat_params: np.ndarray, seeds: List[int],
                 sigma: float) -> List[float]:
        env = self.cfg["env"]
        T = self.cfg["episode_length"]

        def apply_fn(params, obs):
            return mlp_apply(params["pi"], obs)

        if self._fit is None:
            unflatten = self.cfg["unflatten"]
            base = _episode_fitness(env, apply_fn, T)
            self._fit = jax.jit(
                lambda fp, rng: base(fp, unflatten, rng))
        flat = jnp.asarray(flat_params)
        out = []
        for seed in seeds:
            noise = jax.random.normal(
                jax.random.key(seed), flat.shape)
            for sign in (1.0, -1.0):  # antithetic pair
                out.append(float(self._fit(
                    flat + sign * sigma * noise,
                    jax.random.key(seed + 1))))
        return out


class ES:
    """Algorithm: ``.train()`` one generation -> result dict."""

    def __init__(self, config: ESConfig):
        self.config = config
        env = config.env
        rng = jax.random.key(config.seed)
        k_param, self._rng = jax.random.split(rng)
        params = {"pi": mlp_init(
            k_param, (env.observation_size, *config.hidden_sizes,
                      env.num_actions))}
        self._flat, self._unflatten = _flatten_params(params)
        self._iteration = 0
        self._workers: List = []
        if config.num_rollout_workers > 0:
            cls = ray_tpu.remote(ESWorker)
            cfg_dict = {"env": env,
                        "episode_length": config.episode_length,
                        "unflatten": self._unflatten}
            self._workers = [cls.remote(cfg_dict)
                             for _ in range(config.num_rollout_workers)]
        else:
            self._gen_iter = self._build_local()

    def _build_local(self):
        cfg = self.config
        env = cfg.env
        half = cfg.population // 2

        def apply_fn(params, obs):
            return mlp_apply(params["pi"], obs)

        fitness1 = _episode_fitness(env, apply_fn, cfg.episode_length)

        @jax.jit
        def gen_iter(flat, rng):
            k_noise, k_ep = jax.random.split(rng)
            eps = jax.random.normal(k_noise, (half,) + flat.shape)
            ep_keys = jax.random.split(k_ep, half)
            vfit = jax.vmap(
                lambda p, k: fitness1(p, self._unflatten, k))
            # Antithetic pairs share episode keys (common random numbers
            # cancel env stochasticity out of the pair difference).
            fit_pos = vfit(flat[None] + cfg.sigma * eps, ep_keys)
            fit_neg = vfit(flat[None] - cfg.sigma * eps, ep_keys)
            fit = jnp.concatenate([fit_pos, fit_neg])
            if cfg.estimator == "ars":
                # ARS V1-t: keep the top-k directions by max(f+, f-),
                # weight by raw reward differences, scale by the
                # surviving rewards' std (the paper's sigma_R).
                # Clamp: there are only `half` antithetic directions; a
                # larger user top_k would crash lax.top_k at trace time.
                k = min(cfg.top_k or max(1, half // 4), half)
                direction_best = jnp.maximum(fit_pos, fit_neg)
                _, top = jax.lax.top_k(direction_best, k)
                diff = (fit_pos - fit_neg)[top]
                sigma_r = jnp.std(
                    jnp.concatenate([fit_pos[top], fit_neg[top]])) + 1e-8
                grad = (diff[:, None] * eps[top]).mean(0) / sigma_r
            else:
                shaped = _centered_ranks(fit)
                w_pos, w_neg = shaped[:half], shaped[half:]
                grad = ((w_pos - w_neg)[:, None] * eps).mean(0) / cfg.sigma
            flat = flat + cfg.lr * grad - cfg.lr * cfg.l2_coeff * flat
            return flat, fit

        return gen_iter

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        cfg = self.config
        self._rng, k = jax.random.split(self._rng)
        if self._workers:
            half = cfg.population // 2
            base_seed = int(jax.random.randint(k, (), 0, 2**31 - 1))
            seeds = [base_seed + i for i in range(half)]
            chunks = np.array_split(seeds, len(self._workers))
            flat_np = np.asarray(self._flat)
            outs = ray_tpu.get(
                [w.evaluate.remote(flat_np, list(map(int, c)), cfg.sigma)
                 for w, c in zip(self._workers, chunks) if len(c)],
                timeout=600)
            fit_pos, fit_neg, eps_rows = [], [], []
            flat_out = [f for o in outs for f in o]
            for i, seed in enumerate(seeds):
                fit_pos.append(flat_out[2 * i])
                fit_neg.append(flat_out[2 * i + 1])
                eps_rows.append(np.asarray(jax.random.normal(
                    jax.random.key(seed), self._flat.shape)))
            fit = jnp.asarray(fit_pos + fit_neg)
            shaped = _centered_ranks(fit)
            w_pos, w_neg = shaped[:half], shaped[half:]
            eps = jnp.asarray(np.stack(eps_rows))
            grad = ((w_pos - w_neg)[:, None] * eps).mean(0) / cfg.sigma
            self._flat = (self._flat + cfg.lr * grad
                          - cfg.lr * cfg.l2_coeff * self._flat)
        else:
            self._flat, fit = self._gen_iter(self._flat, k)
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "episode_reward_mean": float(jnp.mean(fit)),
            "episode_reward_max": float(jnp.max(fit)),
            "timesteps_this_iter": cfg.population * cfg.episode_length,
            "time_this_iter_s": time.perf_counter() - start,
        }

    def save(self) -> dict:
        return {"flat": np.asarray(self._flat),
                "iteration": self._iteration}

    def restore(self, state: dict) -> None:
        self._flat = jnp.asarray(state["flat"])
        self._iteration = state["iteration"]


class ARSConfig(ESConfig):
    """Augmented Random Search (Mania et al. 2018;
    ``rllib/algorithms/ars``): the ES machinery with the V1-t estimator —
    top-k antithetic directions by max(f+, f-), raw reward-difference
    weights, step normalized by the survivors' reward std."""

    def __init__(self):
        super().__init__()
        self.estimator = "ars"
        self.lr = 0.02
        self.sigma = 0.05

    def build(self) -> "ARS":
        return ARS(self)


class ARS(ES):
    pass
