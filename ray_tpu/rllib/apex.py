"""Ape-X DQN: distributed prioritized experience replay (reference
``rllib/algorithms/apex_dqn/apex_dqn.py``, after Horgan et al. 2018).

The Ape-X signatures, mapped to the TPU design:

- **The epsilon ladder.** Ape-X runs hundreds of actors, actor i pinned
  to epsilon_i = eps^(1 + i/(N-1) * alpha) so the fleet explores at every
  temperature at once. Here the ladder lives on the VECTORIZED env axis:
  env lane i of the jitted rollout acts with its own fixed epsilon_i —
  the whole fleet is one device program instead of hundreds of processes
  (with ``num_rollout_workers > 0`` the same ladder also spreads across
  real ``ray_tpu`` actor processes, each owning a slice of it).
- **Prioritized replay.** ``replay.pbuffer_*``: categorical draw over
  p^alpha, importance weights (N*P)^-beta, TD-error priority refresh for
  the sampled indices each update — the learner half of Ape-X's replay
  server, as one on-device pytree.
- **Double-Q targets + periodic sync**, shared with ``dqn.py``.

Acceptance (``tests/test_rllib_apex.py``): solves CartPole, the ladder
really acts at per-lane epsilons, and prioritized sampling concentrates
on high-TD transitions vs uniform.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu.rllib.algorithm import EpisodeStats
from ray_tpu.rllib.env import CartPole, make_vec_env
from ray_tpu.rllib.optim import adam_step as _adam
from ray_tpu.rllib.optim import periodic_target_sync
from ray_tpu.rllib.dqn import q_td_errors
from ray_tpu.rllib.ppo import mlp_apply, mlp_init
from ray_tpu.rllib.replay import (
    pbuffer_add,
    pbuffer_init,
    pbuffer_sample,
    pbuffer_update_priorities,
)

__all__ = ["ApexDQN", "ApexDQNConfig"]


class ApexDQNConfig:
    """Builder-style config (``ApexDQNConfig().rollouts(num_envs=64)``)."""

    def __init__(self):
        self.env = CartPole()
        self.num_envs = 32              # epsilon-ladder lanes
        self.num_rollout_workers = 0    # >0: real actor processes
        self.steps_per_iter = 128
        self.buffer_size = 50_000
        self.batch_size = 128
        self.updates_per_iter = 48
        self.gamma = 0.99
        self.lr = 1e-3
        self.hidden_sizes = (64, 64)
        self.eps_base = 0.4             # ladder: eps_base^(1 + i/(N-1)*a)
        self.eps_alpha = 7.0
        self.per_alpha = 0.6
        self.per_beta = 0.4
        self.target_update_every = 200
        self.learning_starts = 1_000
        self.seed = 0

    def environment(self, env=None) -> "ApexDQNConfig":
        if env is not None:
            self.env = env
        return self

    def rollouts(self, *, num_envs: Optional[int] = None,
                 num_rollout_workers: Optional[int] = None,
                 ) -> "ApexDQNConfig":
        if num_envs is not None:
            self.num_envs = num_envs
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        return self

    def training(self, **kwargs) -> "ApexDQNConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown ApexDQN option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "ApexDQNConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "ApexDQN":
        return ApexDQN(self)


def epsilon_ladder(n: int, base: float, alpha: float) -> jnp.ndarray:
    """Horgan et al. eq. (1): eps_i = base^(1 + i/(N-1) * alpha)."""
    i = jnp.arange(n, dtype=jnp.float32)
    expo = 1.0 + i / jnp.maximum(n - 1, 1) * alpha
    return base ** expo


def _make_pieces(cfg: ApexDQNConfig, ladder_slice=None):
    env = cfg.env
    n_act = env.num_actions
    reset_fn, step_fn, obs_fn = make_vec_env(env, cfg.num_envs)
    eps = jnp.asarray(ladder_slice) if ladder_slice is not None else \
        epsilon_ladder(cfg.num_envs, cfg.eps_base, cfg.eps_alpha)

    def sample_rollout(params, states, rng):
        """Epsilon-ladder rollout -> flat transition batch."""
        def env_step(carry, _):
            states, rng = carry
            rng, k_rand, k_expl, k_step = jax.random.split(rng, 4)
            obs = obs_fn(states)
            q = mlp_apply(params, obs)
            greedy = jnp.argmax(q, axis=1)
            randa = jax.random.randint(k_rand, (cfg.num_envs,), 0, n_act)
            explore = jax.random.uniform(k_expl, (cfg.num_envs,)) < eps
            actions = jnp.where(explore, randa, greedy)
            nstates, nobs, rew, done = step_fn(states, actions, k_step)
            out = {"obs": obs, "actions": actions, "rewards": rew,
                   "next_obs": nobs, "dones": done.astype(jnp.float32)}
            return (nstates, rng), out

        (states, rng), traj = jax.lax.scan(
            env_step, (states, rng), None, length=cfg.steps_per_iter)
        flat = jax.tree.map(
            lambda x: x.reshape((-1,) + x.shape[2:]), traj)
        return states, rng, flat

    def weighted_loss(params, target_params, batch):
        err = q_td_errors(params, target_params, batch, cfg.gamma)
        return jnp.mean(batch["weights"] * err * err), err

    @jax.jit
    def reset(rng):
        return reset_fn(rng)

    @jax.jit
    def learn(learner, flat, rng):
        learner = dict(
            learner,
            buffer=pbuffer_add(learner["buffer"], cfg.buffer_size, **flat),
            env_steps=learner["env_steps"] + flat["dones"].shape[0],
            reward_sum=learner["reward_sum"] + jnp.sum(flat["rewards"]),
            done_count=learner["done_count"]
            + jnp.sum(flat["dones"]).astype(jnp.int32),
        )

        def update(carry, _):
            learner, rng = carry
            rng, k = jax.random.split(rng)
            buf = learner["buffer"]
            batch = pbuffer_sample(
                buf, k, cfg.batch_size,
                ("obs", "actions", "rewards", "next_obs", "dones"),
                alpha=cfg.per_alpha, beta=cfg.per_beta)
            (loss, err), grads = jax.value_and_grad(
                weighted_loss, has_aux=True)(
                learner["params"], learner["target_params"], batch)
            ready = (buf["size"] >= cfg.learning_starts).astype(jnp.float32)
            grads = jax.tree.map(lambda g: g * ready, grads)
            params, opt = _adam(learner["params"], learner["opt"], grads,
                                lr=cfg.lr)
            # Priority refresh for the sampled rows (gated like the
            # gradient so warmup doesn't overwrite the insert priority).
            # new_p is FINAL either way (the TD branch bakes the eps in),
            # so eps=0: a warm-up rewrite must preserve priorities
            # exactly, not creep them by eps per update.
            new_p = ready * (jnp.abs(err) + 1e-3) + (1.0 - ready) * \
                buf["priority"][batch["indices"]]
            buf = pbuffer_update_priorities(
                buf, batch["indices"], new_p, eps=0.0)
            target = periodic_target_sync(
                learner["target_params"], params, opt["t"],
                cfg.target_update_every)
            learner = dict(learner, params=params, opt=opt,
                           target_params=target, buffer=buf)
            return (learner, rng), loss * ready

        (learner, rng), losses = jax.lax.scan(
            update, (learner, rng), None, length=cfg.updates_per_iter)
        return learner, rng, {"loss": jnp.mean(losses)}

    return reset, jax.jit(sample_rollout), learn


class ApexRolloutWorker:
    """Actor process owning a slice of the epsilon ladder — the 'actor'
    half of Ape-X, sampling with a (possibly stale) weight snapshot."""

    def __init__(self, cfg_dict: dict, ladder_slice, seed: int):
        cfg = ApexDQNConfig()
        cfg.__dict__.update(cfg_dict)
        cfg.num_rollout_workers = 0
        self.cfg = cfg
        self._reset, self._sample, _ = _make_pieces(cfg, ladder_slice)
        self.rng = jax.random.key(seed)
        self.states = self._reset(jax.random.key(seed + 1))

    def sample(self, params) -> dict:
        self.states, self.rng, flat = self._sample(
            params, self.states, self.rng)
        return {k: np.asarray(v) for k, v in flat.items()}


class ApexDQN(EpisodeStats):
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""

    def __init__(self, config: ApexDQNConfig):
        self.config = config
        env = config.env
        rng = jax.random.key(config.seed)
        k_param, k_env, self._rng = jax.random.split(rng, 3)
        params = mlp_init(
            k_param,
            (env.observation_size, *config.hidden_sizes, env.num_actions))
        obs_size = env.observation_size
        self._learner = {
            "params": params,
            "target_params": jax.tree.map(jnp.copy, params),
            "opt": {"mu": jax.tree.map(jnp.zeros_like, params),
                    "nu": jax.tree.map(jnp.zeros_like, params),
                    "t": jnp.zeros((), jnp.int32)},
            "buffer": pbuffer_init(
                config.buffer_size,
                {"obs": (obs_size,), "actions": (), "rewards": (),
                 "next_obs": (obs_size,), "dones": ()},
                dtypes={"actions": jnp.int32}),
            "env_steps": jnp.zeros((), jnp.int32),
            "reward_sum": jnp.zeros(()),
            "done_count": jnp.zeros((), jnp.int32),
        }
        self._reset, self._sample, self._learn = _make_pieces(config)
        self._workers: List = []
        if config.num_rollout_workers > 0:
            full = np.asarray(epsilon_ladder(
                config.num_envs * config.num_rollout_workers,
                config.eps_base, config.eps_alpha))
            worker_cls = ray_tpu.remote(ApexRolloutWorker)
            self._workers = [
                worker_cls.remote(
                    dict(config.__dict__),
                    full[i * config.num_envs:(i + 1) * config.num_envs],
                    config.seed + 100 + i)
                for i in range(config.num_rollout_workers)
            ]
            self._states = None
        else:
            self._states = self._reset(k_env)
        self._iteration = 0

    def _gather(self) -> dict:
        if self._workers:
            batches = ray_tpu.get(
                [w.sample.remote(self._learner["params"])
                 for w in self._workers], timeout=300)
            return {k: jnp.concatenate([jnp.asarray(b[k]) for b in batches])
                    for k in batches[0]}
        self._states, self._rng, flat = self._sample(
            self._learner["params"], self._states, self._rng)
        return flat

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        prev_steps = int(self._learner["env_steps"])
        snap = self._episode_snapshot()
        flat = self._gather()
        self._learner, self._rng, metrics = self._learn(
            self._learner, flat, self._rng)
        self._iteration += 1
        steps = int(self._learner["env_steps"]) - prev_steps
        reward_mean = self._episode_reward_mean(snap)
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter": steps,
            "episode_reward_mean": reward_mean,
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(v) for k, v in metrics.items()},
        }

    @property
    def params(self):
        return self._learner["params"]

    def compute_single_action(self, obs) -> int:
        q = mlp_apply(self._learner["params"], jnp.asarray(obs)[None])
        return int(jnp.argmax(q[0]))
