"""MADDPG: multi-agent DDPG with centralized critics (reference
``rllib/algorithms/maddpg/maddpg.py``, after Lowe et al. 2017) — the
continuous-action counterpart to QMIX in the multi-agent corner of the
inventory: DECENTRALIZED deterministic actors (each sees only its own
observation) trained against CENTRALIZED critics Q_i(o_1..o_n, a_1..a_n)
that condition on every agent's observation and action, which removes
the non-stationarity that breaks independent DDPG.

TPU-native shape: all n actors, n critics, their targets, the joint
replay buffer, and the environment batch live in ONE jitted Anakin
program; the agent axis is a static Python loop over small per-agent
parameter pytrees (n is 2-4 — unrolling beats a lax axis here). The
actor gradient follows the paper's eq. 6: agent i's own action comes
from its CURRENT policy, the other agents' actions from the replay
sample.

``MultiAgentSpread`` is a jitted simplification of the MPE
``simple_spread`` task the reference benchmarks MADDPG on: n agents
must cover n landmarks under a shared reward.
"""

from __future__ import annotations

import time
from typing import Any, Dict, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithm import EpisodeStats
from ray_tpu.rllib.optim import adam_init
from ray_tpu.rllib.optim import adam_step as _adam
from ray_tpu.rllib.ppo import mlp_apply, mlp_init
from ray_tpu.rllib.replay import buffer_add, buffer_init, buffer_sample

__all__ = ["MADDPG", "MADDPGConfig", "MultiAgentSpread"]


class SpreadState(NamedTuple):
    pos: jax.Array        # [n_agents, 2]
    landmarks: jax.Array  # [n_agents, 2]
    t: jax.Array


class MultiAgentSpread:
    """n agents cover n landmarks on [-1, 1]^2; continuous velocity
    actions; shared reward = -sum over landmarks of the closest agent's
    distance (cooperative coverage). Fixed horizon, auto-reset."""

    def __init__(self, n_agents: int = 2, max_steps: int = 25,
                 dt: float = 0.25):
        self.n_agents = n_agents
        self.max_steps = max_steps
        self.dt = dt
        self.action_size = 2
        # own pos + all landmarks (relative) + other agents (relative)
        self.observation_size = 2 + 2 * n_agents + 2 * (n_agents - 1)

    def reset(self, rng: jax.Array) -> SpreadState:
        kp, kl = jax.random.split(rng)
        return SpreadState(
            jax.random.uniform(kp, (self.n_agents, 2), minval=-1.0,
                               maxval=1.0),
            jax.random.uniform(kl, (self.n_agents, 2), minval=-1.0,
                               maxval=1.0),
            jnp.zeros((), jnp.int32))

    def obs(self, s: SpreadState) -> jax.Array:
        """[n_agents, obs_size]."""
        n = self.n_agents
        rel_lm = (s.landmarks[None] - s.pos[:, None]).reshape(n, -1)
        rel_ag = (s.pos[None] - s.pos[:, None])          # [n, n, 2]
        # Drop the self row per agent (numpy mask: concrete under jit).
        mask = ~np.eye(n, dtype=bool)
        rel_others = rel_ag[mask].reshape(n, -1)
        return jnp.concatenate([s.pos, rel_lm, rel_others], axis=1)

    def _coverage_cost(self, pos, landmarks) -> jax.Array:
        d = jnp.linalg.norm(
            landmarks[:, None] - pos[None], axis=-1)      # [lm, agent]
        return jnp.sum(jnp.min(d, axis=1))

    def step(self, s: SpreadState, actions: jax.Array, rng: jax.Array):
        """actions [n_agents, 2] in [-1, 1] -> (state, obs, rewards
        [n_agents] (shared), done)."""
        npos = jnp.clip(s.pos + self.dt * jnp.clip(actions, -1, 1),
                        -1.0, 1.0)
        reward = -self._coverage_cost(npos, s.landmarks)
        t = s.t + 1
        done = t >= self.max_steps
        fresh = self.reset(rng)
        nxt = SpreadState(
            jnp.where(done, fresh.pos, npos),
            jnp.where(done, fresh.landmarks, s.landmarks),
            jnp.where(done, fresh.t, t))
        return nxt, self.obs(nxt), jnp.full((self.n_agents,), reward), done


class MADDPGConfig:
    """Builder-style config (``MADDPGConfig().training(tau=0.01)``)."""

    def __init__(self):
        self.env = MultiAgentSpread()
        self.num_envs = 16
        self.steps_per_iter = 64
        self.buffer_size = 50_000
        self.batch_size = 256
        self.updates_per_iter = 32
        self.gamma = 0.95
        self.tau = 0.01
        self.actor_lr = 1e-3
        self.critic_lr = 1e-3
        self.hidden_sizes = (64, 64)
        self.learning_starts = 1_000
        self.explore_noise = 0.2
        self.centralized = True     # False -> independent DDPG baseline
        self.seed = 0

    def environment(self, env=None) -> "MADDPGConfig":
        if env is not None:
            self.env = env
        return self

    def rollouts(self, *, num_envs: Optional[int] = None
                 ) -> "MADDPGConfig":
        if num_envs is not None:
            self.num_envs = num_envs
        return self

    def training(self, **kwargs) -> "MADDPGConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown MADDPG option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "MADDPGConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "MADDPG":
        return MADDPG(self)


def _make_train_iter(cfg: MADDPGConfig):
    env = cfg.env
    n, act_size = env.n_agents, env.action_size
    obs_size = env.observation_size

    vreset = jax.vmap(env.reset)
    vobs = jax.vmap(env.obs)
    vstep = jax.vmap(env.step)

    def actor_apply(ap, obs_i):
        return jnp.tanh(mlp_apply(ap, obs_i))

    def critic_in(batch_obs, batch_act, i):
        """Centralized: concat every agent's obs+act; independent: own."""
        if cfg.centralized:
            return jnp.concatenate(
                [batch_obs.reshape(batch_obs.shape[0], -1),
                 batch_act.reshape(batch_act.shape[0], -1)], axis=1)
        return jnp.concatenate(
            [batch_obs[:, i], batch_act[:, i]], axis=1)

    def critic_loss(cp, i, learner, batch):
        next_acts = jnp.stack(
            [actor_apply(learner["target_actors"][j], batch["nobs"][:, j])
             for j in range(n)], axis=1)
        tq = mlp_apply(learner["target_critics"][i],
                       critic_in(batch["nobs"], next_acts, i))[:, 0]
        y = batch["rew"][:, i] + cfg.gamma * (1 - batch["done"]) * \
            jax.lax.stop_gradient(tq)
        q = mlp_apply(cp, critic_in(batch["obs"], batch["act"], i))[:, 0]
        return jnp.mean((q - y) ** 2)

    def actor_loss(ap, i, critic_i, batch):
        # Paper eq. 6: own action from the CURRENT policy, other agents'
        # actions from the replay sample.
        own = actor_apply(ap, batch["obs"][:, i])
        acts = batch["act"].at[:, i].set(own)
        q = mlp_apply(critic_i, critic_in(batch["obs"], acts, i))[:, 0]
        return -jnp.mean(q)

    @jax.jit
    def reset(rng):
        return vreset(jax.random.split(rng, cfg.num_envs))

    @jax.jit
    def train_iter(learner, states, rng):
        def env_step(carry, _):
            learner, states, rng = carry
            rng, k_n, k_step = jax.random.split(rng, 3)
            obs = vobs(states)                        # [E, n, O]
            act = jnp.stack(
                [actor_apply(learner["actors"][i], obs[:, i])
                 for i in range(n)], axis=1)
            act = jnp.clip(
                act + cfg.explore_noise
                * jax.random.normal(k_n, act.shape), -1.0, 1.0)
            nstates, nobs, rew, done = vstep(
                states, act, jax.random.split(k_step, cfg.num_envs))
            # Spread terminates only on the time limit — store done=0 so
            # the critic bootstraps THROUGH truncation (td3.py's
            # TIME_LIMIT_ONLY convention).
            learner = dict(
                learner,
                buffer=buffer_add(
                    learner["buffer"], cfg.buffer_size,
                    obs=obs, act=act, rew=rew, nobs=nobs,
                    done=jnp.zeros(cfg.num_envs)),
                env_steps=learner["env_steps"] + cfg.num_envs,
                reward_sum=learner["reward_sum"] + jnp.sum(rew[:, 0]),
                done_count=learner["done_count"] + jnp.sum(done),
            )
            return (learner, nstates, rng), None

        (learner, states, rng), _ = jax.lax.scan(
            env_step, (learner, states, rng), None,
            length=cfg.steps_per_iter)

        def update(carry, _):
            learner, rng = carry
            rng, k = jax.random.split(rng)
            buf = learner["buffer"]
            batch = buffer_sample(
                buf, k, cfg.batch_size,
                ("obs", "act", "rew", "nobs", "done"))
            ready = (buf["size"] >= cfg.learning_starts).astype(jnp.float32)

            closs_sum = 0.0
            new_c, new_copt, new_a, new_aopt = [], [], [], []
            for i in range(n):
                closs, cg = jax.value_and_grad(critic_loss)(
                    learner["critics"][i], i, learner, batch)
                cg = jax.tree.map(lambda g: g * ready, cg)
                ci, coi = _adam(learner["critics"][i],
                                learner["copts"][i], cg,
                                lr=cfg.critic_lr)
                new_c.append(ci)
                new_copt.append(coi)
                closs_sum = closs_sum + closs

                aloss, ag = jax.value_and_grad(actor_loss)(
                    learner["actors"][i], i, ci, batch)
                ag = jax.tree.map(lambda g: g * ready, ag)
                ai, aoi = _adam(learner["actors"][i],
                                learner["aopts"][i], ag,
                                lr=cfg.actor_lr)
                new_a.append(ai)
                new_aopt.append(aoi)

            blend = cfg.tau * ready
            polyak = lambda t_, p_: jax.tree.map(      # noqa: E731
                lambda a, b: (1 - blend) * a + blend * b, t_, p_)
            learner = dict(
                learner,
                actors=new_a, critics=new_c,
                aopts=new_aopt, copts=new_copt,
                target_actors=[polyak(t_, p_) for t_, p_ in
                               zip(learner["target_actors"], new_a)],
                target_critics=[polyak(t_, p_) for t_, p_ in
                                zip(learner["target_critics"], new_c)],
            )
            return (learner, rng), closs_sum * ready / n

        (learner, rng), losses = jax.lax.scan(
            update, (learner, rng), None, length=cfg.updates_per_iter)
        return learner, states, rng, {"critic_loss": jnp.mean(losses)}

    return reset, train_iter


class MADDPG(EpisodeStats):
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""

    def __init__(self, config: MADDPGConfig):
        self.config = config
        env = config.env
        n = env.n_agents
        obs_size, act_size = env.observation_size, env.action_size
        cin = (obs_size + act_size) * (n if config.centralized else 1)
        rng = jax.random.key(config.seed)
        keys = jax.random.split(rng, 2 * n + 2)
        self._rng = keys[-1]
        actors = [mlp_init(keys[i],
                           (obs_size, *config.hidden_sizes, act_size))
                  for i in range(n)]
        critics = [mlp_init(keys[n + i],
                            (cin, *config.hidden_sizes, 1))
                   for i in range(n)]

        self._learner = {
            "actors": actors,
            "critics": critics,
            "target_actors": jax.tree.map(jnp.copy, actors),
            "target_critics": jax.tree.map(jnp.copy, critics),
            "aopts": [adam_init(a) for a in actors],
            "copts": [adam_init(c) for c in critics],
            "buffer": buffer_init(
                config.buffer_size,
                {"obs": (n, obs_size), "act": (n, act_size),
                 "rew": (n,), "nobs": (n, obs_size), "done": ()}),
            "env_steps": jnp.zeros((), jnp.int32),
            "reward_sum": jnp.zeros(()),
            "done_count": jnp.zeros((), jnp.int32),
        }
        self._reset, self._train_iter = _make_train_iter(config)
        self._states = self._reset(keys[-2])
        self._iteration = 0

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        snap = self._episode_snapshot()
        self._learner, self._states, self._rng, metrics = self._train_iter(
            self._learner, self._states, self._rng)
        self._iteration += 1
        reward_mean = self._episode_reward_mean(snap)
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter":
                self.config.num_envs * self.config.steps_per_iter,
            "episode_reward_mean": reward_mean,
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(v) for k, v in metrics.items()},
        }

    def greedy_coverage(self, rng) -> float:
        """Play one greedy episode; return the FINAL coverage cost
        (sum over landmarks of distance to the closest agent)."""
        env = self.config.env
        s = env.reset(rng)
        for _ in range(env.max_steps - 1):
            obs = env.obs(s)
            act = jnp.stack(
                [jnp.tanh(mlp_apply(self._learner["actors"][i],
                                    obs[i][None]))[0]
                 for i in range(env.n_agents)])
            rng, k = jax.random.split(rng)
            s, _, _, _ = env.step(s, act, k)
        return float(env._coverage_cost(s.pos, s.landmarks))
