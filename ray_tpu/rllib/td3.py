"""TD3: twin-delayed deep deterministic policy gradient (reference
``rllib/algorithms/td3``/``ddpg``) — the deterministic-policy counterpart
to SAC for continuous control. Shares SAC's twin critics, on-device
replay, Polyak targets, and Anakin execution shape; differs in the three
TD3 tricks: clipped target-policy smoothing noise, taking min(Q1, Q2) for
the target, and DELAYED (every ``policy_delay`` updates) actor + target
synchronization."""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithm import EpisodeStats
from ray_tpu.rllib.env import Pendulum, make_vec_env
from ray_tpu.rllib.optim import adam_init
from ray_tpu.rllib.optim import adam_step as _adam
from ray_tpu.rllib.ppo import mlp_apply, mlp_init
from ray_tpu.rllib.replay import buffer_add, buffer_init, buffer_sample
from ray_tpu.rllib.sac import critic_init


class TD3Config:
    def __init__(self):
        self.env = Pendulum()
        self.num_envs = 16
        self.steps_per_iter = 64
        self.buffer_size = 50_000
        self.batch_size = 256
        self.updates_per_iter = 32
        self.gamma = 0.99
        self.tau = 0.005
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.hidden_sizes = (128, 128)
        self.learning_starts = 1_000
        self.action_scale = 2.0
        self.explore_noise = 0.1        # behavior-policy gaussian noise
        self.target_noise = 0.2         # target-policy smoothing
        self.target_noise_clip = 0.5
        self.policy_delay = 2           # actor updates every N critic steps
        self.twin_q = True              # False -> DDPG's single critic
        self.seed = 0

    def environment(self, env=None) -> "TD3Config":
        if env is not None:
            self.env = env
        return self

    def rollouts(self, *, num_envs: Optional[int] = None) -> "TD3Config":
        if num_envs is not None:
            self.num_envs = num_envs
        return self

    def training(self, **kwargs) -> "TD3Config":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown TD3 option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "TD3Config":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "TD3":
        return TD3(self)


def _actor_apply(params, obs, scale):
    return scale * jnp.tanh(mlp_apply(params, obs))


def critic_apply(params, obs, act):
    """SAC's twin forward, tolerating the single-critic (DDPG) pytree:
    with no "q2" both returns alias q1, and the twin-only terms are
    never used because the loss branches on cfg.twin_q."""
    x = jnp.concatenate([obs, act], axis=-1)
    q1 = mlp_apply(params["q1"], x)[..., 0]
    q2 = mlp_apply(params["q2"], x)[..., 0] if "q2" in params else q1
    return q1, q2


def _make_train_iter(cfg: TD3Config):
    env = cfg.env
    reset_fn, step_fn, obs_fn = make_vec_env(env, cfg.num_envs)
    scale = cfg.action_scale
    time_limit_only = bool(getattr(env, "TIME_LIMIT_ONLY", False))

    @jax.jit
    def reset(rng):
        return reset_fn(rng)

    @jax.jit
    def train_iter(learner, states, rng):
        def env_step(carry, _):
            learner, states, rng = carry
            rng, k_n, k_step = jax.random.split(rng, 3)
            obs = obs_fn(states)
            act = _actor_apply(learner["actor"], obs, scale)
            act = jnp.clip(
                act + cfg.explore_noise * scale
                * jax.random.normal(k_n, act.shape),
                -scale, scale)
            nstates, _, rew, done = step_fn(states, act, k_step)
            done_f = done.astype(jnp.float32)
            stored = jnp.zeros_like(done_f) if time_limit_only else done_f
            learner = dict(
                learner,
                buffer=buffer_add(
                    learner["buffer"], cfg.buffer_size,
                    obs=obs, act=act, rew=rew, nobs=obs_fn(nstates),
                    done=stored),
                env_steps=learner["env_steps"] + cfg.num_envs,
                reward_sum=learner["reward_sum"] + jnp.sum(rew),
                done_count=learner["done_count"] + jnp.sum(done),
            )
            return (learner, nstates, rng), None

        (learner, states, rng), _ = jax.lax.scan(
            env_step, (learner, states, rng), None,
            length=cfg.steps_per_iter)

        def critic_loss(cp, batch, k):
            # Target-policy smoothing: clipped noise on the target action.
            noise = jnp.clip(
                cfg.target_noise * scale
                * jax.random.normal(k, batch["act"].shape),
                -cfg.target_noise_clip * scale,
                cfg.target_noise_clip * scale)
            next_act = jnp.clip(
                _actor_apply(learner["target_actor"], batch["nobs"], scale)
                + noise, -scale, scale)
            tq1, tq2 = critic_apply(
                learner["target_critic"], batch["nobs"], next_act)
            tq = jnp.minimum(tq1, tq2) if cfg.twin_q else tq1
            y = batch["rew"] + cfg.gamma * (1 - batch["done"]) * \
                jax.lax.stop_gradient(tq)
            q1, q2 = critic_apply(cp, batch["obs"], batch["act"])
            if cfg.twin_q:
                return jnp.mean((q1 - y) ** 2 + (q2 - y) ** 2)
            return jnp.mean((q1 - y) ** 2)

        def actor_loss(ap, cp, batch):
            act = _actor_apply(ap, batch["obs"], scale)
            q1, _ = critic_apply(cp, batch["obs"], act)
            return -jnp.mean(q1)

        def update(carry, i):
            learner, rng = carry
            rng, k_idx, k_t = jax.random.split(rng, 3)
            buf = learner["buffer"]
            batch = buffer_sample(buf, k_idx, cfg.batch_size,
                                  ("obs", "act", "rew", "nobs", "done"))
            ready = (buf["size"] >= cfg.learning_starts).astype(jnp.float32)

            closs, cgrads = jax.value_and_grad(critic_loss)(
                learner["critic"], batch, k_t)
            cgrads = jax.tree.map(lambda g: g * ready, cgrads)
            critic, copt = _adam(learner["critic"], learner["copt"],
                                 cgrads, lr=cfg.critic_lr)

            # Delayed policy + target updates (TD3 trick #3).
            do_pi = ready * ((i % cfg.policy_delay) == 0)
            aloss, agrads = jax.value_and_grad(actor_loss)(
                learner["actor"], critic, batch)
            agrads = jax.tree.map(lambda g: g * do_pi, agrads)
            actor, aopt = _adam(learner["actor"], learner["aopt"],
                                agrads, lr=cfg.actor_lr)
            blend = cfg.tau * do_pi
            target_actor = jax.tree.map(
                lambda t, p: (1 - blend) * t + blend * p,
                learner["target_actor"], actor)
            target_critic = jax.tree.map(
                lambda t, p: (1 - blend) * t + blend * p,
                learner["target_critic"], critic)
            learner = dict(learner, actor=actor, critic=critic,
                           aopt=aopt, copt=copt,
                           target_actor=target_actor,
                           target_critic=target_critic)
            return (learner, rng), {"critic_loss": closs * ready,
                                    "actor_loss": aloss * do_pi}

        (learner, rng), losses = jax.lax.scan(
            update, (learner, rng), jnp.arange(cfg.updates_per_iter))
        metrics = {
            "critic_loss": jnp.mean(losses["critic_loss"]),
            "actor_loss": jnp.mean(losses["actor_loss"]),
            "buffer_size": learner["buffer"]["size"].astype(jnp.float32),
        }
        return learner, states, rng, metrics

    return reset, train_iter


class TD3(EpisodeStats):
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""

    def __init__(self, config: TD3Config):
        self.config = config
        env = config.env
        rng = jax.random.key(config.seed)
        ka, kc, k_env, self._rng = jax.random.split(rng, 4)
        obs_size, act_size = env.observation_size, env.action_size
        actor = mlp_init(ka, (obs_size, *config.hidden_sizes, act_size))
        critic = critic_init(kc, obs_size, act_size, config.hidden_sizes)
        if not config.twin_q:
            critic = {"q1": critic["q1"]}  # DDPG: one critic, half the state

        self._learner = {
            "actor": actor,
            "critic": critic,
            "target_actor": jax.tree.map(jnp.copy, actor),
            "target_critic": jax.tree.map(jnp.copy, critic),
            "aopt": adam_init(actor),
            "copt": adam_init(critic),
            "buffer": buffer_init(
                config.buffer_size,
                {"obs": (obs_size,), "act": (act_size,), "rew": (),
                 "nobs": (obs_size,), "done": ()},
            ),
            "env_steps": jnp.zeros((), jnp.int32),
            "reward_sum": jnp.zeros(()),
            "done_count": jnp.zeros((), jnp.int32),
        }
        self._reset, self._train_iter = _make_train_iter(config)
        self._states = self._reset(k_env)
        self._iteration = 0

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        snap = self._episode_snapshot()
        prev_steps = int(self._learner["env_steps"])
        self._learner, self._states, self._rng, metrics = self._train_iter(
            self._learner, self._states, self._rng)
        self._iteration += 1
        steps = int(self._learner["env_steps"]) - prev_steps
        reward_mean = self._episode_reward_mean(snap)
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter": steps,
            "episode_reward_mean": reward_mean,
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(v) for k, v in metrics.items()},
        }

    def compute_single_action(self, obs):
        return _actor_apply(
            self._learner["actor"], jnp.asarray(obs)[None],
            self.config.action_scale)[0]
