"""RLlib-equivalent: RL algorithms on the task/actor substrate.

Reference parity (SURVEY.md §7 step 11): Algorithm/Trainable contract,
builder-style configs, pure-jax vectorized envs, SampleBatch, and a
string-name registry (``registry.get_algorithm_class``). The algorithm
inventory now spans every family class the reference ships (~30
algorithms): on-policy, off-policy/replay, distributed, multi-agent,
offline, meta-learning, search-based, bandits, and recommendation:
* PPO — fully jitted on-policy learner (Anakin) plus RolloutWorker
  actors (Sebulba);
* DQN — off-policy double-Q with an ON-DEVICE replay buffer, the whole
  act/store/sample/update iteration as one jitted program;
* IMPALA — the distributed actor-learner architecture: stale behavior
  policies on rollout actors, V-trace correction on the learner;
* SAC — continuous control: squashed-Gaussian actor, twin Q critics,
  on-device replay, automatic entropy temperature;
* A2C — the on-policy family's simplest member (shared PPO substrate);
* TD3 — deterministic continuous control: twin delayed critics, target
  smoothing (shared SAC substrate);
* multi-agent PPO (policy-map routing) and offline DQN (JSON datasets);
* PG / SimpleQ / DDPG — the family ancestors, each the tricks-off point
  of its descendant's jitted program;
* A3C — asynchronous gradient application over worker actors (the
  HogWild ancestor; workers run A2C's factored-out gradient program);
* Ape-X DQN — epsilon-ladder actors + prioritized replay — and
  Ape-X DDPG, the continuous noise-ladder variant on the TD3 substrate
  (twin_q=True is Apex-TD3);
* MADDPG — centralized critics / decentralized actors for cooperative
  continuous control (spread coverage task);
* R2D2 — recurrent sequence replay with stored state + burn-in;
* QMIX (with VDN) — monotonic value factorization for cooperative MARL;
* Decision Transformer — offline RL as return-conditioned sequence
  modeling (a control-sized causal GPT);
* LinUCB / LinTS contextual bandits — closed-form posterior updates as
  one jitted scan;
* AlphaZero — PUCT MCTS self-play (host tree, batched leaf evals on
  device) + policy-value net, tactical tests exact on TicTacToe;
* CRR — critic-regularized regression, the continuous offline member
  (binary/exp advantage weighting vs its BC ablation);
* MAML — meta-learned initialization whose inner PG adaptation is a
  literal ``grad`` composed under the outer ``grad`` (second-order
  term included), vmapped over the task batch;
* DD-PPO — decentralized PPO: no central learner, per-rank minibatch
  gradients allreduced through util.collective, parameters
  bit-identical across ranks by construction;
* SlateQ — slate recommendation through the user-choice-model Q
  decomposition; its gamma=0 ablation falls into the clickbait trap
  (worse than random) while SlateQ sustains the user state.
The execution model (jit the whole train iteration; actors only for
off-device sampling) is the part of the reference's ~30 algorithms that
generalizes.
"""

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu("rllib")

from ray_tpu.rllib.a2c import A2C, A2CConfig
from ray_tpu.rllib.a3c import A3C, A3CConfig
from ray_tpu.rllib.connectors import (
    ClipActions,
    ClipObs,
    Connector,
    ConnectorPipeline,
    FlattenObs,
    FrameStack,
    NormalizeObs,
    UnsquashActions,
)
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.env import CartPole, make_vec_env
from ray_tpu.rllib.env import Pendulum
from ray_tpu.rllib.appo import APPO, APPOConfig
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, vtrace
from ray_tpu.rllib.multi_agent import (
    MultiAgentEnv,
    MultiAgentGridWorld,
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rllib.offline import (
    JsonReader,
    JsonWriter,
    OfflineDQN,
    collect_transitions,
    read_sample_batches,
)
from ray_tpu.rllib.offline_algos import (
    BC,
    BCConfig,
    CQL,
    MARWIL,
    MARWILConfig,
)
from ray_tpu.rllib.alpha_zero import AlphaZero, AlphaZeroConfig, TicTacToe
from ray_tpu.rllib.apex import ApexDQN, ApexDQNConfig
from ray_tpu.rllib.apex_ddpg import ApexDDPG, ApexDDPGConfig
from ray_tpu.rllib.bandit import (
    BanditConfig,
    BanditLinTS,
    BanditLinUCB,
    LinearBanditEnv,
)
from ray_tpu.rllib.crr import CRR, CRRConfig
from ray_tpu.rllib.ddpg import DDPG, DDPGConfig
from ray_tpu.rllib.ddppo import DDPPO, DDPPOConfig
from ray_tpu.rllib.maddpg import MADDPG, MADDPGConfig, MultiAgentSpread
from ray_tpu.rllib.maml import MAML, MAMLConfig, PointGoalTasks
from ray_tpu.rllib.dt import DT, DTConfig, collect_episodes
from ray_tpu.rllib.es import ARS, ARSConfig, ES, ESConfig
from ray_tpu.rllib.pg import PG, PGConfig
from ray_tpu.rllib.qmix import QMIX, QMIXConfig, TwoStepGame
from ray_tpu.rllib.r2d2 import R2D2, R2D2Config
from ray_tpu.rllib.simple_q import SimpleQ, SimpleQConfig
from ray_tpu.rllib.slateq import SlateDocEnv, SlateQ, SlateQConfig
from ray_tpu.rllib.evaluation import EvalWorker, EvaluationWorkerSet
from ray_tpu.rllib.models import ModelCatalog
from ray_tpu.rllib.registry import get_algorithm_class, get_algorithm_config
from ray_tpu.rllib.recurrent import (
    MemoryChain,
    RecurrentPPO,
    RecurrentPPOConfig,
)
from ray_tpu.rllib.sac import SAC, SACConfig
from ray_tpu.rllib.td3 import TD3, TD3Config
from ray_tpu.rllib.ppo import PPO, PPOConfig, RolloutWorker, policy_apply
from ray_tpu.rllib.sample_batch import SampleBatch

__all__ = [
    "A2C",
    "Connector",
    "ConnectorPipeline",
    "ClipObs",
    "ClipActions",
    "FlattenObs",
    "FrameStack",
    "NormalizeObs",
    "UnsquashActions",
    "A2CConfig",
    "A3C",
    "A3CConfig",
    "MADDPG",
    "MADDPGConfig",
    "MultiAgentSpread",
    "MAML",
    "MAMLConfig",
    "PointGoalTasks",
    "TD3",
    "TD3Config",
    "CartPole",
    "make_vec_env",
    "DQN",
    "DQNConfig",
    "APPO",
    "APPOConfig",
    "ARS",
    "ARSConfig",
    "BC",
    "BCConfig",
    "CQL",
    "MARWIL",
    "MARWILConfig",
    "ES",
    "ESConfig",
    "EvalWorker",
    "EvaluationWorkerSet",
    "IMPALA",
    "IMPALAConfig",
    "MemoryChain",
    "ModelCatalog",
    "RecurrentPPO",
    "RecurrentPPOConfig",
    "SAC",
    "SACConfig",
    "Pendulum",
    "vtrace",
    "PPO",
    "PPOConfig",
    "RolloutWorker",
    "policy_apply",
    "SampleBatch",
    "MultiAgentEnv",
    "MultiAgentGridWorld",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "JsonReader",
    "JsonWriter",
    "OfflineDQN",
    "collect_transitions",
    "read_sample_batches",
    "AlphaZero",
    "AlphaZeroConfig",
    "TicTacToe",
    "ApexDQN",
    "ApexDQNConfig",
    "ApexDDPG",
    "ApexDDPGConfig",
    "CRR",
    "CRRConfig",
    "BanditConfig",
    "BanditLinTS",
    "BanditLinUCB",
    "LinearBanditEnv",
    "DDPG",
    "DDPGConfig",
    "DDPPO",
    "DDPPOConfig",
    "DT",
    "DTConfig",
    "collect_episodes",
    "PG",
    "PGConfig",
    "QMIX",
    "QMIXConfig",
    "TwoStepGame",
    "R2D2",
    "R2D2Config",
    "SimpleQ",
    "SimpleQConfig",
    "SlateQ",
    "SlateQConfig",
    "SlateDocEnv",
    "get_algorithm_class",
    "get_algorithm_config",
]
