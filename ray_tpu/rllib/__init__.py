"""RLlib-equivalent: RL algorithms on the task/actor substrate.

Reference parity (minimum viable, SURVEY.md §7 step 11): Algorithm/
Trainable contract, builder-style config, PPO with a fully jitted learner
(Anakin) plus RolloutWorker actors (Sebulba), pure-jax vectorized envs,
SampleBatch. The reference's ~30 algorithms narrow to PPO first — the
execution model (jit the whole train iteration; actors only for
off-device sampling) is the part that generalizes.
"""

from ray_tpu.rllib.env import CartPole, make_vec_env
from ray_tpu.rllib.ppo import PPO, PPOConfig, RolloutWorker, policy_apply
from ray_tpu.rllib.sample_batch import SampleBatch

__all__ = [
    "CartPole",
    "make_vec_env",
    "PPO",
    "PPOConfig",
    "RolloutWorker",
    "policy_apply",
    "SampleBatch",
]
