"""RLlib-equivalent: RL algorithms on the task/actor substrate.

Reference parity (SURVEY.md §7 step 11): Algorithm/Trainable contract,
builder-style configs, pure-jax vectorized envs, SampleBatch. Two
algorithm families:
* PPO — fully jitted on-policy learner (Anakin) plus RolloutWorker
  actors (Sebulba);
* DQN — off-policy double-Q with an ON-DEVICE replay buffer, the whole
  act/store/sample/update iteration as one jitted program;
* IMPALA — the distributed actor-learner architecture: stale behavior
  policies on rollout actors, V-trace correction on the learner;
* SAC — continuous control: squashed-Gaussian actor, twin Q critics,
  on-device replay, automatic entropy temperature;
* A2C — the on-policy family's simplest member (shared PPO substrate);
* TD3 — deterministic continuous control: twin delayed critics, target
  smoothing (shared SAC substrate);
* multi-agent PPO (policy-map routing) and offline DQN (JSON datasets).
The execution model (jit the whole train iteration; actors only for
off-device sampling) is the part of the reference's ~30 algorithms that
generalizes.
"""

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu("rllib")

from ray_tpu.rllib.a2c import A2C, A2CConfig
from ray_tpu.rllib.connectors import (
    ClipActions,
    ClipObs,
    Connector,
    ConnectorPipeline,
    FlattenObs,
    FrameStack,
    NormalizeObs,
    UnsquashActions,
)
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.env import CartPole, make_vec_env
from ray_tpu.rllib.env import Pendulum
from ray_tpu.rllib.appo import APPO, APPOConfig
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, vtrace
from ray_tpu.rllib.multi_agent import (
    MultiAgentEnv,
    MultiAgentGridWorld,
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rllib.offline import (
    JsonReader,
    JsonWriter,
    OfflineDQN,
    collect_transitions,
    read_sample_batches,
)
from ray_tpu.rllib.offline_algos import (
    BC,
    BCConfig,
    CQL,
    MARWIL,
    MARWILConfig,
)
from ray_tpu.rllib.es import ARS, ARSConfig, ES, ESConfig
from ray_tpu.rllib.evaluation import EvalWorker, EvaluationWorkerSet
from ray_tpu.rllib.models import ModelCatalog
from ray_tpu.rllib.recurrent import (
    MemoryChain,
    RecurrentPPO,
    RecurrentPPOConfig,
)
from ray_tpu.rllib.sac import SAC, SACConfig
from ray_tpu.rllib.td3 import TD3, TD3Config
from ray_tpu.rllib.ppo import PPO, PPOConfig, RolloutWorker, policy_apply
from ray_tpu.rllib.sample_batch import SampleBatch

__all__ = [
    "A2C",
    "Connector",
    "ConnectorPipeline",
    "ClipObs",
    "ClipActions",
    "FlattenObs",
    "FrameStack",
    "NormalizeObs",
    "UnsquashActions",
    "A2CConfig",
    "TD3",
    "TD3Config",
    "CartPole",
    "make_vec_env",
    "DQN",
    "DQNConfig",
    "APPO",
    "APPOConfig",
    "ARS",
    "ARSConfig",
    "BC",
    "BCConfig",
    "CQL",
    "MARWIL",
    "MARWILConfig",
    "ES",
    "ESConfig",
    "EvalWorker",
    "EvaluationWorkerSet",
    "IMPALA",
    "IMPALAConfig",
    "MemoryChain",
    "ModelCatalog",
    "RecurrentPPO",
    "RecurrentPPOConfig",
    "SAC",
    "SACConfig",
    "Pendulum",
    "vtrace",
    "PPO",
    "PPOConfig",
    "RolloutWorker",
    "policy_apply",
    "SampleBatch",
    "MultiAgentEnv",
    "MultiAgentGridWorld",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "JsonReader",
    "JsonWriter",
    "OfflineDQN",
    "collect_transitions",
    "read_sample_batches",
]
