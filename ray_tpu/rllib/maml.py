"""MAML: model-agnostic meta-learning for RL (reference
``rllib/algorithms/maml/maml.py``, after Finn et al. 2017) — the
meta-learning member of the inventory: train an INITIALIZATION such
that one inner-loop policy-gradient step on a new task's own rollouts
produces a good task-specific policy.

This is the algorithm jax was built for: the inner adaptation is
``theta' = theta - alpha * grad(L_inner)(theta)`` written literally,
and the outer gradient differentiates THROUGH it (the second-order
MAML term comes from composing ``jax.grad`` twice — no manual Hessian
plumbing like the reference's torch higher-order workarounds). The
whole meta-iteration — vmapped over the task batch: inner rollout,
inner update, post-update rollout, outer surrogate — is ONE jitted
program.

The task family is the reference's point-navigation example
(``rllib/examples/env/point_env.py`` analog): goal positions the agent
cannot observe, so the meta-learned behavior must (a) explore enough
that the inner PG carries goal information and (b) sit in a parameter
region where one gradient step specializes it. The acceptance test is
the paper's claim itself: one adaptation step on a HELD-OUT task jumps
the return, and the meta-trained init adapts far better than a random
init given the identical update rule.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.rllib.optim import adam_init, adam_step
from ray_tpu.rllib.ppo import mlp_apply, mlp_init

__all__ = ["MAML", "MAMLConfig", "PointGoalTasks"]


class PointGoalTasks:
    """2D point navigation; a TASK is a hidden goal in [-1, 1]^2. The
    observation is the position only — the goal reaches the learner
    exclusively through rewards, which is what makes adaptation
    necessary. Fixed horizon, no terminal states."""

    observation_size = 2
    action_size = 2
    horizon = 20
    max_step = 0.15

    def sample_tasks(self, rng, n: int) -> jax.Array:
        return jax.random.uniform(rng, (n, 2), minval=-1.0, maxval=1.0)

    def rollout_reward(self, pos, goal):
        return -jnp.linalg.norm(pos - goal, axis=-1)


class MAMLConfig:
    """Builder-style config (``MAMLConfig().training(inner_lr=0.2)``)."""

    def __init__(self):
        self.tasks = PointGoalTasks()
        self.meta_batch_size = 8     # tasks per meta-iteration
        self.num_envs = 32           # rollouts per task per phase
        self.inner_lr = 0.3
        self.outer_lr = 5e-3
        self.inner_steps = 2
        self.gamma = 0.99
        self.hidden_sizes = (64, 64)
        self.log_std = -0.5          # fixed exploration noise (log scale)
        self.seed = 0

    def environment(self, tasks=None) -> "MAMLConfig":
        if tasks is not None:
            self.tasks = tasks
        return self

    def training(self, **kwargs) -> "MAMLConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown MAML option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "MAMLConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "MAML":
        return MAML(self)


def _make_meta_iter(cfg: MAMLConfig):
    tasks = cfg.tasks
    T, E = tasks.horizon, cfg.num_envs
    std = jnp.exp(cfg.log_std)

    def rollout(params, goal, rng):
        """E parallel episodes on one task -> (traj, mean_return)."""
        def step(carry, _):
            pos, rng = carry
            rng, k = jax.random.split(rng)
            mean = tasks.max_step * jnp.tanh(mlp_apply(params, pos))
            act = mean + tasks.max_step * std * \
                jax.random.normal(k, mean.shape)
            npos = jnp.clip(pos + act, -1.5, 1.5)
            rew = tasks.rollout_reward(npos, goal)
            return (npos, rng), {"obs": pos, "act": act, "rew": rew}

        pos0 = jnp.zeros((E, 2))
        (_, _), traj = jax.lax.scan(step, (pos0, rng), None, length=T)
        return traj

    def pg_loss(params, traj):
        """REINFORCE on reward-to-go with a batch-mean baseline. The
        logp is the Gaussian density of the STORED actions under
        ``params`` — differentiable wrt params, so this same function
        serves as inner loss, and (applied to post-update trajectories
        with the adapted params) as the outer surrogate."""
        def rtg_step(running, rew):
            running = rew + cfg.gamma * running
            return running, running

        _, rtg = jax.lax.scan(
            rtg_step, jnp.zeros(traj["rew"].shape[1]), traj["rew"],
            reverse=True)
        # Standardized advantages: the inner update must have a
        # task-independent gradient SCALE or a single inner_lr cannot
        # serve every task (far goals have larger raw reward-to-go).
        adv = (rtg - jnp.mean(rtg)) / (jnp.std(rtg) + 1e-6)
        mean = tasks.max_step * jnp.tanh(mlp_apply(params, traj["obs"]))
        sigma = tasks.max_step * std
        logp = jnp.sum(
            -0.5 * ((traj["act"] - mean) / sigma) ** 2, axis=-1)
        return -jnp.mean(logp * adv)

    def adapt(params, goal, rng):
        """Inner loop: ``inner_steps`` plain-SGD PG updates on fresh
        task rollouts. Differentiable wrt ``params``."""
        for i in range(cfg.inner_steps):
            traj = rollout(params, goal, jax.random.fold_in(rng, i))
            grads = jax.grad(pg_loss)(params, traj)
            params = jax.tree.map(
                lambda p, g: p - cfg.inner_lr * g, params, grads)
        return params

    def task_outer_loss(params, goal, rng):
        k_in, k_out = jax.random.split(rng)
        adapted = adapt(params, goal, k_in)
        traj = rollout(adapted, goal, k_out)
        post_return = jnp.mean(jnp.sum(traj["rew"], axis=0))
        return pg_loss(adapted, traj), post_return

    @jax.jit
    def meta_iter(params, opt, rng):
        rng, k_task, k_roll = jax.random.split(rng, 3)
        goals = tasks.sample_tasks(k_task, cfg.meta_batch_size)
        keys = jax.random.split(k_roll, cfg.meta_batch_size)

        def mean_outer(p):
            losses, post = jax.vmap(
                lambda g, k: task_outer_loss(p, g, k))(goals, keys)
            return jnp.mean(losses), jnp.mean(post)

        (loss, post_return), grads = jax.value_and_grad(
            mean_outer, has_aux=True)(params)
        params, opt = adam_step(params, opt, grads, lr=cfg.outer_lr,
                                max_grad_norm=1.0)
        return params, opt, rng, {"meta_loss": loss,
                                  "post_adapt_return": post_return}

    return rollout, adapt, meta_iter


class MAML:
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""

    def __init__(self, config: MAMLConfig):
        self.config = config
        tasks = config.tasks
        k_param, self._rng = jax.random.split(
            jax.random.key(config.seed))
        self.params = mlp_init(
            k_param,
            (tasks.observation_size, *config.hidden_sizes,
             tasks.action_size))
        self.opt = adam_init(self.params)
        self._rollout, self._adapt, self._meta_iter = \
            _make_meta_iter(config)
        self._iteration = 0

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        self.params, self.opt, self._rng, metrics = self._meta_iter(
            self.params, self.opt, self._rng)
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter":
                self.config.meta_batch_size * self.config.num_envs
                * self.config.tasks.horizon
                * (self.config.inner_steps + 1),
            "episode_reward_mean": float(metrics["post_adapt_return"]),
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(v) for k, v in metrics.items()},
        }

    # -- evaluation -----------------------------------------------------

    def mean_return(self, params, goal, rng) -> float:
        traj = self._rollout(params, jnp.asarray(goal), rng)
        return float(jnp.mean(jnp.sum(traj["rew"], axis=0)))

    def adapt_to(self, goal, rng, params=None):
        """One full inner-loop adaptation on a (held-out) task."""
        return self._adapt(
            params if params is not None else self.params,
            jnp.asarray(goal), rng)
