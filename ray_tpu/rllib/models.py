"""Model catalog: policy network families behind a uniform interface.

Reference parity: ``rllib/models/catalog.py`` (ModelCatalog — the
registry mapping model_config to a network: fcnet, LSTM wrapper,
attention nets) re-done functionally for jax: every model is an
``(init, initial_state, apply)`` triple

    params = init(rng, obs_size, num_actions, cfg)
    state  = initial_state(params, batch_size)          # pytree, may be ()
    logits, value, state' = apply(params, obs[B, D], state)

so recurrent and stateless models share one rollout loop (the reference
wraps torch modules with hidden-state plumbing in ``use_lstm`` /
``use_attention``; here state is an explicit scan carry — the natural
jax/Anakin shape).

Models:
  * ``mlp``       — tanh MLP, separate value head (fcnet analog)
  * ``lstm``      — MLP encoder -> LSTM cell -> pi/vf heads
                    (``rllib/models/torch/recurrent_net.py`` analog)
  * ``attention`` — MLP encoder -> causal attention over a rolling
                    K-step memory of encodings -> pi/vf heads (GTrXL-
                    lite: ``rllib/models/torch/attention_net.py`` analog)
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp


def _dense_init(rng, din, dout, scale):
    return {"w": jax.random.normal(rng, (din, dout)) * scale,
            "b": jnp.zeros((dout,))}


def _dense(p, x):
    return x @ p["w"] + p["b"]


def _mlp_init(rng, sizes, out_scale=0.01):
    params = []
    for i, (din, dout) in enumerate(zip(sizes[:-1], sizes[1:])):
        k, rng = jax.random.split(rng)
        scale = np.sqrt(2.0 / din) if i < len(sizes) - 2 else out_scale
        params.append(_dense_init(k, din, dout, scale))
    return params


def _mlp(params, x, act=jnp.tanh):
    for i, layer in enumerate(params):
        x = _dense(layer, x)
        if i < len(params) - 1:
            x = act(x)
    return x


# -- mlp --------------------------------------------------------------------


def _make_mlp(obs_size: int, num_actions: int, cfg: Dict[str, Any]):
    hidden = tuple(cfg.get("fcnet_hiddens", (64, 64)))

    def init(rng):
        kp, kv = jax.random.split(rng)
        return {"pi": _mlp_init(kp, (obs_size, *hidden, num_actions)),
                "vf": _mlp_init(kv, (obs_size, *hidden, 1), out_scale=1.0)}

    def initial_state(params, batch_size):
        return ()

    def apply(params, obs, state):
        return (_mlp(params["pi"], obs),
                _mlp(params["vf"], obs)[..., 0], state)

    return init, initial_state, apply


# -- lstm -------------------------------------------------------------------


def _make_lstm(obs_size: int, num_actions: int, cfg: Dict[str, Any]):
    embed = int(cfg.get("embed_size", 64))
    cell = int(cfg.get("lstm_cell_size", 64))

    def init(rng):
        ke, kx, kh, kp, kv = jax.random.split(rng, 5)
        return {
            "enc": _mlp_init(ke, (obs_size, embed), out_scale=1.0),
            # One fused matmul computes all four gates (i, f, g, o).
            "wx": _dense_init(kx, embed, 4 * cell,
                              np.sqrt(1.0 / embed)),
            "wh": _dense_init(kh, cell, 4 * cell, np.sqrt(1.0 / cell)),
            "pi": _mlp_init(kp, (cell, num_actions)),
            "vf": _mlp_init(kv, (cell, 1), out_scale=1.0),
        }

    def initial_state(params, batch_size):
        z = jnp.zeros((batch_size, cell))
        return (z, z)

    def apply(params, obs, state):
        h, c = state
        x = jnp.tanh(_mlp(params["enc"], obs))
        gates = _dense(params["wx"], x) + _dense(params["wh"], h)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (_mlp(params["pi"], h), _mlp(params["vf"], h)[..., 0],
                (h, c))

    return init, initial_state, apply


# -- attention --------------------------------------------------------------


def _make_attention(obs_size: int, num_actions: int, cfg: Dict[str, Any]):
    embed = int(cfg.get("embed_size", 64))
    memory = int(cfg.get("attention_memory", 16))
    heads = int(cfg.get("attention_heads", 2))
    head_dim = embed // heads

    def init(rng):
        ke, kq, kk, kv_, ko, kp, kv = jax.random.split(rng, 7)
        s = np.sqrt(1.0 / embed)
        return {
            "enc": _mlp_init(ke, (obs_size, embed), out_scale=1.0),
            "q": _dense_init(kq, embed, embed, s),
            "k": _dense_init(kk, embed, embed, s),
            "v": _dense_init(kv_, embed, embed, s),
            "o": _dense_init(ko, embed, embed, s),
            "pi": _mlp_init(kp, (embed, num_actions)),
            "vf": _mlp_init(kv, (embed, 1), out_scale=1.0),
        }

    def initial_state(params, batch_size):
        # Rolling memory of the last K step encodings + a validity mask
        # (GTrXL's memory tensor; fixed shape keeps everything jittable).
        return (jnp.zeros((batch_size, memory, embed)),
                jnp.zeros((batch_size, memory)))

    def apply(params, obs, state):
        mem, mask = state
        x = jnp.tanh(_mlp(params["enc"], obs))          # [B, E]
        mem = jnp.concatenate([mem[:, 1:], x[:, None]], axis=1)
        mask = jnp.concatenate(
            [mask[:, 1:], jnp.ones_like(mask[:, :1])], axis=1)
        B = x.shape[0]

        def split_heads(t):  # [B, K, E] -> [B, H, K, hd]
            return t.reshape(B, -1, heads, head_dim).transpose(0, 2, 1, 3)

        q = split_heads(_dense(params["q"], x[:, None]))   # [B,H,1,hd]
        k = split_heads(_dense(params["k"], mem))          # [B,H,K,hd]
        v = split_heads(_dense(params["v"], mem))
        att = (q @ k.transpose(0, 1, 3, 2))[..., 0, :] / np.sqrt(head_dim)
        att = jnp.where(mask[:, None] > 0, att, -1e9)      # [B,H,K]
        w = jax.nn.softmax(att, axis=-1)
        ctx = jnp.einsum("bhk,bhkd->bhd", w, v).reshape(B, embed)
        y = x + _dense(params["o"], ctx)                   # residual
        return (_mlp(params["pi"], y), _mlp(params["vf"], y)[..., 0],
                (mem, mask))

    return init, initial_state, apply


_REGISTRY = {"mlp": _make_mlp, "lstm": _make_lstm,
             "attention": _make_attention}


class ModelCatalog:
    """``rllib/models/catalog.py`` registry analog."""

    @staticmethod
    def register(name: str, factory) -> None:
        _REGISTRY[name] = factory

    @staticmethod
    def get(obs_size: int, num_actions: int,
            model_config: Dict[str, Any] | None = None):
        cfg = dict(model_config or {})
        name = cfg.get("model", "mlp")
        try:
            factory = _REGISTRY[name]
        except KeyError:
            raise ValueError(
                f"unknown model {name!r} (known: {sorted(_REGISTRY)})"
            ) from None
        return factory(obs_size, num_actions, cfg)
