"""AlphaZero: MCTS self-play + policy-value network (reference
``rllib/algorithms/alpha_zero/``, after Silver et al. 2017). The
reference runs a numpy MCTS per rollout worker around a torch net
(``alpha_zero/mcts.py``); the structure here is the same host/device
split done the jax way — the TREE lives on the host (python dicts of
small numpy arrays; pointer-chasing is host work), while every leaf
evaluation crosses to the device BATCHED: all parallel self-play games
advance their searches in lockstep, so one jitted net call serves one
leaf per game per simulation instead of a call per leaf.

Pieces: PUCT selection with Dirichlet root noise, visit-count policy
targets with a temperature cutoff, value targets from the game outcome
propagated with alternating signs, CE + MSE + L2 training on a replay
window of recent games, and a canonical-board representation (the board
always from the player-to-move's perspective) so one net plays both
sides.

``TicTacToe`` is the acceptance game: small enough that the tactical
unit tests are exact (an untrained net's MCTS must already find a
mate-in-1 — tree search, not the net, supplies tactics), and large
enough that self-play measurably improves play vs. random and 1-ply
opponents.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.optim import adam_init, adam_step
from ray_tpu.rllib.ppo import mlp_init

__all__ = ["AlphaZero", "AlphaZeroConfig", "TicTacToe", "MCTS"]


class TicTacToe:
    """3x3; board is a length-9 int8 array in {+1 (to move), -1, 0} —
    CANONICAL: always from the perspective of the player to move."""

    n_actions = 9
    obs_size = 9
    max_moves = 9
    _LINES = np.array([
        [0, 1, 2], [3, 4, 5], [6, 7, 8],
        [0, 3, 6], [1, 4, 7], [2, 5, 8],
        [0, 4, 8], [2, 4, 6]])

    def initial_state(self) -> np.ndarray:
        return np.zeros(9, np.int8)

    def legal_mask(self, board: np.ndarray) -> np.ndarray:
        return board == 0

    def next_state(self, board: np.ndarray, action: int) -> np.ndarray:
        """Play for the player to move (+1), then flip perspective."""
        nxt = board.copy()
        nxt[action] = 1
        return -nxt

    def terminal_value(self, board: np.ndarray) -> Optional[float]:
        """From the PLAYER TO MOVE's perspective: -1 if the opponent
        (who just moved) completed a line, 0 for a draw, None if the
        game continues."""
        sums = board[self._LINES].sum(axis=1)
        if (sums == -3).any():
            return -1.0
        if (board != 0).all():
            return 0.0
        return None


# ---------------------------------------------------------------------------
# the net: canonical board -> (move logits, value in [-1, 1])
# ---------------------------------------------------------------------------


def _net_init(rng, obs_size: int, n_actions: int, hidden):
    kt, kp, kv = jax.random.split(rng, 3)
    return {
        "trunk": mlp_init(kt, (obs_size, *hidden)),
        "pi": mlp_init(kp, (hidden[-1], n_actions)),
        "v": mlp_init(kv, (hidden[-1], 1)),
    }


def _net_apply(params, boards):
    x = boards
    for layer in params["trunk"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    logits = x @ params["pi"][0]["w"] + params["pi"][0]["b"]
    value = jnp.tanh(x @ params["v"][0]["w"] + params["v"][0]["b"])[..., 0]
    return logits, value


# ---------------------------------------------------------------------------
# MCTS (host): PUCT tree over canonical boards
# ---------------------------------------------------------------------------


class _Node:
    __slots__ = ("prior", "children", "n", "w")

    def __init__(self, prior: float):
        self.prior = prior
        self.children: Dict[int, "_Node"] = {}
        self.n = 0
        self.w = 0.0

    @property
    def q(self) -> float:
        return self.w / self.n if self.n else 0.0


class MCTS:
    """One search tree; ``run_batch`` advances many trees in lockstep so
    leaf evaluations batch into single device calls."""

    def __init__(self, game, c_puct: float = 1.5,
                 dirichlet_alpha: float = 0.6, noise_frac: float = 0.25):
        self.game = game
        self.c_puct = c_puct
        self.alpha = dirichlet_alpha
        self.noise_frac = noise_frac

    def _select(self, root: _Node, board: np.ndarray
                ) -> Tuple[List[Tuple[_Node, int]], np.ndarray,
                           Optional[float]]:
        """Walk PUCT to a leaf; returns (path, leaf board, terminal value
        at the leaf from its player-to-move's perspective or None)."""
        node, path = root, []
        while True:
            term = self.game.terminal_value(board)
            if term is not None:
                return path, board, term
            if not node.children:
                return path, board, None
            total_n = max(1, sum(c.n for c in node.children.values()))
            best, best_score = None, -np.inf
            for a, child in node.children.items():
                u = self.c_puct * child.prior * np.sqrt(total_n) / \
                    (1 + child.n)
                # child.q is from the CHILD's player perspective: negate.
                score = -child.q + u
                if score > best_score:
                    best, best_score = a, score
            path.append((node, best))
            node = node.children[best]
            board = self.game.next_state(board, best)

    def _backprop(self, path, value: float) -> None:
        """``value`` is from the LEAF's player-to-move perspective; node
        n_j on the chain root->leaf sees it as value * (-1)^(k-j). Each
        child node stores (n, w) from its OWN perspective — which is why
        selection scores ``-child.q`` for the parent's mover."""
        chain = [parent.children[action] for parent, action in path]
        k = len(chain)
        for j, child in enumerate(chain, start=1):
            child.w += value * ((-1.0) ** (k - j))
            child.n += 1

    def run_batch(self, params, boards: List[np.ndarray],
                  n_simulations: int, rng: np.random.Generator,
                  add_noise: bool = True) -> List[np.ndarray]:
        """For each board, run ``n_simulations`` and return visit-count
        vectors [n_actions]. All trees advance in lockstep; leaf net
        evaluations are one batched device call per simulation round."""
        game = self.game
        n_act = game.n_actions
        roots = [_Node(0.0) for _ in boards]

        # Root expansion: one batched eval.
        logits, _ = _net_apply(params, jnp.asarray(
            np.stack(boards).astype(np.float32)))
        logits = np.asarray(logits)
        for i, (root, board) in enumerate(zip(roots, boards)):
            mask = game.legal_mask(board)
            p = _masked_softmax(logits[i], mask)
            if add_noise:
                noise = rng.dirichlet([self.alpha] * int(mask.sum()))
                p_noisy = p.copy()
                p_noisy[mask] = (1 - self.noise_frac) * p[mask] + \
                    self.noise_frac * noise
                p = p_noisy
            for a in np.flatnonzero(mask):
                root.children[int(a)] = _Node(float(p[a]))

        for _ in range(n_simulations):
            paths, leaf_boards, terms, idxs = [], [], [], []
            for i, (root, board) in enumerate(zip(roots, boards)):
                path, leaf, term = self._select(root, board.copy())
                paths.append(path)
                terms.append(term)
                if term is None:
                    idxs.append(i)
                    leaf_boards.append(leaf)
            if leaf_boards:
                logits, values = _net_apply(params, jnp.asarray(
                    np.stack(leaf_boards).astype(np.float32)))
                logits, values = np.asarray(logits), np.asarray(values)
            li = 0
            for i in range(len(boards)):
                path, term = paths[i], terms[i]
                if term is None:
                    leaf = leaf_boards[li]
                    mask = game.legal_mask(leaf)
                    p = _masked_softmax(logits[li], mask)
                    # Expand the leaf.
                    if path:
                        leaf_node = path[-1][0].children[path[-1][1]]
                    else:
                        leaf_node = roots[i]
                    if not leaf_node.children:
                        for a in np.flatnonzero(mask):
                            leaf_node.children[int(a)] = _Node(float(p[a]))
                    value = float(values[li])
                    li += 1
                else:
                    value = term
                self._backprop(path, value)

        visits = []
        for root in roots:
            v = np.zeros(n_act)
            for a, child in root.children.items():
                v[a] = child.n
            visits.append(v)
        return visits


def _masked_softmax(logits: np.ndarray, mask: np.ndarray) -> np.ndarray:
    x = np.where(mask, logits, -1e9)
    x = x - x.max()
    e = np.exp(x) * mask
    s = e.sum()
    return e / s if s > 0 else mask / max(1, mask.sum())


# ---------------------------------------------------------------------------
# the algorithm
# ---------------------------------------------------------------------------


class AlphaZeroConfig:
    """Builder-style config (``AlphaZeroConfig().training(...)``)."""

    def __init__(self):
        self.game = TicTacToe()
        self.games_per_iter = 16
        self.num_simulations = 48
        self.temperature_moves = 4   # sample ~ N^1 before, argmax after
        self.buffer_games = 256
        self.batch_size = 128
        self.updates_per_iter = 48
        self.lr = 3e-3
        self.l2 = 1e-4
        self.hidden = (64, 64)
        self.c_puct = 1.5
        self.dirichlet_alpha = 0.6
        self.noise_frac = 0.25
        self.seed = 0

    def environment(self, game=None) -> "AlphaZeroConfig":
        if game is not None:
            self.game = game
        return self

    def training(self, **kwargs) -> "AlphaZeroConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown AlphaZero option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlphaZeroConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "AlphaZero":
        return AlphaZero(self)


class AlphaZero:
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""

    def __init__(self, config: AlphaZeroConfig):
        self.config = config
        game = config.game
        k_param, _ = jax.random.split(jax.random.key(config.seed))
        self.params = _net_init(
            k_param, game.obs_size, game.n_actions, config.hidden)
        self.opt = adam_init(self.params)
        self._rng = np.random.default_rng(config.seed)
        self._mcts = MCTS(game, config.c_puct, config.dirichlet_alpha,
                          config.noise_frac)
        self._examples: List[Tuple[np.ndarray, np.ndarray, float]] = []
        self._iteration = 0
        self._update = self._build_update()

    def _build_update(self):
        cfg = self.config

        def loss_fn(params, boards, pis, zs):
            logits, values = _net_apply(params, boards)
            ce = -jnp.mean(jnp.sum(
                pis * jax.nn.log_softmax(logits), axis=1))
            mse = jnp.mean((values - zs) ** 2)
            l2 = sum(jnp.sum(l["w"] ** 2)
                     for l in jax.tree.leaves(
                         params, is_leaf=lambda x: isinstance(x, dict)
                         and "w" in x))
            return ce + mse + cfg.l2 * l2

        @jax.jit
        def update(params, opt, boards, pis, zs):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, boards, pis, zs)
            params, opt = adam_step(params, opt, grads, lr=cfg.lr)
            return params, opt, loss

        return update

    # -- self-play ------------------------------------------------------

    def _self_play(self) -> Tuple[int, float]:
        """Play ``games_per_iter`` games in lockstep; append (board, pi,
        z) examples. Returns (n_examples, mean_game_len)."""
        cfg, game = self.config, self.config.game
        G = cfg.games_per_iter
        boards = [game.initial_state() for _ in range(G)]
        histories: List[List[Tuple[np.ndarray, np.ndarray]]] = \
            [[] for _ in range(G)]
        results: List[Optional[float]] = [None] * G  # z for player 0
        move_no = 0
        live = list(range(G))
        # Track each game's perspective parity: board is canonical, so
        # z flips sign per move when assigned at the end.
        while live:
            live_boards = [boards[i] for i in live]
            visits = self._mcts.run_batch(
                self.params, live_boards, cfg.num_simulations, self._rng)
            next_live = []
            for j, i in enumerate(live):
                v = visits[j]
                pi = v / v.sum()
                histories[i].append((boards[i].copy(), pi))
                if move_no < cfg.temperature_moves:
                    a = int(self._rng.choice(game.n_actions, p=pi))
                else:
                    a = int(np.argmax(v))
                boards[i] = game.next_state(boards[i], a)
                term = game.terminal_value(boards[i])
                if term is not None:
                    # term: perspective of the player to move AFTER the
                    # final move; the player who made move k sees
                    # (-term) if an odd number of flips separate them.
                    n_moves = len(histories[i])
                    for k, (b, p) in enumerate(histories[i]):
                        # mover at step k is (n_moves - k) flips before
                        # the terminal perspective.
                        sign = -1.0 if (n_moves - k) % 2 == 1 else 1.0
                        self._examples.append((b, p, sign * term))
                    results[i] = term
                else:
                    next_live.append(i)
            live = next_live
            move_no += 1

        # Trim the example window to the most recent games.
        max_examples = cfg.buffer_games * getattr(
            game, "max_moves", game.n_actions)
        if len(self._examples) > max_examples:
            self._examples = self._examples[-max_examples:]
        lens = [len(h) for h in histories]
        return sum(lens), float(np.mean(lens))

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        cfg = self.config
        n_new, mean_len = self._self_play()
        losses = []
        n = len(self._examples)
        for _ in range(cfg.updates_per_iter):
            idx = self._rng.integers(0, n, min(cfg.batch_size, n))
            boards = jnp.asarray(np.stack(
                [self._examples[i][0] for i in idx]).astype(np.float32))
            pis = jnp.asarray(np.stack(
                [self._examples[i][1] for i in idx]).astype(np.float32))
            zs = jnp.asarray(np.asarray(
                [self._examples[i][2] for i in idx], np.float32))
            self.params, self.opt, loss = self._update(
                self.params, self.opt, boards, pis, zs)
            losses.append(float(loss))
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter": n_new,
            "mean_game_length": mean_len,
            "loss": float(np.mean(losses)),
            "examples": n,
            "time_this_iter_s": time.perf_counter() - start,
        }

    # -- evaluation -----------------------------------------------------

    def compute_action(self, board: np.ndarray,
                       num_simulations: Optional[int] = None) -> int:
        visits = self._mcts.run_batch(
            self.params, [board],
            num_simulations or self.config.num_simulations,
            self._rng, add_noise=False)[0]
        return int(np.argmax(visits))

    def play_vs(self, opponent_fn, as_first: bool, rng) -> float:
        """One game vs ``opponent_fn(board, rng) -> action``; returns
        +1 win / 0 draw / -1 loss from OUR perspective."""
        game = self.config.game
        board = game.initial_state()
        our_turn = as_first
        while True:
            if our_turn:
                a = self.compute_action(board)
            else:
                a = opponent_fn(board, rng)
            board = game.next_state(board, a)
            term = game.terminal_value(board)
            if term is not None:
                # term is from the NEXT player's perspective; the mover
                # just played, so mover sees -term.
                mover_score = -term
                return mover_score if our_turn else -mover_score
            our_turn = not our_turn


def random_player(board: np.ndarray, rng) -> int:
    return int(rng.choice(np.flatnonzero(board == 0)))


def one_ply_player(board: np.ndarray, rng) -> int:
    """Takes an immediate win if present, else blocks an immediate
    opponent win, else random — the classic 1-ply heuristic."""
    game = TicTacToe()
    legal = np.flatnonzero(board == 0)
    for a in legal:
        # terminal_value is from the NEXT player's view: -1 == we won.
        if game.terminal_value(game.next_state(board, int(a))) == -1.0:
            return int(a)
    for a in legal:
        pretend = board.copy()
        pretend[a] = -1   # what if the opponent got this square?
        if (pretend[TicTacToe._LINES].sum(axis=1) == -3).any():
            return int(a)  # block
    return int(rng.choice(legal))
