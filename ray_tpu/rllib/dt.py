"""Decision Transformer (reference ``rllib/algorithms/dt/dt.py``, after
Chen et al. 2021): offline RL as conditional sequence modeling — a causal
transformer over interleaved (return-to-go, state, action) tokens,
trained with action cross-entropy on logged episodes and STEERED at eval
time by the target return it is conditioned on.

This is the most TPU-native member of the offline family: the model IS a
small GPT (same pre-LN block structure as ``models/gpt2.py``, sized for
control), so training is pure MXU matmuls over [B, 3K, d] token batches
— no TD bootstrapping, no replay priorities, no target networks. The
collector and the jitted update follow the offline-family conventions of
``rllib/offline_algos.py``; episodes are fixed-horizon padded arrays so
everything stays static-shaped.

The acceptance test (``tests/test_rllib_dt.py``) exercises the paper's
defining property, return-conditioned steering: the SAME trained model
rolled out with a high target return recovers near-expert behavior from
a mostly-random mixture, and with a low target it obeys and performs
poorly.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.env import CartPole
from ray_tpu.rllib.optim import adam_init
from ray_tpu.rllib.optim import adam_step as _adam

__all__ = ["DT", "DTConfig", "collect_episodes"]


# ---------------------------------------------------------------------------
# episode collection (fixed-horizon padded arrays)
# ---------------------------------------------------------------------------


def collect_episodes(policy_fn, n_episodes: int, max_len: int,
                     seed: int = 0, env=None) -> Dict[str, np.ndarray]:
    """Roll ``policy_fn(obs [N, O], rng) -> actions [N]`` for one episode
    per vmapped lane; steps after the first done are masked out (the env
    auto-resets, so the mask is what delimits the episode).

    Returns {obs [N,T,O], actions [N,T], rewards [N,T], mask [N,T]}.
    """
    env = env or CartPole()
    vreset = jax.vmap(env.reset)
    vobs = jax.vmap(env.obs)
    vstep = jax.vmap(env.step)

    @jax.jit
    def rollout(rng):
        states = vreset(jax.random.split(rng, n_episodes))

        def step(carry, _):
            states, alive, rng = carry
            rng, k_p, k_s = jax.random.split(rng, 3)
            obs = vobs(states)
            act = policy_fn(obs, k_p)
            nstates, _, rew, done = vstep(
                states, act, jax.random.split(k_s, n_episodes))
            out = {"obs": obs, "actions": act, "rewards": rew * alive,
                   "mask": alive}
            return (nstates, alive * (1.0 - done.astype(jnp.float32)),
                    rng), out

        _, traj = jax.lax.scan(
            step, (states, jnp.ones(n_episodes), jax.random.fold_in(rng, 1)),
            None, length=max_len)
        return traj

    traj = rollout(jax.random.key(seed))
    return {k: np.asarray(jnp.swapaxes(v, 0, 1)) for k, v in traj.items()}


# ---------------------------------------------------------------------------
# the model: a control-sized causal GPT over (rtg, s, a) token triples
# ---------------------------------------------------------------------------


class DTConfig:
    """Builder-style config (``DTConfig().training(context_len=16)``)."""

    def __init__(self):
        self.env = CartPole()
        self.context_len = 16       # K timesteps = 3K tokens
        self.max_ep_len = 256       # timestep-embedding table size
        self.d_model = 64
        self.n_heads = 2
        self.n_layers = 2
        self.lr = 1e-3
        self.batch_size = 64
        self.updates_per_iter = 100
        self.rtg_scale = 100.0      # normalize returns into O(1)
        self.seed = 0

    def environment(self, env=None) -> "DTConfig":
        if env is not None:
            self.env = env
        return self

    def training(self, **kwargs) -> "DTConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown DT option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "DTConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self, episodes: Dict[str, np.ndarray]) -> "DT":
        return DT(self, episodes)


def _dt_init(rng, cfg: DTConfig, obs_size: int, n_act: int):
    d = cfg.d_model
    keys = jax.random.split(rng, 6 + cfg.n_layers)

    def lin(k, din, dout, scale=None):
        scale = scale if scale is not None else 1.0 / np.sqrt(din)
        return {"w": jax.random.normal(k, (din, dout)) * scale,
                "b": jnp.zeros((dout,))}

    def block(k):
        k1, k2, k3, k4 = jax.random.split(k, 4)
        return {
            "ln1": {"g": jnp.ones((d,)), "b": jnp.zeros((d,))},
            "attn": {"wqkv": lin(k1, d, 3 * d),
                     "wo": lin(k2, d, d, scale=0.5 / np.sqrt(d))},
            "ln2": {"g": jnp.ones((d,)), "b": jnp.zeros((d,))},
            "mlp": {"up": lin(k3, d, 4 * d),
                    "down": lin(k4, 4 * d, d, scale=0.25 / np.sqrt(d))},
        }

    return {
        "embed_rtg": lin(keys[0], 1, d),
        "embed_obs": lin(keys[1], obs_size, d),
        "embed_act": jax.random.normal(keys[2], (n_act + 1, d)) * 0.02,
        "embed_t": jax.random.normal(keys[3], (cfg.max_ep_len, d)) * 0.02,
        "blocks": [block(k) for k in keys[4:4 + cfg.n_layers]],
        "ln_f": {"g": jnp.ones((d,)), "b": jnp.zeros((d,))},
        "head": lin(keys[4 + cfg.n_layers], d, n_act),
    }


def _ln(p, x):
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return p["g"] * (x - mu) * jax.lax.rsqrt(var + 1e-5) + p["b"]


def _dt_forward(params, cfg: DTConfig, rtg, obs, acts, timesteps):
    """rtg [B,K], obs [B,K,O], acts [B,K] (-1 = not-yet-taken),
    timesteps [B,K] -> action logits [B,K,A] read at the state tokens."""
    B, K = rtg.shape
    d, H = cfg.d_model, cfg.n_heads
    t_emb = params["embed_t"][jnp.clip(timesteps, 0, cfg.max_ep_len - 1)]
    e_rtg = rtg[..., None] @ params["embed_rtg"]["w"] \
        + params["embed_rtg"]["b"] + t_emb
    e_obs = obs @ params["embed_obs"]["w"] \
        + params["embed_obs"]["b"] + t_emb
    # Index -1 ("not yet taken") maps to the table's extra last row.
    e_act = params["embed_act"][
        jnp.where(acts < 0, params["embed_act"].shape[0] - 1, acts)] + t_emb
    # Interleave (rtg_t, s_t, a_t): [B, 3K, d].
    x = jnp.stack([e_rtg, e_obs, e_act], axis=2).reshape(B, 3 * K, d)

    causal = jnp.tril(jnp.ones((3 * K, 3 * K), bool))
    for blk in params["blocks"]:
        h = _ln(blk["ln1"], x)
        qkv = h @ blk["attn"]["wqkv"]["w"] + blk["attn"]["wqkv"]["b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(z):
            return z.reshape(B, 3 * K, H, d // H).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        scores = q @ k.transpose(0, 1, 3, 2) / np.sqrt(d // H)
        scores = jnp.where(causal, scores, -1e9)
        att = jax.nn.softmax(scores, axis=-1) @ v
        att = att.transpose(0, 2, 1, 3).reshape(B, 3 * K, d)
        x = x + att @ blk["attn"]["wo"]["w"] + blk["attn"]["wo"]["b"]
        h = _ln(blk["ln2"], x)
        h = jax.nn.gelu(h @ blk["mlp"]["up"]["w"] + blk["mlp"]["up"]["b"])
        x = x + h @ blk["mlp"]["down"]["w"] + blk["mlp"]["down"]["b"]

    x = _ln(params["ln_f"], x)
    state_tokens = x.reshape(B, K, 3, d)[:, :, 1]   # position 3t+1
    return state_tokens @ params["head"]["w"] + params["head"]["b"]


class DT:
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""

    def __init__(self, config: DTConfig, episodes: Dict[str, np.ndarray]):
        self.config = config
        env = config.env
        self._n_act = env.num_actions
        rng = jax.random.key(config.seed)
        k_param, self._rng = jax.random.split(rng)
        self.params = _dt_init(
            k_param, config, env.observation_size, env.num_actions)
        self.opt = adam_init(self.params)

        # Precompute per-episode returns-to-go (gamma = 1, as the paper).
        rew, mask = episodes["rewards"], episodes["mask"]
        rtg = np.flip(np.cumsum(np.flip(rew * mask, 1), 1), 1)
        self._data = {
            "obs": np.asarray(episodes["obs"], np.float32),
            "actions": np.asarray(episodes["actions"], np.int32),
            "rtg": (rtg / config.rtg_scale).astype(np.float32),
            "mask": np.asarray(mask, np.float32),
            "lengths": np.maximum(
                mask.sum(1).astype(np.int64), 1),
        }
        self._np_rng = np.random.default_rng(config.seed)
        self._update = self._build_update()
        self._iteration = 0

    def _build_update(self):
        cfg = self.config

        def loss_fn(params, batch):
            logits = _dt_forward(
                params, cfg, batch["rtg"], batch["obs"], batch["acts_in"],
                batch["timesteps"])
            logp = jax.nn.log_softmax(logits)
            taken = jnp.take_along_axis(
                logp, batch["actions"][..., None], axis=-1)[..., 0]
            m = batch["mask"]
            return -jnp.sum(taken * m) / jnp.maximum(jnp.sum(m), 1.0)

        @jax.jit
        def update(params, opt, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            params, opt = _adam(params, opt, grads, lr=cfg.lr,
                                max_grad_norm=1.0)
            return params, opt, loss

        return update

    def _sample_windows(self) -> Dict[str, jnp.ndarray]:
        cfg = self.config
        K, B = cfg.context_len, cfg.batch_size
        d = self._data
        n = d["obs"].shape[0]
        ep = self._np_rng.integers(0, n, B)
        lengths = d["lengths"][ep]
        # RIGHT-aligned windows ending at a sampled position e in
        # [1, len]: an early-episode window is LEFT-padded with the same
        # zero obs / zero rtg / -1 action / timestep-0 filler the eval
        # loop's history buffer starts from — so the padding the model
        # attends to at eval time is in-distribution.
        end = 1 + (self._np_rng.random(B) * lengths).astype(np.int64)
        idx = end[:, None] - K + np.arange(K)[None]        # [B, K], <0 pad
        valid = (idx >= 0) & (idx < lengths[:, None])
        idx_c = np.clip(idx, 0, d["obs"].shape[1] - 1)
        gather = lambda a: a[ep[:, None], idx_c]           # noqa: E731
        vf = valid.astype(np.float32)
        actions = np.where(valid, gather(d["actions"]), 0)
        acts_in = np.where(valid, actions, -1)
        return {
            "obs": jnp.asarray(gather(d["obs"]) * vf[..., None]),
            "actions": jnp.asarray(actions.astype(np.int32)),
            "acts_in": jnp.asarray(acts_in.astype(np.int32)),
            "rtg": jnp.asarray(gather(d["rtg"]) * vf),
            "timesteps": jnp.asarray((idx_c * valid).astype(np.int32)),
            "mask": jnp.asarray(vf * gather(d["mask"])),
        }

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        losses = []
        for _ in range(self.config.updates_per_iter):
            batch = self._sample_windows()
            self.params, self.opt, loss = self._update(
                self.params, self.opt, batch)
            losses.append(float(loss))
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "loss": float(np.mean(losses)),
            "time_this_iter_s": time.perf_counter() - start,
        }

    # -- return-conditioned evaluation ---------------------------------

    def evaluate(self, target_return: float, *, n_episodes: int = 8,
                 max_len: int = 200, seed: int = 123) -> float:
        """Greedy rollout conditioned on ``target_return``; the rtg token
        decrements by each observed reward (the paper's eval loop)."""
        cfg = self.config
        env = cfg.env
        K = cfg.context_len

        @jax.jit
        def act_fn(params, rtg_h, obs_h, act_h, t_h):
            logits = _dt_forward(params, cfg, rtg_h[None], obs_h[None],
                                 act_h[None], t_h[None])
            return jnp.argmax(logits[0, -1])

        total = 0.0
        for ep in range(n_episodes):
            rng = jax.random.key(seed + ep)
            s = env.reset(rng)
            rtg_h = jnp.zeros((K,)).at[-1].set(
                target_return / cfg.rtg_scale)
            obs_h = jnp.zeros((K, env.observation_size)).at[-1].set(
                env.obs(s))
            act_h = jnp.full((K,), -1, jnp.int32)
            t_h = jnp.zeros((K,), jnp.int32)
            ret, rtg = 0.0, target_return
            for t in range(max_len):
                a = act_fn(self.params, rtg_h, obs_h, act_h, t_h)
                rng, k = jax.random.split(rng)
                s, _, rew, done = env.step(s, a, k)
                ret += float(rew)
                rtg -= float(rew)
                if bool(done):
                    break
                # Record the taken action, then shift history left and
                # open a fresh (rtg, obs, pending-action) slot.
                act_h = act_h.at[-1].set(a)
                act_h = jnp.roll(act_h, -1).at[-1].set(-1)
                rtg_h = jnp.roll(rtg_h, -1).at[-1].set(rtg / cfg.rtg_scale)
                obs_h = jnp.roll(obs_h, -1).at[-1].set(env.obs(s))
                t_h = jnp.roll(t_h, -1).at[-1].set(
                    min(t + 1, cfg.max_ep_len - 1))
            total += ret
        return total / n_episodes
