"""Ape-X DDPG (reference ``rllib/algorithms/apex_ddpg/apex_ddpg.py``):
the continuous-control member of the Ape-X family — DDPG/TD3 learning
from PRIORITIZED replay fed by a fleet of actors exploring at a ladder
of noise scales (the continuous analog of Ape-X DQN's epsilon ladder,
Horgan et al. 2018 §A.2).

Composition over duplication: the critic/actor machinery is td3.py's
(twin critics, target smoothing, delayed policy — all still config
switches, so both ApexDDPG and "Apex-TD3" are points of this one
program), the prioritized buffer is replay.pbuffer_* shared with Ape-X
DQN, and the noise ladder lives on the vectorized env axis exactly as
in apex.py. TD errors from the twin-min target refresh the priorities
of the sampled rows each update.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithm import EpisodeStats
from ray_tpu.rllib.env import Pendulum, make_vec_env
from ray_tpu.rllib.optim import adam_init, adam_step
from ray_tpu.rllib.ppo import mlp_init
from ray_tpu.rllib.replay import (
    pbuffer_add,
    pbuffer_init,
    pbuffer_sample,
    pbuffer_update_priorities,
)
from ray_tpu.rllib.sac import critic_init
from ray_tpu.rllib.td3 import _actor_apply, critic_apply

__all__ = ["ApexDDPG", "ApexDDPGConfig", "noise_ladder"]


def noise_ladder(n: int, low: float, high: float) -> jnp.ndarray:
    """Per-lane exploration noise scales, log-spaced low..high — the
    continuous analog of the Ape-X epsilon ladder."""
    i = jnp.arange(n, dtype=jnp.float32) / jnp.maximum(n - 1, 1)
    return low * (high / low) ** i


class ApexDDPGConfig:
    """Builder-style config (``ApexDDPGConfig().training(twin_q=True)``
    is Apex-TD3)."""

    def __init__(self):
        self.env = Pendulum()
        self.num_envs = 16              # noise-ladder lanes
        self.steps_per_iter = 64
        self.buffer_size = 50_000
        self.batch_size = 256
        self.updates_per_iter = 32
        self.gamma = 0.99
        self.tau = 0.005
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.hidden_sizes = (128, 128)
        self.learning_starts = 1_000
        self.action_scale = 2.0
        self.noise_low = 0.05           # ladder endpoints
        self.noise_high = 0.8
        self.per_alpha = 0.6
        self.per_beta = 0.4
        self.twin_q = False             # DDPG default; True -> Apex-TD3
        self.target_noise = 0.0
        self.target_noise_clip = 0.0
        self.policy_delay = 1
        self.seed = 0

    def environment(self, env=None) -> "ApexDDPGConfig":
        if env is not None:
            self.env = env
        return self

    def rollouts(self, *, num_envs: Optional[int] = None
                 ) -> "ApexDDPGConfig":
        if num_envs is not None:
            self.num_envs = num_envs
        return self

    def training(self, **kwargs) -> "ApexDDPGConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown ApexDDPG option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "ApexDDPGConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "ApexDDPG":
        return ApexDDPG(self)


def _make_train_iter(cfg: ApexDDPGConfig):
    env = cfg.env
    reset_fn, step_fn, obs_fn = make_vec_env(env, cfg.num_envs)
    scale = cfg.action_scale
    ladder = noise_ladder(cfg.num_envs, cfg.noise_low, cfg.noise_high)
    time_limit_only = bool(getattr(env, "TIME_LIMIT_ONLY", False))

    def td_errors(cp, learner, batch, k):
        noise = jnp.clip(
            cfg.target_noise * scale
            * jax.random.normal(k, batch["act"].shape),
            -cfg.target_noise_clip * scale,
            cfg.target_noise_clip * scale)
        next_act = jnp.clip(
            _actor_apply(learner["target_actor"], batch["nobs"], scale)
            + noise, -scale, scale)
        tq1, tq2 = critic_apply(
            learner["target_critic"], batch["nobs"], next_act)
        tq = jnp.minimum(tq1, tq2) if cfg.twin_q else tq1
        y = batch["rew"] + cfg.gamma * (1 - batch["done"]) * \
            jax.lax.stop_gradient(tq)
        q1, q2 = critic_apply(cp, batch["obs"], batch["act"])
        return q1 - y, q2 - y

    def critic_loss(cp, learner, batch, k):
        e1, e2 = td_errors(cp, learner, batch, k)
        w = batch["weights"]
        if cfg.twin_q:
            loss = jnp.mean(w * (e1 ** 2 + e2 ** 2))
        else:
            loss = jnp.mean(w * e1 ** 2)
        return loss, e1

    def actor_loss(ap, cp, batch):
        act = _actor_apply(ap, batch["obs"], scale)
        q1, _ = critic_apply(cp, batch["obs"], act)
        return -jnp.mean(q1)

    @jax.jit
    def reset(rng):
        return reset_fn(rng)

    @jax.jit
    def train_iter(learner, states, rng):
        def env_step(carry, _):
            learner, states, rng = carry
            rng, k_n, k_step = jax.random.split(rng, 3)
            obs = obs_fn(states)
            act = _actor_apply(learner["actor"], obs, scale)
            # The ladder: lane i explores at its own fixed noise scale.
            act = jnp.clip(
                act + ladder[:, None] * scale
                * jax.random.normal(k_n, act.shape),
                -scale, scale)
            nstates, _, rew, done = step_fn(states, act, k_step)
            done_f = done.astype(jnp.float32)
            stored = jnp.zeros_like(done_f) if time_limit_only else done_f
            learner = dict(
                learner,
                buffer=pbuffer_add(
                    learner["buffer"], cfg.buffer_size,
                    obs=obs, act=act, rew=rew, nobs=obs_fn(nstates),
                    done=stored),
                env_steps=learner["env_steps"] + cfg.num_envs,
                reward_sum=learner["reward_sum"] + jnp.sum(rew),
                done_count=learner["done_count"] + jnp.sum(done),
            )
            return (learner, nstates, rng), None

        (learner, states, rng), _ = jax.lax.scan(
            env_step, (learner, states, rng), None,
            length=cfg.steps_per_iter)

        def update(carry, i):
            learner, rng = carry
            rng, k_idx, k_t = jax.random.split(rng, 3)
            buf = learner["buffer"]
            batch = pbuffer_sample(
                buf, k_idx, cfg.batch_size,
                ("obs", "act", "rew", "nobs", "done"),
                alpha=cfg.per_alpha, beta=cfg.per_beta)
            ready = (buf["size"] >= cfg.learning_starts).astype(jnp.float32)

            (closs, e1), cgrads = jax.value_and_grad(
                critic_loss, has_aux=True)(
                learner["critic"], learner, batch, k_t)
            cgrads = jax.tree.map(lambda g: g * ready, cgrads)
            critic, copt = adam_step(learner["critic"], learner["copt"],
                                     cgrads, lr=cfg.critic_lr)
            # Final priorities either way (TD branch bakes the eps in);
            # eps=0 so warm-up rewrites preserve priorities exactly.
            new_p = ready * (jnp.abs(e1) + 1e-3) + (1.0 - ready) * \
                buf["priority"][batch["indices"]]
            buf = pbuffer_update_priorities(
                buf, batch["indices"], new_p, eps=0.0)

            do_pi = ready * ((i % cfg.policy_delay) == 0)
            aloss, agrads = jax.value_and_grad(actor_loss)(
                learner["actor"], critic, batch)
            agrads = jax.tree.map(lambda g: g * do_pi, agrads)
            actor, aopt = adam_step(learner["actor"], learner["aopt"],
                                    agrads, lr=cfg.actor_lr)
            blend = cfg.tau * do_pi
            polyak = lambda t_, p_: jax.tree.map(      # noqa: E731
                lambda a, b: (1 - blend) * a + blend * b, t_, p_)
            learner = dict(
                learner, actor=actor, critic=critic, aopt=aopt,
                copt=copt, buffer=buf,
                target_actor=polyak(learner["target_actor"], actor),
                target_critic=polyak(learner["target_critic"], critic))
            return (learner, rng), closs * ready

        (learner, rng), losses = jax.lax.scan(
            update, (learner, rng), jnp.arange(cfg.updates_per_iter))
        return learner, states, rng, {"critic_loss": jnp.mean(losses)}

    return reset, train_iter


class ApexDDPG(EpisodeStats):
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""

    def __init__(self, config: ApexDDPGConfig):
        self.config = config
        env = config.env
        rng = jax.random.key(config.seed)
        ka, kc, k_env, self._rng = jax.random.split(rng, 4)
        obs_size, act_size = env.observation_size, env.action_size
        actor = mlp_init(ka, (obs_size, *config.hidden_sizes, act_size))
        critic = critic_init(kc, obs_size, act_size, config.hidden_sizes)
        if not config.twin_q:
            critic = {"q1": critic["q1"]}
        self._learner = {
            "actor": actor,
            "critic": critic,
            "target_actor": jax.tree.map(jnp.copy, actor),
            "target_critic": jax.tree.map(jnp.copy, critic),
            "aopt": adam_init(actor),
            "copt": adam_init(critic),
            "buffer": pbuffer_init(
                config.buffer_size,
                {"obs": (obs_size,), "act": (act_size,), "rew": (),
                 "nobs": (obs_size,), "done": ()}),
            "env_steps": jnp.zeros((), jnp.int32),
            "reward_sum": jnp.zeros(()),
            "done_count": jnp.zeros((), jnp.int32),
        }
        self._reset, self._train_iter = _make_train_iter(config)
        self._states = self._reset(k_env)
        self._iteration = 0

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        snap = self._episode_snapshot()
        self._learner, self._states, self._rng, metrics = self._train_iter(
            self._learner, self._states, self._rng)
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter":
                self.config.num_envs * self.config.steps_per_iter,
            "episode_reward_mean": self._episode_reward_mean(snap),
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(v) for k, v in metrics.items()},
        }

    def compute_single_action(self, obs):
        return _actor_apply(
            self._learner["actor"], jnp.asarray(obs)[None],
            self.config.action_scale)[0]
