"""Shared RL optimizer step (one copy for ppo/dqn/impala).

Bias-corrected Adam with optional clip-by-global-norm, shaped for use
inside jitted train iterations: ``opt`` is the plain pytree
``{"mu", "nu", "t"}`` each algorithm carries in its learner state.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def adam_step(params, opt, grads, *, lr: float,
              max_grad_norm: Optional[float] = None,
              b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8):
    """One Adam update; returns (params, opt)."""
    if max_grad_norm is not None:
        gnorm = jnp.sqrt(sum(
            jnp.sum(g * g) for g in jax.tree.leaves(grads)))
        scale = jnp.minimum(1.0, max_grad_norm / (gnorm + 1e-8))
        grads = jax.tree.map(lambda g: g * scale, grads)
    t = opt["t"] + 1
    mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, opt["mu"], grads)
    nu = jax.tree.map(lambda n, g: b2 * n + (1 - b2) * g * g, opt["nu"], grads)
    bc1 = 1 - b1 ** t.astype(jnp.float32)
    bc2 = 1 - b2 ** t.astype(jnp.float32)
    params = jax.tree.map(
        lambda p, m, n: p - lr * (m / bc1) / (jnp.sqrt(n / bc2) + eps),
        params, mu, nu,
    )
    return params, {"mu": mu, "nu": nu, "t": t}


def adam_init(params):
    """Zeroed Adam state for ``adam_step`` (one copy of the
    {"mu","nu","t"} pytree constructor every algorithm carries)."""
    return {"mu": jax.tree.map(jnp.zeros_like, params),
            "nu": jax.tree.map(jnp.zeros_like, params),
            "t": jnp.zeros((), jnp.int32)}


def linear_epsilon(global_step, start: float, end: float,
                   decay_steps: int):
    """Linearly decayed exploration epsilon (one copy for
    dqn/qmix/r2d2): start -> end over ``decay_steps`` env steps."""
    frac = jnp.clip(global_step / decay_steps, 0.0, 1.0)
    return start + frac * (end - start)


def periodic_target_sync(target_params, params, t, every: int):
    """Hard target-network sync every ``every`` optimizer steps (one
    copy for the DQN family): jit-safe elementwise where."""
    sync = (t % every) == 0
    return jax.tree.map(
        lambda tp, p: jnp.where(sync, p, tp), target_params, params)


def clipped_surrogate(logp, logp_old, adv, clip_param: float,
                      normalize: bool = True):
    """PPO's clipped policy-gradient surrogate (one copy for
    ppo/recurrent/appo): -E[min(r*A, clip(r, 1-eps, 1+eps)*A)] with
    advantages standardized over the batch."""
    if normalize:
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    ratio = jnp.exp(logp - logp_old)
    pg1 = ratio * adv
    pg2 = jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv
    return -jnp.mean(jnp.minimum(pg1, pg2))
