"""APPO: Asynchronous Proximal Policy Optimization.

Reference parity: ``rllib/algorithms/appo`` — the IMPALA actor-learner
architecture (stale behavior snapshots, V-trace off-policy correction)
with PPO's clipped surrogate as the policy loss, bounding how far one
update can move the target policy from the behavior data. Implemented
exactly the way the reference does it: a thin specialization of IMPALA
(``impala.py`` carries the shared machinery; ``surrogate="ppo_clip"``
selects the clipped objective on V-trace advantages).
"""

from __future__ import annotations

from ray_tpu.rllib.impala import IMPALA, IMPALAConfig


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.surrogate = "ppo_clip"
        self.clip_param = 0.3

    def build(self) -> "APPO":
        return APPO(self)


class APPO(IMPALA):
    """``.train()`` one iteration -> result dict (Trainable contract)."""
