"""Contextual bandits: LinUCB and Linear Thompson Sampling (reference
``rllib/algorithms/bandit/bandit.py`` — BanditLinUCB / BanditLinTS with
their ``LinearDiscreteModel``s).

The reference runs bandits through the full RLlib rollout machinery with
torch models; a linear bandit needs none of that — the posterior update
is a closed-form rank-1 refresh of per-arm Gram matrices, so the whole
interaction loop (context draw -> score arms -> pull -> posterior
update), over ``rounds_per_iter`` rounds, is ONE ``lax.scan`` inside ONE
jitted program. Per-arm state is batched into a single [K, d, d] Gram
tensor so arm scoring is a vmapped solve on the MXU, not a Python loop.

Both policies share the state and differ only in the acquisition score:
LinUCB adds the deterministic confidence width ``alpha *
sqrt(x^T A^-1 x)``; LinTS samples a weight vector from the Gaussian
posterior ``N(theta_hat, v^2 A^-1)`` via Cholesky.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

__all__ = ["BanditLinUCB", "BanditLinTS", "BanditConfig", "LinearBanditEnv"]


class LinearBanditEnv:
    """Synthetic K-armed contextual bandit: reward = <theta_arm, x> + noise.

    The hidden arm parameters are drawn once from the seed; ``best_reward``
    exposes the oracle arm's mean reward so tests can measure regret.
    """

    def __init__(self, num_arms: int = 5, context_dim: int = 8,
                 noise: float = 0.1, seed: int = 0):
        self.num_arms = num_arms
        self.context_dim = context_dim
        self.noise = noise
        k = jax.random.key(seed)
        self.theta = jax.random.normal(k, (num_arms, context_dim)) / \
            jnp.sqrt(context_dim)

    def context(self, rng):
        return jax.random.normal(rng, (self.context_dim,))

    def pull(self, rng, x, arm):
        mean = self.theta[arm] @ x
        return mean + self.noise * jax.random.normal(rng)

    def means(self, x):
        return self.theta @ x


class BanditConfig:
    """Builder-style config (``BanditConfig().environment(...)``)."""

    def __init__(self):
        self.env = LinearBanditEnv()
        self.rounds_per_iter = 256
        self.lam = 1.0            # ridge prior on the Gram matrix
        self.alpha = 1.0          # LinUCB confidence width
        self.ts_scale = 0.5       # LinTS posterior scale v
        self.seed = 0
        # Which bandit build() constructs. ONE config class serves both
        # registry entries; get_algorithm_config binds the resolved
        # algorithm class here so "BanditLinTS" builds a LinTS.
        self.algo_class: Optional[type] = None

    def environment(self, env=None) -> "BanditConfig":
        if env is not None:
            self.env = env
        return self

    def training(self, **kwargs) -> "BanditConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown bandit option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "BanditConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "_BanditBase":
        """Construct the configured bandit (LinUCB unless algo_class
        says otherwise) — the Trainable build() contract every other
        registered config satisfies."""
        cls = self.algo_class or BanditLinUCB
        return cls(self)


def _make_iter(cfg: BanditConfig, kind: str):
    env = cfg.env
    K, d = env.num_arms, env.context_dim

    def score_linucb(state, x, rng):
        theta_hat = jax.vmap(jnp.linalg.solve)(state["A"], state["b"])
        Ainv_x = jax.vmap(lambda A: jnp.linalg.solve(A, x))(state["A"])
        width = jnp.sqrt(jnp.maximum(x @ Ainv_x.T, 0.0))  # [K]
        return theta_hat @ x + cfg.alpha * width

    def score_lints(state, x, rng):
        theta_hat = jax.vmap(jnp.linalg.solve)(state["A"], state["b"])
        # Sample from N(theta_hat, v^2 A^-1) per arm: A = L L^T =>
        # A^-1 = L^-T L^-1, so theta = theta_hat + v * L^-T z.
        L = jax.vmap(jnp.linalg.cholesky)(state["A"])
        z = jax.random.normal(rng, (K, d))
        perturb = jax.vmap(
            lambda Lk, zk: jax.scipy.linalg.solve_triangular(
                Lk.T, zk, lower=False))(L, z)
        return (theta_hat + cfg.ts_scale * perturb) @ x

    score = {"linucb": score_linucb, "lints": score_lints}[kind]

    @jax.jit
    def run_iter(state, rng):
        def one_round(carry, _):
            state, rng = carry
            rng, k_ctx, k_score, k_rew = jax.random.split(rng, 4)
            x = env.context(k_ctx)
            arm = jnp.argmax(score(state, x, k_score))
            r = env.pull(k_rew, x, arm)
            onehot = jax.nn.one_hot(arm, K)
            state = {
                "A": state["A"] + onehot[:, None, None] * jnp.outer(x, x),
                "b": state["b"] + onehot[:, None] * (r * x),
            }
            regret = jnp.max(env.means(x)) - env.means(x)[arm]
            return (state, rng), {"reward": r, "regret": regret}

        (state, rng), out = jax.lax.scan(
            one_round, (state, rng), None, length=cfg.rounds_per_iter)
        return state, rng, {
            "reward_mean": jnp.mean(out["reward"]),
            "regret_sum": jnp.sum(out["regret"]),
        }

    return run_iter


class _BanditBase:
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""

    _KIND = ""

    def __init__(self, config: Optional[BanditConfig] = None):
        self.config = config or BanditConfig()
        env = self.config.env
        K, d = env.num_arms, env.context_dim
        self._state = {
            "A": jnp.tile(self.config.lam * jnp.eye(d), (K, 1, 1)),
            "b": jnp.zeros((K, d)),
        }
        self._rng = jax.random.key(self.config.seed)
        self._iter_fn = _make_iter(self.config, self._KIND)
        self._iteration = 0
        self._cumulative_regret = 0.0

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        self._state, self._rng, metrics = self._iter_fn(
            self._state, self._rng)
        self._iteration += 1
        self._cumulative_regret += float(metrics["regret_sum"])
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter": self.config.rounds_per_iter,
            "episode_reward_mean": float(metrics["reward_mean"]),
            "regret_this_iter": float(metrics["regret_sum"]),
            "cumulative_regret": self._cumulative_regret,
            "time_this_iter_s": time.perf_counter() - start,
        }

    def compute_single_action(self, x) -> int:
        x = jnp.asarray(x)
        theta_hat = jax.vmap(jnp.linalg.solve)(
            self._state["A"], self._state["b"])
        return int(jnp.argmax(theta_hat @ x))


class BanditLinUCB(_BanditBase):
    _KIND = "linucb"


class BanditLinTS(_BanditBase):
    _KIND = "lints"
