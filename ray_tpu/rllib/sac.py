"""SAC: soft actor-critic for continuous control, fully on-device.

Fourth algorithm family (reference ``rllib/algorithms/sac/``), covering
the continuous-action side of the reference's catalog. Same TPU-native
Anakin shape as DQN: vectorized env, squashed-Gaussian actor, twin Q
critics with target networks, ON-DEVICE replay buffer, and automatic
entropy-temperature tuning — the whole act/store/sample/update iteration
is one jitted program (the reference's SAC moves batches host-side
through replay actors).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.rllib.env import Pendulum, make_vec_env
from ray_tpu.rllib.optim import adam_step as _adam
from ray_tpu.rllib.ppo import mlp_apply, mlp_init
from ray_tpu.rllib.replay import buffer_add, buffer_init, buffer_sample

_LOG_STD_MIN, _LOG_STD_MAX = -5.0, 2.0


class SACConfig:
    """Builder-style config (``SACConfig().training(...)``)."""

    def __init__(self):
        self.env = Pendulum()
        self.num_envs = 16
        self.steps_per_iter = 64        # env steps (per env) per train()
        self.buffer_size = 50_000
        self.batch_size = 256
        self.updates_per_iter = 32
        self.gamma = 0.99
        self.tau = 0.005                # polyak target update rate
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.hidden_sizes = (128, 128)
        self.learning_starts = 1_000
        self.action_scale = 2.0         # Pendulum torque range
        self.seed = 0

    def environment(self, env=None) -> "SACConfig":
        if env is not None:
            self.env = env
        return self

    def rollouts(self, *, num_envs: Optional[int] = None) -> "SACConfig":
        if num_envs is not None:
            self.num_envs = num_envs
        return self

    def training(self, **kwargs) -> "SACConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown SAC option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "SACConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "SAC":
        return SAC(self)


def actor_init(rng, obs_size, act_size, hidden):
    return mlp_init(rng, (obs_size, *hidden, 2 * act_size))


def actor_dist(params, obs):
    """-> (mean, log_std) of the pre-squash Gaussian."""
    out = mlp_apply(params, obs)
    mean, log_std = jnp.split(out, 2, axis=-1)
    log_std = jnp.clip(log_std, _LOG_STD_MIN, _LOG_STD_MAX)
    return mean, log_std


def actor_sample(params, obs, rng, action_scale):
    """Squashed-Gaussian sample -> (action, logp). tanh squash with the
    standard log-det-Jacobian correction."""
    mean, log_std = actor_dist(params, obs)
    std = jnp.exp(log_std)
    eps = jax.random.normal(rng, mean.shape)
    pre = mean + std * eps
    logp_gauss = jnp.sum(
        -0.5 * (eps ** 2 + 2 * log_std + jnp.log(2 * jnp.pi)), axis=-1)
    squashed = jnp.tanh(pre)
    # log|d tanh/dx| summed over action dims (numerically stable form),
    # plus the scale Jacobian: action = scale*tanh(pre) contributes
    # act_size * log(scale) to the log-density change.
    log_det = jnp.sum(
        2.0 * (jnp.log(2.0) - pre - jax.nn.softplus(-2.0 * pre)), axis=-1)
    log_det = log_det + mean.shape[-1] * jnp.log(action_scale)
    return action_scale * squashed, logp_gauss - log_det


def critic_init(rng, obs_size, act_size, hidden):
    k1, k2 = jax.random.split(rng)
    sizes = (obs_size + act_size, *hidden, 1)
    return {"q1": mlp_init(k1, sizes), "q2": mlp_init(k2, sizes)}


def critic_apply(params, obs, act):
    x = jnp.concatenate([obs, act], axis=-1)
    return mlp_apply(params["q1"], x)[..., 0], mlp_apply(params["q2"], x)[..., 0]


def _make_train_iter(cfg: SACConfig):
    env = cfg.env
    obs_size, act_size = env.observation_size, env.action_size
    reset_fn, step_fn, obs_fn = make_vec_env(env, cfg.num_envs)
    target_entropy = -float(act_size)

    # Time-limit-only envs (Pendulum): a "done" is truncation, not a
    # terminal state — store done=0 so the critic bootstraps THROUGH the
    # horizon (standard SAC truncation handling).
    time_limit_only = bool(getattr(env, "TIME_LIMIT_ONLY", False))

    @jax.jit
    def reset(rng):
        return reset_fn(rng)

    @jax.jit
    def train_iter(learner, states, rng):
        def env_step(carry, _):
            learner, states, rng = carry
            rng, k_act, k_step = jax.random.split(rng, 3)
            obs = obs_fn(states)
            act, _ = actor_sample(
                learner["actor"], obs, k_act, cfg.action_scale)
            nstates, nobs, rew, done = step_fn(states, act, k_step)
            done_f = done.astype(jnp.float32)
            stored_done = jnp.zeros_like(done_f) if time_limit_only \
                else done_f
            learner = dict(
                learner,
                buffer=buffer_add(
                    learner["buffer"], cfg.buffer_size,
                    obs=obs, act=act, rew=rew, nobs=nobs,
                    done=stored_done),
                env_steps=learner["env_steps"] + cfg.num_envs,
                reward_sum=learner["reward_sum"] + jnp.sum(rew),
                done_count=learner["done_count"] + jnp.sum(done),
            )
            return (learner, nstates, rng), None

        (learner, states, rng), _ = jax.lax.scan(
            env_step, (learner, states, rng), None,
            length=cfg.steps_per_iter)

        def critic_loss(cp, actor_p, target_p, alpha, batch, k):
            next_act, next_logp = actor_sample(
                actor_p, batch["nobs"], k, cfg.action_scale)
            tq1, tq2 = critic_apply(target_p, batch["nobs"], next_act)
            target_q = jnp.minimum(tq1, tq2) - alpha * next_logp
            y = batch["rew"] + cfg.gamma * (1 - batch["done"]) * \
                jax.lax.stop_gradient(target_q)
            q1, q2 = critic_apply(cp, batch["obs"], batch["act"])
            return jnp.mean((q1 - y) ** 2 + (q2 - y) ** 2)

        def actor_loss(ap, cp, alpha, batch, k):
            act, logp = actor_sample(ap, batch["obs"], k, cfg.action_scale)
            q1, q2 = critic_apply(cp, batch["obs"], act)
            return jnp.mean(alpha * logp - jnp.minimum(q1, q2)), logp

        def update(carry, _):
            learner, rng = carry
            rng, k_idx, k1, k2 = jax.random.split(rng, 4)
            buf = learner["buffer"]
            batch = buffer_sample(buf, k_idx, cfg.batch_size,
                                  ("obs", "act", "rew", "nobs", "done"))
            alpha = jnp.exp(learner["log_alpha"])
            ready = (buf["size"] >= cfg.learning_starts).astype(jnp.float32)

            closs, cgrads = jax.value_and_grad(critic_loss)(
                learner["critic"], learner["actor"], learner["target"],
                alpha, batch, k1)
            cgrads = jax.tree.map(lambda g: g * ready, cgrads)
            critic, copt = _adam(learner["critic"], learner["copt"],
                                 cgrads, lr=cfg.critic_lr)

            (aloss, logp), agrads = jax.value_and_grad(
                actor_loss, has_aux=True)(
                learner["actor"], critic, alpha, batch, k2)
            agrads = jax.tree.map(lambda g: g * ready, agrads)
            actor, aopt = _adam(learner["actor"], learner["aopt"],
                                agrads, lr=cfg.actor_lr)

            # Automatic temperature: push E[logp] toward target entropy.
            alpha_grad = -jnp.mean(
                jax.lax.stop_gradient(logp) + target_entropy) * \
                jnp.exp(learner["log_alpha"])
            log_alpha = learner["log_alpha"] - \
                cfg.alpha_lr * ready * alpha_grad

            target = jax.tree.map(
                lambda t, p: (1 - cfg.tau * ready) * t
                + cfg.tau * ready * p,
                learner["target"], critic,
            )
            learner = dict(learner, actor=actor, critic=critic,
                           target=target, aopt=aopt, copt=copt,
                           log_alpha=log_alpha)
            return (learner, rng), {"critic_loss": closs * ready,
                                    "actor_loss": aloss * ready}

        (learner, rng), losses = jax.lax.scan(
            update, (learner, rng), None, length=cfg.updates_per_iter)
        metrics = {
            "critic_loss": jnp.mean(losses["critic_loss"]),
            "actor_loss": jnp.mean(losses["actor_loss"]),
            "alpha": jnp.exp(learner["log_alpha"]),
            "buffer_size": learner["buffer"]["size"].astype(jnp.float32),
        }
        return learner, states, rng, metrics

    return reset, train_iter


class SAC:
    """Algorithm: ``.train()`` one iteration -> result dict
    (``rllib/algorithms/algorithm.py:142`` Trainable contract)."""

    def __init__(self, config: SACConfig):
        self.config = config
        env = config.env
        rng = jax.random.key(config.seed)
        ka, kc, k_env, self._rng = jax.random.split(rng, 4)
        obs_size, act_size = env.observation_size, env.action_size
        actor = actor_init(ka, obs_size, act_size, config.hidden_sizes)
        critic = critic_init(kc, obs_size, act_size, config.hidden_sizes)
        n = config.buffer_size

        def opt_for(p):
            return {"mu": jax.tree.map(jnp.zeros_like, p),
                    "nu": jax.tree.map(jnp.zeros_like, p),
                    "t": jnp.zeros((), jnp.int32)}

        self._learner = {
            "actor": actor,
            "critic": critic,
            "target": jax.tree.map(jnp.copy, critic),
            "aopt": opt_for(actor),
            "copt": opt_for(critic),
            "log_alpha": jnp.zeros((), jnp.float32),
            "buffer": buffer_init(n, {
                "obs": (obs_size,), "act": (act_size,), "rew": (),
                "nobs": (obs_size,), "done": (),
            }),
            "env_steps": jnp.zeros((), jnp.int32),
            "reward_sum": jnp.zeros((), jnp.float32),
            "done_count": jnp.zeros((), jnp.int32),
        }
        self._reset, self._train_iter = _make_train_iter(config)
        self._states = self._reset(k_env)
        self._iteration = 0

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        prev_steps = int(self._learner["env_steps"])
        prev_rew = float(self._learner["reward_sum"])
        prev_dones = int(self._learner["done_count"])
        self._learner, self._states, self._rng, metrics = self._train_iter(
            self._learner, self._states, self._rng)
        self._iteration += 1
        steps = int(self._learner["env_steps"]) - prev_steps
        rew = float(self._learner["reward_sum"]) - prev_rew
        dones = int(self._learner["done_count"]) - prev_dones
        # Real episode boundaries; before the first one completes, report
        # the running mean over the partial episodes instead of inf.
        episodes = dones if dones > 0 else max(
            1e-6, steps / max(1, int(getattr(self.config.env,
                                             "MAX_STEPS", steps))))
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter": steps,
            "episode_reward_mean": rew / episodes,
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(v) for k, v in metrics.items()},
        }

    def compute_single_action(self, obs):
        mean, _ = actor_dist(self._learner["actor"],
                             jnp.asarray(obs)[None])
        return (self.config.action_scale
                * jnp.tanh(mean[0])).tolist()
