"""A2C: synchronous advantage actor-critic (reference
``rllib/algorithms/a2c``): the on-policy family's simplest member — one
policy-gradient step per rollout on n-step advantages, no surrogate
clipping, no minibatch epochs. Shares PPO's model, vectorized envs, and
Anakin execution shape (rollout + GAE + update in ONE jitted program)."""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.rllib.env import CartPole, make_vec_env
from ray_tpu.rllib.optim import adam_step as _adam
from ray_tpu.rllib.ppo import policy_apply, policy_init


class A2CConfig:
    def __init__(self):
        self.env = CartPole()
        self.num_envs = 64
        self.rollout_length = 32
        self.gamma = 0.99
        self.gae_lambda = 1.0           # A2C default: plain n-step returns
        self.lr = 2.5e-3
        self.entropy_coeff = 0.01
        self.vf_coeff = 0.5
        self.grad_clip = 0.5
        self.hidden_sizes = (64, 64)
        self.seed = 0

    def environment(self, env=None) -> "A2CConfig":
        if env is not None:
            self.env = env
        return self

    def rollouts(self, *, num_envs: Optional[int] = None,
                 rollout_length: Optional[int] = None) -> "A2CConfig":
        if num_envs is not None:
            self.num_envs = num_envs
        if rollout_length is not None:
            self.rollout_length = rollout_length
        return self

    def training(self, **kwargs) -> "A2CConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown A2C option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "A2CConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "A2C":
        return A2C(self)


def _make_grad_fn(cfg: A2CConfig):
    """(reset, grad_fn) where grad_fn(params, states, rng) -> (grads,
    states, rng, metrics) — the rollout + n-step-advantage + gradient
    half of A2C, factored out so A3C's worker actors can compute the
    SAME gradient remotely and push it to an async learner."""
    env = cfg.env
    n_envs, t_len = cfg.num_envs, cfg.rollout_length
    reset, vstep, vobs = make_vec_env(env, n_envs)

    @jax.jit
    def grad_fn(params, states, rng):
        def step_fn(carry, _):
            states, rng = carry
            rng, k_act, k_step = jax.random.split(rng, 3)
            obs = vobs(states)
            logits, value = policy_apply(params, obs)
            action = jax.random.categorical(k_act, logits)
            nxt, _, reward, done = vstep(states, action, k_step)
            out = {"obs": obs, "actions": action, "rewards": reward,
                   "dones": done, "values": value}
            return (nxt, rng), out

        (states, rng), traj = jax.lax.scan(
            step_fn, (states, rng), None, length=t_len)
        _, last_value = policy_apply(params, vobs(states))

        def adv_scan(adv, x):
            reward, done, value, next_value = x
            nonterm = 1.0 - done.astype(jnp.float32)
            delta = reward + cfg.gamma * next_value * nonterm - value
            adv = delta + cfg.gamma * cfg.gae_lambda * nonterm * adv
            return adv, adv

        values = traj["values"]
        next_values = jnp.concatenate([values[1:], last_value[None]], 0)
        _, advs = jax.lax.scan(
            adv_scan, jnp.zeros_like(last_value),
            (traj["rewards"], traj["dones"], values, next_values),
            reverse=True)
        returns = advs + values

        def loss_fn(p):
            logits, value = policy_apply(
                p, traj["obs"].reshape(-1, env.observation_size))
            logp_all = jax.nn.log_softmax(logits)
            acts = traj["actions"].reshape(-1)
            logp = jnp.take_along_axis(logp_all, acts[:, None], 1)[:, 0]
            adv = advs.reshape(-1)
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            pg = -jnp.mean(logp * adv)
            vf = jnp.mean((value - returns.reshape(-1)) ** 2)
            ent = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
            return pg + cfg.vf_coeff * vf - cfg.entropy_coeff * ent, ent

        (loss, entropy), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        n_done = jnp.maximum(
            jnp.sum(traj["dones"].astype(jnp.float32)), 1.0)
        metrics = {
            "loss": loss,
            "entropy": entropy,
            # True mean return of episodes that ended this rollout (works
            # for any reward scheme, not just +1-per-step envs).
            "episode_reward_mean": jnp.sum(traj["rewards"]) / n_done,
        }
        return grads, states, rng, metrics

    return reset, grad_fn


def _make_train_iter(cfg: A2CConfig):
    reset, grad_fn = _make_grad_fn(cfg)

    @jax.jit
    def train_iter(params, opt, states, rng):
        grads, states, rng, metrics = grad_fn(params, states, rng)
        params, opt = _adam(params, opt, grads, lr=cfg.lr,
                            max_grad_norm=cfg.grad_clip, eps=1e-5)
        return params, opt, states, rng, metrics

    return reset, train_iter


class A2C:
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""

    def __init__(self, config: A2CConfig):
        self.config = config
        rng = jax.random.key(config.seed)
        k_param, k_env, self._rng = jax.random.split(rng, 3)
        env = config.env
        self.params = policy_init(
            k_param, env.observation_size, env.num_actions,
            config.hidden_sizes)
        self.opt = {
            "mu": jax.tree.map(jnp.zeros_like, self.params),
            "nu": jax.tree.map(jnp.zeros_like, self.params),
            "t": jnp.zeros((), jnp.int32),
        }
        self._reset, self._train_iter = _make_train_iter(config)
        self._states = self._reset(k_env)
        self._iteration = 0

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        (self.params, self.opt, self._states, self._rng,
         metrics) = self._train_iter(
            self.params, self.opt, self._states, self._rng)
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter":
                self.config.num_envs * self.config.rollout_length,
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(v) for k, v in metrics.items()},
        }

    def compute_single_action(self, obs) -> int:
        logits, _ = policy_apply(self.params, jnp.asarray(obs)[None])
        return int(jnp.argmax(logits[0]))
