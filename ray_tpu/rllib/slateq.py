"""SlateQ: Q-learning for slate recommendation (reference
``rllib/algorithms/slateq/slateq.py``, after Ie et al. 2019) — the
recommendation-domain member of the inventory. The combinatorial
action space (choose m of D documents) is decomposed through the user
CHOICE MODEL: under conditional-logit choice,

    Q(s, S) = sum_{d in S} P(click d | s, S) * Qbar(s, d)

so learning reduces to the per-ITEM long-term value ``Qbar`` with a TD
update on the clicked item only, and slate construction to maximizing
the closed-form F(S) — done here by greedy marginal gain (m rounds of
the vectorized closed form over all D candidates), which is exact
enough at these sizes and fully jittable.

``SlateDocEnv`` is a RecSim-flavored interest-evolution environment
with the myopic trap built in: "clickbait" documents carry a choice
bonus and an immediate-reward bonus but DECAY the user's interest
vector (shrinking every future engagement), while "quality" documents
grow it. A myopic recommender (the ``gamma=0`` point of this same
program — the ablation the tests compare, like BC for CRR) fills
slates with clickbait; SlateQ learns to forgo immediate clicks for
user-state growth.

Everything (vectorized envs, choice sampling, replay, greedy slate
search, decomposed TD) runs as one jitted Anakin program.
"""

from __future__ import annotations

import time
from typing import Any, Dict, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithm import EpisodeStats
from ray_tpu.rllib.optim import adam_init, adam_step, periodic_target_sync
from ray_tpu.rllib.ppo import mlp_apply, mlp_init
from ray_tpu.rllib.replay import buffer_add, buffer_init, buffer_sample

__all__ = ["SlateQ", "SlateQConfig", "SlateDocEnv"]


class SlateState(NamedTuple):
    u: jax.Array   # [k] user interest
    t: jax.Array


class SlateDocEnv:
    """D documents with fixed topic vectors; slate of m per step; the
    user clicks by conditional logit over the slate plus a null option.
    Clicking clickbait decays |u| (future engagement shrinks); clicking
    quality docs grows u toward the doc topic."""

    def __init__(self, n_docs: int = 20, n_clickbait: int = 6,
                 topic_dim: int = 4, slate_size: int = 3,
                 max_steps: int = 30, seed: int = 0):
        self.n_docs = n_docs
        self.slate_size = slate_size
        self.topic_dim = topic_dim
        self.max_steps = max_steps
        k = jax.random.key(seed)
        topics = jax.random.normal(k, (n_docs, topic_dim))
        self.topics = topics / jnp.linalg.norm(topics, axis=1,
                                               keepdims=True)
        self.is_clickbait = (jnp.arange(n_docs) < n_clickbait
                             ).astype(jnp.float32)
        self.choice_bonus = 2.0 * self.is_clickbait
        self.reward_bonus = 1.2 * self.is_clickbait
        self.decay = 0.55          # clickbait: u <- decay * u
        self.grow = 0.4            # quality: u <- u + grow * topic
        self.max_norm = 2.0
        self.beta = 2.0            # choice-model temperature
        self.null_logit = 0.0

    def reset(self, rng: jax.Array) -> SlateState:
        u = jax.random.normal(rng, (self.topic_dim,))
        return SlateState(u / jnp.linalg.norm(u), jnp.zeros((), jnp.int32))

    def choice_logits(self, u, slate):
        """[m] conditional-logit scores of the slate's docs for user u."""
        return self.beta * (self.topics[slate] @ u) + \
            self.choice_bonus[slate]

    def step(self, s: SlateState, slate: jax.Array, rng: jax.Array):
        """slate: [m] int doc ids -> (state, reward, click_idx, done).
        click_idx in [0, m) or m for the null (no-click) option."""
        logits = jnp.concatenate(
            [self.choice_logits(s.u, slate),
             jnp.array([self.null_logit])])
        k_choice, k_reset = jax.random.split(rng)
        click = jax.random.categorical(k_choice, logits)
        clicked = click < self.slate_size
        doc = slate[jnp.minimum(click, self.slate_size - 1)]
        # The clickbait bonus SCALES WITH the interest norm: a decayed
        # user pays less for everything, clickbait included — that is
        # what makes the myopic policy's clickbait spiral a trap rather
        # than a steady income.
        engagement = self.topics[doc] @ s.u + \
            self.reward_bonus[doc] * jnp.linalg.norm(s.u)
        reward = jnp.where(clicked, engagement, 0.0)
        cb = self.is_clickbait[doc]
        u_clicked = cb * (self.decay * s.u) + \
            (1.0 - cb) * (s.u + self.grow * self.topics[doc])
        u_new = jnp.where(clicked, u_clicked, s.u)
        norm = jnp.linalg.norm(u_new)
        u_new = u_new * jnp.minimum(1.0, self.max_norm / norm)
        t = s.t + 1
        done = t >= self.max_steps
        fresh = self.reset(k_reset)
        nxt = SlateState(
            jnp.where(done, fresh.u, u_new),
            jnp.where(done, fresh.t, t))
        return nxt, reward, click.astype(jnp.int32), done


class SlateQConfig:
    """Builder-style config (``SlateQConfig().training(gamma=0.0)`` is
    the myopic ablation)."""

    def __init__(self):
        self.env = SlateDocEnv()
        self.num_envs = 16
        self.steps_per_iter = 128
        self.buffer_size = 50_000
        self.batch_size = 128
        self.updates_per_iter = 64
        self.gamma = 0.95
        self.lr = 1e-3
        self.hidden_sizes = (64, 64)
        self.epsilon = 0.2          # prob of a uniform-random slate
        self.target_update_every = 200
        self.learning_starts = 1_000
        self.seed = 0

    def environment(self, env=None) -> "SlateQConfig":
        if env is not None:
            self.env = env
        return self

    def rollouts(self, *, num_envs: Optional[int] = None) -> "SlateQConfig":
        if num_envs is not None:
            self.num_envs = num_envs
        return self

    def training(self, **kwargs) -> "SlateQConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown SlateQ option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "SlateQConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "SlateQ":
        return SlateQ(self)


def _make_train_iter(cfg: SlateQConfig):
    env = cfg.env
    D, m, k_dim = env.n_docs, env.slate_size, env.topic_dim

    vreset = jax.vmap(env.reset)
    vstep = jax.vmap(env.step)

    # Per-doc static features, broadcast against the user state.
    doc_feats = jnp.concatenate(
        [env.topics, env.is_clickbait[:, None]], axis=1)   # [D, k+1]

    def qbar_all(params, u):
        """Qbar(s, d) for every doc: u [k] -> [D]."""
        x = jnp.concatenate(
            [jnp.tile(u[None], (D, 1)), doc_feats], axis=1)
        return mlp_apply(params, x)[:, 0]

    def slate_value(u, slate_mask, qbars):
        """Closed-form F(S) = sum p_d(S) Qbar_d under conditional logit
        with the null option; slate described by a [D] 0/1 mask."""
        logits = env.beta * (env.topics @ u) + env.choice_bonus
        w = jnp.exp(logits) * slate_mask
        denom = jnp.sum(w) + jnp.exp(env.null_logit)
        return jnp.sum(w * qbars) / denom

    def greedy_slate(params, u):
        """m rounds of greedy marginal gain over the closed form."""
        qbars = qbar_all(params, u)

        def add_one(mask, _):
            def f_with(d):
                return slate_value(u, mask.at[d].set(1.0), qbars)

            gains = jax.vmap(f_with)(jnp.arange(D))
            gains = jnp.where(mask > 0, -jnp.inf, gains)
            best = jnp.argmax(gains)
            return mask.at[best].set(1.0), best

        mask, picks = jax.lax.scan(
            add_one, jnp.zeros(D), None, length=m)
        return picks.astype(jnp.int32)

    def td_loss(p, tp, batch):
        # Update ONLY the clicked item's Qbar toward
        # r + gamma * F(s', greedy slate at s'); null-click rows and
        # warmup rows are masked out of the mean.
        def one(u, slate, click, rew, u_next, done):
            clicked = (click < m).astype(jnp.float32)
            doc = slate[jnp.minimum(click, m - 1)]
            x = jnp.concatenate([u, doc_feats[doc]])
            q = mlp_apply(p, x[None])[0, 0]
            next_slate = greedy_slate(tp, u_next)
            next_mask = jnp.zeros(D).at[next_slate].set(1.0)
            f_next = slate_value(u_next, next_mask, qbar_all(tp, u_next))
            y = rew + cfg.gamma * (1.0 - done) * \
                jax.lax.stop_gradient(f_next)
            return clicked * (q - y) ** 2, clicked

        errs, clicked = jax.vmap(one)(
            batch["u"], batch["slate"], batch["click"], batch["rew"],
            batch["u_next"], batch["done"])
        return jnp.sum(errs) / jnp.maximum(jnp.sum(clicked), 1.0)

    @jax.jit
    def reset(rng):
        return vreset(jax.random.split(rng, cfg.num_envs))

    @jax.jit
    def train_iter(learner, states, rng):
        def env_step(carry, _):
            learner, states, rng = carry
            rng, k_g, k_r, k_e, k_step = jax.random.split(rng, 5)
            greedy = jax.vmap(
                lambda u: greedy_slate(learner["params"], u))(states.u)
            # Epsilon-exploration: a uniform slate (m distinct-ish docs
            # via uniform without-replacement approximation).
            randa = jax.vmap(
                lambda k: jax.random.choice(k, D, (m,), replace=False))(
                jax.random.split(k_r, cfg.num_envs))
            explore = jax.random.uniform(k_e, (cfg.num_envs,)) < cfg.epsilon
            slates = jnp.where(explore[:, None], randa, greedy)
            nstates, rew, click, done = vstep(
                states, slates, jax.random.split(k_step, cfg.num_envs))
            learner = dict(
                learner,
                buffer=buffer_add(
                    learner["buffer"], cfg.buffer_size,
                    u=states.u, slate=slates, click=click, rew=rew,
                    u_next=nstates.u, done=done.astype(jnp.float32)),
                env_steps=learner["env_steps"] + cfg.num_envs,
                reward_sum=learner["reward_sum"] + jnp.sum(rew),
                done_count=learner["done_count"] + jnp.sum(done),
            )
            return (learner, nstates, rng), None

        (learner, states, rng), _ = jax.lax.scan(
            env_step, (learner, states, rng), None,
            length=cfg.steps_per_iter)

        def update(carry, _):
            learner, rng = carry
            rng, k = jax.random.split(rng)
            buf = learner["buffer"]
            batch = buffer_sample(
                buf, k, cfg.batch_size,
                ("u", "slate", "click", "rew", "u_next", "done"))
            loss, grads = jax.value_and_grad(td_loss)(
                learner["params"], learner["target_params"], batch)
            ready = (buf["size"] >= cfg.learning_starts).astype(jnp.float32)
            grads = jax.tree.map(lambda g: g * ready, grads)
            params, opt = adam_step(learner["params"], learner["opt"],
                                    grads, lr=cfg.lr)
            target = periodic_target_sync(
                learner["target_params"], params, opt["t"],
                cfg.target_update_every)
            learner = dict(learner, params=params, opt=opt,
                           target_params=target)
            return (learner, rng), loss * ready

        (learner, rng), losses = jax.lax.scan(
            update, (learner, rng), None, length=cfg.updates_per_iter)
        return learner, states, rng, {"loss": jnp.mean(losses)}

    return reset, train_iter, jax.jit(greedy_slate)


class SlateQ(EpisodeStats):
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""

    def __init__(self, config: SlateQConfig):
        self.config = config
        env = config.env
        rng = jax.random.key(config.seed)
        k_param, k_env, self._rng = jax.random.split(rng, 3)
        params = mlp_init(
            k_param,
            (env.topic_dim + env.topic_dim + 1, *config.hidden_sizes, 1))
        self._learner = {
            "params": params,
            "target_params": jax.tree.map(jnp.copy, params),
            "opt": adam_init(params),
            "buffer": buffer_init(
                config.buffer_size,
                {"u": (env.topic_dim,), "slate": (env.slate_size,),
                 "click": (), "rew": (), "u_next": (env.topic_dim,),
                 "done": ()},
                dtypes={"slate": jnp.int32, "click": jnp.int32}),
            "env_steps": jnp.zeros((), jnp.int32),
            "reward_sum": jnp.zeros(()),
            "done_count": jnp.zeros((), jnp.int32),
        }
        self._reset, self._train_iter, self._greedy = \
            _make_train_iter(config)
        self._states = self._reset(k_env)
        self._iteration = 0

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        snap = self._episode_snapshot()
        self._learner, self._states, self._rng, metrics = self._train_iter(
            self._learner, self._states, self._rng)
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter":
                self.config.num_envs * self.config.steps_per_iter,
            "episode_reward_mean": self._episode_reward_mean(snap),
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(v) for k, v in metrics.items()},
        }

    def evaluate(self, n_episodes: int = 8, seed: int = 77) -> float:
        """Greedy-slate episodes; returns mean cumulative engagement."""
        env = self.config.env
        total = 0.0
        for ep in range(n_episodes):
            rng = jax.random.key(seed + ep)
            s = env.reset(rng)
            ret = 0.0
            for _ in range(env.max_steps):
                slate = self._greedy(self._learner["params"], s.u)
                rng, k = jax.random.split(rng)
                s, rew, _, done = env.step(s, slate, k)
                ret += float(rew)
                if bool(done):
                    break
            total += ret
        return total / n_episodes

    def clickbait_fraction(self, n_states: int = 64, seed: int = 3) -> float:
        """Fraction of greedy-slate slots filled with clickbait over
        random user states (diagnostic for the myopic trap)."""
        env = self.config.env
        rngs = jax.random.split(jax.random.key(seed), n_states)
        frac = 0.0
        for r in rngs:
            u = env.reset(r).u
            slate = self._greedy(self._learner["params"], u)
            frac += float(jnp.mean(env.is_clickbait[slate]))
        return frac / n_states
