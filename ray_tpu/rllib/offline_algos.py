"""Offline-RL algorithm family: BC, MARWIL, CQL.

Reference parity: ``rllib/algorithms/bc`` (behavior cloning),
``rllib/algorithms/marwil`` (exponentially advantage-weighted imitation
— BC is exactly its beta=0 case), ``rllib/algorithms/cql``
(conservative Q-learning: the discrete-action CQL(H) penalty on top of
the offline DQN learner). All three train as single jitted programs
over a dataset staged on device; no env interaction.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.dqn import DQNConfig
from ray_tpu.rllib.offline import OfflineDQN, read_dataset
from ray_tpu.rllib.optim import adam_step as _adam
from ray_tpu.rllib.ppo import mlp_apply, mlp_init
from ray_tpu.rllib.sample_batch import SampleBatch


def compute_returns(batch: SampleBatch, gamma: float) -> np.ndarray:
    """Per-step discounted return-to-go, reset at episode boundaries
    (MARWIL's advantage target; the dataset's dones delimit episodes)."""
    rewards = np.asarray(batch["rewards"], np.float32)
    dones = np.asarray(batch["dones"], np.float32)
    out = np.zeros_like(rewards)
    acc = 0.0
    for i in range(len(rewards) - 1, -1, -1):
        acc = rewards[i] + gamma * (1.0 - dones[i]) * acc
        out[i] = acc
    return out


class MARWILConfig:
    def __init__(self):
        from ray_tpu.rllib.env import CartPole

        self.env = CartPole()
        #: 0.0 = plain behavior cloning (the BC algorithm IS this case).
        self.beta = 1.0
        self.gamma = 0.99
        self.lr = 1e-3
        self.vf_lr = 1e-3
        self.hidden_sizes = (64, 64)
        self.batch_size = 256
        self.updates_per_iter = 200
        self.w_clip = 20.0  # exp-advantage weight cap (stability)
        self.seed = 0

    def training(self, **kw) -> "MARWILConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown config key {k!r}")
            setattr(self, k, v)
        return self

    def build(self, dataset) -> "MARWIL":
        return MARWIL(self, dataset)


class MARWIL:
    """Monotonic Advantage Re-Weighted Imitation Learning (Wang et al.
    2018; ``rllib/algorithms/marwil``): imitate the dataset with each
    transition weighted exp(beta * normalized advantage), advantage =
    return-to-go minus a jointly-learned value baseline."""

    def __init__(self, config: MARWILConfig, dataset):
        self.config = config
        batch = read_dataset(dataset)
        if batch.count == 0:
            raise ValueError("offline dataset is empty")
        rng = jax.random.key(config.seed)
        k_pi, k_vf, self._rng = jax.random.split(rng, 3)
        env = config.env
        obs = np.asarray(batch["obs"], np.float32)
        self._data = {
            "obs": jnp.asarray(obs),
            "actions": jnp.asarray(batch["actions"], jnp.int32),
            "returns": jnp.asarray(
                compute_returns(batch, config.gamma)),
        }
        self._n = batch.count
        self.params = {
            "pi": mlp_init(k_pi, (env.observation_size,
                                  *config.hidden_sizes, env.num_actions)),
            "vf": mlp_init(k_vf, (env.observation_size,
                                  *config.hidden_sizes, 1)),
        }
        self.opt = {
            "mu": jax.tree.map(jnp.zeros_like, self.params),
            "nu": jax.tree.map(jnp.zeros_like, self.params),
            "t": jnp.zeros((), jnp.int32),
        }
        self._iteration = 0
        self._train_iter = self._build()

    def _build(self):
        cfg = self.config
        data, n = self._data, self._n

        def loss_fn(params, idx):
            obs = data["obs"][idx]
            acts = data["actions"][idx]
            ret = data["returns"][idx]
            logits = mlp_apply(params["pi"], obs)
            value = mlp_apply(params["vf"], obs)[:, 0]
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(logits), acts[:, None], axis=1)[:, 0]
            adv = ret - jax.lax.stop_gradient(value)
            # Normalize before exponentiating (the reference keeps a
            # running average of |adv| for the same purpose); clip the
            # weights so one outlier can't dominate a minibatch.
            adv_n = adv / (jnp.abs(adv).mean() + 1e-8)
            w = jnp.clip(jnp.exp(cfg.beta * adv_n), 0.0, cfg.w_clip)
            bc_loss = -jnp.mean(jax.lax.stop_gradient(w) * logp)
            vf_loss = jnp.mean((value - ret) ** 2)
            return bc_loss + 0.5 * vf_loss, (bc_loss, vf_loss)

        @jax.jit
        def train_iter(params, opt, rng):
            def update(carry, _):
                params, opt, rng = carry
                rng, k = jax.random.split(rng)
                idx = jax.random.randint(
                    k, (cfg.batch_size,), 0, n)
                (_, (bc, vf)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, idx)
                params, opt = _adam(params, opt, grads, lr=cfg.lr)
                return (params, opt, rng), (bc, vf)

            (params, opt, rng), (bcs, vfs) = jax.lax.scan(
                update, (params, opt, rng), None,
                length=cfg.updates_per_iter)
            return params, opt, rng, jnp.mean(bcs), jnp.mean(vfs)

        return train_iter

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        (self.params, self.opt, self._rng, bc_loss,
         vf_loss) = self._train_iter(self.params, self.opt, self._rng)
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "bc_loss": float(bc_loss),
            "vf_loss": float(vf_loss),
            "dataset_size": self._n,
            "timesteps_this_iter": 0,
            "time_this_iter_s": time.perf_counter() - start,
        }

    def compute_single_action(self, obs) -> int:
        logits = mlp_apply(self.params["pi"],
                           jnp.asarray(obs, jnp.float32)[None])
        return int(jnp.argmax(logits, axis=-1)[0])

    def evaluate(self, n_steps: int = 2000, seed: int = 7,
                 epsilon: float = 0.05) -> float:
        """Mean episode length under the greedy policy (+noise floor;
        same honesty note as OfflineDQN.evaluate)."""
        from ray_tpu.rllib.env import make_vec_env

        cfg = self.config
        n_envs = 16
        n_act = cfg.env.num_actions
        reset_fn, step_fn, obs_fn = make_vec_env(cfg.env, n_envs)
        pi = self.params["pi"]

        @jax.jit
        def rollout(params, rng):
            states = reset_fn(rng)

            def step(carry, _):
                states, rng = carry
                rng, k_r, k_m, k_s = jax.random.split(rng, 4)
                act = jnp.argmax(mlp_apply(params, obs_fn(states)), axis=1)
                rnd = jax.random.randint(k_r, (n_envs,), 0, n_act)
                noisy = jax.random.uniform(k_m, (n_envs,)) < epsilon
                act = jnp.where(noisy, rnd, act)
                nstates, _, _, done = step_fn(states, act, k_s)
                return (nstates, rng), jnp.sum(done)

            (_, _), dones = jax.lax.scan(
                step, (states, jax.random.fold_in(rng, 1)), None,
                length=max(1, n_steps // n_envs))
            return jnp.sum(dones)

        n_done = float(rollout(pi, jax.random.key(seed)))
        steps = max(1, n_steps // n_envs) * n_envs
        return steps / max(n_done, 1.0)

    def save(self) -> dict:
        return {"params": jax.tree.map(np.asarray, self.params),
                "iteration": self._iteration}

    def restore(self, state: dict) -> None:
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self._iteration = state["iteration"]


class BCConfig(MARWILConfig):
    """Behavior cloning (``rllib/algorithms/bc``): MARWIL at beta=0 —
    pure supervised imitation, no advantage weighting."""

    def __init__(self):
        super().__init__()
        self.beta = 0.0

    def build(self, dataset) -> "BC":
        return BC(self, dataset)


class BC(MARWIL):
    pass


class CQLConfig(DQNConfig):
    """CQL's natural config is the DQN family's (CQL extends OfflineDQN)
    plus the conservative-penalty weight. Registered as the "CQL" config
    so ``get_algorithm_config("CQL").build(dataset)`` yields a CQL — the
    earlier MARWILConfig pairing silently built a MARWIL instead."""

    def __init__(self):
        super().__init__()
        self.cql_alpha = 1.0

    def build(self, dataset) -> "CQL":
        return CQL(self, dataset, cql_alpha=self.cql_alpha)


class CQL(OfflineDQN):
    """Discrete CQL(H) (Kumar et al. 2020; ``rllib/algorithms/cql``):
    the OfflineDQN TD loss plus the conservative penalty
    alpha * E[logsumexp_a Q(s, a) - Q(s, a_data)], which pushes down
    Q-values for actions the DATASET never took — the overestimation
    that makes plain Q-learning fail on narrow offline data."""

    def __init__(self, config: DQNConfig, dataset, *,
                 cql_alpha: float = 1.0):
        self.cql_alpha = cql_alpha
        super().__init__(config, dataset)

    def _build_offline_iter(self):
        cfg = self.config
        alpha = self.cql_alpha
        from ray_tpu.rllib.replay import buffer_sample

        def cql_loss(params, target_params, batch):
            q = mlp_apply(params, batch["obs"])
            q_taken = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=1)[:, 0]
            next_online = mlp_apply(params, batch["next_obs"])
            next_act = jnp.argmax(next_online, axis=1)
            next_target = mlp_apply(target_params, batch["next_obs"])
            next_q = jnp.take_along_axis(
                next_target, next_act[:, None], axis=1)[:, 0]
            target = batch["rewards"] + cfg.gamma * (
                1.0 - batch["dones"]) * jax.lax.stop_gradient(next_q)
            err = q_taken - target
            td = jnp.mean(err * err)
            conservative = jnp.mean(
                jax.nn.logsumexp(q, axis=1) - q_taken)
            return td + alpha * conservative, (td, conservative)

        @jax.jit
        def offline_iter(learner, rng):
            def update(carry, _):
                learner, rng = carry
                rng, k = jax.random.split(rng)
                batch = buffer_sample(
                    learner["buffer"], k, cfg.batch_size,
                    ("obs", "actions", "rewards", "next_obs", "dones"))
                (loss, (_td, gap)), grads = jax.value_and_grad(
                    cql_loss, has_aux=True)(
                    learner["params"], learner["target_params"], batch)
                params, opt = _adam(
                    learner["params"], learner["opt"], grads, lr=cfg.lr)
                sync = (opt["t"] % cfg.target_update_every) == 0
                target = jax.tree.map(
                    lambda t_, p: jnp.where(sync, p, t_),
                    learner["target_params"], params)
                return (dict(learner, params=params, opt=opt,
                             target_params=target), rng), (loss, gap)

            (learner, rng), (losses, gaps) = jax.lax.scan(
                update, (learner, rng), None, length=cfg.updates_per_iter)
            return learner, rng, jnp.mean(losses), jnp.mean(gaps)

        self._offline_iter = offline_iter

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        self._learner, self._rng, loss, gap = self._offline_iter(
            self._learner, self._rng)
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "loss": float(loss),
            # Mean logsumexp(Q) - Q(s, a_data): how much probability mass
            # the net still puts on out-of-dataset actions (CQL drives
            # this toward ~log|A| from above as it grows conservative).
            "conservative_gap": float(gap),
            "dataset_size": self._dataset_size,
            "timesteps_this_iter": 0,
            "time_this_iter_s": time.perf_counter() - start,
        }

    def mean_q_gap(self, obs) -> float:
        """Diagnostic: mean max_a Q - Q(data action is unknown here);
        used by tests to compare conservatism against plain OfflineDQN."""
        q = mlp_apply(self._learner["params"],
                      jnp.asarray(obs, jnp.float32))
        return float(jnp.mean(jnp.max(q, axis=1)))
