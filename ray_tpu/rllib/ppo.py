"""PPO: jitted Anakin-style learner + optional rollout-worker actors.

Reference parity: ``rllib/algorithms/ppo`` — clipped surrogate objective,
GAE, minibatch epochs, entropy bonus — with the TPU-native execution
model (SURVEY.md §7 step 11, Podracer split):

  * **Anakin path** (default): envs are vmapped jax code; rollout + GAE +
    the PPO epochs compile into ONE jitted ``train_iter`` — zero
    host<->device traffic per iteration. Scales with ``pmap``-free pjit
    over dp by sharding the env batch.
  * **Sebulba path** (``num_rollout_workers > 0``): RolloutWorker actors
    sample on CPU hosts with broadcast weights; the learner aggregates
    their SampleBatches and runs the same jitted update — the shape of
    the reference's WorkerSet (``rllib/evaluation/worker_set.py:77``).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu.rllib.env import CartPole, make_vec_env
from ray_tpu.rllib.optim import adam_step as _adam
from ray_tpu.rllib.sample_batch import SampleBatch


# -- model ------------------------------------------------------------------


def mlp_init(rng, sizes):
    params = []
    for i, (din, dout) in enumerate(zip(sizes[:-1], sizes[1:])):
        k1, rng = jax.random.split(rng)
        scale = np.sqrt(2.0 / din) if i < len(sizes) - 2 else 0.01
        params.append({
            "w": jax.random.normal(k1, (din, dout)) * scale,
            "b": jnp.zeros((dout,)),
        })
    return params


def mlp_apply(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


def policy_init(rng, obs_size, num_actions, hidden=(64, 64)):
    kp, kv = jax.random.split(rng)
    return {
        "pi": mlp_init(kp, (obs_size, *hidden, num_actions)),
        "vf": mlp_init(kv, (obs_size, *hidden, 1)),
    }


def policy_apply(params, obs):
    logits = mlp_apply(params["pi"], obs)
    value = mlp_apply(params["vf"], obs)[..., 0]
    return logits, value


# -- config -----------------------------------------------------------------


class PPOConfig:
    """Builder-style config (``rllib/algorithms/algorithm_config.py``)."""

    def __init__(self):
        self.env = CartPole()
        self.num_envs = 64
        self.rollout_length = 128
        self.gamma = 0.99
        self.gae_lambda = 0.95
        self.clip_param = 0.2
        self.lr = 2.5e-3
        self.entropy_coeff = 0.01
        self.vf_coeff = 0.5
        self.num_sgd_iter = 4
        self.minibatch_count = 4
        self.grad_clip = 0.5
        self.hidden_sizes = (64, 64)
        self.num_rollout_workers = 0
        self.gym_env = None  # gymnasium env id for external-env workers
        self.obs_connectors = None  # env-to-module pipeline (connectors.py)
        # Evaluation (rllib/evaluation/worker_set.py:77 analog): every
        # `evaluation_interval` train() calls, run greedy rollouts on
        # SEPARATE eval workers; results nest under result["evaluation"].
        self.evaluation_interval = 0  # 0 = never evaluate
        self.evaluation_num_workers = 1
        self.evaluation_duration = 5  # episodes per evaluation
        self.seed = 0

    def environment(self, env=None) -> "PPOConfig":
        if env is not None:
            self.env = env
        return self

    def rollouts(self, *, num_envs: Optional[int] = None,
                 rollout_length: Optional[int] = None,
                 num_rollout_workers: Optional[int] = None,
                 gym_env: Optional[str] = None,
                 obs_connectors: Optional[list] = None) -> "PPOConfig":
        if num_envs is not None:
            self.num_envs = num_envs
        if rollout_length is not None:
            self.rollout_length = rollout_length
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if gym_env is not None:
            # External-env mode (reference rollout_worker.py): workers
            # step real gymnasium envs host-side instead of the pure-jax
            # vectorized env. Requires num_rollout_workers > 0.
            self.gym_env = gym_env
        if obs_connectors is not None:
            # Env-to-module connector pipeline (reference
            # rllib/connectors): gym workers transform observations
            # before the policy sees them.
            self.obs_connectors = list(obs_connectors)
        return self

    def training(self, **kwargs) -> "PPOConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def evaluation(self, *, evaluation_interval: Optional[int] = None,
                   evaluation_num_workers: Optional[int] = None,
                   evaluation_duration: Optional[int] = None) -> "PPOConfig":
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_num_workers is not None:
            self.evaluation_num_workers = evaluation_num_workers
        if evaluation_duration is not None:
            self.evaluation_duration = evaluation_duration
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "PPOConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "PPO":
        return PPO(self)


# -- jitted train iteration -------------------------------------------------


def ppo_surrogate_loss(params, batch, *, clip_param, vf_coeff,
                       entropy_coeff):
    """The PPO loss on a flat minibatch (module-level so ddppo.py's
    decentralized workers compute the IDENTICAL objective)."""
    logits, value = policy_apply(params, batch["obs"])
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][:, None], axis=1)[:, 0]
    from ray_tpu.rllib.optim import clipped_surrogate

    pg_loss = clipped_surrogate(
        logp, batch["logp"], batch["adv"], clip_param)
    vf_loss = jnp.mean((value - batch["returns"]) ** 2)
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
    total = pg_loss + vf_coeff * vf_loss - entropy_coeff * entropy
    return total, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                   "entropy": entropy}


def _make_train_iter(cfg: PPOConfig):
    env = cfg.env
    n_envs, t_len = cfg.num_envs, cfg.rollout_length
    reset, vstep, vobs = make_vec_env(env, n_envs)

    def sample_rollout(params, states, rng):
        def step_fn(carry, _):
            states, rng = carry
            rng, k_act, k_step = jax.random.split(rng, 3)
            obs = vobs(states)
            logits, value = policy_apply(params, obs)
            action = jax.random.categorical(k_act, logits)
            logp = jax.nn.log_softmax(logits)[jnp.arange(n_envs), action]
            nxt, _, reward, done = vstep(states, action, k_step)
            out = {"obs": obs, "actions": action, "rewards": reward,
                   "dones": done, "logp": logp, "values": value}
            return (nxt, rng), out

        (states, rng), traj = jax.lax.scan(
            step_fn, (states, rng), None, length=t_len
        )
        return states, rng, traj  # traj leaves: [T, n_envs, ...]

    def compute_gae(traj, last_value):
        def scan_fn(carry, x):
            adv = carry
            reward, done, value, next_value = x
            nonterminal = 1.0 - done.astype(jnp.float32)
            delta = reward + cfg.gamma * next_value * nonterminal - value
            adv = delta + cfg.gamma * cfg.gae_lambda * nonterminal * adv
            return adv, adv

        values = traj["values"]
        next_values = jnp.concatenate([values[1:], last_value[None]], axis=0)
        _, advs = jax.lax.scan(
            scan_fn,
            jnp.zeros_like(last_value),
            (traj["rewards"], traj["dones"], values, next_values),
            reverse=True,
        )
        return advs, advs + values

    def ppo_loss(params, batch):
        return ppo_surrogate_loss(
            params, batch, clip_param=cfg.clip_param,
            vf_coeff=cfg.vf_coeff, entropy_coeff=cfg.entropy_coeff)

    def adam_step(params, opt, grads):
        return _adam(params, opt, grads, lr=cfg.lr,
                     max_grad_norm=cfg.grad_clip, eps=1e-5)

    def sgd_on_batch(params, opt, flat, rng):
        n = flat["obs"].shape[0]
        mb = n // cfg.minibatch_count

        def epoch(carry, _):
            params, opt, rng = carry
            rng, k = jax.random.split(rng)
            perm = jax.random.permutation(k, n)

            def mb_step(carry, i):
                params, opt = carry
                idx = jax.lax.dynamic_slice_in_dim(perm, i * mb, mb)
                batch = jax.tree.map(lambda x: x[idx], flat)
                (_, aux), grads = jax.value_and_grad(ppo_loss, has_aux=True)(
                    params, batch
                )
                params, opt = adam_step(params, opt, grads)
                return (params, opt), aux

            (params, opt), auxs = jax.lax.scan(
                mb_step, (params, opt), jnp.arange(cfg.minibatch_count)
            )
            return (params, opt, rng), auxs

        (params, opt, rng), auxs = jax.lax.scan(
            epoch, (params, opt, rng), None, length=cfg.num_sgd_iter
        )
        return params, opt, jax.tree.map(lambda x: x[-1, -1], auxs)

    @jax.jit
    def train_iter(params, opt, states, rng):
        states, rng, traj = sample_rollout(params, states, rng)
        _, last_value = policy_apply(params, vobs(states))
        advs, returns = compute_gae(traj, last_value)
        flat = {
            "obs": traj["obs"].reshape(-1, env.observation_size),
            "actions": traj["actions"].reshape(-1),
            "logp": traj["logp"].reshape(-1),
            "adv": advs.reshape(-1),
            "returns": returns.reshape(-1),
        }
        rng, k = jax.random.split(rng)
        params, opt, aux = sgd_on_batch(params, opt, flat, k)
        metrics = {
            "episode_reward_mean": _episode_reward(traj),
            **aux,
        }
        return params, opt, states, rng, metrics

    def _episode_reward(traj):
        # Mean undiscounted return of episodes that ENDED in this rollout;
        # approximated as steps / episodes (reward is 1/step for CartPole).
        dones = traj["dones"].astype(jnp.float32)
        n_done = jnp.maximum(jnp.sum(dones), 1.0)
        return (t_len * n_envs) / n_done

    @jax.jit
    def update_only(params, opt, flat, rng):
        return sgd_on_batch(params, opt, flat, rng)

    return reset, train_iter, update_only, sample_rollout, compute_gae, vobs


# -- rollout worker (Sebulba path) -----------------------------------------


def _make_greedy_eval(cfg: "PPOConfig"):
    """Jitted greedy evaluation on the pure-jax env (the in-process
    analog of the reference's explore=False eval workers)."""
    env = cfg.env
    n = cfg.num_envs
    reset, vstep, vobs = make_vec_env(env, n)
    T = cfg.rollout_length * 2

    @jax.jit
    def eval_iter(params, rng):
        states = reset(rng)

        def step_fn(carry, _):
            states, rng = carry
            rng, k_step = jax.random.split(rng)
            logits, _v = policy_apply(params, vobs(states))
            action = jnp.argmax(logits, axis=-1)
            states, _, reward, done = vstep(states, action, k_step)
            return (states, rng), (reward, done)

        _, (rewards, dones) = jax.lax.scan(
            step_fn, (states, rng), None, length=T)
        return rewards.sum(), dones.sum()

    return eval_iter


class RolloutWorker:
    """Actor sampling with its own env batch (WorkerSet parity)."""

    def __init__(self, cfg_dict: dict, seed: int):
        cfg = PPOConfig()
        cfg.__dict__.update(cfg_dict)
        cfg.num_rollout_workers = 0
        self.cfg = cfg
        (self.reset, _, _, self.sample_rollout, self.compute_gae,
         self.vobs) = _make_train_iter(cfg)
        self.rng = jax.random.key(seed)
        self.states = self.reset(jax.random.key(seed + 1))

    def sample(self, params) -> dict:
        self.states, self.rng, traj = jax.jit(self.sample_rollout)(
            params, self.states, self.rng
        )
        _, last_value = policy_apply(params, self.vobs(self.states))
        advs, returns = self.compute_gae(traj, last_value)
        return {
            "obs": np.asarray(traj["obs"]).reshape(-1, self.cfg.env.observation_size),
            "actions": np.asarray(traj["actions"]).reshape(-1),
            "logp": np.asarray(traj["logp"]).reshape(-1),
            "adv": np.asarray(advs).reshape(-1),
            "returns": np.asarray(returns).reshape(-1),
            "dones_sum": float(np.asarray(traj["dones"]).sum()),
        }


# -- algorithm --------------------------------------------------------------


class PPO:
    """Algorithm: ``.train()`` one iteration -> result dict
    (``rllib/algorithms/algorithm.py:142`` Trainable contract)."""

    def __init__(self, config: PPOConfig):
        self.config = config
        rng = jax.random.key(config.seed)
        k_param, k_env, self._rng = jax.random.split(rng, 3)
        gym_mode = bool(getattr(config, "gym_env", None))
        if gym_mode and config.num_rollout_workers <= 0:
            raise ValueError(
                "gym_env requires num_rollout_workers > 0 — external "
                "gymnasium envs are stepped by worker actors, not by the "
                "jitted local sampler"
            )
        if gym_mode:
            # Policy geometry comes from the GYM env's spaces, not the
            # (unused) jax env default.
            import gymnasium as gym

            probe = gym.make(config.gym_env)
            obs_size = int(probe.observation_space.shape[0])
            num_actions = int(probe.action_space.n)
            probe.close()
            if config.obs_connectors:
                # Shape-changing connectors (FrameStack, Flatten...) set
                # the POLICY's input width: probe the pipeline with a
                # batch shaped like the workers' (stateful connectors are
                # batch-shape-bound).
                import numpy as _np

                from ray_tpu.rllib.connectors import ConnectorPipeline

                pipe = ConnectorPipeline(list(config.obs_connectors))
                _, out = pipe(
                    pipe.init(),
                    _np.zeros((config.num_envs, obs_size), _np.float32))
                obs_size = int(_np.asarray(out).shape[-1])
                self._infer_pipe = pipe
            else:
                self._infer_pipe = None
            self._infer_state = None
        else:
            obs_size = config.env.observation_size
            num_actions = config.env.num_actions
        self.params = policy_init(
            k_param, obs_size, num_actions, config.hidden_sizes,
        )
        self.opt = {
            "mu": jax.tree.map(jnp.zeros_like, self.params),
            "nu": jax.tree.map(jnp.zeros_like, self.params),
            "t": jnp.zeros((), jnp.int32),
        }
        pieces = _make_train_iter(config)
        self._reset, self._train_iter, self._update_only = pieces[0:3]
        # Worker modes never use the local jitted sampler: skip building
        # (and compiling) its env-state batch.
        self._states = (None if config.num_rollout_workers > 0
                        else self._reset(k_env))
        self._iteration = 0
        self._eval_set = None
        if config.evaluation_interval > 0:
            if gym_mode:
                from ray_tpu.rllib.evaluation import EvaluationWorkerSet

                self._eval_set = EvaluationWorkerSet(
                    config.gym_env,
                    num_workers=config.evaluation_num_workers,
                    duration_episodes=config.evaluation_duration,
                    seed=config.seed,
                    obs_connectors=config.obs_connectors,
                )
            else:
                # Pure-jax env: greedy eval rollout, jitted once.
                self._eval_iter = _make_greedy_eval(config)
        self._workers: List = []
        if config.num_rollout_workers > 0:
            if getattr(config, "gym_env", None):
                from ray_tpu.rllib.gym_env import GymRolloutWorker

                worker_cls = ray_tpu.remote(GymRolloutWorker)
                self._workers = [
                    worker_cls.remote(
                        config.gym_env,
                        num_envs=config.num_envs,
                        rollout_length=config.rollout_length,
                        gamma=config.gamma,
                        gae_lambda=config.gae_lambda,
                        seed=config.seed + 100 + i,
                        obs_connectors=config.obs_connectors,
                    )
                    for i in range(config.num_rollout_workers)
                ]
            else:
                worker_cls = ray_tpu.remote(RolloutWorker)
                # FULL config crosses (env included) — workers must
                # sample the configured env, not a rebuilt default.
                cfg_dict = dict(config.__dict__)
                self._workers = [
                    worker_cls.remote(cfg_dict, config.seed + 100 + i)
                    for i in range(config.num_rollout_workers)
                ]

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        if self._workers:
            batches = ray_tpu.get(
                [w.sample.remote(self.params) for w in self._workers],
                timeout=300,
            )
            flat = {
                k: np.concatenate([b[k] for b in batches])
                for k in ("obs", "actions", "logp", "adv", "returns")
            }
            flat = {k: jnp.asarray(v) for k, v in flat.items()}
            self._rng, k = jax.random.split(self._rng)
            self.params, self.opt, aux = self._update_only(
                self.params, self.opt, flat, k
            )
            steps = flat["obs"].shape[0]
            if "episode_return_sum" in batches[0]:
                # Real per-episode returns (gym workers report them).
                n_done = max(1.0, sum(b["episodes_done"] for b in batches))
                reward_mean = sum(
                    b["episode_return_sum"] for b in batches) / n_done
            else:
                # +1-per-step envs only (builtin CartPole): episode
                # length == return.
                n_done = max(1.0, sum(b["dones_sum"] for b in batches))
                reward_mean = steps / n_done
            metrics = {k: float(v) for k, v in aux.items()}
        else:
            (self.params, self.opt, self._states, self._rng,
             metrics) = self._train_iter(
                self.params, self.opt, self._states, self._rng
            )
            steps = self.config.num_envs * self.config.rollout_length
            reward_mean = float(metrics.pop("episode_reward_mean"))
            metrics = {k: float(v) for k, v in metrics.items()}
        self._iteration += 1
        result = {
            "training_iteration": self._iteration,
            "episode_reward_mean": reward_mean,
            "timesteps_this_iter": int(steps),
            "time_this_iter_s": time.perf_counter() - start,
            **metrics,
        }
        interval = self.config.evaluation_interval
        if interval > 0 and self._iteration % interval == 0:
            # Separate workers/config (greedy, no exploration): eval
            # metrics stay distinct from training sample stats.
            if self._eval_set is not None:
                result["evaluation"] = self._eval_set.evaluate(self.params)
            else:
                self._rng, k = jax.random.split(self._rng)
                rsum, ndone = self._eval_iter(self.params, k)
                ndone = max(1.0, float(ndone))
                result["evaluation"] = {
                    "episode_reward_mean": float(rsum) / ndone,
                    "episodes_this_eval": int(ndone),
                }
        return result

    # Trainable contract: save/restore.
    def save(self) -> dict:
        out = {
            "params": jax.tree.map(np.asarray, self.params),
            "iteration": self._iteration,
        }
        if getattr(self, "_infer_pipe", None) is not None and self._workers:
            # Connector state (running obs stats etc.) checkpoints with
            # the policy — worker 0's view (per-worker stats, like the
            # reference's per-worker observation filters).
            try:
                out["connector_state"] = ray_tpu.get(
                    self._workers[0].get_connector_state.remote(),
                    timeout=30)
            except Exception:
                pass
        return out

    def restore(self, state: dict) -> None:
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self._iteration = state["iteration"]
        cs = state.get("connector_state")
        if cs is not None:
            self._infer_state = cs
            for w in self._workers:
                try:
                    ray_tpu.get(
                        w.set_connector_state.remote(cs), timeout=30)
                except Exception:
                    pass

    def stop(self) -> None:
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass

    def compute_single_action(self, obs) -> int:
        obs = jnp.asarray(obs)[None]
        pipe = getattr(self, "_infer_pipe", None)
        if pipe is not None:
            # Inference applies the SAME env-to-module pipeline the
            # policy trained through, with frozen stats (pulled from
            # worker 0 lazily, or set by restore()).
            if self._infer_state is None and self._workers:
                try:
                    self._infer_state = ray_tpu.get(
                        self._workers[0].get_connector_state.remote(),
                        timeout=30)
                except Exception:
                    pass
            state = (self._infer_state if self._infer_state is not None
                     else pipe.init())
            import numpy as _np

            _, out = pipe(state, _np.asarray(obs, _np.float32))
            obs = jnp.asarray(out)
        logits, _ = policy_apply(self.params, obs)
        return int(jnp.argmax(logits[0]))
