"""SampleBatch: the dict-of-arrays experience container.

Reference parity: ``rllib/policy/sample_batch.py`` — named columns,
concat, row count, minibatch slicing, shuffling.
"""

from __future__ import annotations

from typing import Dict, Iterator, List

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
LOGPS = "action_logp"
VALUES = "vf_preds"
ADVANTAGES = "advantages"
RETURNS = "value_targets"


class SampleBatch(dict):
    @property
    def count(self) -> int:
        if not self:
            return 0
        return len(next(iter(self.values())))

    @staticmethod
    def concat_samples(batches: List["SampleBatch"]) -> "SampleBatch":
        keys = batches[0].keys()
        return SampleBatch(
            {k: np.concatenate([np.asarray(b[k]) for b in batches]) for k in keys}
        )

    def shuffle(self, rng: np.random.Generator) -> "SampleBatch":
        perm = rng.permutation(self.count)
        return SampleBatch({k: np.asarray(v)[perm] for k, v in self.items()})

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        n = self.count
        for start in range(0, n - size + 1, size):
            yield SampleBatch(
                {k: np.asarray(v)[start : start + size] for k, v in self.items()}
            )

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: np.asarray(v)[start:end] for k, v in self.items()})
