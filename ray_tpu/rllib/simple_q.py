"""SimpleQ: plain deep Q-learning (reference
``rllib/algorithms/simple_q/simple_q.py``) — the reference keeps the
un-extended Q-learner as its own algorithm (the DQN class ADDS double-Q,
dueling, n-step, prioritized replay on top of it); here the relationship
is expressed the jax way: SimpleQ is the ``double_q=False`` point of the
same jitted DQN program, so the TD target is the overestimating
``max_a Q_target(s', a)`` instead of the decoupled argmax/eval pair.
"""

from __future__ import annotations

from ray_tpu.rllib.dqn import DQN, DQNConfig

__all__ = ["SimpleQ", "SimpleQConfig"]


class SimpleQConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.double_q = False

    def build(self) -> "SimpleQ":
        return SimpleQ(self)


class SimpleQ(DQN):
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""
