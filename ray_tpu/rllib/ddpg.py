"""DDPG: deep deterministic policy gradient (reference
``rllib/algorithms/ddpg/ddpg.py``). Historically DDPG came first and TD3
is "DDPG + three tricks"; the reference implements them as separate
algorithms sharing a policy class. Here the lineage runs the other way
through config space — DDPG is the TD3 program with every trick turned
off: no target-policy smoothing (``target_noise=0``), no delayed actor
(``policy_delay=1``), a single critic (``twin_q=False``). The jitted
train iteration, replay buffer, and Polyak targets are shared code.
"""

from __future__ import annotations

from ray_tpu.rllib.td3 import TD3, TD3Config

__all__ = ["DDPG", "DDPGConfig"]


class DDPGConfig(TD3Config):
    def __init__(self):
        super().__init__()
        self.target_noise = 0.0
        self.target_noise_clip = 0.0
        self.policy_delay = 1
        self.twin_q = False

    def build(self) -> "DDPG":
        return DDPG(self)


class DDPG(TD3):
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""
