"""Evaluation workers: rollouts with frozen, (near-)greedy policies,
separate from training sample collection.

Reference parity: ``rllib/evaluation/worker_set.py:77`` (the evaluation
WorkerSet an Algorithm keeps NEXT TO its training workers) +
``algorithm.py`` ``evaluation_interval`` / ``evaluation_duration``
handling — eval metrics are collected with their own workers/config and
nested under ``result["evaluation"]`` so training throughput and eval
quality never contaminate each other.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu


class EvalWorker:
    """Steps a gymnasium env with the given params for N episodes.
    Greedy (argmax) by default — evaluation measures the policy, not the
    exploration noise (reference: ``explore=False`` eval config)."""

    def __init__(self, env_name: str, *, seed: int = 0,
                 obs_connectors: Optional[list] = None,
                 greedy: bool = True, max_steps: int = 1000):
        import gymnasium as gym

        self.env = gym.make(env_name)
        self.greedy = greedy
        self.max_steps = max_steps
        self.seed = seed
        self._apply = None
        if obs_connectors:
            from ray_tpu.rllib.connectors import ConnectorPipeline

            self._pipe = ConnectorPipeline(list(obs_connectors))
            self._pipe_state = self._pipe.init()
        else:
            self._pipe = None

    def _transform(self, obs: np.ndarray) -> np.ndarray:
        row = obs[None].astype(np.float32)
        if self._pipe is None:
            return row
        self._pipe_state, out = self._pipe(self._pipe_state, row)
        return np.asarray(out, np.float32)

    def evaluate(self, params, num_episodes: int = 5) -> dict:
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.ppo import policy_apply

        if self._apply is None:
            self._apply = jax.jit(policy_apply)
        rng = np.random.default_rng(self.seed)
        returns: List[float] = []
        lengths: List[int] = []
        for ep in range(num_episodes):
            if self._pipe is not None:
                self._pipe_state = self._pipe.init()  # fresh episode stats
            obs, _ = self.env.reset(seed=self.seed + 1000 * ep)
            total, steps = 0.0, 0
            for _ in range(self.max_steps):
                logits, _v = self._apply(
                    params, jnp.asarray(self._transform(obs)))
                logits = np.asarray(logits)[0]
                if self.greedy:
                    action = int(np.argmax(logits))
                else:
                    g = rng.gumbel(size=logits.shape)
                    action = int(np.argmax(logits + g))
                obs, reward, term, trunc, _ = self.env.step(action)
                total += float(reward)
                steps += 1
                if term or trunc:
                    break
            returns.append(total)
            lengths.append(steps)
        return {"episode_returns": returns, "episode_lengths": lengths}


class EvaluationWorkerSet:
    """The eval half of the reference's WorkerSet: owns its actors, its
    own config (greedy, duration), aggregates across workers."""

    def __init__(self, env_name: str, *, num_workers: int = 1,
                 duration_episodes: int = 5, seed: int = 0,
                 obs_connectors: Optional[list] = None,
                 greedy: bool = True):
        cls = ray_tpu.remote(EvalWorker)
        self.duration = duration_episodes
        self._workers = [
            cls.remote(env_name, seed=seed + 7000 + i,
                       obs_connectors=obs_connectors, greedy=greedy)
            for i in range(max(1, num_workers))
        ]

    def evaluate(self, params) -> Dict[str, Any]:
        # Distribute duration_episodes exactly: base episodes everywhere,
        # remainder to the first workers (5 episodes / 2 workers = 3+2,
        # not 2+2).
        n = len(self._workers)
        base, rem = divmod(max(self.duration, n), n)
        outs = ray_tpu.get(
            [w.evaluate.remote(params, base + (1 if i < rem else 0))
             for i, w in enumerate(self._workers)],
            timeout=300)
        returns = [r for o in outs for r in o["episode_returns"]]
        lengths = [l for o in outs for l in o["episode_lengths"]]
        return {
            "episode_reward_mean": float(np.mean(returns)),
            "episode_reward_min": float(np.min(returns)),
            "episode_reward_max": float(np.max(returns)),
            "episode_len_mean": float(np.mean(lengths)),
            "episodes_this_eval": len(returns),
        }
