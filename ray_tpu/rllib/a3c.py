"""A3C: ASYNCHRONOUS advantage actor-critic (reference
``rllib/algorithms/a3c/a3c.py``) — the HogWild ancestor of A2C. The
reference's execution plan is exactly "workers compute gradients on
their own rollouts against a stale parameter snapshot; the learner
applies each gradient the moment it arrives" (``a3c.py``'s
``training_step`` waits on ``ray.wait`` for the next gradient, applies,
and re-dispatches THAT worker) — no synchronization barrier, which is
the entire difference from A2C.

Mapped here: worker actors run A2C's factored-out ``_make_grad_fn`` (the
same jitted rollout+gradient program the synchronous learner uses, so
A2C and A3C provably optimize the same objective), the learner loop is
``ray_tpu.wait(num_returns=1)`` -> adam -> redispatch with fresh
params. With ``num_rollout_workers=0`` it degenerates to exactly A2C.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu.rllib.a2c import A2C, A2CConfig, _make_grad_fn
from ray_tpu.rllib.optim import adam_step as _adam

__all__ = ["A3C", "A3CConfig"]


class A3CConfig(A2CConfig):
    def __init__(self):
        super().__init__()
        self.num_rollout_workers = 2
        self.grads_per_iter = 8     # async applies per .train() call

    def rollouts(self, *, num_envs: Optional[int] = None,
                 rollout_length: Optional[int] = None,
                 num_rollout_workers: Optional[int] = None) -> "A3CConfig":
        super().rollouts(num_envs=num_envs, rollout_length=rollout_length)
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        return self

    def build(self) -> "A3C":
        return A3C(self)


class A3CGradientWorker:
    """Actor computing A2C gradients on a stale parameter snapshot."""

    def __init__(self, cfg_dict: dict, seed: int):
        cfg = A2CConfig()
        for k, v in cfg_dict.items():
            if hasattr(cfg, k):
                setattr(cfg, k, v)
        self.cfg = cfg
        reset, self._grad_fn = _make_grad_fn(cfg)
        self.rng = jax.random.key(seed)
        self.states = reset(jax.random.key(seed + 1))

    def compute_grads(self, params) -> dict:
        grads, self.states, self.rng, metrics = self._grad_fn(
            params, self.states, self.rng)
        return {"grads": jax.tree.map(np.asarray, grads),
                "metrics": {k: float(v) for k, v in metrics.items()}}


class A3C(A2C):
    """Algorithm (Trainable contract): async gradient application when
    workers are configured, plain A2C otherwise."""

    def __init__(self, config: A3CConfig):
        super().__init__(config)
        self._workers: List = []
        self._inflight: Dict = {}
        if config.num_rollout_workers > 0:
            worker_cls = ray_tpu.remote(A3CGradientWorker)
            self._workers = [
                worker_cls.remote(dict(config.__dict__),
                                  config.seed + 100 + i)
                for i in range(config.num_rollout_workers)
            ]
            self._apply = jax.jit(
                lambda p, o, g: _adam(p, o, g, lr=config.lr,
                                      max_grad_norm=config.grad_clip,
                                      eps=1e-5))

    def train(self) -> Dict[str, Any]:
        if not self._workers:
            return super().train()
        cfg = self.config
        start = time.perf_counter()
        if not self._inflight:
            self._inflight = {
                w.compute_grads.remote(self.params): w
                for w in self._workers}
        applied, last_metrics = 0, {}
        while applied < cfg.grads_per_iter:
            # The A3C kernel: take whichever worker finishes FIRST,
            # apply its (stale) gradient, send it fresh params.
            ready, _ = ray_tpu.wait(
                list(self._inflight), num_returns=1, timeout=120)
            if not ready:
                raise TimeoutError("A3C worker stalled")
            ref = ready[0]
            worker = self._inflight.pop(ref)
            out = ray_tpu.get(ref, timeout=60)
            grads = jax.tree.map(jnp.asarray, out["grads"])
            self.params, self.opt = self._apply(
                self.params, self.opt, grads)
            last_metrics = out["metrics"]
            applied += 1
            self._inflight[worker.compute_grads.remote(self.params)] = \
                worker
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter":
                applied * cfg.num_envs * cfg.rollout_length,
            "gradients_applied": applied,
            "time_this_iter_s": time.perf_counter() - start,
            **last_metrics,
        }
