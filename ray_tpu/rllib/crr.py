"""CRR: critic-regularized regression (reference
``rllib/algorithms/crr/crr.py``, after Wang et al. 2020) — the
CONTINUOUS-control member of the offline family, next to discrete CQL
and the sequence-model DT. The actor never maximizes Q directly (the
exploitation that detonates on out-of-distribution actions offline);
it does weighted behavior cloning where the critic supplies the
weights:

    L_actor = -E_data[ f(A(s, a)) * log pi(a | s) ]

with ``f`` the paper's variants: ``binary`` 1[A > 0] (clone only
better-than-policy actions), ``exp`` exp(A / beta) clipped, and ``bc``
f == 1 — plain behavior cloning, kept as the ablation point the tests
compare against (the same relationship SimpleQ/DDPG have to their
descendants). The critic is SARSA-style twin TD on dataset actions with
policy actions only at s' — never an argmax over actions.

The policy is the DETERMINISTIC variant (weighted regression on the
action mean, CWBC-style): with a Gaussian density the NLL objective
fits sigma where the mean is hard to fit, which on discontinuous
controllers (bang-bang energy pumping) buries the very actions worth
cloning — measured in ``tests/test_rllib_crr.py``'s development: NLL
cloning of a swingup expert evals at -606 vs -145 for regression.

Everything (twin critics, a mean-only MLP actor head, Polyak targets,
minibatch updates over the on-device dataset) runs as one jitted scan
per ``.train()``.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.env import Pendulum
from ray_tpu.rllib.optim import adam_init, adam_step
from ray_tpu.rllib.ppo import mlp_apply, mlp_init
from ray_tpu.rllib.sac import critic_apply, critic_init

__all__ = ["CRR", "CRRConfig"]


class CRRConfig:
    """Builder-style config (``CRRConfig().training(mode="binary")``)."""

    def __init__(self):
        self.env = Pendulum()
        self.mode = "binary"        # "bc" | "binary" | "exp"
        self.beta = 1.0             # exp-mode temperature
        self.exp_clip = 20.0
        self.m_samples = 4          # policy samples for the A baseline
        self.baseline_noise = 0.3   # exploration noise for those samples
        self.gamma = 0.95           # short horizon: offline critic
        self.tau = 0.02              # converges in few passes
        self.actor_lr = 3e-4
        self.critic_lr = 1e-3
        self.hidden_sizes = (128, 128)
        self.batch_size = 256
        self.updates_per_iter = 500
        self.action_scale = 2.0
        self.seed = 0

    def environment(self, env=None) -> "CRRConfig":
        if env is not None:
            self.env = env
        return self

    def training(self, **kwargs) -> "CRRConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown CRR option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "CRRConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self, dataset: Dict[str, np.ndarray]) -> "CRR":
        return CRR(self, dataset)


def _actor_sample_clipped(params, obs, rng, scale, noise: float):
    """Mean action + fixed exploration noise, clipped to the bounds —
    how the advantage baseline and the critic's s' actions are drawn."""
    mean = mlp_apply(params, obs)
    a = mean + noise * jax.random.normal(rng, mean.shape)
    return jnp.clip(a, -scale, scale)


class CRR:
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""

    def __init__(self, config: CRRConfig, dataset: Dict[str, np.ndarray]):
        self.config = config
        env = config.env
        obs_size, act_size = env.observation_size, env.action_size
        rng = jax.random.key(config.seed)
        ka, kc, self._rng = jax.random.split(rng, 3)
        actor = mlp_init(
            ka, (obs_size, *config.hidden_sizes, act_size))
        critic = critic_init(kc, obs_size, act_size, config.hidden_sizes)
        self._learner = {
            "actor": actor,
            "critic": critic,
            "target_critic": jax.tree.map(jnp.copy, critic),
            "aopt": adam_init(actor),
            "copt": adam_init(critic),
        }
        self._data = {
            k: jnp.asarray(np.asarray(v, np.float32))
            for k, v in dataset.items()}
        self._n = int(self._data["obs"].shape[0])
        self._train_iter = self._build()
        self._iteration = 0

    def _build(self):
        cfg = self.config
        scale = cfg.action_scale
        n = self._n

        def critic_loss(cp, learner, batch, k):
            next_act = _actor_sample_clipped(
                learner["actor"], batch["nobs"], k, scale,
                cfg.baseline_noise)
            tq1, tq2 = critic_apply(
                learner["target_critic"], batch["nobs"], next_act)
            y = batch["rew"] + cfg.gamma * (1 - batch["done"]) * \
                jax.lax.stop_gradient(jnp.minimum(tq1, tq2))
            q1, q2 = critic_apply(cp, batch["obs"], batch["act"])
            return jnp.mean((q1 - y) ** 2 + (q2 - y) ** 2)

        def advantage(learner, batch, k):
            """A(s, a_data) = Q(s, a_data) - mean_m Q(s, a ~ pi)."""
            q1, q2 = critic_apply(
                learner["critic"], batch["obs"], batch["act"])
            q_data = jnp.minimum(q1, q2)
            qs = []
            for i in range(cfg.m_samples):
                a_pi = _actor_sample_clipped(
                    learner["actor"], batch["obs"],
                    jax.random.fold_in(k, i), scale, cfg.baseline_noise)
                p1, p2 = critic_apply(
                    learner["critic"], batch["obs"], a_pi)
                qs.append(jnp.minimum(p1, p2))
            return q_data - jnp.mean(jnp.stack(qs), axis=0)

        def actor_loss(ap, learner, batch, k):
            # Weighted REGRESSION on dataset actions (the deterministic
            # CRR variant): measured here, Gaussian-NLL cloning lets the
            # net inflate sigma instead of fitting a discontinuous
            # controller's mean (BC-on-expert: NLL -606 vs MSE -145 on
            # Pendulum swingup), so the density form buries exactly the
            # sharp-switching actions worth cloning.
            mean = mlp_apply(ap, batch["obs"])
            mse = jnp.sum((mean - batch["act"]) ** 2, axis=-1)
            adv = jax.lax.stop_gradient(
                advantage(dict(learner, actor=ap), batch, k))
            if cfg.mode == "bc":
                w = jnp.ones_like(adv)
            elif cfg.mode == "binary":
                w = (adv > 0).astype(jnp.float32)
            else:  # exp
                w = jnp.clip(jnp.exp(adv / cfg.beta), 0.0, cfg.exp_clip)
            return jnp.mean(w * mse), jnp.mean(w)

        @jax.jit
        def train_iter(learner, data, rng):
            def update(carry, _):
                learner, rng = carry
                rng, k_idx, k_c, k_a = jax.random.split(rng, 4)
                idx = jax.random.randint(
                    k_idx, (cfg.batch_size,), 0, n)
                batch = {k: v[idx] for k, v in data.items()}
                closs, cg = jax.value_and_grad(critic_loss)(
                    learner["critic"], learner, batch, k_c)
                critic, copt = adam_step(
                    learner["critic"], learner["copt"], cg,
                    lr=cfg.critic_lr)
                (aloss, w_mean), ag = jax.value_and_grad(
                    actor_loss, has_aux=True)(
                    learner["actor"], learner, batch, k_a)
                actor, aopt = adam_step(
                    learner["actor"], learner["aopt"], ag,
                    lr=cfg.actor_lr)
                target = jax.tree.map(
                    lambda t, p: (1 - cfg.tau) * t + cfg.tau * p,
                    learner["target_critic"], critic)
                learner = dict(learner, actor=actor, critic=critic,
                               aopt=aopt, copt=copt, target_critic=target)
                return (learner, rng), {"critic_loss": closs,
                                        "actor_loss": aloss,
                                        "weight_mean": w_mean}

            (learner, rng), metrics = jax.lax.scan(
                update, (learner, rng), None, length=cfg.updates_per_iter)
            return learner, rng, jax.tree.map(jnp.mean, metrics)

        return train_iter

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        self._learner, self._rng, metrics = self._train_iter(
            self._learner, self._data, self._rng)
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(v) for k, v in metrics.items()},
        }

    def evaluate(self, n_episodes: int = 4, seed: int = 9) -> float:
        """Greedy (clipped-mean) rollout return on the real env."""
        cfg = self.config
        env = cfg.env
        total = 0.0
        for ep in range(n_episodes):
            rng = jax.random.key(seed + ep)
            s = env.reset(rng)
            ret = 0.0
            for _ in range(200):
                mean = mlp_apply(self._learner["actor"],
                                 env.obs(s)[None])
                a = jnp.clip(mean[0], -cfg.action_scale, cfg.action_scale)
                rng, k = jax.random.split(rng)
                s, _, rew, done = env.step(s, a, k)
                ret += float(rew)
                if bool(done):
                    break
            total += ret
        return total / n_episodes
