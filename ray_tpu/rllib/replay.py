"""Shared on-device replay buffer (one copy for dqn/sac — the
``utils/replay_buffers`` analog, jit-native: a plain pytree of
fixed-shape arrays with ring-buffer add and uniform sampling)."""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def buffer_init(capacity: int, fields: Dict[str, Tuple[int, ...]],
                dtypes: Dict[str, object] | None = None) -> dict:
    """``fields``: name -> per-item trailing shape (() for scalars)."""
    dtypes = dtypes or {}
    buf = {
        name: jnp.zeros((capacity, *shape),
                        dtypes.get(name, jnp.float32))
        for name, shape in fields.items()
    }
    buf["ptr"] = jnp.zeros((), jnp.int32)
    buf["size"] = jnp.zeros((), jnp.int32)
    return buf


def buffer_add(buf: dict, capacity: int, **items) -> dict:
    """Append a batch of items (arrays [n_new, ...]); ring-wraps."""
    n_new = next(iter(items.values())).shape[0]
    idx = (buf["ptr"] + jnp.arange(n_new)) % capacity
    out = dict(buf)
    for name, value in items.items():
        out[name] = buf[name].at[idx].set(value)
    out["ptr"] = (buf["ptr"] + n_new) % capacity
    out["size"] = jnp.minimum(buf["size"] + n_new, capacity)
    return out


def buffer_sample(buf: dict, rng, batch_size: int,
                  fields: Tuple[str, ...]) -> dict:
    """Uniform sample over the filled region (valid once size >= 1;
    callers gate updates on their own learning_starts threshold)."""
    idx = jax.random.randint(
        rng, (batch_size,), 0, jnp.maximum(buf["size"], 1))
    return {name: buf[name][idx] for name in fields}
