"""Shared on-device replay buffer (one copy for dqn/sac — the
``utils/replay_buffers`` analog, jit-native: a plain pytree of
fixed-shape arrays with ring-buffer add and uniform sampling)."""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def buffer_init(capacity: int, fields: Dict[str, Tuple[int, ...]],
                dtypes: Dict[str, object] | None = None) -> dict:
    """``fields``: name -> per-item trailing shape (() for scalars)."""
    dtypes = dtypes or {}
    buf = {
        name: jnp.zeros((capacity, *shape),
                        dtypes.get(name, jnp.float32))
        for name, shape in fields.items()
    }
    buf["ptr"] = jnp.zeros((), jnp.int32)
    buf["size"] = jnp.zeros((), jnp.int32)
    return buf


def buffer_add(buf: dict, capacity: int, **items) -> dict:
    """Append a batch of items (arrays [n_new, ...]); ring-wraps."""
    n_new = next(iter(items.values())).shape[0]
    idx = (buf["ptr"] + jnp.arange(n_new)) % capacity
    out = dict(buf)
    for name, value in items.items():
        out[name] = buf[name].at[idx].set(value)
    out["ptr"] = (buf["ptr"] + n_new) % capacity
    out["size"] = jnp.minimum(buf["size"] + n_new, capacity)
    return out


def buffer_sample(buf: dict, rng, batch_size: int,
                  fields: Tuple[str, ...]) -> dict:
    """Uniform sample over the filled region (valid once size >= 1;
    callers gate updates on their own learning_starts threshold)."""
    idx = jax.random.randint(
        rng, (batch_size,), 0, jnp.maximum(buf["size"], 1))
    return {name: buf[name][idx] for name in fields}


# -- prioritized variant (Ape-X / PER; reference
# ``utils/replay_buffers/prioritized_replay_buffer.py``) ------------------
#
# The reference uses a segment tree for O(log n) sampling on the host; on
# an accelerator the O(n) normalized-categorical draw over the whole
# priority vector is a single fused reduction + gumbel top-k, which at
# these capacities is faster than pointer chasing would be — so the jax
# design drops the tree entirely.


def pbuffer_init(capacity: int, fields: Dict[str, Tuple[int, ...]],
                 dtypes: Dict[str, object] | None = None) -> dict:
    buf = buffer_init(capacity, fields, dtypes)
    buf["priority"] = jnp.zeros((capacity,))
    buf["max_priority"] = jnp.ones(())
    return buf


def pbuffer_add(buf: dict, capacity: int, **items) -> dict:
    """New items enter at the running max priority so every transition
    is sampled at least once before its TD error takes over."""
    n_new = next(iter(items.values())).shape[0]
    idx = (buf["ptr"] + jnp.arange(n_new)) % capacity
    out = buffer_add(buf, capacity, **items)
    out["priority"] = out["priority"].at[idx].set(buf["max_priority"])
    return out


def pbuffer_sample(buf: dict, rng, batch_size: int,
                   fields: Tuple[str, ...], *, alpha: float = 0.6,
                   beta: float = 0.4) -> dict:
    """Sample ~ p^alpha; returns the batch plus ``indices`` and the
    importance weights ``weights`` (max-normalized, (N*P)^-beta)."""
    capacity = buf["priority"].shape[0]
    # Like buffer_sample, valid once size >= 1 — but fail SAFE on an
    # empty buffer: slot 0 stays sampleable so the categorical draw and
    # the weights are finite (all-(-inf) logits would yield NaN weights
    # that no ready-gating downstream could mask out, since NaN*0=NaN).
    valid = jnp.arange(capacity) < jnp.maximum(buf["size"], 1)
    logits = jnp.where(
        valid, alpha * jnp.log(jnp.maximum(buf["priority"], 1e-12)),
        -jnp.inf)
    idx = jax.random.categorical(rng, logits, shape=(batch_size,))
    probs = jax.nn.softmax(logits)
    n = jnp.maximum(buf["size"], 1).astype(jnp.float32)
    w = (n * jnp.maximum(probs[idx], 1e-12)) ** (-beta)
    out = {name: buf[name][idx] for name in fields}
    out["indices"] = idx
    out["weights"] = w / jnp.maximum(jnp.max(w), 1e-12)
    return out


def pbuffer_update_priorities(buf: dict, indices, priorities,
                              eps: float = 1e-3) -> dict:
    """Write |priorities| + eps at ``indices``. Pass ``eps=0.0`` when the
    values are ALREADY final priorities (e.g. re-writing unchanged rows
    during learning_starts gating — an unconditional +eps there made
    insert priorities creep upward on every warm-up update)."""
    p = jnp.abs(priorities) + eps
    out = dict(buf)
    out["priority"] = buf["priority"].at[indices].set(p)
    out["max_priority"] = jnp.maximum(buf["max_priority"], jnp.max(p))
    return out
