"""Vectorized pure-jax environments (Anakin-style: envs live ON device).

The reference's RolloutWorker actors step Python gym envs
(``rllib/evaluation/rollout_worker.py:153``); the TPU-native fast path
keeps the whole env batch in device memory and vmaps the dynamics, so the
rollout is part of the jitted learner program (no host<->device bounce per
step). CartPole here follows the classic gym dynamics/termination.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class CartPoleState(NamedTuple):
    x: jax.Array
    x_dot: jax.Array
    theta: jax.Array
    theta_dot: jax.Array
    t: jax.Array  # steps since reset


class CartPole:
    """Classic control CartPole-v1 dynamics, vectorizable with vmap."""

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    TOTAL_MASS = MASSCART + MASSPOLE
    LENGTH = 0.5
    POLEMASS_LENGTH = MASSPOLE * LENGTH
    FORCE_MAG = 10.0
    TAU = 0.02
    X_LIMIT = 2.4
    THETA_LIMIT = 12 * 2 * jnp.pi / 360
    MAX_STEPS = 500

    observation_size = 4
    num_actions = 2

    def reset(self, rng: jax.Array) -> CartPoleState:
        vals = jax.random.uniform(rng, (4,), minval=-0.05, maxval=0.05)
        return CartPoleState(vals[0], vals[1], vals[2], vals[3],
                             jnp.zeros((), jnp.int32))

    def obs(self, s: CartPoleState) -> jax.Array:
        return jnp.stack([s.x, s.x_dot, s.theta, s.theta_dot])

    def step(self, s: CartPoleState, action: jax.Array,
             rng: jax.Array) -> tuple[CartPoleState, jax.Array, jax.Array, jax.Array]:
        """-> (next_state, obs, reward, done); auto-resets on done."""
        force = jnp.where(action == 1, self.FORCE_MAG, -self.FORCE_MAG)
        cos, sin = jnp.cos(s.theta), jnp.sin(s.theta)
        temp = (force + self.POLEMASS_LENGTH * s.theta_dot**2 * sin) / self.TOTAL_MASS
        theta_acc = (self.GRAVITY * sin - cos * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASSPOLE * cos**2 / self.TOTAL_MASS)
        )
        x_acc = temp - self.POLEMASS_LENGTH * theta_acc * cos / self.TOTAL_MASS
        nxt = CartPoleState(
            s.x + self.TAU * s.x_dot,
            s.x_dot + self.TAU * x_acc,
            s.theta + self.TAU * s.theta_dot,
            s.theta_dot + self.TAU * theta_acc,
            s.t + 1,
        )
        done = (
            (jnp.abs(nxt.x) > self.X_LIMIT)
            | (jnp.abs(nxt.theta) > self.THETA_LIMIT)
            | (nxt.t >= self.MAX_STEPS)
        )
        reward = jnp.ones(())
        fresh = self.reset(rng)
        nxt = jax.tree.map(
            lambda a, b: jnp.where(done, a, b), fresh, nxt
        )
        return nxt, self.obs(nxt), reward, done


class PendulumState(NamedTuple):
    theta: jax.Array
    theta_dot: jax.Array
    t: jax.Array


class Pendulum:
    """Classic control Pendulum-v1 dynamics (continuous torque in
    [-2, 2]) — the continuous-action counterpart to CartPole for SAC.
    obs = [cos(theta), sin(theta), theta_dot]; reward = -(angle^2 +
    0.1*thetadot^2 + 0.001*torque^2); fixed-length 200-step episodes."""

    GRAVITY = 10.0
    MASS = 1.0
    LENGTH = 1.0
    DT = 0.05
    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    MAX_STEPS = 200
    TIME_LIMIT_ONLY = True  # "done" is truncation, never a terminal state

    observation_size = 3
    action_size = 1  # continuous
    num_actions = None  # marker: not discrete

    def reset(self, rng: jax.Array) -> PendulumState:
        k1, k2 = jax.random.split(rng)
        return PendulumState(
            jax.random.uniform(k1, (), minval=-jnp.pi, maxval=jnp.pi),
            jax.random.uniform(k2, (), minval=-1.0, maxval=1.0),
            jnp.zeros((), jnp.int32),
        )

    def obs(self, s: PendulumState) -> jax.Array:
        return jnp.stack([jnp.cos(s.theta), jnp.sin(s.theta), s.theta_dot])

    def step(self, s: PendulumState, action: jax.Array, rng: jax.Array):
        """action: [1] torque -> (next_state, obs, reward, done)."""
        u = jnp.clip(action[0], -self.MAX_TORQUE, self.MAX_TORQUE)
        th = ((s.theta + jnp.pi) % (2 * jnp.pi)) - jnp.pi  # wrap to [-pi,pi]
        cost = th ** 2 + 0.1 * s.theta_dot ** 2 + 0.001 * u ** 2
        g, m, ln, dt = self.GRAVITY, self.MASS, self.LENGTH, self.DT
        new_dot = s.theta_dot + (
            3 * g / (2 * ln) * jnp.sin(th) + 3.0 / (m * ln ** 2) * u) * dt
        new_dot = jnp.clip(new_dot, -self.MAX_SPEED, self.MAX_SPEED)
        new_theta = s.theta + new_dot * dt
        t = s.t + 1
        done = t >= self.MAX_STEPS
        # auto-reset on done (fixed-horizon episode)
        fresh = self.reset(rng)
        nxt = PendulumState(
            jnp.where(done, fresh.theta, new_theta),
            jnp.where(done, fresh.theta_dot, new_dot),
            jnp.where(done, fresh.t, t),
        )
        return nxt, self.obs(nxt), -cost, done


def make_vec_env(env: CartPole, n_envs: int):
    """(reset_fn, step_fn) vmapped over the env batch."""

    def reset(rng):
        return jax.vmap(env.reset)(jax.random.split(rng, n_envs))

    def step(states, actions, rng):
        return jax.vmap(env.step)(states, actions, jax.random.split(rng, n_envs))

    def obs(states):
        return jax.vmap(env.obs)(states)

    return reset, step, obs
