"""Algorithm registry (reference ``rllib/algorithms/registry.py``):
string name -> (Algorithm class, default config factory), the lookup
that lets Tune experiments name an algorithm ("PPO") instead of
importing it. Lazy imports keep ``ray_tpu.rllib.registry`` cheap to
load and avoid importing every algorithm at once.
"""

from __future__ import annotations

from typing import Callable, Tuple

__all__ = ["get_algorithm_class", "get_algorithm_config", "ALGORITHMS"]


def _lazy(module: str, algo: str, config: str) -> Callable:
    def load() -> Tuple[type, type]:
        import importlib

        mod = importlib.import_module(f"ray_tpu.rllib.{module}")
        return getattr(mod, algo), getattr(mod, config)

    return load


ALGORITHMS = {
    "A2C": _lazy("a2c", "A2C", "A2CConfig"),
    "AlphaZero": _lazy("alpha_zero", "AlphaZero", "AlphaZeroConfig"),
    "A3C": _lazy("a3c", "A3C", "A3CConfig"),
    "APPO": _lazy("appo", "APPO", "APPOConfig"),
    "ARS": _lazy("es", "ARS", "ARSConfig"),
    "ApexDQN": _lazy("apex", "ApexDQN", "ApexDQNConfig"),
    "ApexDDPG": _lazy("apex_ddpg", "ApexDDPG", "ApexDDPGConfig"),
    "BC": _lazy("offline_algos", "BC", "BCConfig"),
    "BanditLinTS": _lazy("bandit", "BanditLinTS", "BanditConfig"),
    "BanditLinUCB": _lazy("bandit", "BanditLinUCB", "BanditConfig"),
    "CQL": _lazy("offline_algos", "CQL", "CQLConfig"),
    "CRR": _lazy("crr", "CRR", "CRRConfig"),
    "DDPG": _lazy("ddpg", "DDPG", "DDPGConfig"),
    "DDPPO": _lazy("ddppo", "DDPPO", "DDPPOConfig"),
    "DQN": _lazy("dqn", "DQN", "DQNConfig"),
    "DT": _lazy("dt", "DT", "DTConfig"),
    "ES": _lazy("es", "ES", "ESConfig"),
    "IMPALA": _lazy("impala", "IMPALA", "IMPALAConfig"),
    "MADDPG": _lazy("maddpg", "MADDPG", "MADDPGConfig"),
    "MAML": _lazy("maml", "MAML", "MAMLConfig"),
    "MARWIL": _lazy("offline_algos", "MARWIL", "MARWILConfig"),
    "PG": _lazy("pg", "PG", "PGConfig"),
    "PPO": _lazy("ppo", "PPO", "PPOConfig"),
    "QMIX": _lazy("qmix", "QMIX", "QMIXConfig"),
    "R2D2": _lazy("r2d2", "R2D2", "R2D2Config"),
    "SAC": _lazy("sac", "SAC", "SACConfig"),
    "SimpleQ": _lazy("simple_q", "SimpleQ", "SimpleQConfig"),
    "SlateQ": _lazy("slateq", "SlateQ", "SlateQConfig"),
    "TD3": _lazy("td3", "TD3", "TD3Config"),
}


def get_algorithm_class(name: str, return_config: bool = False):
    """Resolve an algorithm by its registry name
    (``rllib/algorithms/registry.py:get_algorithm_class``)."""
    try:
        loader = ALGORITHMS[name]
    except KeyError:
        raise ValueError(
            f"unknown algorithm {name!r} "
            f"(known: {sorted(ALGORITHMS)})") from None
    cls, config_cls = loader()
    if return_config:
        return cls, config_cls
    return cls


def get_algorithm_config(name: str):
    """Default config instance for a registered algorithm. Configs
    shared by several entries (BanditConfig serves LinUCB and LinTS)
    expose an ``algo_class`` slot; binding the resolved class there
    makes ``get_algorithm_config(name).build(...)`` construct exactly
    the algorithm ``name`` resolves to."""
    cls, config_cls = get_algorithm_class(name, return_config=True)
    cfg = config_cls()
    if getattr(cfg, "algo_class", "__absent__") is None:
        cfg.algo_class = cls
    return cfg
