"""DD-PPO: decentralized distributed PPO (reference
``rllib/algorithms/ddppo/ddppo.py``, after Wijmans et al. 2019). The
architecture inverts the Sebulba learner/worker split the other PPO
path uses: there is NO central learner and sample batches never move.
Each worker rolls out on its own envs, computes gradients on its own
minibatches, ALLREDUCES the gradients with its peers (the reference
rides torch.distributed; here it is ``ray_tpu.util.collective`` over
the object plane — the same group API the XLA in-mesh path shares),
and applies the identical averaged update locally. Parameters start
identical (same init seed) and stay bit-identical by construction —
asserted in the tests, because that invariant IS the algorithm.

Gradients cross the wire as ONE ravelled vector per minibatch
(``jax.flatten_util.ravel_pytree``) rather than a call per leaf.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List

import numpy as np

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu.rllib.ppo import PPOConfig, _make_train_iter, policy_apply, \
    policy_init, ppo_surrogate_loss
from ray_tpu.rllib.optim import adam_init
from ray_tpu.rllib.optim import adam_step as _adam
from ray_tpu.util import collective

__all__ = ["DDPPO", "DDPPOConfig"]


class DDPPOConfig(PPOConfig):
    def __init__(self):
        super().__init__()
        self.num_workers = 2
        self.group_name = "ddppo"

    def build(self) -> "DDPPO":
        return DDPPO(self)


class DDPPOWorker:
    """One decentralized rank: rollout, local minibatch grads, peer
    allreduce, local apply."""

    def __init__(self, cfg_dict: dict, rank: int, world_size: int,
                 group_name: str, seed: int):
        cfg = PPOConfig()
        for k, v in cfg_dict.items():
            if hasattr(cfg, k):
                setattr(cfg, k, v)
        cfg.num_rollout_workers = 0
        self.cfg = cfg
        self.rank, self.world = rank, world_size
        collective.init_collective_group(
            world_size, rank, group_name=group_name)
        self.group = group_name

        (self._reset, _, _, sample, gae, self._vobs) = \
            _make_train_iter(cfg)
        # The PPO factory only ever runs these inside its own jitted
        # train_iter; here they are called directly, so jit them once.
        self._sample = jax.jit(sample)
        self._gae = jax.jit(gae)
        self._policy_apply = jax.jit(policy_apply)
        env = cfg.env
        # SAME param seed on every rank — the decentralized invariant.
        self.params = policy_init(
            jax.random.key(seed), env.observation_size, env.num_actions,
            cfg.hidden_sizes)
        self.opt = adam_init(self.params)
        # Per-rank env/rollout seeds (the data is what differs).
        self.rng = jax.random.key(seed + 1000 + rank)
        self.states = self._reset(jax.random.key(seed + 2000 + rank))

        from jax.flatten_util import ravel_pytree

        flat0, self._unravel = ravel_pytree(self.params)
        self._grad_size = flat0.shape[0]

        def mb_grads(params, batch):
            (_, aux), grads = jax.value_and_grad(
                ppo_surrogate_loss, has_aux=True)(
                params, batch, clip_param=cfg.clip_param,
                vf_coeff=cfg.vf_coeff, entropy_coeff=cfg.entropy_coeff)
            return ravel_pytree(grads)[0], aux

        self._mb_grads = jax.jit(mb_grads)
        self._apply = jax.jit(
            lambda p, o, g: _adam(p, o, self._unravel(g), lr=cfg.lr,
                                  max_grad_norm=cfg.grad_clip, eps=1e-5))

    def train_iter(self) -> dict:
        cfg = self.cfg
        self.states, self.rng, traj = self._sample(
            self.params, self.states, self.rng)
        _, last_value = self._policy_apply(
            self.params, self._vobs(self.states))
        advs, returns = self._gae(traj, last_value)
        env = cfg.env
        flat = {
            "obs": traj["obs"].reshape(-1, env.observation_size),
            "actions": traj["actions"].reshape(-1),
            "logp": traj["logp"].reshape(-1),
            "adv": advs.reshape(-1),
            "returns": returns.reshape(-1),
        }
        n = flat["obs"].shape[0]
        mb = n // cfg.minibatch_count
        aux = {}
        rng = np.random.default_rng(int(jax.random.randint(
            jax.random.fold_in(self.rng, 7), (), 0, 2**31 - 1)))
        for _ in range(cfg.num_sgd_iter):
            perm = rng.permutation(n)
            for i in range(cfg.minibatch_count):
                idx = perm[i * mb:(i + 1) * mb]
                batch = jax.tree.map(lambda x: x[idx], flat)
                g, aux = self._mb_grads(self.params, batch)
                # The DD-PPO kernel: average gradients across ranks,
                # apply the identical update everywhere.
                g = collective.allreduce(
                    np.asarray(g), group_name=self.group) / self.world
                self.params, self.opt = self._apply(
                    self.params, self.opt, jnp.asarray(g))
        dones = float(np.asarray(traj["dones"]).sum())
        return {
            "timesteps": n,
            "episodes": dones,
            "reward_sum": float(np.asarray(traj["rewards"]).sum()),
            **{k: float(v) for k, v in aux.items()},
        }

    def destroy_group(self) -> None:
        collective.destroy_collective_group(self.group)

    def params_digest(self) -> str:
        import hashlib

        leaves = jax.tree.leaves(self.params)
        h = hashlib.sha256()
        for leaf in leaves:
            h.update(np.asarray(leaf).tobytes())
        return h.hexdigest()

    def get_params(self):
        return jax.tree.map(np.asarray, self.params)


class DDPPO:
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""

    def __init__(self, config: DDPPOConfig):
        self.config = config
        # Per-instance collective group: two concurrent DDPPO runs (a
        # Tune sweep) must not share a coordinator or their allreduce
        # slots would mix gradients across unrelated models.
        import uuid

        self._group = f"{config.group_name}-{uuid.uuid4().hex[:8]}"
        worker_cls = ray_tpu.remote(DDPPOWorker)
        self._workers: List = [
            worker_cls.remote(dict(config.__dict__), rank,
                              config.num_workers, self._group,
                              config.seed)
            for rank in range(config.num_workers)
        ]
        self._iteration = 0

    def __enter__(self) -> "DDPPO":
        return self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    def __del__(self):
        # Repeated builds (a Tune sweep) must not leak worker actors /
        # collective groups when a trial forgets stop(). Guarded: at
        # interpreter teardown the backend may already be gone, and
        # stop() on an un-initialized runtime would auto-init one.
        try:
            from ray_tpu._private import worker as _worker_mod

            if self._workers and _worker_mod.is_initialized():
                self.stop()
        except Exception:  # noqa: BLE001 — destructors never raise
            pass

    def stop(self) -> None:
        """Tear down the collective group and the worker actors.
        Idempotent; also runs via the context-manager exit and __del__."""
        if not self._workers:
            return
        try:
            ray_tpu.get(
                [w.destroy_group.remote() for w in self._workers],
                timeout=30)
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
        self._workers = []

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        outs = ray_tpu.get(
            [w.train_iter.remote() for w in self._workers], timeout=600)
        self._iteration += 1
        steps = sum(o["timesteps"] for o in outs)
        episodes = max(1.0, sum(o["episodes"] for o in outs))
        rewards = sum(o["reward_sum"] for o in outs)
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter": steps,
            "episode_reward_mean": rewards / episodes,
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(np.mean([o[k] for o in outs]))
               for k in ("pg_loss", "vf_loss", "entropy") if k in outs[0]},
        }

    def params_digests(self) -> List[str]:
        return ray_tpu.get(
            [w.params_digest.remote() for w in self._workers], timeout=60)

    def compute_single_action(self, obs) -> int:
        params = jax.tree.map(
            jnp.asarray,
            ray_tpu.get(self._workers[0].get_params.remote(), timeout=60))
        logits, _ = policy_apply(params, jnp.asarray(obs)[None])
        return int(jnp.argmax(logits[0]))
