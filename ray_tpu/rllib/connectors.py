"""Connectors: composable transforms between env and policy.

Reference parity: ``rllib/connectors/`` — per-policy pipelines that
reshape observations on the way INTO the policy (env-to-module) and
actions on the way OUT (module-to-env), checkpointable alongside the
policy so a trained policy can be served against raw env data.

Rebuilt TPU-native: a connector is a PURE function over (state, value) —
state is an explicit pytree, so the same pipeline runs host-side (numpy,
gym workers) or inside a jitted rollout (jax arrays through lax.scan),
and serializes with plain pickle. Stateful connector state travels with
the algorithm checkpoint (``PPO.save`` pulls it from the gym workers and
``restore`` pushes it back) and ``compute_single_action`` applies the
same pipeline at inference. With several rollout workers each maintains
its own running stats (the reference's per-worker observation filters
behave the same way without an explicit sync).

    pipe = ConnectorPipeline([ClipObs(-5, 5), NormalizeObs(4)])
    state = pipe.init()
    state, obs = pipe(state, obs)       # env -> module
    act_pipe = ConnectorPipeline([ClipActions(-2.0, 2.0)])
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import numpy as np


class Connector:
    """One transform. ``init() -> state``; ``__call__(state, x) ->
    (state, x)``. Stateless connectors return their state unchanged."""

    def init(self):
        return ()

    def __call__(self, state, x):
        raise NotImplementedError

    def reset_rows(self, state, done_mask):
        """Clear per-env rows of the state at episode boundaries (only
        meaningful for per-env-stateful connectors like FrameStack)."""
        return state


class ConnectorPipeline(Connector):
    """Left-to-right composition; state is the tuple of stage states
    (a pytree — jit/scan friendly)."""

    def __init__(self, connectors: Sequence[Connector]):
        self.connectors = list(connectors)

    def init(self) -> Tuple:
        return tuple(c.init() for c in self.connectors)

    def __call__(self, state, x):
        out_states = []
        for c, s in zip(self.connectors, state):
            s, x = c(s, x)
            out_states.append(s)
        return tuple(out_states), x

    def append(self, connector: Connector) -> "ConnectorPipeline":
        return ConnectorPipeline(self.connectors + [connector])

    def reset_rows(self, state, done_mask):
        return tuple(
            c.reset_rows(s, done_mask)
            for c, s in zip(self.connectors, state))


# -- observation connectors (env -> module) ---------------------------------


class ClipObs(Connector):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def __call__(self, state, x):
        import jax.numpy as jnp

        xp = jnp if not isinstance(x, np.ndarray) else np
        return state, xp.clip(x, self.low, self.high)


class FlattenObs(Connector):
    """[..., *dims] -> [..., prod(dims)] keeping the batch axis."""

    def __call__(self, state, x):
        return state, x.reshape(x.shape[0], -1)


class NormalizeObs(Connector):
    """Running mean/std normalization (the reference's
    MeanStdObservationFilter): Welford-style accumulators carried in the
    explicit state, updated on every batch seen during sampling."""

    def __init__(self, obs_size: int, clip: float = 10.0,
                 update: bool = True):
        self.obs_size = obs_size
        self.clip = clip
        self.update = update

    def init(self):
        return {
            "count": np.float32(1e-4),
            "mean": np.zeros(self.obs_size, np.float32),
            "m2": np.zeros(self.obs_size, np.float32),
        }

    def __call__(self, state, x):
        import jax.numpy as jnp

        xp = jnp if not isinstance(x, np.ndarray) else np
        if self.update:
            b = x.shape[0]
            b_mean = x.mean(axis=0)
            b_var = x.var(axis=0)
            count = state["count"] + b
            delta = b_mean - state["mean"]
            mean = state["mean"] + delta * (b / count)
            m2 = (state["m2"] + b_var * b
                  + (delta ** 2) * state["count"] * b / count)
            state = {"count": count, "mean": mean, "m2": m2}
        std = xp.sqrt(state["m2"] / state["count"]) + 1e-8
        return state, xp.clip(
            (x - state["mean"]) / std, -self.clip, self.clip)


class FrameStack(Connector):
    """Stack the last k observations along the feature axis (Atari-style
    temporal context without recurrence). State holds the ring of k-1
    previous frames per batch row."""

    def __init__(self, obs_size: int, num_envs: int, k: int = 4):
        self.obs_size = obs_size
        self.num_envs = num_envs
        self.k = k

    def init(self):
        return np.zeros(
            (self.k - 1, self.num_envs, self.obs_size), np.float32)

    def __call__(self, state, x):
        import jax.numpy as jnp

        xp = jnp if not isinstance(x, np.ndarray) else np
        frames = xp.concatenate([state, x[None]], axis=0)  # [k, B, D]
        stacked = xp.concatenate(
            [frames[i] for i in range(self.k)], axis=-1)   # [B, k*D]
        return frames[1:], stacked

    def reset_rows(self, state, done_mask):
        """Zero a finished env's history so a new episode never stacks
        against the previous one's frames."""
        import jax.numpy as jnp

        xp = jnp if not isinstance(state, np.ndarray) else np
        mask = xp.asarray(done_mask, bool)[None, :, None]
        return xp.where(mask, 0.0, state)


# -- action connectors (module -> env) --------------------------------------


class ClipActions(Connector):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def __call__(self, state, x):
        import jax.numpy as jnp

        xp = jnp if not isinstance(x, np.ndarray) else np
        return state, xp.clip(x, self.low, self.high)


class UnsquashActions(Connector):
    """[-1, 1] policy outputs -> the env's [low, high] box."""

    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def __call__(self, state, x):
        return state, self.low + (x + 1.0) * 0.5 * (self.high - self.low)
