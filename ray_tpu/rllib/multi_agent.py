"""Multi-agent RL: env API, per-policy batches, multi-policy PPO.

Reference parity: ``rllib/env/multi_agent_env.py:24`` (dict-keyed
obs/action/reward spaces per agent), ``rllib/policy/policy_map.py`` (a map
of independently-updated policies) and the config's
``multi_agent(policies=..., policy_mapping_fn=...)`` surface — rebuilt
TPU-native: the env is vmapped jax code, the agent set and the
agent->policy mapping are static, so the multi-agent rollout AND every
policy's PPO update compile into ONE jitted train iteration.

* ``MultiAgentEnv`` — the Python-level API contract (host envs / external
  simulators), matching the reference's reset/step dict shapes;
* ``MultiAgentGridWorld`` — a jax N-agent gridworld (each agent walks to
  its own goal corner; per-agent shaped rewards);
* ``MultiAgentPPO`` — one policy per policy_id, agents routed by
  ``policy_mapping``; each policy trains on the concatenated batches of
  ITS agents only.
"""

from __future__ import annotations

import time
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ray_tpu.rllib.optim import adam_step as _adam
from ray_tpu.rllib.ppo import policy_apply, policy_init


class MultiAgentEnv:
    """API contract for host-side multi-agent envs (reference
    ``env/multi_agent_env.py:24``): dict-keyed per-agent views.

    ``reset() -> {agent_id: obs}``
    ``step({agent_id: action}) -> (obs_dict, reward_dict, done_dict, info)``
    where ``done_dict`` carries the special key ``"__all__"``.
    """

    agent_ids: tuple = ()

    def reset(self) -> Dict[str, Any]:
        raise NotImplementedError

    def step(self, actions: Dict[str, Any]):
        raise NotImplementedError


# ---------------------------------------------------------------------------
# jax gridworld
# ---------------------------------------------------------------------------


class GridState(NamedTuple):
    pos: jax.Array  # [n_agents, 2] int32
    t: jax.Array


class MultiAgentGridWorld:
    """N agents on a size x size grid, each assigned a goal corner; actions
    are the 4 moves; reward = potential-based shaping toward the agent's
    own goal + terminal bonus. Episodes are fixed-horizon with auto-reset
    (vmap/scan friendly: no dynamic shapes)."""

    observation_size = 4  # own (x, y), goal (x, y) — normalized
    num_actions = 4       # up, down, left, right

    def __init__(self, size: int = 5, n_agents: int = 2,
                 max_steps: int = 24):
        self.size = size
        self.n_agents = n_agents
        self.max_steps = max_steps
        self.agent_ids = tuple(f"agent_{i}" for i in range(n_agents))
        corners = jnp.array(
            [[size - 1, size - 1], [0, 0], [size - 1, 0], [0, size - 1]],
            jnp.int32)
        self.goals = jnp.stack(
            [corners[i % 4] for i in range(n_agents)])  # [n_agents, 2]

    def reset(self, rng: jax.Array) -> GridState:
        pos = jax.random.randint(
            rng, (self.n_agents, 2), 0, self.size, jnp.int32)
        return GridState(pos, jnp.zeros((), jnp.int32))

    def obs(self, s: GridState) -> jax.Array:
        """[n_agents, 4] — each row is that agent's view."""
        scale = 1.0 / max(self.size - 1, 1)
        return jnp.concatenate(
            [s.pos.astype(jnp.float32) * scale,
             self.goals.astype(jnp.float32) * scale], axis=1)

    def step(self, s: GridState, actions: jax.Array, rng: jax.Array):
        """actions: [n_agents] int -> (state, obs, rewards [n_agents],
        done). Auto-resets on the shared fixed horizon."""
        moves = jnp.array(
            [[0, 1], [0, -1], [-1, 0], [1, 0]], jnp.int32)
        nxt = jnp.clip(s.pos + moves[actions], 0, self.size - 1)
        d_old = jnp.abs(s.pos - self.goals).sum(axis=1).astype(jnp.float32)
        d_new = jnp.abs(nxt - self.goals).sum(axis=1).astype(jnp.float32)
        at_goal = (d_new == 0).astype(jnp.float32)
        rewards = 0.1 * (d_old - d_new) + at_goal * 1.0 - 0.01
        t = s.t + 1
        done = t >= self.max_steps
        fresh = self.reset(rng)
        state = GridState(
            jnp.where(done, fresh.pos, nxt),
            jnp.where(done, fresh.t, t),
        )
        return state, self.obs(state), rewards, done


# ---------------------------------------------------------------------------
# multi-policy PPO
# ---------------------------------------------------------------------------


class MultiAgentPPOConfig:
    """``.multi_agent(policies=..., policy_mapping=...)`` mirrors the
    reference's AlgorithmConfig.multi_agent surface."""

    def __init__(self):
        self.env = MultiAgentGridWorld()
        self.num_envs = 32
        self.rollout_length = 64
        self.gamma = 0.99
        self.gae_lambda = 0.95
        self.clip_param = 0.2
        self.lr = 3e-3
        self.entropy_coeff = 0.01
        self.vf_coeff = 0.5
        self.num_sgd_iter = 4
        self.minibatch_count = 4
        self.grad_clip = 0.5
        self.hidden_sizes = (64, 64)
        self.policies: tuple = ()            # policy ids
        self.policy_mapping: Dict[str, str] = {}  # agent_id -> policy_id
        self.seed = 0

    def environment(self, env=None) -> "MultiAgentPPOConfig":
        if env is not None:
            self.env = env
        return self

    def multi_agent(self, *, policies=None,
                    policy_mapping=None) -> "MultiAgentPPOConfig":
        if policies is not None:
            self.policies = tuple(policies)
        if policy_mapping is not None:
            self.policy_mapping = dict(policy_mapping)
        return self

    def rollouts(self, *, num_envs: Optional[int] = None,
                 rollout_length: Optional[int] = None):
        if num_envs is not None:
            self.num_envs = num_envs
        if rollout_length is not None:
            self.rollout_length = rollout_length
        return self

    def training(self, **kwargs) -> "MultiAgentPPOConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None):
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


def _make_ma_train_iter(cfg: MultiAgentPPOConfig):
    env = cfg.env
    agent_ids = env.agent_ids
    n_agents = len(agent_ids)
    n_envs, t_len = cfg.num_envs, cfg.rollout_length
    # agent index -> policy id (static; baked into the jitted program).
    agent_policy = [cfg.policy_mapping[a] for a in agent_ids]

    def vreset(rng):
        return jax.vmap(env.reset)(jax.random.split(rng, n_envs))

    def vobs(states):
        return jax.vmap(env.obs)(states)  # [n_envs, n_agents, obs]

    def vstep(states, actions, rng):
        return jax.vmap(env.step)(
            states, actions, jax.random.split(rng, n_envs))

    def apply_per_agent(policies, obs):
        """obs [n_envs, n_agents, D] -> (logits, values) stacked on the
        agent axis, each agent through ITS policy (static routing)."""
        logits, values = [], []
        for i in range(n_agents):
            lg, v = policy_apply(policies[agent_policy[i]], obs[:, i])
            logits.append(lg)
            values.append(v)
        return jnp.stack(logits, 1), jnp.stack(values, 1)

    def sample_rollout(policies, states, rng):
        def step_fn(carry, _):
            states, rng = carry
            rng, k_act, k_step = jax.random.split(rng, 3)
            obs = vobs(states)                       # [E, A, D]
            logits, values = apply_per_agent(policies, obs)
            action = jax.random.categorical(k_act, logits)  # [E, A]
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(logits), action[..., None], -1)[..., 0]
            nstates, _, rewards, done = vstep(states, action, k_step)
            out = {"obs": obs, "actions": action, "rewards": rewards,
                   "dones": done, "logp": logp, "values": values}
            return (nstates, rng), out

        (states, rng), traj = jax.lax.scan(
            step_fn, (states, rng), None, length=t_len)
        return states, rng, traj  # leaves [T, E, (A,) ...]

    def compute_gae(traj, last_values):
        """Per-agent GAE over the shared done signal."""
        def scan_fn(adv, x):
            reward, done, value, next_value = x
            nonterm = 1.0 - done[:, None].astype(jnp.float32)
            delta = reward + cfg.gamma * next_value * nonterm - value
            adv = delta + cfg.gamma * cfg.gae_lambda * nonterm * adv
            return adv, adv

        values = traj["values"]                       # [T, E, A]
        next_values = jnp.concatenate(
            [values[1:], last_values[None]], axis=0)
        _, advs = jax.lax.scan(
            scan_fn, jnp.zeros_like(last_values),
            (traj["rewards"], traj["dones"], values, next_values),
            reverse=True)
        return advs, advs + values

    def ppo_loss(params, batch):
        logits, value = policy_apply(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], 1)[:, 0]
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["adv"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        pg = -jnp.mean(jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - cfg.clip_param, 1 + cfg.clip_param) * adv))
        vf = jnp.mean((value - batch["returns"]) ** 2)
        ent = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
        return pg + cfg.vf_coeff * vf - cfg.entropy_coeff * ent

    def sgd_policy(params, opt, flat, rng):
        n = flat["obs"].shape[0]
        mb = n // cfg.minibatch_count

        def epoch(carry, _):
            params, opt, rng = carry
            rng, k = jax.random.split(rng)
            perm = jax.random.permutation(k, n)

            def mb_step(carry, i):
                params, opt = carry
                idx = jax.lax.dynamic_slice_in_dim(perm, i * mb, mb)
                batch = jax.tree.map(lambda x: x[idx], flat)
                loss, grads = jax.value_and_grad(ppo_loss)(params, batch)
                params, opt = _adam(params, opt, grads, lr=cfg.lr,
                                    max_grad_norm=cfg.grad_clip, eps=1e-5)
                return (params, opt), loss

            (params, opt), losses = jax.lax.scan(
                mb_step, (params, opt), jnp.arange(cfg.minibatch_count))
            return (params, opt, rng), losses

        (params, opt, rng), losses = jax.lax.scan(
            epoch, (params, opt, rng), None, length=cfg.num_sgd_iter)
        return params, opt, losses[-1, -1]

    @jax.jit
    def train_iter(policies, opts, states, rng):
        states, rng, traj = sample_rollout(policies, states, rng)
        _, last_values = apply_per_agent(policies, vobs(states))
        advs, returns = compute_gae(traj, last_values)
        obs_size = env.observation_size

        metrics = {}
        new_policies, new_opts = dict(policies), dict(opts)
        for pid in cfg.policies:
            # Per-policy batch: concat the columns of every agent mapped
            # to this policy (reference policy_map semantics).
            mine = [i for i in range(n_agents) if agent_policy[i] == pid]
            flat = {
                "obs": jnp.concatenate(
                    [traj["obs"][:, :, i].reshape(-1, obs_size)
                     for i in mine]),
                "actions": jnp.concatenate(
                    [traj["actions"][:, :, i].reshape(-1) for i in mine]),
                "logp": jnp.concatenate(
                    [traj["logp"][:, :, i].reshape(-1) for i in mine]),
                "adv": jnp.concatenate(
                    [advs[:, :, i].reshape(-1) for i in mine]),
                "returns": jnp.concatenate(
                    [returns[:, :, i].reshape(-1) for i in mine]),
            }
            rng, k = jax.random.split(rng)
            p, o, loss = sgd_policy(policies[pid], opts[pid], flat, k)
            new_policies[pid] = p
            new_opts[pid] = o
            metrics[f"{pid}/loss"] = loss
            metrics[f"{pid}/reward_mean"] = jnp.mean(jnp.stack(
                [traj["rewards"][:, :, i] for i in mine]))
        return new_policies, new_opts, states, rng, metrics

    return vreset, train_iter


class MultiAgentPPO:
    """Algorithm (Trainable contract) with one policy per policy_id."""

    def __init__(self, config: MultiAgentPPOConfig):
        env = config.env
        if not config.policies:
            config.policies = ("default",)
            config.policy_mapping = {a: "default" for a in env.agent_ids}
        missing = [a for a in env.agent_ids
                   if a not in config.policy_mapping]
        if missing:
            raise ValueError(f"agents with no policy mapping: {missing}")
        self.config = config
        rng = jax.random.key(config.seed)
        keys = jax.random.split(rng, len(config.policies) + 2)
        self.policies = {
            pid: policy_init(
                keys[i], env.observation_size, env.num_actions,
                config.hidden_sizes)
            for i, pid in enumerate(config.policies)
        }
        self.opts = {
            pid: {
                "mu": jax.tree.map(jnp.zeros_like, p),
                "nu": jax.tree.map(jnp.zeros_like, p),
                "t": jnp.zeros((), jnp.int32),
            }
            for pid, p in self.policies.items()
        }
        self._reset, self._train_iter = _make_ma_train_iter(config)
        self._states = self._reset(keys[-2])
        self._rng = keys[-1]
        self._iteration = 0

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        (self.policies, self.opts, self._states, self._rng,
         metrics) = self._train_iter(
            self.policies, self.opts, self._states, self._rng)
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter":
                self.config.num_envs * self.config.rollout_length,
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(v) for k, v in metrics.items()},
        }

    def compute_single_action(self, agent_id: str, obs) -> int:
        pid = self.config.policy_mapping[agent_id]
        logits, _ = policy_apply(self.policies[pid], jnp.asarray(obs)[None])
        return int(jnp.argmax(logits[0]))
