"""IMPALA: distributed actor-learner RL with V-trace off-policy correction.

Reference parity: ``rllib/algorithms/impala/`` — the architecture the
reference's distributed RL story is built around: rollout-worker actors
sample with a stale BEHAVIOR policy snapshot while the learner updates the
TARGET policy; the decoupling is corrected by V-trace (clipped importance
weights rho/c, Espeholt et al. 2018), so the learner never waits for
on-policy data.

TPU-native shape (Sebulba, like ``rllib/ppo.py``): workers are actors with
their own jitted on-device env batch; the learner's V-trace update is one
jitted program over time-major [T, B] trajectories. With
``num_rollout_workers=0`` the same program runs Anakin-style (sample +
update in-process; importance ratios are then ~1 and V-trace reduces to
n-step TD, which is exactly the algorithm's on-policy limit).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.env import CartPole, make_vec_env
from ray_tpu.rllib.optim import adam_step as _adam
from ray_tpu.rllib.ppo import policy_apply, policy_init


class IMPALAConfig:
    """Builder-style config (``IMPALAConfig().training(...)``)."""

    def __init__(self):
        self.env = CartPole()
        self.num_envs = 16
        self.rollout_length = 64         # T per sample()
        self.num_rollout_workers = 0
        self.gamma = 0.99
        self.lr = 5e-4
        self.hidden_sizes = (64, 64)
        self.entropy_coef = 0.01
        self.vf_coef = 0.5
        self.rho_clip = 1.0              # V-trace rho-bar
        self.c_clip = 1.0                # V-trace c-bar
        self.max_grad_norm = 40.0
        # Policy-gradient surrogate: "is" = plain importance-weighted PG
        # (canonical IMPALA); "ppo_clip" = the clipped PPO surrogate on
        # V-trace advantages (APPO, rllib/algorithms/appo).
        self.surrogate = "is"
        self.clip_param = 0.3
        self.seed = 0

    def environment(self, env=None) -> "IMPALAConfig":
        if env is not None:
            self.env = env
        return self

    def rollouts(self, *, num_envs: Optional[int] = None,
                 num_rollout_workers: Optional[int] = None,
                 rollout_length: Optional[int] = None) -> "IMPALAConfig":
        if num_envs is not None:
            self.num_envs = num_envs
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if rollout_length is not None:
            self.rollout_length = rollout_length
        return self

    def training(self, **kwargs) -> "IMPALAConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown IMPALA option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "IMPALAConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "IMPALA":
        return IMPALA(self)


def vtrace(values, bootstrap_value, rewards, dones, logp_target,
           logp_behavior, gamma, rho_clip, c_clip):
    """V-trace targets + policy-gradient advantages over time-major [T, B].

    Returns (vs [T,B], pg_adv [T,B]). ``values`` are the TARGET policy's
    value estimates V(x_t); ``bootstrap_value`` is V(x_T)."""
    rho = jnp.minimum(rho_clip, jnp.exp(logp_target - logp_behavior))
    c = jnp.minimum(c_clip, jnp.exp(logp_target - logp_behavior))
    discounts = gamma * (1.0 - dones)
    values_next = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = rho * (rewards + discounts * values_next - values)

    def backward(acc, xs):
        delta_t, discount_t, c_t = xs
        acc = delta_t + discount_t * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        backward, jnp.zeros_like(bootstrap_value),
        (deltas, discounts, c), reverse=True)
    vs = vs_minus_v + values
    vs_next = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_adv = rho * (rewards + discounts * vs_next - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


def _make_pieces(cfg: IMPALAConfig):
    env = cfg.env
    reset_fn, step_fn, obs_fn = make_vec_env(env, cfg.num_envs)

    def sample_rollout(params, states, rng):
        """Behavior-policy rollout -> time-major trajectory + bootstrap."""
        def one_step(carry, _):
            states, rng = carry
            rng, k_act, k_step = jax.random.split(rng, 3)
            obs = obs_fn(states)
            logits, _ = policy_apply(params, obs)
            actions = jax.random.categorical(k_act, logits)
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(logits), actions[:, None], axis=1)[:, 0]
            nstates, _, rewards, dones = step_fn(states, actions, k_step)
            out = {"obs": obs, "actions": actions, "logp": logp,
                   "rewards": rewards, "dones": dones.astype(jnp.float32)}
            return (nstates, rng), out

        (states, rng), traj = jax.lax.scan(
            one_step, (states, rng), None, length=cfg.rollout_length)
        return states, rng, traj, obs_fn(states)

    def adam_step(params, opt, grads):
        return _adam(params, opt, grads, lr=cfg.lr,
                     max_grad_norm=cfg.max_grad_norm)

    def loss_fn(params, batch):
        t_, b_ = batch["actions"].shape
        flat_obs = batch["obs"].reshape(t_ * b_, -1)
        logits, values = policy_apply(params, flat_obs)
        logits = logits.reshape(t_, b_, -1)
        values = values.reshape(t_, b_)
        _, bootstrap = policy_apply(params, batch["bootstrap_obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp_target = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1)[..., 0]
        vs, pg_adv = vtrace(
            values, bootstrap, batch["rewards"], batch["dones"],
            logp_target, batch["logp"], cfg.gamma, cfg.rho_clip, cfg.c_clip)
        if cfg.surrogate == "ppo_clip":
            # APPO: PPO's clipped objective with V-trace advantages —
            # bounds the update the stale behavior data can drive
            # (rllib/algorithms/appo; note pg_adv already carries the
            # rho clip, so the ratio here is target/behavior fresh).
            from ray_tpu.rllib.optim import clipped_surrogate

            pg_loss = clipped_surrogate(
                logp_target, batch["logp"], pg_adv, cfg.clip_param)
        else:
            pg_loss = -jnp.mean(logp_target * pg_adv)
        vf_loss = 0.5 * jnp.mean((vs - values) ** 2)
        entropy = -jnp.mean(
            jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = pg_loss + cfg.vf_coef * vf_loss - cfg.entropy_coef * entropy
        return total, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy}

    @jax.jit
    def reset(rng):
        return reset_fn(rng)

    @jax.jit
    def update(params, opt, batch):
        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch)
        params, opt = adam_step(params, opt, grads)
        return params, opt, {"loss": loss, **aux}

    return reset, jax.jit(sample_rollout), update


class ImpalaRolloutWorker:
    """Actor sampling with a (possibly stale) behavior-policy snapshot —
    the 'actor' half of the actor-learner architecture."""

    def __init__(self, cfg_dict: dict, seed: int):
        cfg = IMPALAConfig()
        cfg.__dict__.update(cfg_dict)
        cfg.num_rollout_workers = 0
        self.cfg = cfg
        self._reset, self._sample, _ = _make_pieces(cfg)
        self.rng = jax.random.key(seed)
        self.states = self._reset(jax.random.key(seed + 1))

    def sample(self, params) -> dict:
        self.states, self.rng, traj, boot = self._sample(
            params, self.states, self.rng)
        out = {k: np.asarray(v) for k, v in traj.items()}
        out["bootstrap_obs"] = np.asarray(boot)
        return out


class IMPALA:
    """Algorithm: ``.train()`` one iteration -> result dict
    (``rllib/algorithms/algorithm.py:142`` Trainable contract)."""

    def __init__(self, config: IMPALAConfig):
        self.config = config
        rng = jax.random.key(config.seed)
        k_param, k_env, self._rng = jax.random.split(rng, 3)
        env = config.env
        self.params = policy_init(
            k_param, env.observation_size, env.num_actions,
            config.hidden_sizes)
        self.opt = {
            "mu": jax.tree.map(jnp.zeros_like, self.params),
            "nu": jax.tree.map(jnp.zeros_like, self.params),
            "t": jnp.zeros((), jnp.int32),
        }
        self._reset, self._sample, self._update = _make_pieces(config)
        self._iteration = 0
        self._ep_steps = 0.0
        self._ep_dones = 0.0
        self._workers: List = []
        if config.num_rollout_workers > 0:
            # Distributed: sampling lives on the worker actors — the
            # learner never builds a local env batch.
            self._states = None
            worker_cls = ray_tpu.remote(ImpalaRolloutWorker)
            # The FULL config crosses (env included: it's a plain object
            # the actor args pickler handles) — workers must sample the
            # configured env, not a default.
            self._workers = [
                worker_cls.remote(dict(config.__dict__),
                                  config.seed + 100 + i)
                for i in range(config.num_rollout_workers)
            ]
        else:
            self._states = self._reset(k_env)

    def _gather(self) -> dict:
        if self._workers:
            # Learner-side barrier per iteration; staleness comes from the
            # params snapshot each worker used (V-trace corrects it).
            batches = ray_tpu.get(
                [w.sample.remote(self.params) for w in self._workers],
                timeout=300)
            return {
                k: (np.concatenate([b[k] for b in batches], axis=0)
                    if k == "bootstrap_obs"
                    else np.concatenate([b[k] for b in batches], axis=1))
                for k in batches[0]
            }
        self._states, self._rng, traj, boot = self._sample(
            self.params, self._states, self._rng)
        out = {k: v for k, v in traj.items()}
        out["bootstrap_obs"] = boot
        return out

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        batch = self._gather()
        self.params, self.opt, metrics = self._update(
            self.params, self.opt, batch)
        steps = int(np.asarray(batch["actions"]).size)
        dones = float(np.asarray(batch["dones"]).sum())
        self._ep_steps += steps
        self._ep_dones += dones
        self._iteration += 1
        reward_mean = (self._ep_steps / max(1.0, self._ep_dones))
        if dones > 0:  # fresher estimate once episodes complete
            self._ep_steps, self._ep_dones = steps, dones
            reward_mean = steps / dones
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter": steps,
            "episode_reward_mean": reward_mean,
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(v) for k, v in metrics.items()},
        }

    def compute_single_action(self, obs) -> int:
        logits, _ = policy_apply(self.params, jnp.asarray(obs)[None])
        return int(jnp.argmax(logits[0]))
