"""R2D2: recurrent experience replay in distributed RL (reference
``rllib/algorithms/r2d2/r2d2.py``) — the recurrent member of the DQN
family: an LSTM Q-network trained from a replay buffer of SEQUENCES with
the paper's "stored state" strategy (each sequence carries the recurrent
state captured when it was generated) and a burn-in prefix replayed
without gradient to heal state staleness before the TD steps.

TPU-native shape: the rollout chops itself into one sequence per env per
iteration — [T, E, ...] transposed to [E, T, ...] rows dropped into the
replay buffer with the pre-rollout (h, c) attached — and the learner
samples sequence batches and runs burn-in + double-Q TD through a
``lax.scan`` over time. Everything is one jitted program; the LSTM cell
is inlined (16 lines) rather than pulled from the model catalog so the
recurrent state is a plain pair of arrays the buffer can store.

Acceptance (``tests/test_rllib_r2d2.py``): solves ``MemoryChain`` — the
cue-at-t0 task where feedforward DQN cannot beat chance — which is the
capability that separates R2D2 from DQN in the reference's taxonomy.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithm import EpisodeStats
from ray_tpu.rllib.optim import adam_step as _adam
from ray_tpu.rllib.optim import linear_epsilon, periodic_target_sync
from ray_tpu.rllib.ppo import mlp_apply, mlp_init
from ray_tpu.rllib.recurrent import MemoryChain
from ray_tpu.rllib.replay import buffer_add, buffer_init, buffer_sample

__all__ = ["R2D2", "R2D2Config"]


class R2D2Config:
    """Builder-style config (``R2D2Config().training(burn_in=4)``)."""

    def __init__(self):
        self.env = MemoryChain()
        self.num_envs = 32
        self.burn_in = 4                # no-grad state-healing prefix
        self.train_len = 16             # TD steps per sequence
        self.buffer_size = 2_048        # sequences, not steps
        self.batch_size = 64            # sequences per update
        self.updates_per_iter = 16
        self.gamma = 0.99
        self.lr = 2e-3
        self.lstm_hidden = 32
        self.head_hidden = (32,)
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 20_000
        self.target_update_every = 100
        self.learning_starts = 128      # sequences before updates
        self.seed = 0

    def environment(self, env=None) -> "R2D2Config":
        if env is not None:
            self.env = env
        return self

    def rollouts(self, *, num_envs: Optional[int] = None) -> "R2D2Config":
        if num_envs is not None:
            self.num_envs = num_envs
        return self

    def training(self, **kwargs) -> "R2D2Config":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown R2D2 option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "R2D2Config":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "R2D2":
        return R2D2(self)


def _lstm_init(rng, obs_size: int, hidden: int, head_sizes, n_act: int):
    k1, k2, k3 = jax.random.split(rng, 3)
    scale = 1.0 / jnp.sqrt(obs_size + hidden)
    return {
        "wx": jax.random.normal(k1, (obs_size, 4 * hidden)) * scale,
        "wh": jax.random.normal(k2, (hidden, 4 * hidden)) * scale,
        "b": jnp.zeros((4 * hidden,)),
        "head": mlp_init(k3, (hidden, *head_sizes, n_act)),
    }


def _lstm_step(params, x, h, c):
    """One LSTM cell step. x [B, O], h/c [B, H] -> (q [B, A], h, c)."""
    gates = x @ params["wx"] + h @ params["wh"] + params["b"]
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return mlp_apply(params["head"], h), h, c


def _make_train_iter(cfg: R2D2Config):
    env = cfg.env
    n_act = env.num_actions
    H = cfg.lstm_hidden
    L = cfg.burn_in + cfg.train_len + 1   # +1: in-sequence next-step

    vreset = jax.vmap(env.reset)
    vobs = jax.vmap(env.obs)
    vstep = jax.vmap(env.step)

    def mask_hc(h, c, done):
        keep = (1.0 - done.astype(jnp.float32))[:, None]
        return h * keep, c * keep

    def epsilon_at(global_step):
        return linear_epsilon(global_step, cfg.epsilon_start,
                              cfg.epsilon_end, cfg.epsilon_decay_steps)

    def unroll(params, obs_seq, done_seq, h, c):
        """obs_seq [T, B, O] -> q [T, B, A]; state masked on done."""
        def step(carry, x):
            h, c = carry
            obs, done = x
            q, h, c = _lstm_step(params, obs, h, c)
            h, c = mask_hc(h, c, done)
            return (h, c), q

        _, qs = jax.lax.scan(step, (h, c), (obs_seq, done_seq))
        return qs

    def td_loss(p, tp, batch):
        # batch fields are [B, L, ...]; scan wants time-major.
        obs = jnp.swapaxes(batch["obs"], 0, 1)        # [L, B, O]
        dones = jnp.swapaxes(batch["dones"], 0, 1)    # [L, B]
        h0, c0 = batch["h0"], batch["c0"]

        # Burn-in: replay the prefix from the stored state, no gradient.
        if cfg.burn_in > 0:
            def burn(carry, x):
                h, c = carry
                o, d = x
                _, h, c = _lstm_step(jax.lax.stop_gradient(p), o, h, c)
                h, c = mask_hc(h, c, d)
                return (h, c), None

            (h0, c0), _ = jax.lax.scan(
                burn, (h0, c0), (obs[:cfg.burn_in], dones[:cfg.burn_in]))
            h0 = jax.lax.stop_gradient(h0)
            c0 = jax.lax.stop_gradient(c0)

        obs_t = obs[cfg.burn_in:]                     # [train_len+1, B, O]
        done_t = dones[cfg.burn_in:]
        q_online = unroll(p, obs_t, done_t, h0, c0)
        q_target = unroll(tp, obs_t, done_t, h0, c0)

        acts = jnp.swapaxes(batch["actions"], 0, 1)[cfg.burn_in:-1]
        rews = jnp.swapaxes(batch["rewards"], 0, 1)[cfg.burn_in:-1]
        term = done_t[:-1]                            # done AT each step

        q_taken = jnp.take_along_axis(
            q_online[:-1], acts[..., None], axis=-1)[..., 0]
        # Double-Q over the sequence: online argmax, target eval at t+1.
        next_act = jnp.argmax(q_online[1:], axis=-1)
        next_q = jnp.take_along_axis(
            q_target[1:], next_act[..., None], axis=-1)[..., 0]
        y = rews + cfg.gamma * (1.0 - term) * \
            jax.lax.stop_gradient(next_q)
        err = q_taken - y
        return jnp.mean(err * err)

    @jax.jit
    def reset(rng):
        return vreset(jax.random.split(rng, cfg.num_envs))

    @jax.jit
    def train_iter(learner, states, h, c, rng):
        h0_seq, c0_seq = h, c   # stored-state strategy: pre-rollout state

        def env_step(carry, _):
            learner, states, h, c, rng = carry
            rng, k_rand, k_expl, k_step = jax.random.split(rng, 4)
            obs = vobs(states)
            q, h, c = _lstm_step(learner["params"], obs, h, c)
            greedy = jnp.argmax(q, axis=1)
            randa = jax.random.randint(
                k_rand, (cfg.num_envs,), 0, n_act)
            eps = epsilon_at(learner["env_steps"])
            explore = jax.random.uniform(k_expl, (cfg.num_envs,)) < eps
            actions = jnp.where(explore, randa, greedy)
            nstates, _, rew, done = vstep(
                states, actions, jax.random.split(k_step, cfg.num_envs))
            h, c = mask_hc(h, c, done)
            learner = dict(
                learner,
                env_steps=learner["env_steps"] + cfg.num_envs,
                reward_sum=learner["reward_sum"] + jnp.sum(rew),
                done_count=learner["done_count"] + jnp.sum(done),
            )
            out = {"obs": obs, "actions": actions, "rewards": rew,
                   "dones": done.astype(jnp.float32)}
            return (learner, nstates, h, c, rng), out

        (learner, states, h, c, rng), traj = jax.lax.scan(
            env_step, (learner, states, h, c, rng), None, length=L)

        # One sequence per env: [L, E, ...] -> [E, L, ...] rows.
        seqs = jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), traj)
        learner = dict(
            learner,
            buffer=buffer_add(
                learner["buffer"], cfg.buffer_size,
                obs=seqs["obs"], actions=seqs["actions"],
                rewards=seqs["rewards"], dones=seqs["dones"],
                h0=h0_seq, c0=c0_seq))

        def update(carry, _):
            learner, rng = carry
            rng, k = jax.random.split(rng)
            buf = learner["buffer"]
            batch = buffer_sample(
                buf, k, cfg.batch_size,
                ("obs", "actions", "rewards", "dones", "h0", "c0"))
            loss, grads = jax.value_and_grad(td_loss)(
                learner["params"], learner["target_params"], batch)
            ready = (buf["size"] >= cfg.learning_starts).astype(jnp.float32)
            grads = jax.tree.map(lambda g: g * ready, grads)
            params, opt = _adam(learner["params"], learner["opt"], grads,
                                lr=cfg.lr)
            target = periodic_target_sync(
                learner["target_params"], params, opt["t"],
                cfg.target_update_every)
            learner = dict(learner, params=params, opt=opt,
                           target_params=target)
            return (learner, rng), loss * ready

        (learner, rng), losses = jax.lax.scan(
            update, (learner, rng), None, length=cfg.updates_per_iter)
        metrics = {
            "loss": jnp.mean(losses),
            "epsilon": epsilon_at(learner["env_steps"]),
            "buffer_size": learner["buffer"]["size"].astype(jnp.float32),
        }
        return learner, states, h, c, rng, metrics

    return reset, train_iter


class R2D2(EpisodeStats):
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""

    def __init__(self, config: R2D2Config):
        self.config = config
        env = config.env
        rng = jax.random.key(config.seed)
        k_param, k_env, self._rng = jax.random.split(rng, 3)
        params = _lstm_init(
            k_param, env.observation_size, config.lstm_hidden,
            config.head_hidden, env.num_actions)
        L = config.burn_in + config.train_len + 1
        self._learner = {
            "params": params,
            "target_params": jax.tree.map(jnp.copy, params),
            "opt": {"mu": jax.tree.map(jnp.zeros_like, params),
                    "nu": jax.tree.map(jnp.zeros_like, params),
                    "t": jnp.zeros((), jnp.int32)},
            "buffer": buffer_init(
                config.buffer_size,
                {"obs": (L, env.observation_size), "actions": (L,),
                 "rewards": (L,), "dones": (L,),
                 "h0": (config.lstm_hidden,), "c0": (config.lstm_hidden,)},
                dtypes={"actions": jnp.int32}),
            "env_steps": jnp.zeros((), jnp.int32),
            "reward_sum": jnp.zeros(()),
            "done_count": jnp.zeros((), jnp.int32),
        }
        self._reset, self._train_iter = _make_train_iter(config)
        self._states = self._reset(k_env)
        self._h = jnp.zeros((config.num_envs, config.lstm_hidden))
        self._c = jnp.zeros((config.num_envs, config.lstm_hidden))
        self._iteration = 0

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        snap = self._episode_snapshot()
        prev_steps = int(self._learner["env_steps"])
        (self._learner, self._states, self._h, self._c, self._rng,
         metrics) = self._train_iter(
            self._learner, self._states, self._h, self._c, self._rng)
        self._iteration += 1
        reward_mean = self._episode_reward_mean(snap)
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter":
                int(self._learner["env_steps"]) - prev_steps,
            "episode_reward_mean": reward_mean,
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(v) for k, v in metrics.items()},
        }

    def greedy_episode_reward(self, rng) -> float:
        """Play one greedy episode (for tests)."""
        env = self.config.env
        s = env.reset(rng)
        h = jnp.zeros((1, self.config.lstm_hidden))
        c = jnp.zeros((1, self.config.lstm_hidden))
        total = 0.0
        for _ in range(env.length):
            q, h, c = _lstm_step(self._learner["params"], env.obs(s)[None],
                                 h, c)
            rng, k = jax.random.split(rng)
            s, _, rew, done = env.step(s, jnp.argmax(q[0]), k)
            total += float(rew)
            if bool(done):
                break
        return total
