"""DQN: double deep Q-learning with an ON-DEVICE replay buffer.

Second algorithm family next to PPO (``rllib/ppo.py``), same TPU-native
Anakin design: the vectorized env, the epsilon-greedy actor, the replay
buffer, and the learner all live in ONE jitted program — a training
iteration is a single device computation with no host↔device bounce per
step (the reference's DQN moves sample batches host-side through replay
actors, ``rllib/algorithms/dqn/dqn.py``).

Pieces: epsilon-greedy acting with linear decay, uniform replay sampling,
double-DQN targets (online net argmax, target net value), periodic
target-network sync, Adam. ``.train()`` follows the reference's
Trainable contract: one iteration -> result dict.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.rllib.env import CartPole, make_vec_env
from ray_tpu.rllib.optim import adam_step as _adam
from ray_tpu.rllib.optim import linear_epsilon, periodic_target_sync
from ray_tpu.rllib.ppo import mlp_apply, mlp_init
from ray_tpu.rllib.replay import buffer_add as _buf_add
from ray_tpu.rllib.replay import buffer_init, buffer_sample


class DQNConfig:
    """Builder-style config (``DQNConfig().environment(...).training(...)``)."""

    def __init__(self):
        self.env = CartPole()
        self.num_envs = 16
        self.steps_per_iter = 256       # env steps (per env) per train()
        self.buffer_size = 50_000
        self.batch_size = 128
        self.updates_per_iter = 64
        self.gamma = 0.99
        self.lr = 1e-3
        self.hidden_sizes = (64, 64)
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 5_000
        self.target_update_every = 500  # gradient steps between syncs
        self.learning_starts = 500      # buffer fill before updates
        self.double_q = True            # False -> SimpleQ (max over target)
        self.seed = 0

    def environment(self, env=None) -> "DQNConfig":
        if env is not None:
            self.env = env
        return self

    def rollouts(self, *, num_envs: Optional[int] = None) -> "DQNConfig":
        if num_envs is not None:
            self.num_envs = num_envs
        return self

    def training(self, **kwargs) -> "DQNConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown DQN option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "DQNConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "DQN":
        return DQN(self)


def q_td_errors(params, target_params, batch, gamma: float,
                double_q: bool = True):
    """Per-element TD errors for the DQN family (one copy for
    dqn/apex): double-Q decouples argmax (online) from evaluation
    (target); ``double_q=False`` is SimpleQ's overestimating max."""
    q = mlp_apply(params, batch["obs"])  # [B, A]
    q_taken = jnp.take_along_axis(
        q, batch["actions"][:, None], axis=1)[:, 0]
    next_target = mlp_apply(target_params, batch["next_obs"])
    if double_q:
        next_online = mlp_apply(params, batch["next_obs"])
        next_act = jnp.argmax(next_online, axis=1)
        next_q = jnp.take_along_axis(
            next_target, next_act[:, None], axis=1)[:, 0]
    else:
        next_q = jnp.max(next_target, axis=1)
    y = batch["rewards"] + gamma * (1.0 - batch["dones"]) * \
        jax.lax.stop_gradient(next_q)
    return q_taken - y


def _make_train_iter(cfg: DQNConfig):
    env = cfg.env
    obs_size, n_act = env.observation_size, env.num_actions
    reset_fn, step_fn, obs_fn = make_vec_env(env, cfg.num_envs)

    def buffer_add(buf, obs, actions, rewards, next_obs, dones):
        return _buf_add(buf, cfg.buffer_size, obs=obs, actions=actions,
                        rewards=rewards, next_obs=next_obs, dones=dones)

    def epsilon_at(global_step):
        return linear_epsilon(global_step, cfg.epsilon_start,
                              cfg.epsilon_end, cfg.epsilon_decay_steps)

    def td_loss(params, target_params, batch):
        err = q_td_errors(params, target_params, batch, cfg.gamma,
                          double_q=cfg.double_q)
        return jnp.mean(err * err)

    def adam_step(params, opt, grads):
        return _adam(params, opt, grads, lr=cfg.lr)

    @jax.jit
    def reset(rng):
        return reset_fn(rng)

    @jax.jit
    def train_iter(learner, states, rng):
        def env_step(carry, _):
            learner, states, rng = carry
            rng, k_rand, k_expl, k_step = jax.random.split(rng, 4)
            obs = obs_fn(states)
            q = mlp_apply(learner["params"], obs)
            greedy = jnp.argmax(q, axis=1)
            randa = jax.random.randint(
                k_rand, (cfg.num_envs,), 0, n_act)
            eps = epsilon_at(learner["env_steps"])
            explore = jax.random.uniform(k_expl, (cfg.num_envs,)) < eps
            actions = jnp.where(explore, randa, greedy)
            nstates, nobs, rewards, dones = step_fn(states, actions, k_step)
            learner = dict(
                learner,
                buffer=buffer_add(learner["buffer"], obs, actions, rewards,
                                  nobs, dones.astype(jnp.float32)),
                env_steps=learner["env_steps"] + cfg.num_envs,
                done_count=learner["done_count"] + jnp.sum(dones),
            )
            return (learner, nstates, rng), None

        (learner, states, rng), _ = jax.lax.scan(
            env_step, (learner, states, rng), None, length=cfg.steps_per_iter)

        def update(carry, _):
            learner, rng = carry
            rng, k = jax.random.split(rng)
            buf = learner["buffer"]
            batch = buffer_sample(
                buf, k, cfg.batch_size,
                ("obs", "actions", "rewards", "next_obs", "dones"))
            loss, grads = jax.value_and_grad(td_loss)(
                learner["params"], learner["target_params"], batch)
            # Gate the whole update on learning_starts: before the buffer
            # warms up, apply a zero update.
            ready = (buf["size"] >= cfg.learning_starts).astype(jnp.float32)
            grads = jax.tree.map(lambda g: g * ready, grads)
            params, opt = adam_step(learner["params"], learner["opt"], grads)
            target = periodic_target_sync(
                learner["target_params"], params, opt["t"],
                cfg.target_update_every)
            learner = dict(learner, params=params, opt=opt,
                           target_params=target)
            return (learner, rng), loss * ready

        (learner, rng), losses = jax.lax.scan(
            update, (learner, rng), None, length=cfg.updates_per_iter)
        metrics = {
            "loss": jnp.mean(losses),
            "epsilon": epsilon_at(learner["env_steps"]),
            "buffer_size": learner["buffer"]["size"].astype(jnp.float32),
        }
        return learner, states, rng, metrics

    return reset, train_iter


class DQN:
    """Algorithm: ``.train()`` one iteration -> result dict
    (``rllib/algorithms/algorithm.py:142`` Trainable contract)."""

    def __init__(self, config: DQNConfig):
        self.config = config
        rng = jax.random.key(config.seed)
        k_param, k_env, self._rng = jax.random.split(rng, 3)
        env = config.env
        sizes = (env.observation_size, *config.hidden_sizes, env.num_actions)
        params = mlp_init(k_param, sizes)
        self._reset, self._train_iter = _make_train_iter(config)
        n, obs_size = config.buffer_size, env.observation_size
        self._learner = {
            "params": params,
            "target_params": jax.tree.map(jnp.copy, params),
            "opt": {
                "mu": jax.tree.map(jnp.zeros_like, params),
                "nu": jax.tree.map(jnp.zeros_like, params),
                "t": jnp.zeros((), jnp.int32),
            },
            "buffer": buffer_init(
                n,
                {"obs": (obs_size,), "actions": (), "rewards": (),
                 "next_obs": (obs_size,), "dones": ()},
                dtypes={"actions": jnp.int32},
            ),
            "env_steps": jnp.zeros((), jnp.int32),
            "done_count": jnp.zeros((), jnp.int32),
        }
        self._states = self._reset(k_env)
        self._iteration = 0

    @property
    def params(self):
        return self._learner["params"]

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        prev_steps = int(self._learner["env_steps"])
        prev_dones = int(self._learner["done_count"])
        self._learner, self._states, self._rng, metrics = self._train_iter(
            self._learner, self._states, self._rng)
        self._iteration += 1
        steps = int(self._learner["env_steps"]) - prev_steps
        dones = max(1, int(self._learner["done_count"]) - prev_dones)
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter": steps,
            "episode_reward_mean": steps / dones,  # CartPole: reward == len
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(v) for k, v in metrics.items()},
        }

    def compute_single_action(self, obs) -> int:
        q = mlp_apply(self._learner["params"], jnp.asarray(obs)[None])
        return int(jnp.argmax(q[0]))
