"""PG: vanilla policy gradient / REINFORCE (reference
``rllib/algorithms/pg/pg.py``) — the simplest on-policy family, kept in
the inventory for the same reason the reference keeps it: a didactic
baseline and the ancestor the PPO/APPO/IMPALA losses specialize.

Same Anakin shape as ``ppo.py``: the vectorized env rollout and the
update are one jitted program. The loss is the score function estimator
on *reward-to-go* (computed by a reverse ``lax.scan`` that resets the
running return at episode boundaries), with an exponential-moving-average
scalar baseline for variance reduction — no learned critic, which is
exactly what separates PG from A2C in the reference's taxonomy.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.rllib.env import CartPole, make_vec_env
from ray_tpu.rllib.optim import adam_init
from ray_tpu.rllib.optim import adam_step as _adam
from ray_tpu.rllib.ppo import mlp_apply, mlp_init

__all__ = ["PG", "PGConfig"]


class PGConfig:
    """Builder-style config (``PGConfig().environment(...).training(...)``)."""

    def __init__(self):
        self.env = CartPole()
        self.num_envs = 32
        self.rollout_length = 128
        self.gamma = 0.99
        self.lr = 3e-3
        self.hidden_sizes = (64, 64)
        self.baseline_decay = 0.9   # EMA over batch-mean return-to-go
        self.entropy_coeff = 0.0
        self.seed = 0

    def environment(self, env=None) -> "PGConfig":
        if env is not None:
            self.env = env
        return self

    def rollouts(self, *, num_envs: Optional[int] = None,
                 rollout_length: Optional[int] = None) -> "PGConfig":
        if num_envs is not None:
            self.num_envs = num_envs
        if rollout_length is not None:
            self.rollout_length = rollout_length
        return self

    def training(self, **kwargs) -> "PGConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown PG option {k!r}")
            setattr(self, k, v)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "PGConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "PG":
        return PG(self)


def _make_train_iter(cfg: PGConfig):
    reset_fn, step_fn, obs_fn = make_vec_env(cfg.env, cfg.num_envs)

    @jax.jit
    def reset(rng):
        return reset_fn(rng)

    @jax.jit
    def train_iter(params, opt, baseline, states, rng):
        def env_step(carry, _):
            states, rng = carry
            rng, k_act, k_step = jax.random.split(rng, 3)
            obs = obs_fn(states)
            logits = mlp_apply(params, obs)
            actions = jax.random.categorical(k_act, logits)
            nstates, _, rew, done = step_fn(states, actions, k_step)
            return (nstates, rng), {
                "obs": obs, "actions": actions, "rewards": rew,
                "dones": done.astype(jnp.float32)}

        (states, rng), traj = jax.lax.scan(
            env_step, (states, rng), None, length=cfg.rollout_length)

        def rtg_step(running, step):
            # Reward-to-go, zeroed across episode boundaries. The episode
            # tail cut off by the fixed horizon is left unbootstrapped —
            # PG has no value net to bootstrap with (that's A2C).
            running = step["rewards"] + cfg.gamma * running * \
                (1.0 - step["dones"])
            return running, running

        _, rtg = jax.lax.scan(
            rtg_step, jnp.zeros(cfg.num_envs), traj, reverse=True)

        new_baseline = cfg.baseline_decay * baseline + \
            (1.0 - cfg.baseline_decay) * jnp.mean(rtg)
        adv = rtg - new_baseline

        def pg_loss(p):
            logits = mlp_apply(
                p, traj["obs"].reshape(-1, traj["obs"].shape[-1]))
            logp = jax.nn.log_softmax(logits)
            taken = jnp.take_along_axis(
                logp, traj["actions"].reshape(-1)[:, None], axis=1)[:, 0]
            ent = -jnp.mean(jnp.sum(jnp.exp(logp) * logp, axis=1))
            return -jnp.mean(taken * adv.reshape(-1)) \
                - cfg.entropy_coeff * ent

        loss, grads = jax.value_and_grad(pg_loss)(params)
        params, opt = _adam(params, opt, grads, lr=cfg.lr)
        n_done = jnp.maximum(1.0, jnp.sum(traj["dones"]))
        metrics = {
            "loss": loss,
            # True mean return of episodes ending this rollout (any
            # reward scheme, not just +1-per-step — a2c.py convention).
            "episode_reward_mean": jnp.sum(traj["rewards"]) / n_done,
        }
        return params, opt, new_baseline, states, rng, metrics

    return reset, train_iter


class PG:
    """Algorithm (Trainable contract: ``.train()`` -> result dict)."""

    def __init__(self, config: PGConfig):
        self.config = config
        env = config.env
        k_param, k_env, self._rng = jax.random.split(
            jax.random.key(config.seed), 3)
        self._params = mlp_init(
            k_param, (env.observation_size, *config.hidden_sizes,
                      env.num_actions))
        self._opt = adam_init(self._params)
        self._baseline = jnp.zeros(())
        self._reset, self._train_iter = _make_train_iter(config)
        self._states = self._reset(k_env)
        self._iteration = 0

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        (self._params, self._opt, self._baseline, self._states, self._rng,
         metrics) = self._train_iter(
            self._params, self._opt, self._baseline, self._states, self._rng)
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "timesteps_this_iter":
                self.config.num_envs * self.config.rollout_length,
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(v) for k, v in metrics.items()},
        }

    def compute_single_action(self, obs) -> int:
        logits = mlp_apply(self._params, jnp.asarray(obs)[None])
        return int(jnp.argmax(logits[0]))
