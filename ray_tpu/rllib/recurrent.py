"""Recurrent policy optimization (LSTM/attention PPO) + a memory task.

Reference parity: ``rllib/models/torch/recurrent_net.py`` +
``use_lstm``/``use_attention`` in ``rllib/models/catalog.py`` — policies
with hidden state threaded through the rollout, trained with truncated
BPTT. TPU-native shape: the whole thing (rollout with state carry, GAE,
BPTT epochs) is ONE jitted Anakin program; hidden state is just another
``lax.scan`` carry, reset on episode boundaries.

``MemoryChain`` is the acceptance task (reference: RepeatAfterMeEnv in
``rllib/examples/envs``): the cue appears only at t=0 and the reward
depends on acting on it at the episode's last step — an MLP cannot beat
chance, an LSTM solves it.
"""

from __future__ import annotations

import time
from typing import Any, Dict, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.models import ModelCatalog
from ray_tpu.rllib.optim import adam_step as _adam


class MemoryChainState(NamedTuple):
    cue: jax.Array   # which of 2 signals flashed at t=0
    t: jax.Array


class MemoryChain:
    """Flash a 2-way cue at t=0; reward 1 iff the action at the LAST step
    matches the cue. Chance = 0.5; solving requires memory."""

    length = 10
    observation_size = 3   # [cue==0, cue==1] (only at t=0) + phase
    num_actions = 2

    def reset(self, rng: jax.Array) -> MemoryChainState:
        return MemoryChainState(
            jax.random.bernoulli(rng).astype(jnp.int32),
            jnp.zeros((), jnp.int32))

    def obs(self, s: MemoryChainState) -> jax.Array:
        show = (s.t == 0).astype(jnp.float32)
        return jnp.stack([
            show * (s.cue == 0), show * (s.cue == 1),
            s.t.astype(jnp.float32) / self.length,
        ])

    def step(self, s: MemoryChainState, action: jax.Array, rng: jax.Array):
        last = s.t >= self.length - 1
        reward = (last & (action == s.cue)).astype(jnp.float32)
        nxt = MemoryChainState(s.cue, s.t + 1)
        fresh = self.reset(rng)
        nxt = jax.tree.map(lambda a, b: jnp.where(last, a, b), fresh, nxt)
        return nxt, self.obs(nxt), reward, last


class RecurrentPPOConfig:
    def __init__(self):
        self.env = MemoryChain()
        self.model: Dict[str, Any] = {"model": "lstm"}
        self.num_envs = 64
        self.rollout_length = 40
        self.gamma = 0.99
        self.gae_lambda = 0.95
        self.clip_param = 0.2
        self.lr = 3e-3
        self.grad_clip = 0.5
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_sgd_iter = 4
        self.seed = 0

    def training(self, **kw) -> "RecurrentPPOConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown config key {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "RecurrentPPO":
        return RecurrentPPO(self)


class RecurrentPPO:
    """PPO with a stateful catalog model; ``.train()`` -> result dict
    (Trainable contract, ``rllib/algorithms/algorithm.py:142``)."""

    def __init__(self, config: RecurrentPPOConfig):
        self.config = config
        env = config.env
        init, self._initial_state, apply = ModelCatalog.get(
            env.observation_size, env.num_actions, config.model)
        rng = jax.random.key(config.seed)
        k_param, k_env, self._rng = jax.random.split(rng, 3)
        self.params = init(k_param)
        self.opt = {
            "mu": jax.tree.map(jnp.zeros_like, self.params),
            "nu": jax.tree.map(jnp.zeros_like, self.params),
            "t": jnp.zeros((), jnp.int32),
        }
        self._train_iter = self._build(apply)
        reset1 = jax.vmap(env.reset)
        self._env_states = reset1(
            jax.random.split(k_env, config.num_envs))
        self._model_state = self._initial_state(
            self.params, config.num_envs)
        self._iteration = 0

    def _build(self, apply):
        cfg = self.config
        env = cfg.env
        n, T = cfg.num_envs, cfg.rollout_length
        vobs = jax.vmap(env.obs)
        vstep = jax.vmap(env.step)

        def mask_state(state, done):
            # Episode boundary resets the policy memory for that row.
            return jax.tree.map(
                lambda z: jnp.where(
                    done.reshape((-1,) + (1,) * (z.ndim - 1)), 0.0, z),
                state)

        def rollout(params, env_states, model_state, rng):
            def step_fn(carry, _):
                es, ms, rng = carry
                rng, k_act, k_step = jax.random.split(rng, 3)
                obs = vobs(es)
                logits, value, ms2 = apply(params, obs, ms)
                action = jax.random.categorical(k_act, logits)
                logp = jax.nn.log_softmax(logits)[jnp.arange(n), action]
                es2, _, reward, done = vstep(
                    es, action, jax.random.split(k_step, n))
                ms2 = mask_state(ms2, done)
                out = {"obs": obs, "actions": action, "rewards": reward,
                       "dones": done, "logp": logp, "values": value}
                return (es2, ms2, rng), out

            (env_states, model_state, rng), traj = jax.lax.scan(
                step_fn, (env_states, model_state, rng), None, length=T)
            return env_states, model_state, rng, traj

        def gae(traj, last_value):
            def scan_fn(adv, x):
                reward, done, value, next_value = x
                nonterminal = 1.0 - done.astype(jnp.float32)
                delta = (reward + cfg.gamma * next_value * nonterminal
                         - value)
                adv = (delta
                       + cfg.gamma * cfg.gae_lambda * nonterminal * adv)
                return adv, adv

            values = traj["values"]
            next_values = jnp.concatenate(
                [values[1:], last_value[None]], axis=0)
            _, advs = jax.lax.scan(
                scan_fn, jnp.zeros_like(last_value),
                (traj["rewards"], traj["dones"], values, next_values),
                reverse=True)
            return advs, advs + values

        def loss(params, traj, init_model_state):
            # BPTT replay: re-run the model over the stored observation
            # sequence from the rollout's initial state; gradients flow
            # through the state carry (truncated at the rollout edge).
            def replay(ms, x):
                obs, done = x
                logits, value, ms = apply(params, obs, ms)
                ms = mask_state(ms, done)
                return ms, (logits, value)

            _, (logits, values) = jax.lax.scan(
                replay, init_model_state, (traj["obs"], traj["dones"]))
            logp_all = jax.nn.log_softmax(logits)        # [T, n, A]
            logp = jnp.take_along_axis(
                logp_all, traj["actions"][..., None], axis=-1)[..., 0]
            from ray_tpu.rllib.optim import clipped_surrogate

            pg_loss = clipped_surrogate(
                logp, traj["logp"], traj["adv"], cfg.clip_param)
            vf_loss = jnp.mean((values - traj["returns"]) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = (pg_loss + cfg.vf_coeff * vf_loss
                     - cfg.entropy_coeff * entropy)
            return total, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        @jax.jit
        def train_iter(params, opt, env_states, model_state, rng):
            init_ms = model_state
            env_states, model_state, rng, traj = rollout(
                params, env_states, model_state, rng)
            obs_last = vobs(env_states)
            _, last_value, _ = apply(params, obs_last, model_state)
            adv, ret = gae(traj, last_value)
            traj = {**traj, "adv": adv, "returns": ret}

            def epoch(carry, _):
                params, opt = carry
                (_, aux), grads = jax.value_and_grad(
                    loss, has_aux=True)(params, traj, init_ms)
                params, opt = _adam(params, opt, grads, lr=cfg.lr,
                                    max_grad_norm=cfg.grad_clip, eps=1e-5)
                return (params, opt), aux

            (params, opt), auxs = jax.lax.scan(
                epoch, (params, opt), None, length=cfg.num_sgd_iter)
            metrics = jax.tree.map(lambda x: x[-1], auxs)
            metrics["reward_sum"] = traj["rewards"].sum()
            metrics["episodes_done"] = traj["dones"].sum()
            return params, opt, env_states, model_state, rng, metrics

        return train_iter

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        (self.params, self.opt, self._env_states, self._model_state,
         self._rng, metrics) = self._train_iter(
            self.params, self.opt, self._env_states, self._model_state,
            self._rng)
        self._iteration += 1
        n_done = max(1.0, float(metrics.pop("episodes_done")))
        reward_mean = float(metrics.pop("reward_sum")) / n_done
        return {
            "training_iteration": self._iteration,
            "episode_reward_mean": reward_mean,
            "timesteps_this_iter":
                self.config.num_envs * self.config.rollout_length,
            "time_this_iter_s": time.perf_counter() - start,
            **{k: float(v) for k, v in metrics.items()},
        }

    def save(self) -> dict:
        return {"params": jax.tree.map(np.asarray, self.params),
                "iteration": self._iteration}

    def restore(self, state: dict) -> None:
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self._iteration = state["iteration"]
