"""Shared Trainable-contract plumbing for learner-dict algorithms.

The Anakin-style algorithms carry ``reward_sum`` / ``done_count``
counters inside their jitted learner state; every ``.train()`` reports
the mean episodic reward of the episodes that finished THIS iteration
(reference semantics: ``episode_reward_mean`` over the recent window,
``rllib/algorithms/algorithm.py``). One copy of that delta bookkeeping
lives here instead of per algorithm.
"""

from __future__ import annotations


class EpisodeStats:
    """Mixin for classes whose ``self._learner`` dict tracks
    ``reward_sum`` (float accumulator) and ``done_count`` (int)."""

    def _episode_snapshot(self) -> tuple:
        return (float(self._learner["reward_sum"]),
                int(self._learner["done_count"]))

    def _episode_reward_mean(self, snapshot: tuple) -> float:
        """Mean reward of episodes finished since ``snapshot`` (clamped
        to one episode so a done-free iteration reports progress-so-far
        rather than dividing by zero)."""
        drew = float(self._learner["reward_sum"]) - snapshot[0]
        ddone = max(1, int(self._learner["done_count"]) - snapshot[1])
        return drew / ddone
