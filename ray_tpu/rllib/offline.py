"""Offline RL: experience I/O + training from saved datasets.

Reference parity: ``rllib/offline/json_reader.py`` / ``json_writer.py``
(JSON-lines files of SampleBatches) and ``AlgorithmConfig.offline_data``
— here the consumer is the jitted DQN learner: a saved dataset is
ingested into the ON-DEVICE replay buffer, and training runs the same
update program as online DQN with the env-stepping scan skipped.

    writer = JsonWriter(path)
    writer.write(SampleBatch({...}))         # collect
    ds = read_sample_batches(path)           # list[SampleBatch]
    algo = OfflineDQN(DQNConfig(), dataset=ds)
    algo.train()                             # updates only, no env

``read_dataset`` also accepts a ``ray_tpu.data.Dataset`` whose rows are
transition dicts, so collection can flow through the Data library.
"""

from __future__ import annotations

import base64
import json
import os
import time
from typing import Any, Dict, Iterable, List

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.sample_batch import SampleBatch


# ---------------------------------------------------------------------------
# JSON-lines experience files
# ---------------------------------------------------------------------------


def _encode_array(a: np.ndarray) -> dict:
    return {
        "__ndarray__": base64.b64encode(np.ascontiguousarray(a)).decode(),
        "dtype": str(a.dtype),
        "shape": list(a.shape),
    }


def _decode_value(v):
    if isinstance(v, dict) and "__ndarray__" in v:
        raw = base64.b64decode(v["__ndarray__"])
        return np.frombuffer(raw, dtype=v["dtype"]).reshape(v["shape"])
    return v


class JsonWriter:
    """Append SampleBatches to a JSON-lines file (binary columns base64'd,
    like the reference's json_writer)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "a")

    def write(self, batch: SampleBatch) -> None:
        row = {k: _encode_array(np.asarray(v)) for k, v in batch.items()}
        self._f.write(json.dumps(row) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class JsonReader:
    """Iterate SampleBatches back out of a JSON-lines file."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self):
        with open(self.path) as f:
            for line in f:
                if not line.strip():
                    continue
                row = json.loads(line)
                yield SampleBatch(
                    {k: _decode_value(v) for k, v in row.items()})


def read_sample_batches(path: str) -> List[SampleBatch]:
    return list(JsonReader(path))


def read_dataset(ds) -> SampleBatch:
    """Concatenate transitions out of either a list of SampleBatches, a
    JSON-lines path, or a ``ray_tpu.data.Dataset`` of row dicts."""
    if isinstance(ds, str):
        batches = read_sample_batches(ds)
    elif isinstance(ds, dict):  # a single SampleBatch / transition dict
        batches = [SampleBatch(ds)]
    elif isinstance(ds, (list, tuple)):
        batches = [SampleBatch(b) for b in ds]
    else:  # ray_tpu.data.Dataset
        rows = ds.take(ds.count())
        keys = rows[0].keys()
        batches = [SampleBatch(
            {k: np.stack([np.asarray(r[k]) for r in rows]) for k in keys})]
    return SampleBatch.concat_samples(batches)


# ---------------------------------------------------------------------------
# experience collection + offline DQN
# ---------------------------------------------------------------------------


def collect_transitions(algo: DQN, n_steps: int, *,
                        epsilon: float = 0.1, seed: int = 0) -> SampleBatch:
    """Roll the algorithm's CURRENT greedy policy (epsilon-noised) in its
    env and return the transitions — the collection half of the
    reference's ``output`` config."""
    from ray_tpu.rllib.env import make_vec_env
    from ray_tpu.rllib.ppo import mlp_apply

    cfg = algo.config
    env = cfg.env
    reset_fn, step_fn, obs_fn = make_vec_env(env, cfg.num_envs)

    @jax.jit
    def rollout(params, rng):
        states = reset_fn(rng)

        def step(carry, _):
            states, rng = carry
            rng, k_a, k_e, k_s = jax.random.split(rng, 4)
            obs = obs_fn(states)
            q = mlp_apply(params, obs)
            greedy = jnp.argmax(q, axis=1)
            randa = jax.random.randint(
                k_a, (cfg.num_envs,), 0, env.num_actions)
            explore = jax.random.uniform(k_e, (cfg.num_envs,)) < epsilon
            act = jnp.where(explore, randa, greedy)
            nstates, nobs, rew, done = step_fn(states, act, k_s)
            out = {"obs": obs, "actions": act, "rewards": rew,
                   "next_obs": nobs, "dones": done.astype(jnp.float32)}
            return (nstates, rng), out

        _, traj = jax.lax.scan(
            step, (states, jax.random.fold_in(rng, 1)), None,
            length=max(1, n_steps // cfg.num_envs))
        return traj

    traj = rollout(algo.params, jax.random.key(seed))
    flatten = lambda x: np.asarray(x).reshape(
        -1, *np.asarray(x).shape[2:])
    return SampleBatch({k: flatten(v) for k, v in traj.items()})


class OfflineDQN(DQN):
    """DQN trained purely from a saved dataset: the dataset fills the
    on-device replay buffer once, and ``.train()`` runs only the update
    scan (no env interaction) — the reference's ``input_="dataset"``
    mode."""

    def __init__(self, config: DQNConfig, dataset):
        super().__init__(config)
        batch = read_dataset(dataset)
        n = batch.count
        if n == 0:
            raise ValueError("offline dataset is empty")
        from ray_tpu.rllib.replay import buffer_add

        buf = self._learner["buffer"]
        chunk = 4096
        for start in range(0, n, chunk):
            sl = batch.slice(start, min(n, start + chunk))
            buf = buffer_add(
                buf, config.buffer_size,
                obs=jnp.asarray(sl["obs"], jnp.float32),
                actions=jnp.asarray(sl["actions"], jnp.int32),
                rewards=jnp.asarray(sl["rewards"], jnp.float32),
                next_obs=jnp.asarray(sl["next_obs"], jnp.float32),
                dones=jnp.asarray(sl["dones"], jnp.float32),
            )
        self._learner["buffer"] = buf
        self._dataset_size = n
        self._build_offline_iter()

    def _build_offline_iter(self):
        cfg = self.config
        from ray_tpu.rllib.optim import adam_step as _adam
        from ray_tpu.rllib.ppo import mlp_apply
        from ray_tpu.rllib.replay import buffer_sample

        def td_loss(params, target_params, batch):
            q = mlp_apply(params, batch["obs"])
            q_taken = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=1)[:, 0]
            next_online = mlp_apply(params, batch["next_obs"])
            next_act = jnp.argmax(next_online, axis=1)
            next_target = mlp_apply(target_params, batch["next_obs"])
            next_q = jnp.take_along_axis(
                next_target, next_act[:, None], axis=1)[:, 0]
            target = batch["rewards"] + cfg.gamma * (
                1.0 - batch["dones"]) * jax.lax.stop_gradient(next_q)
            err = q_taken - target
            return jnp.mean(err * err)

        @jax.jit
        def offline_iter(learner, rng):
            def update(carry, _):
                learner, rng = carry
                rng, k = jax.random.split(rng)
                batch = buffer_sample(
                    learner["buffer"], k, cfg.batch_size,
                    ("obs", "actions", "rewards", "next_obs", "dones"))
                loss, grads = jax.value_and_grad(td_loss)(
                    learner["params"], learner["target_params"], batch)
                params, opt = _adam(
                    learner["params"], learner["opt"], grads, lr=cfg.lr)
                sync = (opt["t"] % cfg.target_update_every) == 0
                target = jax.tree.map(
                    lambda t_, p: jnp.where(sync, p, t_),
                    learner["target_params"], params)
                return (dict(learner, params=params, opt=opt,
                             target_params=target), rng), loss

            (learner, rng), losses = jax.lax.scan(
                update, (learner, rng), None, length=cfg.updates_per_iter)
            return learner, rng, jnp.mean(losses)

        self._offline_iter = offline_iter

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        self._learner, self._rng, loss = self._offline_iter(
            self._learner, self._rng)
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "loss": float(loss),
            "dataset_size": self._dataset_size,
            "timesteps_this_iter": 0,  # offline: no env interaction
            "time_this_iter_s": time.perf_counter() - start,
        }

    def evaluate(self, n_steps: int = 2000, seed: int = 7,
                 epsilon: float = 0.05) -> float:
        """Epsilon-noised greedy rollout in the config env -> mean episode
        length (CartPole: equals mean return). The small noise floor makes
        the metric honest: an untrained net can deterministically balance
        CartPole from lucky init (a known quirk of random near-linear
        controllers) but cannot RECOVER from perturbations; a trained
        policy can."""
        from ray_tpu.rllib.env import make_vec_env
        from ray_tpu.rllib.ppo import mlp_apply

        cfg = self.config
        n_act = cfg.env.num_actions
        reset_fn, step_fn, obs_fn = make_vec_env(cfg.env, cfg.num_envs)

        @jax.jit
        def rollout(params, rng):
            states = reset_fn(rng)

            def step(carry, _):
                states, rng = carry
                rng, k_r, k_m, k_s = jax.random.split(rng, 4)
                obs = obs_fn(states)
                act = jnp.argmax(mlp_apply(params, obs), axis=1)
                rnd = jax.random.randint(k_r, (cfg.num_envs,), 0, n_act)
                noisy = jax.random.uniform(k_m, (cfg.num_envs,)) < epsilon
                act = jnp.where(noisy, rnd, act)
                nstates, _, _, done = step_fn(states, act, k_s)
                return (nstates, rng), jnp.sum(done)

            (_, _), dones = jax.lax.scan(
                step, (states, jax.random.fold_in(rng, 1)), None,
                length=max(1, n_steps // cfg.num_envs))
            return jnp.sum(dones)

        n_done = float(rollout(self._learner["params"],
                               jax.random.key(seed)))
        steps = max(1, n_steps // cfg.num_envs) * cfg.num_envs
        return steps / max(n_done, 1.0)
