"""External (gymnasium) environments: host-side rollout workers.

The reference's PRIMARY rollout model is actors stepping Python gym envs
(``rllib/evaluation/rollout_worker.py:153``); this build's fast path is
pure-jax on-device envs (``rllib/env.py``), but real workloads bring
arbitrary Python simulators. ``GymRolloutWorker`` covers them: an actor
holding a batch of gymnasium envs, sampling with the current policy
(jax forward on the worker's host devices), computing GAE host-side,
and returning the same flat batch dict the PPO learner consumes — so
``PPO`` can mix jax and gym workers freely.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.ppo import policy_apply


class GymRolloutWorker:
    """Actor: N gymnasium envs, PPO-shaped sample batches."""

    def __init__(self, env_name: str, *, num_envs: int = 8,
                 rollout_length: int = 128, gamma: float = 0.99,
                 gae_lambda: float = 0.95, seed: int = 0,
                 env_kwargs: Optional[dict] = None,
                 obs_connectors: Optional[list] = None):
        import gymnasium as gym

        self.envs = [gym.make(env_name, **(env_kwargs or {}))
                     for _ in range(num_envs)]
        self.obs = np.stack([
            e.reset(seed=seed + i)[0] for i, e in enumerate(self.envs)
        ]).astype(np.float32)
        self.num_envs = num_envs
        self.rollout_length = rollout_length
        self.gamma = gamma
        self.gae_lambda = gae_lambda
        self._rng = np.random.default_rng(seed)
        self._apply = None  # jitted policy forward, built on first sample
        # Per-env running episode return for REAL reward reporting.
        self._ep_return = np.zeros(num_envs, np.float64)
        # Env-to-module connector pipeline (reference rllib/connectors):
        # the policy sees (and trains on) TRANSFORMED observations, and
        # stateful connectors (running normalization) carry their state
        # across sample() calls for the worker's lifetime.
        self._obs_pipe = None
        if obs_connectors:
            from ray_tpu.rllib.connectors import ConnectorPipeline

            self._obs_pipe = ConnectorPipeline(list(obs_connectors))
            self._obs_state = self._obs_pipe.init()

    def _transform_obs(self, obs: np.ndarray,
                       update: bool = True) -> np.ndarray:
        if self._obs_pipe is None:
            return obs
        state, out = self._obs_pipe(self._obs_state, obs)
        if update:
            self._obs_state = state
        return np.asarray(out, np.float32)

    def _transform_single(self, obs_row: np.ndarray,
                          env_idx: int) -> np.ndarray:
        """Transform ONE env's observation through connectors whose state
        is batch-shape-bound (e.g. FrameStack): substitute the row into a
        copy of the current full batch and take its output row — shape
        correct for every connector, never updating the stats."""
        if self._obs_pipe is None:
            return obs_row
        batch = np.array(self.obs, np.float32)
        batch[env_idx] = obs_row
        _, out = self._obs_pipe(self._obs_state, batch)
        return np.asarray(out, np.float32)[env_idx]

    def get_connector_state(self):
        """Pipeline state for checkpointing (PPO.save pulls this)."""
        return self._obs_state if self._obs_pipe is not None else None

    def set_connector_state(self, state) -> None:
        if self._obs_pipe is not None and state is not None:
            self._obs_state = state
        return None

    def sample(self, params) -> dict:
        import jax
        import jax.numpy as jnp

        t_, n = self.rollout_length, self.num_envs
        probe = self._transform_obs(self.obs, update=False)
        obs_buf = np.zeros((t_, n) + probe.shape[1:], np.float32)
        act_buf = np.zeros((t_, n), np.int64)
        logp_buf = np.zeros((t_, n), np.float32)
        val_buf = np.zeros((t_ + 1, n), np.float32)
        rew_buf = np.zeros((t_, n), np.float32)
        done_buf = np.zeros((t_, n), np.float32)

        if self._apply is None:
            self._apply = jax.jit(policy_apply)  # once per worker lifetime
        apply = self._apply
        ep_returns: list = []
        truncated_at: list = []  # (t, i, final_obs) — bootstrap targets
        for t in range(t_):
            cur = self._transform_obs(self.obs)
            logits, values = apply(params, jnp.asarray(cur))
            logits = np.asarray(logits)
            val_buf[t] = np.asarray(values)
            # Gumbel-max categorical sample (numpy side)
            g = self._rng.gumbel(size=logits.shape)
            actions = np.argmax(logits + g, axis=-1)
            logp_all = logits - _logsumexp(logits)
            logp_buf[t] = np.take_along_axis(
                logp_all, actions[:, None], axis=1)[:, 0]
            obs_buf[t] = cur
            act_buf[t] = actions
            for i, env in enumerate(self.envs):
                nobs, rew, term, trunc, _ = env.step(int(actions[i]))
                rew_buf[t, i] = rew
                self._ep_return[i] += rew
                done = term or trunc
                done_buf[t, i] = float(done)
                if trunc and not term:
                    # Time-limit truncation is NOT failure: bootstrap the
                    # return from V(final_obs) instead of zeroing it
                    # (reference rollout postprocessing semantics).
                    # Transform NOW, with the connector state as of this
                    # step — deferring to rollout end would stack the
                    # final obs against frames from later steps/episodes.
                    truncated_at.append((t, i, self._transform_single(
                        np.asarray(nobs, np.float32), i)))
                if done:
                    ep_returns.append(self._ep_return[i])
                    self._ep_return[i] = 0.0
                    nobs, _ = env.reset()
                self.obs[i] = nobs
            if self._obs_pipe is not None and done_buf[t].any():
                # Episode boundaries: clear per-env connector history
                # (frame stacks must not span episodes).
                self._obs_state = self._obs_pipe.reset_rows(
                    self._obs_state, done_buf[t] > 0)
        _, last_vals = apply(
            params, jnp.asarray(self._transform_obs(self.obs,
                                                    update=False)))
        val_buf[t_] = np.asarray(last_vals)
        if truncated_at:
            finals = np.stack([o for _t, _i, o in truncated_at])
            _, vfin = apply(params, jnp.asarray(finals))
            vfin = np.asarray(vfin)
            for k, (t, i, _) in enumerate(truncated_at):
                rew_buf[t, i] += self.gamma * vfin[k]

        # GAE(lambda) host-side.
        adv = np.zeros((t_, n), np.float32)
        last = np.zeros(n, np.float32)
        for t in range(t_ - 1, -1, -1):
            nonterminal = 1.0 - done_buf[t]
            delta = (rew_buf[t] + self.gamma * val_buf[t + 1] * nonterminal
                     - val_buf[t])
            last = delta + self.gamma * self.gae_lambda * nonterminal * last
            adv[t] = last
        returns = adv + val_buf[:t_]
        # Raw advantages (like the jax RolloutWorker): normalization
        # happens ONCE, per minibatch in ppo_loss — normalizing here too
        # would distort relative scale across concatenated workers.
        return {
            "obs": obs_buf.reshape(t_ * n, -1),
            "actions": act_buf.reshape(-1),
            "logp": logp_buf.reshape(-1),
            "adv": adv.reshape(-1),
            "returns": returns.reshape(-1),
            "dones_sum": float(done_buf.sum()),
            # REAL episode returns (steps/episodes is only valid for
            # +1-per-step envs like the builtin CartPole).
            "episode_return_sum": float(sum(ep_returns)),
            "episodes_done": float(len(ep_returns)),
        }

    def close(self):
        for e in self.envs:
            e.close()


def _logsumexp(x: np.ndarray) -> np.ndarray:
    m = x.max(axis=-1, keepdims=True)
    return m + np.log(np.exp(x - m).sum(axis=-1, keepdims=True))
