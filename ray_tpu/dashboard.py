"""Dashboard: REST aggregation of cluster state over HTTP.

Reference: ``dashboard/head.py`` — an HTTP head aggregating GCS state
(nodes, actors, tasks, objects, jobs, logs) behind ``/api/...`` routes,
plus a human landing page. The reference ships a React UI; here the API
surface is the deliverable (everything a UI or ``curl`` needs), with a
minimal self-contained HTML summary at ``/``.

Runs as a thread attached to a driver-style connection to the head —
read-only, so a plain threading HTTP server is plenty (the Serve data
plane, which is latency-sensitive, uses asyncio instead).

    from ray_tpu.dashboard import Dashboard
    dash = Dashboard(head_address)          # serves on 127.0.0.1:8265
    print(dash.url)

CLI: ``python -m ray_tpu.scripts.cli dashboard --address <head>``.
"""

from __future__ import annotations

import hmac
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer
from urllib.parse import parse_qs, urlparse

DEFAULT_PORT = 8265  # the reference dashboard's default


class Dashboard:
    def __init__(self, head_address: str, host: str = "127.0.0.1",
                 port: int = DEFAULT_PORT):
        from ray_tpu.cluster.rpc import RpcClient
        from ray_tpu.core.config import config

        self._head_address = head_address
        self.head = RpcClient(head_address)
        self._token = config.cluster_token.encode() or None
        # Host values a legitimate request can carry; anything else is a
        # browser being pointed at us via DNS rebinding. Only enforceable
        # for loopback binds: an operator binding 0.0.0.0 is reachable
        # under any address, so there the token (mutations) is the guard.
        if host in ("127.0.0.1", "localhost", "::1"):
            self._allowed_hosts = {host, "localhost", "127.0.0.1", "::1", ""}
        else:
            self._allowed_hosts = None  # non-loopback: any Host
        dash = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _respond(self, status, ctype, body):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _guard(self) -> bytes | None:
                """CSRF/DNS-rebinding + auth guard; None means allowed.

                Every request must carry a Host header matching the bound
                address (a rebinding page reaches us with its own domain in
                Host). Mutating requests — POST /api/jobs runs an arbitrary
                entrypoint, PUT /api/serve/applications imports a module —
                additionally require the cluster token when one is
                configured (cf. the reference's ShadowRay history: its
                dashboard shipped these routes unauthenticated)."""
                if dash._allowed_hosts is not None:
                    raw = self.headers.get("Host") or ""
                    if raw.startswith("["):  # bracketed IPv6 literal
                        hosthdr = raw[1:].partition("]")[0]
                    else:
                        hosthdr = raw.partition(":")[0]
                    if hosthdr not in dash._allowed_hosts:
                        return b'{"error": "bad Host header"}'
                if self.command == "GET":
                    return None
                if dash._token:
                    auth = self.headers.get("Authorization") or ""
                    supplied = auth.removeprefix("Bearer ").strip()
                    # Compare as bytes: header values are latin-1 strs and
                    # compare_digest(str, str) raises on non-ASCII.
                    if not hmac.compare_digest(
                            supplied.encode("latin-1", "replace"),
                            dash._token):
                        return (b'{"error": "cluster token required '
                                b'(Authorization: Bearer <token>)"}')
                return None

            def _handle(self, fn, *args):
                denied = self._guard()
                if denied is not None:
                    self._respond(403, "application/json", denied)
                    return
                try:
                    status, ctype, body = fn(*args)
                except Exception as e:  # surface handler bugs as 500s
                    status, ctype, body = (
                        500, "application/json",
                        json.dumps({"error": repr(e)}).encode(),
                    )
                self._respond(status, ctype, body)

            def do_GET(self):
                self._handle(dash._route, self.path)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                self._handle(dash._route_post, self.path, self.rfile.read(n))

            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                self._handle(dash._route_put, self.path, self.rfile.read(n))

            def do_DELETE(self):
                self._handle(dash._route_delete, self.path)

        # Single-threaded on purpose: requests serialize through ONE
        # handler thread, whose pooled RpcClient connection to the head is
        # reused across requests — a polling UI would otherwise dial (and
        # handshake) a fresh head connection per request. Read-only,
        # low-traffic: serialization is fine.
        self._server = HTTPServer((host, port), Handler)
        self.url = f"http://{host}:{self._server.server_address[1]}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()

    # -- routing -----------------------------------------------------------

    def _route(self, path: str):
        parsed = urlparse(path)
        route = parsed.path.rstrip("/") or "/"
        qs = {k: v[0] for k, v in parse_qs(parsed.query).items()}

        def ok_json(payload):
            return 200, "application/json", json.dumps(
                payload, default=str).encode()

        if route == "/":
            # Web frontend (dashboard/client analog): self-contained SPA
            # polling the same /api routes; no build step, no assets.
            from ray_tpu.dashboard_ui import INDEX_HTML

            return 200, "text/html", INDEX_HTML.encode()
        if route == "/status":
            return 200, "text/html", self._index_html().encode()
        if route == "/api/cluster_status":
            return ok_json(self._cluster_status())
        if route == "/api/nodes":
            return ok_json({"nodes": self.head.call("nodes")})
        if route == "/api/autoscaler":
            # Last autoscaler state report (per-type fleet counts,
            # quarantine/backoff, SLO burns); {} when none is attached.
            return ok_json(
                {"autoscaler": self.head.call("autoscaler_status")})
        if route == "/api/actors":
            return ok_json({"actors": self.head.call("list_actors")})
        if route == "/api/tasks":
            limit = int(qs.get("limit", 1000))
            return ok_json({"tasks": self.head.call("list_tasks", limit)})
        if route == "/api/objects":
            limit = int(qs.get("limit", 1000))
            # The head returns {"objects", "truncated", "total"} — pass
            # the clipping report through rather than hiding it.
            got = self.head.call("list_objects", limit)
            if not isinstance(got, dict):
                got = {"objects": got, "truncated": False,
                       "total": len(got)}
            return ok_json(got)
        if route == "/api/memory_summary":
            top = int(qs.get("top", 20))
            group_by = qs.get("group_by", "callsite")
            return ok_json(self.head.call(
                "memory_summary", top, group_by, timeout=30.0))
        if route == "/api/memory_leaks":
            return ok_json({"leaks": self.head.call(
                "memory_leaks", timeout=15.0)})
        if route == "/api/logs":
            after = int(qs.get("after_seq", 0))
            limit = int(qs.get("limit", 1000))
            cursor, entries = self.head.call("drain_logs", after, limit)
            return ok_json({"cursor": cursor, "entries": entries})
        if route == "/api/worker_logs":
            # Node reporter surface (reference dashboard's log index).
            return ok_json({"workers": self.head.call(
                "list_logs", timeout=15.0)})
        if route == "/api/worker_log":
            if "worker_id" not in qs:
                return (400, "application/json",
                        b'{"error": "worker_id is required"}')
            kwargs: dict = {"stream": qs.get("stream", "out")}
            if "offset" in qs:
                kwargs["offset"] = int(qs["offset"])
            else:
                kwargs["tail_lines"] = int(qs.get("tail", 200))
            return ok_json(self.head.call(
                "get_log", qs["worker_id"], timeout=20.0, **kwargs))
        if route == "/api/worker_stats":
            return ok_json({"workers": self.head.call(
                "worker_stats", qs.get("fresh") == "1", timeout=15.0)})
        if route == "/api/device_stats":
            # Devices pane: per-worker JAX/XLA snapshots (HBM + compile
            # counters), stubs where jax never loaded.
            return ok_json({"devices": self.head.call(
                "device_stats", qs.get("fresh") == "1", timeout=20.0)})
        if route == "/api/cluster_metrics":
            # The federated scrape body, proxied for humans/curl (the
            # head's own HTTP endpoint is the one Prometheus scrapes).
            text = self.head.call("cluster_metrics_text", timeout=30.0)
            return 200, "text/plain; version=0.0.4", text.encode()
        if route == "/api/stack":
            if "worker_id" not in qs:
                return (400, "application/json",
                        b'{"error": "worker_id is required"}')
            text = self.head.call(
                "dump_worker_stack", qs["worker_id"], timeout=30.0)
            return 200, "text/plain; charset=utf-8", text.encode()
        if route == "/api/profile":
            if "worker_id" not in qs:
                return (400, "application/json",
                        b'{"error": "worker_id is required"}')
            duration = min(float(qs.get("duration", 0.5)), 30.0)
            interval = float(qs.get("interval", 0.01))
            fmt = qs.get("fmt", "text")
            prof = self.head.call(
                "profile_worker", qs["worker_id"], duration, interval,
                timeout=duration + 60.0)
            from ray_tpu.util import stack_sampler

            if fmt == "text":
                return (200, "text/plain; charset=utf-8",
                        stack_sampler.text_report(prof).encode())
            if fmt == "collapsed":
                return (200, "text/plain; charset=utf-8",
                        stack_sampler.collapsed(prof).encode())
            if fmt == "chrome":
                return ok_json(stack_sampler.chrome_trace(prof))
            return ok_json(prof)
        if route == "/api/placement_groups":
            return ok_json(
                {"placement_groups": self.head.call(
                    "placement_group_table")})
        if route == "/api/pubsub_stats":
            return ok_json(self.head.call("pubsub_stats"))
        if route == "/api/grafana_dashboard":
            # Generated Grafana JSON (reference
            # grafana_dashboard_factory.py): import into Grafana against
            # a Prometheus source scraping the cluster's /metrics.
            from ray_tpu.util.grafana import generate_dashboard

            return ok_json(generate_dashboard())
        if route == "/api/jobs" or route.startswith("/api/jobs/"):
            return self._jobs_get(route)
        if route == "/api/data_stats":
            # Input-pipeline pane: per-stage rollup + consumer-loop
            # stall fraction from the training goodput plane (pure
            # metrics read — no actors spawned).
            self._ensure_client()
            from ray_tpu import state as _state

            return ok_json(_state.data_stats())
        if route == "/api/train_stats":
            # Training goodput pane: per-trial step phases, rank skew,
            # downtime ledger.
            self._ensure_client()
            from ray_tpu import state as _state

            return ok_json(_state.train_stats())
        if route == "/api/serve_stats":
            # Serve pane: per-deployment SLO rollup from the request
            # latency plane. Same no-controller guard as the
            # applications route — a GET must not spawn a controller.
            from ray_tpu.serve import _private as serve_private

            if self.head.call(
                    "get_named_actor", serve_private.CONTROLLER_NAME) is None:
                return ok_json({"deployments": {}})
            from ray_tpu import serve

            self._ensure_client()
            # ?window= answers from the head's metrics history ring —
            # no stall by construction (allow_sleep=False forbids the
            # legacy double-scrape, which would block this single
            # handler thread and stall every other pane). No window,
            # or no ring (signal plane disabled): single scrape,
            # cumulative counts only.
            window = float(qs.get("window", 0.0) or 0.0)
            return ok_json(serve.stats(
                window_s=window, allow_sleep=False))
        if route == "/api/signals":
            # Signals pane: SLO burn-rate table + the `top` rollup from
            # the head's history ring; ?op=...&name=... runs one ad-hoc
            # windowed query. Pure ring reads — zero sleeps.
            window = float(qs.get("window", 60.0) or 60.0)
            if qs.get("op"):
                spec = {"op": qs["op"], "name": qs.get("name", ""),
                        "window_s": window}
                if qs.get("q"):
                    spec["q"] = float(qs["q"])
                if qs.get("group_by"):
                    spec["group_by"] = qs["group_by"]
                return ok_json(self.head.call("query_metrics", spec))
            return ok_json({
                "slo": self.head.call("slo_status"),
                "top": self.head.call("signal_top", window),
            })
        if route == "/api/traces":
            # Traces pane: kept-trace summaries + store health, plus
            # the windowed TTFT decomposition. Head-side ring reads.
            window = float(qs.get("window", 0.0) or 0.0)
            return ok_json({
                "traces": self.head.call(
                    "list_traces", int(qs.get("limit", 50) or 50)),
                "stats": self.head.call("trace_stats"),
                "ttft": self.head.call(
                    "ttft_decomposition", window or None,
                    qs.get("deployment") or None),
            })
        if route == "/api/trace":
            tid = qs.get("id", "")
            tr = self.head.call("get_trace", tid) if tid else None
            if tr is None:
                return (404, "application/json",
                        json.dumps({"error": f"no trace {tid!r}"})
                        .encode())
            return ok_json(tr)
        if route == "/api/serve/applications":
            # Read-only: a cluster that never used serve must stay
            # untouched — probe the controller through the head's named
            # actor table instead of get_or_create (a GET must not spawn
            # a controller actor).
            from ray_tpu.serve import _private as serve_private

            if self.head.call(
                    "get_named_actor", serve_private.CONTROLLER_NAME) is None:
                return ok_json({"applications": {}})
            from ray_tpu import serve

            self._ensure_client()
            return ok_json({"applications": serve.status()})
        return 404, "application/json", b'{"error": "no such route"}'

    # -- jobs REST (reference dashboard/modules/job/job_head.py) -----------

    def _jobs_client(self):
        if getattr(self, "_jobs", None) is None:
            from ray_tpu.job_submission import JobSubmissionClient

            self._ensure_client()
            self._jobs = JobSubmissionClient()
        return self._jobs

    def _jobs_get(self, route: str):
        def ok(payload):
            return 200, "application/json", json.dumps(
                payload, default=str).encode()

        client = self._jobs_client()
        if route == "/api/jobs":
            return ok({"jobs": client.list_jobs()})
        rest = route[len("/api/jobs/"):]
        job_id = rest[: -len("/logs")] if rest.endswith("/logs") else rest
        if not any(j["job_id"] == job_id for j in client.list_jobs()):
            return (404, "application/json",
                    json.dumps({"error": f"no such job {job_id!r}"}).encode())
        if rest.endswith("/logs"):
            return ok({"logs": client.get_job_logs(job_id)})
        return ok(client.get_job_info(job_id))

    def _route_post(self, path: str, payload: bytes):
        route = urlparse(path).path.rstrip("/")
        if route == "/api/jobs":
            cfg = json.loads(payload or b"{}")
            if "entrypoint" not in cfg:
                return (400, "application/json",
                        b'{"error": "entrypoint is required"}')
            client = self._jobs_client()
            job_id = client.submit_job(
                entrypoint=cfg["entrypoint"],
                job_id=cfg.get("submission_id") or cfg.get("job_id"),
                runtime_env=cfg.get("runtime_env"),
                metadata=cfg.get("metadata"),
            )
            return 200, "application/json", json.dumps(
                {"submission_id": job_id, "job_id": job_id}).encode()
        if route.startswith("/api/jobs/") and route.endswith("/stop"):
            job_id = route[len("/api/jobs/"):-len("/stop")]
            stopped = self._jobs_client().stop_job(job_id)
            return 200, "application/json", json.dumps(
                {"stopped": bool(stopped)}).encode()
        return 404, "application/json", b'{"error": "no such route"}'

    # -- serve REST (reference dashboard/modules/serve) --------------------

    def _ensure_client(self):
        """Serve operations need a cluster client in this process (the
        controller is an actor); the read-only routes stay head-RPC-only."""
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(address=self._head_address)

    def _route_put(self, path: str, payload: bytes):
        route = urlparse(path).path.rstrip("/")
        if route != "/api/serve/applications":
            return 404, "application/json", b'{"error": "no such route"}'
        # Declarative deploy (reference serve REST schema): applications
        # with an import_path "module:attr" resolving to a bound
        # Application (or Deployment), plus per-deployment overrides.
        import importlib

        from ray_tpu import serve

        self._ensure_client()
        cfg = json.loads(payload or b"{}")
        deployed = []
        _OVERRIDABLE = ("num_replicas", "max_concurrent_queries",
                        "autoscaling_config")

        def apply_overrides(value, overrides):
            """Per-deployment overrides apply ANYWHERE in the app's graph
            by deployment name (reference serve REST schema semantics),
            not just to the ingress."""
            if isinstance(value, serve.Deployment):
                value = value.bind()
            if isinstance(value, serve.Application):
                dep = value.deployment
                ov = overrides.get(dep.name)
                if ov:
                    dep = dep.options(**{k: v for k, v in ov.items()
                                         if k in _OVERRIDABLE})
                return serve.Application(
                    dep,
                    tuple(apply_overrides(a, overrides)
                          for a in value.init_args),
                    {k: apply_overrides(v, overrides)
                     for k, v in value.init_kwargs.items()},
                )
            if isinstance(value, (list, tuple)):
                return type(value)(
                    apply_overrides(v, overrides) for v in value)
            if isinstance(value, dict):
                return {k: apply_overrides(v, overrides)
                        for k, v in value.items()}
            return value

        for app in cfg.get("applications", []):
            mod_name, _, attr = app["import_path"].partition(":")
            target = getattr(importlib.import_module(mod_name), attr)
            overrides = {d["name"]: d for d in app.get("deployments", [])}
            target = apply_overrides(target, overrides)
            handle = serve.run(
                target,
                name=app.get("name"),
                route_prefix=app.get("route_prefix"),
            )
            deployed.append(handle.deployment_name)
        return 200, "application/json", json.dumps(
            {"deployed": deployed}).encode()

    def _route_delete(self, path: str):
        route = urlparse(path).path.rstrip("/")
        prefix = "/api/serve/applications/"
        if not route.startswith(prefix):
            return 404, "application/json", b'{"error": "no such route"}'
        from ray_tpu import serve

        self._ensure_client()
        serve.delete(route[len(prefix):])
        return 200, "application/json", b'{"deleted": true}'

    def _cluster_status(self):
        nodes = self.head.call("nodes")
        total = self.head.call("cluster_resources")
        avail = self.head.call("available_resources")
        return {
            "head_address": self._head_address,
            "time": time.time(),
            "alive_nodes": sum(
                1 for n in nodes
                if n["Alive"] and n.get("State", "ALIVE") != "DRAINING"),
            "draining_nodes": sum(
                1 for n in nodes if n.get("State") == "DRAINING"),
            "dead_nodes": sum(1 for n in nodes if not n["Alive"]),
            "resources_total": total,
            "resources_available": avail,
        }

    def _index_html(self) -> str:
        import html as _html

        s = self._cluster_status()
        # Escape everything interpolated: resource names / addresses are
        # cluster-user-controlled strings.
        rows = "".join(
            f"<tr><td>{_html.escape(str(k))}<td><code>"
            f"{_html.escape(json.dumps(v, default=str))}</code>"
            for k, v in s.items()
        )
        api = ["/api/cluster_status", "/api/nodes", "/api/autoscaler",
               "/api/actors",
               "/api/tasks", "/api/objects", "/api/memory_summary",
               "/api/memory_leaks", "/api/logs",
               "/api/worker_logs", "/api/worker_stats",
               "/api/device_stats", "/api/cluster_metrics",
               "/api/placement_groups", "/api/pubsub_stats",
               "/api/serve_stats", "/api/data_stats",
               "/api/train_stats", "/api/signals", "/api/traces"]
        links = "".join(f'<li><a href="{r}">{r}</a></li>' for r in api)
        return (
            "<!doctype html><title>ray_tpu dashboard</title>"
            "<h1>ray_tpu cluster</h1>"
            f"<table border=1 cellpadding=4>{rows}</table>"
            f"<h2>API</h2><ul>{links}</ul>"
        )
