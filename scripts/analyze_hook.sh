#!/usr/bin/env bash
# Pre-push wrapper around `ray-tpu analyze --diff`: fail the push when
# the outgoing commits introduce NEW analyzer findings (lock-order,
# blocking-under-lock, finalizer, async-lock, contract drift, retry/
# idempotence, daemon-loop, timeout-ordering, JAX hot-path, lifecycle).
#
# Install:
#   ln -s ../../scripts/analyze_hook.sh .git/hooks/pre-push
# or run ad hoc before pushing:
#   scripts/analyze_hook.sh [upstream-rev]
#
# The diff base defaults to @{upstream} (falling back to origin/main,
# then HEAD~1) so the gate sees exactly the lines this push adds —
# pre-existing findings stay the full repo-wide run's business
# (tests/test_static_analysis.py keeps that clean in tier-1).
set -u

repo_root="$(cd "$(dirname "$0")/.." && pwd)"
cd "$repo_root" || exit 2

base="${1:-}"
if [ -z "$base" ]; then
    if git rev-parse --verify -q '@{upstream}' >/dev/null 2>&1; then
        base='@{upstream}'
    elif git rev-parse --verify -q origin/main >/dev/null 2>&1; then
        base=origin/main
    else
        base=HEAD~1
    fi
fi

echo "analyze_hook: checking lines changed since ${base}" >&2
python -m ray_tpu.scripts.analyze --diff "$base"
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "analyze_hook: push blocked — fix the findings above (or" >&2
    echo "justify them in ANALYZE_BASELINE.json / an inline pragma" >&2
    echo "with a reason; head.py lock-order and blocking findings" >&2
    echo "must be fixed, never baselined)." >&2
fi
exit "$rc"
