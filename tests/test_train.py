"""Train library tests (modeled on the reference's
``python/ray/train/tests/test_data_parallel_trainer.py`` and
``test_backend_executor`` behaviors: multi-worker groups on CPU, reporting,
checkpoints, elastic restart)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import Checkpoint, session


@pytest.fixture(autouse=True, scope="module")
def _runtime():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_basic_report_and_result():
    def loop(config):
        for i in range(3):
            session.report({"step": i, "loss": 1.0 / (i + 1)})

    trainer = train.DataParallelTrainer(
        loop, scaling_config=train.ScalingConfig(num_workers=2)
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert len(result.metrics_history) == 3  # rank-0 reports only


def test_world_rank_and_size():
    def loop(config):
        session.report(
            {"rank": session.get_world_rank(), "ws": session.get_world_size()}
        )

    result = train.DataParallelTrainer(
        loop, scaling_config=train.ScalingConfig(num_workers=3)
    ).fit()
    assert result.metrics == {"rank": 0, "ws": 3}


def test_dataset_sharding():
    data = np.arange(12)

    def loop(config):
        shard = session.get_dataset_shard("train")
        session.report({"total": int(np.sum(shard)), "n": len(shard)})

    result = train.DataParallelTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=2),
        datasets={"train": data},
    ).fit()
    assert result.metrics["n"] == 6  # 12 items over 2 workers


def test_checkpoint_reported_and_best_kept():
    def loop(config):
        for i in range(4):
            session.report(
                {"score": i},
                checkpoint=Checkpoint.from_dict({"model": i}),
            )

    result = train.DataParallelTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(
            checkpoint_config=train.CheckpointConfig(
                num_to_keep=2, checkpoint_score_attribute="score"
            )
        ),
    ).fit()
    assert result.checkpoint is not None
    assert result.checkpoint.to_dict()["model"] == 3  # best score


def test_checkpoint_roundtrip_forms(tmp_path):
    ckpt = Checkpoint.from_dict({"weights": np.ones(4), "step": 7})
    d = ckpt.to_directory(str(tmp_path / "ck"))
    restored = Checkpoint.from_directory(d).to_dict()
    assert restored["step"] == 7
    np.testing.assert_allclose(restored["weights"], np.ones(4))
    ref = ckpt.to_object_ref()
    again = Checkpoint.from_object_ref(ref).to_dict()
    assert again["step"] == 7


def test_elastic_restart_resumes_from_checkpoint():
    """First attempt dies mid-run; retry resumes from the checkpoint."""

    def loop(config):
        start = 0
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_dict()["step"] + 1
        for i in range(start, 4):
            session.report(
                {"step": i}, checkpoint=Checkpoint.from_dict({"step": i})
            )
            if i == 1 and ckpt is None and session.get_world_rank() == 0:
                raise RuntimeError("simulated worker failure")

    result = train.DataParallelTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(
            failure_config=train.FailureConfig(max_failures=2)
        ),
    ).fit()
    assert result.error is None
    assert result.metrics["step"] == 3
    # resumed at 2 (ckpt step 1 + 1), so 0,1 then 2,3 -> 4 reports
    steps = [m["step"] for m in result.metrics_history]
    assert steps == [0, 1, 2, 3]


def test_failure_exhausts_retries():
    def loop(config):
        raise RuntimeError("always fails")

    result = train.DataParallelTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(
            failure_config=train.FailureConfig(max_failures=1)
        ),
    ).fit()
    assert result.error is not None


def test_jax_trainer_mnist_style_mesh(devices8):
    """End-to-end: jitted data-parallel train step inside a train loop on
    the 8-device CPU mesh (the SURVEY.md §7 minimum end-to-end slice)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel.mesh import MeshConfig, build_mesh

    def loop(config):
        mesh = build_mesh(MeshConfig(dp=8))
        w_shard = NamedSharding(mesh, P())
        x_shard = NamedSharding(mesh, P(("dp",)))

        def loss_fn(w, batch):
            x, y = batch
            pred = x @ w
            return jnp.mean((pred - y) ** 2)

        @jax.jit
        def step(w, batch):
            l, g = jax.value_and_grad(loss_fn)(w, batch)
            return w - 0.1 * g, l

        rng = np.random.default_rng(0)
        w = jax.device_put(jnp.zeros((4, 1)), w_shard)
        true_w = np.array([[1.0], [-2.0], [3.0], [0.5]])
        for i in range(30):
            x = rng.normal(size=(64, 4)).astype(np.float32)
            y = (x @ true_w).astype(np.float32)
            batch = (
                jax.device_put(x, x_shard),
                jax.device_put(y, x_shard),
            )
            w, l = step(w, batch)
        session.report({"final_loss": float(l)})

    result = train.JaxTrainer(
        loop, scaling_config=train.ScalingConfig(num_workers=1)
    ).fit()
    assert result.error is None
    assert result.metrics["final_loss"] < 1e-2


def test_sharded_checkpoint_roundtrip(tmp_path, devices8):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel.mesh import MeshConfig, build_mesh

    mesh = build_mesh(MeshConfig(dp=8))
    sharding = NamedSharding(mesh, P("dp"))
    state = {
        "w": jax.device_put(jnp.arange(16.0).reshape(8, 2), sharding),
        "step": jnp.asarray(5),
    }
    path = str(tmp_path / "sharded")
    train.save_sharded(state, path)
    restored = train.load_sharded(
        path, {"w": sharding, "step": NamedSharding(mesh, P())}
    )
    np.testing.assert_allclose(
        np.asarray(restored["w"]), np.arange(16.0).reshape(8, 2)
    )
    assert restored["w"].sharding == sharding
    assert int(restored["step"]) == 5
