"""Serve request-path observability (PR 8): end-to-end trace
propagation across the HTTP and handle paths, per-phase SLO histograms,
deadline sheds at the router and the batch queue, metrics federation
with dead-replica pruning, and the serve_bench client/server latency
cross-check.

Test order matters (``-p no:randomly`` keeps definition order): the
serve_bench and cluster-federation tests tear down the module's local
runtime, so they run last.
"""

import json
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve, state
from ray_tpu.scripts import bench_log
from ray_tpu.serve import _observability as obs
from ray_tpu.util import metrics, tracing


@pytest.fixture(autouse=True, scope="module")
def _runtime():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=16)
    yield
    try:
        if ray_tpu.is_initialized():
            serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _clean_between_tests():
    yield
    tracing.disable()
    try:
        if ray_tpu.is_initialized():
            serve.shutdown()
    except Exception:
        pass


def _snapshot():
    return obs.parse_prometheus(metrics.prometheus_text())


def _delta_since(before):
    return obs.diff_parsed(before, _snapshot())


# -- trace propagation ------------------------------------------------------


def test_trace_propagation_handle_path_one_trace():
    """One trace id covers client -> router -> replica -> NESTED handle
    call, with parent/child nesting intact (the tentpole's acceptance
    shape, on the handle path)."""

    @serve.deployment(name="TraceInner")
    class Inner:
        def __call__(self, x):
            return x * 2

    @serve.deployment(name="TraceOuter")
    class Outer:
        def __init__(self, inner):
            self.inner = inner

        def __call__(self, x):
            return ray_tpu.get(self.inner.remote(x), timeout=30) + 1

    handle = serve.run(Outer.bind(Inner.bind()))
    tracing.enable()
    with tracing.span("client-root") as root:
        assert ray_tpu.get(handle.remote(5), timeout=60) == 11
        trace_id = root["trace_id"]

    spans = {s["span_id"]: s for s in tracing.collect()
             if s["trace_id"] == trace_id and s.get("cat") == "serve"}
    by_name = {}
    for s in spans.values():
        by_name.setdefault(s["name"], []).append(s)
    assert "serve.route:TraceOuter" in by_name
    assert "serve.replica:TraceOuter.__call__" in by_name
    assert "serve.route:TraceInner" in by_name
    assert "serve.replica:TraceInner.__call__" in by_name

    route_outer = by_name["serve.route:TraceOuter"][0]
    rep_outer = by_name["serve.replica:TraceOuter.__call__"][0]
    route_inner = by_name["serve.route:TraceInner"][0]
    rep_inner = by_name["serve.replica:TraceInner.__call__"][0]
    # Parenting: client root -> route(outer) -> replica(outer) ->
    # route(inner) -> replica(inner).
    assert route_outer["parent_id"] == root["span_id"]
    assert rep_outer["parent_id"] == route_outer["span_id"]
    assert route_inner["parent_id"] == rep_outer["span_id"]
    assert rep_inner["parent_id"] == route_inner["span_id"]

    # The merged timeline carries the serve spans under cat "serve".
    serve_events = [e for e in state.timeline()
                    if e.get("cat") == "serve"]
    ids = {e["args"].get("span_id") for e in serve_events}
    assert route_outer["span_id"] in ids and rep_inner["span_id"] in ids


def test_trace_propagation_http_traceparent():
    """A W3C traceparent header at the HTTP proxy joins the caller's
    trace: http ingress span -> route -> replica all carry the header's
    trace id."""
    import http.client

    @serve.deployment(name="HttpTraced", route_prefix="/traced")
    def traced(payload):
        return {"ok": True}

    serve.run(traced.bind())
    port = serve.start_http_proxy()
    # Server-side opt-in: a traceparent header joins a trace only when
    # tracing is already enabled here (the proxy shares this process on
    # the local backend) — the header alone must not switch tracing on.
    conn0 = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn0.request("POST", "/traced", body=b"null", headers={
        "Content-Type": "application/json",
        "traceparent": f"00-{'ef' * 16}-{'01' * 8}-01",
    })
    assert conn0.getresponse().status == 200
    conn0.close()
    assert not tracing.is_enabled()
    assert not any(s["trace_id"] == "ef" * 16 for s in tracing.collect())

    tracing.enable()
    trace_id = "ab" * 16
    parent_span = "cd" * 8
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", "/traced", body=b"null", headers={
        "Content-Type": "application/json",
        "traceparent": f"00-{trace_id}-{parent_span}-01",
    })
    resp = conn.getresponse()
    assert resp.status == 200
    resp.read()
    conn.close()

    spans = [s for s in tracing.collect()
             if s["trace_id"] == trace_id and s.get("cat") == "serve"]
    names = {s["name"] for s in spans}
    assert "serve.http:/traced" in names
    assert "serve.route:HttpTraced" in names
    assert any(n.startswith("serve.replica:HttpTraced") for n in names)
    http_span = next(s for s in spans if s["name"] == "serve.http:/traced")
    assert http_span["parent_id"] == parent_span
    route_span = next(s for s in spans
                      if s["name"] == "serve.route:HttpTraced")
    assert route_span["parent_id"] == http_span["span_id"]


# -- SLO latency plane ------------------------------------------------------


def test_phase_histograms_populated_per_phase():
    before = _snapshot()

    @serve.deployment(name="PhaseDep")
    def phased(x):
        time.sleep(0.002)
        return x

    handle = serve.run(phased.bind())
    for i in range(6):
        assert ray_tpu.get(handle.remote(i), timeout=30) == i

    delta = _delta_since(before)
    for phase in ("route", "queue_wait", "execute", "serialize", "total"):
        dist = obs.histogram_dist(
            delta, "ray_tpu_serve_request_seconds",
            deployment="PhaseDep", phase=phase)
        assert dist is not None, f"phase {phase} unobserved"
        assert dist["count"] == 6, (phase, dist)
    # Status counted once per request, router-side.
    statuses = obs.sum_counter(delta, "ray_tpu_serve_requests_total",
                               "status", deployment="PhaseDep")
    assert statuses == {"ok": 6.0}


def test_batch_wait_phase_and_batch_size_histogram():
    before = _snapshot()

    @serve.deployment(name="BatchDep", max_concurrent_queries=32)
    class BatchModel:
        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05)
        def handle_batch(self, items):
            return [i * 2 for i in items]

        def __call__(self, x):
            return self.handle_batch(x)

    handle = serve.run(BatchModel.bind())
    refs = [handle.remote(i) for i in range(12)]
    assert sorted(ray_tpu.get(refs, timeout=60)) == \
        [2 * i for i in range(12)]

    delta = _delta_since(before)
    wait = obs.histogram_dist(delta, "ray_tpu_serve_request_seconds",
                              deployment="BatchDep", phase="batch_wait")
    assert wait is not None and wait["count"] == 12
    sizes = obs.histogram_dist(delta, "ray_tpu_serve_batch_size",
                               deployment="BatchDep")
    assert sizes is not None and sizes["count"] >= 1
    # Batching actually batched: fewer batches than items.
    assert sizes["count"] < 12


def test_deadline_shed_at_router():
    """A request whose deadline expires while the router waits for
    replica capacity is shed (typed error, counted) instead of executed
    late."""
    before = _snapshot()
    executed = []

    @serve.deployment(name="ShedRouter", num_replicas=1,
                      max_concurrent_queries=1)
    class Slow:
        def __call__(self, x):
            executed.append(x)
            time.sleep(0.4)
            return x

    handle = serve.run(Slow.bind())
    blocker = handle.remote("blocker")
    time.sleep(0.1)  # in flight, capacity now 0
    ref = handle.options(deadline_s=0.05).remote("victim")
    with pytest.raises(Exception) as ei:
        ray_tpu.get(ref, timeout=30)
    assert "RequestShedError" in repr(ei.value) or "shed" in repr(ei.value)
    assert ray_tpu.get(blocker, timeout=30) == "blocker"
    time.sleep(0.1)
    assert "victim" not in executed  # dead work was NOT executed

    delta = _delta_since(before)
    sheds = obs.sum_counter(delta, "ray_tpu_serve_shed_total", "reason",
                            deployment="ShedRouter")
    assert sheds.get("router", 0) >= 1
    statuses = obs.sum_counter(delta, "ray_tpu_serve_requests_total",
                               "status", deployment="ShedRouter")
    assert statuses.get("shed", 0) >= 1


def test_deadline_shed_at_batch_queue():
    """A batched request whose deadline expires while queued behind a
    slow batch is shed by the batch loop, not executed."""
    before = _snapshot()
    seen = []

    @serve.deployment(name="ShedBatch", max_concurrent_queries=32)
    class SlowBatch:
        @serve.batch(max_batch_size=2, batch_wait_timeout_s=0.01)
        def handle_batch(self, items):
            seen.extend(items)
            time.sleep(0.4)
            return [i for i in items]

        def __call__(self, x):
            return self.handle_batch(x)

    handle = serve.run(SlowBatch.bind())
    first = handle.remote("first")
    time.sleep(0.15)  # first batch is mid-execution (0.4s)
    victim = handle.options(deadline_s=0.1).remote("victim")
    with pytest.raises(Exception) as ei:
        ray_tpu.get(victim, timeout=30)
    assert "RequestShedError" in repr(ei.value) or "shed" in repr(ei.value)
    assert ray_tpu.get(first, timeout=30) == "first"
    time.sleep(0.1)
    assert "victim" not in seen

    delta = _delta_since(before)
    sheds = obs.sum_counter(delta, "ray_tpu_serve_shed_total", "reason",
                            deployment="ShedBatch")
    assert sheds.get("batch", 0) >= 1


def test_http_deadline_header_returns_503():
    import http.client

    @serve.deployment(name="Shed503", route_prefix="/shed503")
    def slow(payload):
        time.sleep(0.2)
        return {"ok": True}

    serve.run(slow.bind())
    port = serve.start_http_proxy()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", "/shed503", body=b"null", headers={
        "Content-Type": "application/json",
        serve.DEADLINE_HEADER: "0",
    })
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    assert resp.status == 503
    assert body.get("shed") == "router"


# -- probe exclusion + reconcile gauge --------------------------------------


def test_probes_excluded_from_metrics_and_traces():
    """Controller health probes / autoscaling / long-polls run every
    250ms — they must appear in NEITHER the request counters NOR the
    trace stream; the reconcile pass exports its duration gauge."""
    before = _snapshot()

    @serve.deployment(name="ProbeDep", num_replicas=2)
    def probed(x):
        return x

    handle = serve.run(probed.bind())
    for i in range(5):
        assert ray_tpu.get(handle.remote(i), timeout=30) == i

    tracing.enable()
    tracing.collect(clear=True)  # only spans from here on
    time.sleep(1.2)  # ~5 reconcile ticks of probes + long-polls
    spans = tracing.collect(clear=True)
    polluters = [s["name"] for s in spans
                 if any(k in s["name"] for k in (
                     "get_num_ongoing", "check_health",
                     "listen_for_change", "get_routing_table"))]
    assert polluters == [], polluters

    delta = _delta_since(before)
    statuses = obs.sum_counter(delta, "ray_tpu_serve_requests_total",
                               "status", deployment="ProbeDep")
    # EXACTLY the 5 user requests — probes counted nothing.
    assert statuses == {"ok": 5.0}
    parsed = _snapshot()
    assert parsed.get("ray_tpu_serve_reconcile_seconds"), \
        "reconcile duration gauge never exported"


# -- stats surfaces ---------------------------------------------------------


def test_serve_stats_and_cli(capsys):
    @serve.deployment(name="StatsDep", num_replicas=2)
    def stats_dep(x):
        time.sleep(0.002)
        return x

    handle = serve.run(stats_dep.bind())
    for i in range(4):
        ray_tpu.get(handle.remote(i), timeout=30)

    st = serve.stats()
    entry = st["deployments"]["StatsDep"]
    assert entry["replicas"] == 2
    assert entry["count"] >= 4
    assert entry["requests"]["ok"] >= 4
    assert entry["p50_ms"] is not None and entry["p99_ms"] is not None
    assert set(entry["phases"]) >= {"route", "queue_wait", "execute"}

    from ray_tpu.scripts import cli

    cli.main(["serve", "stats", "--window", "0", "--phases"])
    out = capsys.readouterr().out
    assert "StatsDep" in out and "p99" in out

    cli.main(["serve", "stats", "--window", "0", "--json"])
    out = capsys.readouterr().out
    assert json.loads(out)["deployments"]["StatsDep"]["replicas"] == 2


def test_grafana_dashboard_has_serve_panels():
    from ray_tpu.util.grafana import generate_dashboard

    titles = [p["title"] for p in generate_dashboard()["panels"]]
    for family in ("ray_tpu_serve_request_seconds",
                   "ray_tpu_serve_requests_total",
                   "ray_tpu_serve_shed_total",
                   "ray_tpu_serve_replica_ongoing"):
        assert any(family in t for t in titles), family


# -- evidence lint ----------------------------------------------------------


def test_bench_log_validates_serve_latency(tmp_path):
    path = str(tmp_path / "trail.jsonl")
    # script= provenance rides along (as serve_bench emits it): the
    # 'bench' shape must win over the throughput-point 'script' shape.
    entry = bench_log.record_serve_latency(
        client={"p50_ms": 3.2, "p99_ms": 9.9, "count": 10},
        server={"count": 10, "p50_ms": 3.0},
        agreement={"ok": True, "count_exact": True},
        mode="http", connections=4, n_requests=10,
        device="tpu", path=path, script="serve_bench")
    assert entry["committed_to"] == path
    assert bench_log.check_file(path) == []

    # A client-only line (no server view / verdict) must fail the lint.
    with open(path, "a") as f:
        f.write(json.dumps({
            "bench": "serve_latency", "ts": 1.0, "device": "tpu",
            "client": {"p50_ms": 1.0, "p99_ms": 2.0}}) + "\n")
    problems = "\n".join(bench_log.check_file(path))
    assert "server.count" in problems and "agreement.ok" in problems

    # CPU numbers stay out of the trail entirely.
    assert bench_log.record_serve_latency(
        client={"p50_ms": 1, "p99_ms": 2}, server={"count": 1},
        agreement={"ok": True}, device="cpu",
        path=path)["committed_to"] is None


def test_handle_options_deadline_semantics():
    from ray_tpu.serve._private import DeploymentHandle

    h = DeploymentHandle("D")
    h5 = h.options(deadline_s=5.0)
    assert h5.deadline_s == 5.0 and h.deadline_s is None
    assert h5.options().deadline_s == 5.0  # omitted: inherited
    assert h5.options(deadline_s=None).deadline_s is None  # explicit: clears
    assert h5.method.deadline_s == 5.0  # method access preserves it
    # Round-trips through pickle (handles ride into replicas).
    import pickle

    assert pickle.loads(pickle.dumps(h5)).deadline_s == 5.0


def test_traceparent_helpers_roundtrip():
    ctx = {"trace_id": "ab" * 16, "span_id": "12" * 8}
    hdr = tracing.format_traceparent(ctx)
    assert hdr == f"00-{'ab' * 16}-{'12' * 8}-01"
    assert tracing.parse_traceparent(hdr) == ctx
    for bad in (None, "", "00-short-bad-01", "garbage",
                f"00-{'0' * 32}-{'12' * 8}-01",  # zero trace id
                f"00-{'zz' * 16}-{'12' * 8}-01"):  # non-hex
        assert tracing.parse_traceparent(bad) is None


# -- cross-check + cluster federation (these re-init the runtime: last) ----


def test_serve_bench_client_server_crosscheck(monkeypatch):
    """Small in-process serve_bench run: the client-side latencies and
    the server-side histograms must agree (count exact, quantiles
    within bucket resolution)."""
    monkeypatch.setenv("RAY_TPU_BENCH_LOG", "")
    from ray_tpu.scripts import serve_bench

    res = serve_bench.run(mode="handle", connections=3,
                          requests_per_conn=6, sleep_ms=1.0,
                          shed_probes=2, trace_check=True)
    assert res["agreement"]["ok"], res["agreement"]
    assert res["client"]["count"] == 18
    assert res["server"]["count"] == 18
    assert res["shed"]["client_seen"] == 2
    assert res["trace"]["one_trace"]
    assert set(res["phases_observed"]) >= {
        "route", "queue_wait", "execute", "serialize", "total"}


def test_federation_one_scrape_and_dead_replica_pruned():
    """Cluster backend: serve observations ship over the worker-events
    plane into the agent registry, federate on ONE /metrics/cluster
    scrape, and a deleted deployment's replica gauges are retracted
    when its workers die."""
    from ray_tpu.cluster.cluster_utils import Cluster
    from ray_tpu.cluster.gcs_client import GcsClient

    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=8)
    c.wait_for_nodes()
    ray_tpu.init(c.address)
    gcs = GcsClient(c.address)
    try:
        @serve.deployment(name="FedDep", num_replicas=2,
                          max_concurrent_queries=8)
        class Echo:
            def __call__(self, x):
                time.sleep(0.01)
                return x

        handle = serve.run(Echo.bind())
        refs = [handle.remote(i) for i in range(12)]
        assert sorted(ray_tpu.get(refs, timeout=120)) == list(range(12))

        # One scrape of the federated endpoint must carry the serve
        # series (worker flush 0.25s + agent apply: poll).
        deadline = time.monotonic() + 30
        dist = None
        parsed = {}
        while time.monotonic() < deadline:
            parsed = obs.parse_prometheus(gcs.metrics.cluster_text())
            dist = obs.histogram_dist(
                parsed, "ray_tpu_serve_request_seconds",
                deployment="FedDep", phase="total")
            if dist and dist["count"] >= 12:
                break
            time.sleep(0.5)
        assert dist and dist["count"] >= 12
        statuses = obs.sum_counter(
            parsed, "ray_tpu_serve_requests_total", "status",
            deployment="FedDep")
        assert statuses.get("ok", 0) >= 12

        def ongoing_series(p):
            return [labels for labels in
                    (p.get("ray_tpu_serve_replica_ongoing") or {})
                    if dict(labels).get("deployment") == "FedDep"]

        # Replica gauges present while the deployment lives...
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not ongoing_series(parsed):
            time.sleep(0.5)
            parsed = obs.parse_prometheus(gcs.metrics.cluster_text())
        assert ongoing_series(parsed)

        # ...and retracted once its replicas die.
        serve.delete("FedDep")
        deadline = time.monotonic() + 60
        leftover = ongoing_series(parsed)
        while time.monotonic() < deadline:
            parsed = obs.parse_prometheus(gcs.metrics.cluster_text())
            leftover = ongoing_series(parsed)
            if not leftover:
                break
            time.sleep(1.0)
        assert not leftover, f"dead replica series survived: {leftover}"
    finally:
        gcs.close()
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
        c.shutdown()


@pytest.mark.slow
def test_serve_bench_smoke_slow(monkeypatch):
    """Standing harness gate (test_scalebench_smoke pattern): the full
    serve_bench shape — HTTP mode, batching, sheds, trace check — runs
    end to end and the client/server cross-check holds."""
    monkeypatch.setenv("RAY_TPU_BENCH_LOG", "")
    from ray_tpu.scripts import serve_bench

    res = serve_bench.run(mode="http", connections=6,
                          requests_per_conn=15, sleep_ms=2.0,
                          batch=True, shed_probes=4, trace_check=True)
    assert res["agreement"]["ok"], res["agreement"]
    assert res["trace"]["one_trace"]
    assert "batch_wait" in res["phases_observed"]
    assert res["shed"]["client_seen"] == 4
