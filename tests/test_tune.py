"""Tune tests (modeled on reference searcher/scheduler/trial-runner tests
in ``python/ray/tune/tests/``)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import (
    ASHAScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TuneConfig,
    Tuner,
)
from ray_tpu.tune.search_space import generate_variants


@pytest.fixture(autouse=True, scope="module")
def _runtime():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=16)
    yield
    ray_tpu.shutdown()


def test_generate_variants_grid_and_samples():
    space = {
        "lr": tune.grid_search([0.1, 0.01]),
        "bs": tune.grid_search([8, 16]),
        "wd": tune.uniform(0.0, 1.0),
        "depth": tune.randint(1, 5),
        "act": tune.choice(["relu", "gelu"]),
    }
    variants = generate_variants(space, num_samples=3, seed=0)
    assert len(variants) == 12  # 2x2 grid x 3 samples
    assert {(v["lr"], v["bs"]) for v in variants} == {
        (0.1, 8), (0.1, 16), (0.01, 8), (0.01, 16)
    }
    assert all(0.0 <= v["wd"] <= 1.0 for v in variants)
    assert all(v["depth"] in (1, 2, 3, 4) for v in variants)
    # deterministic under the same seed
    again = generate_variants(space, num_samples=3, seed=0)
    assert [v["wd"] for v in again] == [v["wd"] for v in variants]


def test_loguniform_bounds():
    vals = [tune.loguniform(1e-4, 1e-1).sample(np.random.default_rng(i))
            for i in range(50)]
    assert all(1e-4 <= v <= 1e-1 for v in vals)


def test_tuner_fit_and_best_result():
    def objective(config):
        score = -((config["x"] - 3.0) ** 2)
        tune.report(score=score)

    grid = Tuner(
        objective,
        param_space={"x": tune.grid_search([0.0, 1.0, 3.0, 5.0])},
        tune_config=TuneConfig(metric="score", mode="max"),
    ).fit()
    assert len(grid) == 4
    best = grid.get_best_result()
    assert best.config["x"] == 3.0
    assert best.metrics["score"] == 0.0
    df = grid.get_dataframe()
    assert "config/x" in df.columns and len(df) == 4


def test_trial_error_is_captured():
    def objective(config):
        if config["x"] == 1:
            raise RuntimeError("bad trial")
        tune.report(score=config["x"])

    grid = Tuner(
        objective,
        param_space={"x": tune.grid_search([0, 1, 2])},
        tune_config=TuneConfig(metric="score"),
    ).fit()
    assert len(grid.errors) == 1
    assert grid.get_best_result().config["x"] == 2


def test_asha_stops_bad_trials_early():
    iterations_run = {}

    def objective(config):
        for i in range(32):
            tune.report(score=config["target"] * (i + 1))

    # Descending order: good trials populate the rungs first, so the bad
    # ones are stopped at their first rung (async halving semantics — a
    # trial with no peers at a rung can never be stopped).
    grid = Tuner(
        objective,
        param_space={"target": tune.grid_search([10.0, 1.0, 0.1, 0.0])},
        tune_config=TuneConfig(
            metric="score",
            mode="max",
            scheduler=ASHAScheduler(
                metric="score", mode="max", max_t=32, grace_period=2,
                reduction_factor=2,
            ),
            max_concurrent_trials=1,  # deterministic rung order
        ),
    ).fit()
    best = grid.get_best_result()
    assert best.config["target"] == 10.0
    # the worst trial must have been stopped before 32 iterations
    worst = min(grid, key=lambda r: r.config["target"])
    assert len(worst.metrics_history) < 32


def test_median_stopping_rule_runs():
    def objective(config):
        for i in range(8):
            tune.report(score=config["q"])

    grid = Tuner(
        objective,
        param_space={"q": tune.grid_search([1.0, 1.0, 1.0, 0.0])},
        tune_config=TuneConfig(
            metric="score",
            scheduler=MedianStoppingRule(metric="score", grace_period=1,
                                         min_samples_required=2),
            max_concurrent_trials=2,
        ),
    ).fit()
    assert grid.get_best_result().metrics["score"] == 1.0


def test_trial_retry_from_checkpoint():
    def objective(config):
        ckpt = tune.get_checkpoint()
        start = ckpt.to_dict()["i"] + 1 if ckpt else 0
        for i in range(start, 5):
            tune.report({"i": i}, checkpoint=tune.Checkpoint.from_dict({"i": i}))
            if i == 2 and ckpt is None:
                raise RuntimeError("mid-trial crash")

    from ray_tpu.train.config import FailureConfig, RunConfig

    grid = Tuner(
        objective,
        param_space={"x": tune.grid_search([0])},
        tune_config=TuneConfig(metric="i"),
        run_config=RunConfig(failure_config=FailureConfig(max_failures=1)),
    ).fit()
    r = grid[0]
    assert r.error is None
    assert r.metrics["i"] == 4
    # history: 0,1,2 then resumed 3,4
    assert [m["i"] for m in r.metrics_history] == [0, 1, 2, 3, 4]


def test_pbt_exploits_and_perturbs():
    """Low-lr trials should adopt (a perturbation of) the best lr."""

    def objective(config):
        # score grows with lr; PBT should migrate the population upward.
        lr = config["lr"]
        ckpt = tune.get_checkpoint()
        total = ckpt.to_dict()["total"] if ckpt else 0.0
        for i in range(16):
            total += lr
            tune.report(
                {"score": total, "lr": lr},
                checkpoint=tune.Checkpoint.from_dict({"total": total}),
            )

    pbt = PopulationBasedTraining(
        metric="score",
        mode="max",
        perturbation_interval=4,
        hyperparam_mutations={"lr": (0.001, 1.0)},
        quantile_fraction=0.5,
        seed=0,
    )
    grid = Tuner(
        objective,
        param_space={"lr": tune.grid_search([0.001, 0.002, 0.5, 0.6])},
        tune_config=TuneConfig(metric="score", mode="max", scheduler=pbt,
                               max_concurrent_trials=4),
    ).fit()
    best = grid.get_best_result()
    assert best.metrics["score"] > 0.5
    # at least one low-lr trial was exploited into a higher-lr config
    final_lrs = sorted(r.config["lr"] for r in grid)
    assert final_lrs[0] > 0.001 or final_lrs[1] > 0.002


def test_tune_run_legacy_entry():
    def objective(config):
        tune.report(score=config["x"] ** 2)

    grid = tune.run(
        objective,
        config={"x": tune.grid_search([1, 2, 3])},
        metric="score",
        mode="min",
    )
    assert grid.get_best_result().config["x"] == 1


def test_tuner_over_trainer():
    """Tune × Train composition: each trial runs a DataParallelTrainer."""
    from ray_tpu import train

    def trial_fn(config):
        def loop(loop_config):
            train.session.report({"loss": loop_config["lr"] * 10})

        result = train.DataParallelTrainer(
            loop,
            train_loop_config={"lr": config["lr"]},
            scaling_config=train.ScalingConfig(num_workers=1),
        ).fit()
        tune.report(loss=result.metrics["loss"])

    grid = Tuner(
        trial_fn,
        param_space={"lr": tune.grid_search([0.1, 0.01])},
        tune_config=TuneConfig(metric="loss", mode="min",
                               max_concurrent_trials=2),
    ).fit()
    assert grid.get_best_result().config["lr"] == 0.01


def test_stoppers_and_with_resources():
    """RunConfig(stop=...) conditions + tune.with_resources (reference
    tune/stopper/ and tune.with_resources)."""
    from ray_tpu.train import RunConfig
    from ray_tpu.tune import (
        MaximumIterationStopper,
        TrialPlateauStopper,
        with_resources,
    )

    def trainable(config):
        for i in range(50):
            tune.report({"score": float(min(i, 10))})  # plateaus at 10

    # dict stop: score >= 5 ends the trial early
    grid = tune.Tuner(
        trainable, param_space={},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(stop={"score": 5.0}),
    ).fit()
    assert not grid.errors
    assert grid[0].metrics["score"] == 5.0
    assert len(grid[0].metrics_history) <= 7

    # Stopper instance: max iterations
    grid = tune.Tuner(
        trainable, param_space={},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(stop=MaximumIterationStopper(3)),
    ).fit()
    assert len(grid[0].metrics_history) <= 3

    # plateau stopper fires once the metric flatlines at 10
    grid = tune.Tuner(
        trainable, param_space={},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(stop=TrialPlateauStopper(
            "score", std=0.0, num_results=3)),
    ).fit()
    assert len(grid[0].metrics_history) < 50

    # with_resources attaches per-trial resources
    wrapped = with_resources(trainable, {"CPU": 2})
    assert wrapped._tune_resources == {"CPU": 2}
    grid = tune.Tuner(
        wrapped, param_space={},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(stop=MaximumIterationStopper(2)),
    ).fit()
    assert not grid.errors


def test_with_resources_rewrap_does_not_mutate():
    from ray_tpu.tune import with_resources

    def fn(config):
        pass

    w1 = with_resources(fn, {"CPU": 1})
    w2 = with_resources(w1, {"CPU": 4})
    assert w1._tune_resources == {"CPU": 1}
    assert w2._tune_resources == {"CPU": 4}
    assert w1 is not w2


def test_tuner_persistence_and_restore(tmp_path):
    """Experiment-level resume (reference ``Tuner.restore``): the runner
    snapshots trial state + checkpoints continuously; a restored Tuner
    keeps finished trials' results and re-runs unfinished ones from their
    last checkpoint."""
    import json
    import os

    from ray_tpu.train.config import RunConfig

    def trainable(config):
        ckpt = tune.get_checkpoint()
        start = (ckpt.to_dict()["it"] + 1) if ckpt else 1
        for it in range(start, 6):
            tune.report(
                score=config["x"] * it, iteration_seen=it,
                checkpoint=tune.Checkpoint.from_dict({"it": it}))

    storage = str(tmp_path)
    res = Tuner(
        trainable,
        param_space={"x": tune.grid_search([1.0, 2.0, 3.0])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="exp1", storage_path=storage),
    ).fit()
    assert len(res) == 3
    exp_dir = os.path.join(storage, "exp1")
    state_path = os.path.join(exp_dir, "experiment_state.json")
    state = json.load(open(state_path))
    assert len(state["trials"]) == 3
    assert all(r["status"] == "TERMINATED" for r in state["trials"])
    assert all(r["checkpoint_file"] for r in state["trials"])

    # Simulate a crash snapshot: two trials mid-flight at iteration 3
    # when the process died (exactly what the continuous _persist would
    # have left: RUNNING status + an it=3 checkpoint on disk).
    import pickle

    for rec in state["trials"][:2]:
        rec["status"] = "RUNNING"
        rec["last_result"] = {"score": 0.0, "training_iteration": 3}
        with open(rec["checkpoint_file"], "wb") as f:
            pickle.dump({"it": 3}, f)
    with open(state_path, "w") as f:
        json.dump(state, f)

    res2 = Tuner.restore(exp_dir, trainable).fit()
    assert len(res2) == 3
    for r in res2:
        assert r.metrics["score"] == r.config["x"] * 5  # all completed
    # The re-run trials RESUMED from it=3 (first fresh report is it=4:
    # training_iteration restarts at 1 for the new attempt and ends at 2
    # after reporting iterations 4 and 5) — a from-scratch run would show
    # training_iteration 5.
    rerun = [r for r in res2
             if r.trial_id in {t["trial_id"]
                               for t in state["trials"][:2]}]
    assert len(rerun) == 2
    for r in rerun:
        assert r.metrics["iteration_seen"] == 5
        assert r.metrics["training_iteration"] == 2, r.metrics
