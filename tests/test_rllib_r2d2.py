"""R2D2 — recurrent replay DQN. The capability test: MemoryChain's cue
flashes at t=0 and the rewarded action happens at t=9; the observation
at the decision step is cue-INDEPENDENT (asserted structurally below),
so no feedforward Q-network can beat chance from replayively sampled
single transitions — while R2D2's sequence replay + stored-state LSTM
solves it. Also unit-checks the prioritized-free sequence plumbing:
burn-in gradient stop and the stored initial state."""

import jax
import jax.numpy as jnp

from ray_tpu.rllib.r2d2 import R2D2, R2D2Config, _lstm_step
from ray_tpu.rllib.recurrent import MemoryChain, MemoryChainState


def test_memorychain_final_obs_hides_the_cue():
    env = MemoryChain()
    late0 = MemoryChainState(jnp.asarray(0), jnp.asarray(env.length - 1))
    late1 = MemoryChainState(jnp.asarray(1), jnp.asarray(env.length - 1))
    assert bool(jnp.all(env.obs(late0) == env.obs(late1)))


def test_r2d2_solves_memorychain():
    algo = R2D2Config().training(
        epsilon_decay_steps=12_000, updates_per_iter=16).debugging(
        seed=0).build()
    solved = False
    for i in range(60):
        algo.train()
        if i % 5 == 4:
            mean = sum(
                algo.greedy_episode_reward(jax.random.key(1000 + j))
                for j in range(10)) / 10.0
            if mean >= 0.9:
                solved = True
                break
    assert solved, mean


def test_burn_in_heals_state_but_takes_no_gradient():
    cfg = R2D2Config().training(burn_in=2, train_len=4)
    algo = cfg.build()
    learner = algo._learner
    # One train step populates the buffer and runs updates without error.
    algo.train()
    assert int(algo._learner["buffer"]["size"]) >= cfg.num_envs


def test_lstm_state_distinguishes_cues():
    """The untrained LSTM already separates hidden states for the two
    cues at the final step — the representational premise of R2D2."""
    algo = R2D2Config().debugging(seed=1).build()
    env = algo.config.env
    params = algo._learner["params"]

    def final_h(cue):
        s = MemoryChainState(jnp.asarray(cue), jnp.asarray(0))
        h = jnp.zeros((1, algo.config.lstm_hidden))
        c = jnp.zeros((1, algo.config.lstm_hidden))
        for _ in range(env.length):
            _, h, c = _lstm_step(params, env.obs(s)[None], h, c)
            s = MemoryChainState(s.cue, s.t + 1)
        return h

    assert float(jnp.max(jnp.abs(final_h(0) - final_h(1)))) > 1e-6
