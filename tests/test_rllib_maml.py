"""MAML: the paper's claim, tested directly — one inner-loop gradient
step on a held-out task's own rollouts jumps the return, and the
meta-trained initialization adapts far better than a random init under
the IDENTICAL update rule."""

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.maml import MAML, MAMLConfig
from ray_tpu.rllib.ppo import mlp_init


HELD_OUT_GOALS = [(0.8, 0.6), (-0.7, 0.5), (0.4, -0.9), (-0.6, -0.6)]


def _adaptation_gain(algo, params, seed=100):
    """Mean (pre, post) return over held-out tasks for an init."""
    pres, posts = [], []
    for i, goal in enumerate(HELD_OUT_GOALS):
        k1 = jax.random.key(seed + 2 * i)
        k2 = jax.random.key(seed + 2 * i + 1)
        pres.append(algo.mean_return(params, goal, k1))
        adapted = algo.adapt_to(goal, k1, params=params)
        posts.append(algo.mean_return(adapted, goal, k2))
    return float(np.mean(pres)), float(np.mean(posts))


def test_maml_adaptation_jumps_on_held_out_tasks():
    algo = MAMLConfig().debugging(seed=0).build()
    for _ in range(250):
        r = algo.train()

    pre, post = _adaptation_gain(algo, algo.params)
    # Pre-adaptation the goal is unknown (returns ~ -goal_dist * T, the
    # held-out goals sit ~1.0 away: pre ~ -21); the inner loop on the
    # task's own rollouts must close most of the gap (measured: -13).
    assert post > pre + 4.0, (pre, post)
    assert post > -15.0, (pre, post)

    # The init is what was learned: a random init under the IDENTICAL
    # update rule adapts measurably worse (measured: -15.9 vs -13.0 —
    # normalized-PG inner steps help any init, the meta-trained one
    # more).
    rand_params = mlp_init(
        jax.random.key(123),
        (2, *algo.config.hidden_sizes, 2))
    _, rand_post = _adaptation_gain(algo, rand_params)
    assert post > rand_post + 1.5, (post, rand_post)


def test_second_order_term_flows():
    """The outer gradient must differentiate THROUGH the inner update:
    with inner_lr=0 the adapted params equal the init, so the two
    configs' meta-gradients must differ — a cheap structural check that
    the composition isn't silently first-order-only."""
    algo = MAMLConfig().training(meta_batch_size=2, num_envs=4) \
        .debugging(seed=1).build()
    r1 = algo.train()
    algo0 = MAMLConfig().training(
        meta_batch_size=2, num_envs=4, inner_lr=0.0).debugging(
        seed=1).build()
    r0 = algo0.train()
    assert r1["meta_loss"] != r0["meta_loss"]
