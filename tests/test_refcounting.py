"""Distributed ref-counting over the cluster backend.

Reference parity: ``src/ray/core_worker/reference_count.h:61`` — owners,
borrowers (task-arg borrows + deserialized holds), containment (objects
holding nested refs), free-on-zero broadcast to holding nodes. Here the
table is centralized on the head (``cluster/head.py``), clients batch
local 0->1/1->0 transitions, and borrows are registered at submission.
"""

import gc
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


def wait_for(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {msg}")


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2, store_capacity=64 << 20)
    c.add_node(num_cpus=2, store_capacity=64 << 20)
    c.wait_for_nodes()
    ray_tpu.init(c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _used(node):
    return node.store.stats()["used"]


def test_drop_ref_frees_object(cluster):
    node = cluster.nodes[0]
    base = _used(node)
    ref = ray_tpu.put(np.ones(1 << 20, np.uint8))  # 1 MiB on the driver node
    wait_for(lambda: _used(node) > base, msg="object stored")
    assert ray_tpu.get(ref).sum() == 1 << 20
    del ref
    gc.collect()
    wait_for(lambda: _used(node) <= base, msg="object freed after last ref",
             timeout=15)


def test_borrow_across_nodes_then_free(cluster):
    """Object created on node A, borrowed by a task on node B, freed only
    when the last handle dies — the caller drops its ref mid-flight."""
    node_a, node_b = cluster.nodes[0], cluster.nodes[1]
    base = _used(node_a)

    @ray_tpu.remote
    def consume(arr):
        time.sleep(0.3)  # widen the window: caller drops its ref meanwhile
        return int(arr.sum())

    ref = ray_tpu.put(np.ones(1 << 20, np.uint8))
    out = consume.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(node_b.node_id)
    ).remote(ref)
    del ref  # only the in-flight borrow keeps the object alive now
    gc.collect()
    assert ray_tpu.get(out, timeout=30) == 1 << 20
    del out
    gc.collect()
    wait_for(lambda: _used(node_a) <= base, msg="freed after borrow ended",
             timeout=15)


def test_container_holds_nested_ref(cluster):
    node = cluster.nodes[0]
    base = _used(node)
    inner = ray_tpu.put(np.full(1 << 19, 7, np.uint8))
    outer = ray_tpu.put({"payload": inner})
    del inner  # the container still holds it
    gc.collect()
    time.sleep(0.5)  # let any (wrong) free propagate
    got = ray_tpu.get(outer)
    assert ray_tpu.get(got["payload"])[0] == 7
    del got
    del outer
    gc.collect()
    wait_for(lambda: _used(node) <= base,
             msg="container + nested freed together", timeout=15)


def test_actor_keeps_deserialized_ref_alive(cluster):
    node = cluster.nodes[0]
    base = _used(node)

    @ray_tpu.remote
    class Keeper:
        def __init__(self):
            self.ref = None

        def keep(self, refs):
            self.ref = refs[0]
            return True

        def read(self):
            return int(ray_tpu.get(self.ref).sum())

    keeper = Keeper.remote()
    ref = ray_tpu.put(np.ones(1 << 19, np.uint8))
    # Pass the ref inside a container so it isn't auto-resolved: the actor
    # deserializes it and becomes a holder.
    assert ray_tpu.get(keeper.keep.remote([ref]), timeout=30)
    del ref
    gc.collect()
    time.sleep(0.5)
    assert ray_tpu.get(keeper.read.remote(), timeout=30) == 1 << 19
    ray_tpu.kill(keeper)
    wait_for(lambda: _used(node) <= base,
             msg="freed after holding actor died", timeout=20)


def test_error_objects_freed_too(cluster):
    node = cluster.nodes[0]

    @ray_tpu.remote
    def boom():
        raise ValueError("x")

    base = _used(node)
    refs = [boom.remote() for _ in range(4)]
    for r in refs:
        try:
            ray_tpu.get(r, timeout=30)
            raise AssertionError("expected task error")
        except Exception as e:
            assert "ValueError" in repr(e) or "x" in str(e)
            # Drop the exception explicitly: its traceback frames would
            # otherwise pin `refs` via the get() call frame.
            del e
    del refs, r
    gc.collect()
    wait_for(lambda: _used(node) <= base, msg="error objects freed",
             timeout=15)
