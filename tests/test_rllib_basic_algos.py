"""PG / SimpleQ / DDPG / bandits — the round-5 small-family additions.

Each test exercises the algorithm's REASON to exist, not just that it
runs: PG improves CartPole without any critic; SimpleQ's plain-max
target still solves CartPole while being measurably more optimistic
than double-DQN on the same stream; DDPG solves Pendulum with the TD3
tricks disabled; LinUCB/LinTS drive per-round regret toward zero and
beat a uniform-random puller.
"""

import jax
import jax.numpy as jnp
import pytest

from ray_tpu.rllib.bandit import (
    BanditConfig,
    BanditLinTS,
    BanditLinUCB,
    LinearBanditEnv,
)
from ray_tpu.rllib.ddpg import DDPG, DDPGConfig
from ray_tpu.rllib.pg import PG, PGConfig
from ray_tpu.rllib.simple_q import SimpleQ, SimpleQConfig


def test_pg_improves_cartpole():
    algo = PGConfig().rollouts(num_envs=32, rollout_length=128) \
        .training(lr=3e-3).debugging(seed=0).build()
    first = algo.train()["episode_reward_mean"]
    last = None
    for _ in range(30):
        last = algo.train()["episode_reward_mean"]
        if last > 3 * first and last > 60:
            break
    assert last > 3 * first and last > 60, (first, last)


def test_pg_has_no_value_net():
    # The family split from A2C: a PG policy is ONE mlp, no critic pytree.
    algo = PGConfig().build()
    assert isinstance(algo._params, list)  # bare mlp layers, no {"pi","vf"}


def test_simple_q_solves_cartpole():
    algo = SimpleQConfig().build()
    assert algo.config.double_q is False
    best = 0.0
    for _ in range(25):
        best = max(best, algo.train()["episode_reward_mean"])
        if best > 80:
            break
    assert best > 80, best


def test_simple_q_target_dominates_double_pointwise():
    """The structural relation between the two targets: with the SAME
    target net, SimpleQ's max_a Q_t(s',a) >= Q_t(s', argmax_online) —
    i.e. dropping double-Q re-admits the overestimating max. Checked on
    real (online != target) nets from a briefly trained DQN."""
    from ray_tpu.rllib.dqn import DQNConfig
    from ray_tpu.rllib.ppo import mlp_apply

    algo = DQNConfig().debugging(seed=3).build()
    for _ in range(3):
        algo.train()
    p, tp = algo._learner["params"], algo._learner["target_params"]
    obs = jax.random.normal(jax.random.key(0), (256, 4)) * 0.1
    next_target = mlp_apply(tp, obs)
    simple = jnp.max(next_target, axis=1)
    next_act = jnp.argmax(mlp_apply(p, obs), axis=1)
    double = jnp.take_along_axis(
        next_target, next_act[:, None], axis=1)[:, 0]
    assert bool(jnp.all(simple >= double))
    # And the nets have actually diverged enough that the bound is
    # strict somewhere (otherwise the test is vacuous).
    assert float(jnp.max(simple - double)) > 0, "nets identical"


def test_ddpg_improves_pendulum():
    algo = DDPGConfig().debugging(seed=0).build()
    assert algo.config.twin_q is False and algo.config.policy_delay == 1
    first = None
    last = None
    for i in range(30):
        r = algo.train()["episode_reward_mean"]
        if i == 2:
            first = r        # after warmup, before learning bites
        last = r
        if first is not None and last > first + 300:
            break
    # Pendulum episodic return rises from ~-1400 toward > -900.
    assert last > first + 300, (first, last)


@pytest.mark.parametrize("cls", [BanditLinUCB, BanditLinTS])
def test_bandit_regret_shrinks_and_beats_random(cls):
    env = LinearBanditEnv(num_arms=5, context_dim=8, noise=0.1, seed=1)
    cfg = BanditConfig().environment(env).debugging(seed=0)
    algo = cls(cfg)
    first = algo.train()["regret_this_iter"]
    for _ in range(5):
        last = algo.train()["regret_this_iter"]
    assert last < 0.3 * first, (first, last)

    # Uniform-random baseline regret per round, computed in closed form
    # over fresh contexts: E[max arm - random arm].
    rng = jax.random.key(7)
    xs = jax.random.normal(rng, (512, env.context_dim))
    means = xs @ env.theta.T
    rand_regret = float(jnp.mean(jnp.max(means, axis=1)
                                 - jnp.mean(means, axis=1)))
    per_round = last / cfg.rounds_per_iter
    assert per_round < 0.2 * rand_regret, (per_round, rand_regret)


def test_bandit_greedy_action_matches_oracle():
    env = LinearBanditEnv(num_arms=4, context_dim=6, noise=0.05, seed=2)
    algo = BanditLinUCB(BanditConfig().environment(env))
    for _ in range(6):
        algo.train()
    xs = jax.random.normal(jax.random.key(11), (64, env.context_dim))
    hits = sum(
        int(algo.compute_single_action(x) == int(jnp.argmax(env.means(x))))
        for x in xs)
    assert hits >= 55, hits


def test_algorithm_registry_resolves_all():
    from ray_tpu.rllib.registry import ALGORITHMS, get_algorithm_class

    for name in ALGORITHMS:
        cls, cfg_cls = get_algorithm_class(name, return_config=True)
        assert isinstance(cls, type), name
        assert isinstance(cfg_cls, type), name
    # The Tune-style flow: name -> config -> build.
    cls, cfg_cls = get_algorithm_class("PG", return_config=True)
    algo = cfg_cls().rollouts(num_envs=4, rollout_length=8).build()
    assert isinstance(algo, cls)
    with pytest.raises(ValueError, match="unknown algorithm"):
        get_algorithm_class("NOPE")
