"""Native C++ worker API (reference: ``cpp/`` worker + cross_language.py).

Covers both directions:
* Python driver → C++ worker: ``cross_language.cpp_function`` submits by
  name, the node agent spawns the C++ binary as a pool worker, the result
  comes back through the shm store into ``ray_tpu.get``;
* C++ driver → C++ worker: the sample binary's ``--driver`` mode submits
  tasks and reads results with no Python in the loop.
"""

import subprocess
import sys
import time

import cloudpickle
import pytest

import ray_tpu
from ray_tpu import cross_language
from ray_tpu._native.build import build_cpp_worker
from ray_tpu.cluster import Cluster

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def cluster_and_bin():
    bin_path = build_cpp_worker()
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=4)
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c, bin_path
    ray_tpu.shutdown()
    c.shutdown()


def test_python_to_cpp_roundtrip(cluster_and_bin):
    _, bin_path = cluster_and_bin
    add = cross_language.cpp_function("add", worker_bin=bin_path)
    assert ray_tpu.get(add.remote(40, 2), timeout=60) == 42

    concat = cross_language.cpp_function("concat", worker_bin=bin_path)
    assert ray_tpu.get(concat.remote("ray", "-", "tpu"), timeout=30) == \
        "ray-tpu"

    # Full codec round trip: nested containers, bytes, floats, None.
    echo = cross_language.cpp_function("echo", worker_bin=bin_path)
    payload = {"ints": [1, -7, 2**40], "f": 3.5, "b": b"\x00\xff",
               "nested": {"ok": True, "none": None}}
    assert ray_tpu.get(echo.remote(payload), timeout=30) == payload


def test_cpp_results_feed_python_tasks(cluster_and_bin):
    """A C++ task's output object is a first-class ref: passable into a
    Python task as an argument (cross-language object plane)."""
    _, bin_path = cluster_and_bin
    fib = cross_language.cpp_function("fib", worker_bin=bin_path)
    ref = fib.remote(20)

    @ray_tpu.remote
    def double(x):
        return x * 2

    assert ray_tpu.get(double.remote(ref), timeout=60) == 2 * 6765


def test_cpp_task_error_surfaces(cluster_and_bin):
    _, bin_path = cluster_and_bin
    boom = cross_language.cpp_function("boom", worker_bin=bin_path)
    with pytest.raises(ray_tpu.TaskError, match="intentional"):
        ray_tpu.get(boom.remote(), timeout=30)


def test_unregistered_function_errors(cluster_and_bin):
    _, bin_path = cluster_and_bin
    nope = cross_language.cpp_function("no_such_fn", worker_bin=bin_path)
    with pytest.raises(ray_tpu.TaskError, match="no C\\+\\+ function"):
        ray_tpu.get(nope.remote(), timeout=30)


def test_restricted_type_check():
    class Custom:
        pass

    with pytest.raises(TypeError, match="restricted"):
        cross_language.pack_args((Custom(),))
    with pytest.raises(TypeError, match="keys must be str"):
        cross_language.pack_args(({1: "x"},))


def test_cpp_driver_end_to_end(cluster_and_bin):
    """C++ driver → head scheduler → C++ worker → shm store → C++ get."""
    c, bin_path = cluster_and_bin
    out = subprocess.run(
        [bin_path, "--driver", c.address, bin_path],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "add=42" in out.stdout
    assert "fib=6765" in out.stdout
    assert "put=cpp-put" in out.stdout


def test_cpp_worker_reused_across_tasks(cluster_and_bin):
    """Consecutive tasks to the same binary reuse the pooled worker
    (lease/return parity) — and interleave fine with Python tasks."""
    _, bin_path = cluster_and_bin
    add = cross_language.cpp_function("add", worker_bin=bin_path)

    @ray_tpu.remote
    def py_add(a, b):
        return a + b

    t0 = time.monotonic()
    refs = [add.remote(i, i) for i in range(8)]
    py_refs = [py_add.remote(i, i) for i in range(4)]
    assert ray_tpu.get(refs, timeout=60) == [2 * i for i in range(8)]
    assert ray_tpu.get(py_refs, timeout=60) == [2 * i for i in range(4)]
    # 8 tasks through at most 4 CPU slots: reuse must have happened and
    # the whole batch should be fast (no per-task process spawn).
    assert time.monotonic() - t0 < 30.0
