"""Typed wire codec + schema'd RPC protocol (round 5).

Reference parity: the protobuf message layer + gRPC scaffolding
(``src/ray/protobuf/*.proto``, ``src/ray/rpc/grpc_server.h``) — here a
msgpack envelope with extension types, streaming responses, and the
security property that unauthenticated bytes can never reach pickle.
"""

import os
import random
import socket
import struct
import threading
import time

import pytest

from ray_tpu.cluster.rpc import (
    AuthError,
    ConnectionLost,
    RpcClient,
    RpcServer,
)
from ray_tpu.cluster.wire import RemoteError, WireCodec, WireError


# -- codec roundtrips ------------------------------------------------------


CODEC = WireCodec(allow_pickle=True)
STRICT = WireCodec(allow_pickle=False)


@pytest.mark.parametrize("value", [
    None, True, False, 0, -1, 2**53, -(2**53), 1.5, float("inf"),
    "", "héllo", b"", b"\x00\xff" * 10,
    [], [1, "a", None], {"k": [1, 2]}, {1: "int-key"},
    (), (1, 2, "x"), ((1,), [2, (3,)]),
    set(), {1, 2, 3}, frozenset({"a", "b"}),
    {"spec": {"task_id": "t" * 32, "args": b"blob", "demand": {"CPU": 1.0},
              "oids": ["a", "b"], "sinfo": {"strategy": None}}},
])
def test_roundtrip(value):
    for codec in (CODEC, STRICT):
        out = codec.unpackb(codec.packb(value))
        assert out == value, (value, out)
        if isinstance(value, tuple):
            assert isinstance(out, tuple)
        if isinstance(value, frozenset):
            assert isinstance(out, frozenset)


def test_exception_roundtrip_builtin():
    e = ValueError("bad input", 42)
    out = CODEC.unpackb(CODEC.packb(e))
    assert isinstance(out, ValueError)
    assert out.args == ("bad input", 42)


def test_exception_roundtrip_ray_tpu():
    from ray_tpu.core.object_ref import TaskError

    e = TaskError("fn", "traceback here", "ValueError('x')")
    out = STRICT.unpackb(STRICT.packb(e))
    assert isinstance(out, TaskError)
    assert "fn" in str(out)


def test_exception_non_whitelisted_module_becomes_remote_error():
    codec = WireCodec(allow_pickle=False)
    # Forge an EXT_EXC naming a module outside the whitelist.
    import msgpack

    from ray_tpu.cluster import wire

    payload = msgpack.packb(
        ["os", "system", ["boom"], {}, "tb"], use_bin_type=True)
    blob = codec.packb("x").replace(
        codec.packb("x"), b"")  # noop, keep codec warm
    frame = msgpack.packb(
        msgpack.ExtType(wire.EXT_EXC, payload), use_bin_type=True)
    out = codec.unpackb(frame)
    assert isinstance(out, RemoteError)
    assert "os.system" in str(out)


class _ModuleLevelCustom:
    pass


def test_strict_profile_refuses_pickle_both_ways():
    with pytest.raises(WireError, match="not wire-encodable"):
        STRICT.packb(_ModuleLevelCustom())
    # And refuses to DECODE a pickle ext a hostile peer sends anyway.
    blob = CODEC.packb(_ModuleLevelCustom())
    with pytest.raises(WireError, match="unauthenticated"):
        STRICT.unpackb(blob)


def test_pickle_gadgets_blocked_even_authenticated():
    import pickle

    import msgpack

    from ray_tpu.cluster import wire

    evil = pickle.dumps(os.getcwd)  # callable from a blocked module
    frame = msgpack.packb(
        msgpack.ExtType(wire.EXT_PICKLE, evil), use_bin_type=True)
    with pytest.raises(WireError, match="allowlist"):
        CODEC.unpackb(frame)


def test_pickle_reentry_gadget_blocked():
    """REDUCE(pickle.loads, inner) re-enters an UNRESTRICTED unpickler —
    the classic blocklist bypass. The allowlist must refuse module
    'pickle' outright."""
    import pickle as _pickle

    import msgpack

    from ray_tpu.cluster import wire

    inner = _pickle.dumps(os.getcwd)
    evil = (b"\x80\x05c_pickle\nloads\n" + _pickle.dumps(inner)[2:-1]
            + b"\x85R.")
    frame = msgpack.packb(
        msgpack.ExtType(wire.EXT_PICKLE, evil), use_bin_type=True)
    with pytest.raises(WireError, match="allowlist"):
        CODEC.unpackb(frame)
    # And via the plain-named module too.
    evil2 = (b"\x80\x05cpickle\nloads\n" + _pickle.dumps(inner)[2:-1]
             + b"\x85R.")
    frame2 = msgpack.packb(
        msgpack.ExtType(wire.EXT_PICKLE, evil2), use_bin_type=True)
    with pytest.raises(WireError, match="allowlist"):
        CODEC.unpackb(frame2)


def test_fuzz_random_frames_never_execute():
    """Random bytes into the decoder: WireError or a value, never a
    crash/execution (schema'd-protocol fuzz ask, VERDICT r4 #1)."""
    rng = random.Random(1234)
    for _ in range(3000):
        blob = bytes(rng.randrange(256)
                     for _ in range(rng.randrange(0, 64)))
        for codec in (CODEC, STRICT):
            try:
                codec.unpackb(blob)
            except WireError:
                pass


def test_fuzz_mutated_valid_frames():
    spec = {"m": "submit", "a": [{"task_id": "x" * 32, "args": b"b" * 100,
                                  "demand": {"CPU": 1.0}}], "k": {}}
    base = CODEC.packb(spec)
    rng = random.Random(99)
    for _ in range(3000):
        mutated = bytearray(base)
        for _ in range(rng.randrange(1, 6)):
            mutated[rng.randrange(len(mutated))] = rng.randrange(256)
        try:
            CODEC.unpackb(bytes(mutated))
        except WireError:
            pass


# -- RPC on the new wire ---------------------------------------------------


class _Handler:
    def rpc_echo(self, x):
        return x

    def rpc_add(self, a, b=1):
        return a + b

    def rpc_boom(self):
        raise ValueError("expected failure")

    def rpc_count(self, n, delay=0.0):
        for i in range(n):
            if delay:
                time.sleep(delay)
            yield i

    def rpc_stream_fail(self):
        yield 1
        raise RuntimeError("mid-stream")


@pytest.fixture()
def server():
    srv = RpcServer(_Handler(), token=b"t0k")
    yield srv
    srv.stop()


def _client(srv, token=b"t0k"):
    return RpcClient(srv.address, token=token)


def test_rpc_basic_call(server):
    cli = _client(server)
    assert cli.call("echo", {"a": (1, 2), "s": {3}}) == {"a": (1, 2),
                                                         "s": {3}}
    assert cli.call("add", 5, b=10) == 15
    cli.close()


def test_rpc_error_reconstructed(server):
    cli = _client(server)
    with pytest.raises(ValueError, match="expected failure"):
        cli.call("boom")
    # Connection stays usable after a handler error.
    assert cli.call("echo", 1) == 1
    cli.close()


def test_rpc_streaming(server):
    cli = _client(server)
    items = list(cli.call_stream("count", 5))
    assert items == [0, 1, 2, 3, 4]
    # Items arrive incrementally: first item lands before the stream is
    # done producing (handler sleeps per item).
    gen = cli.call_stream("count", 3, delay=0.2)
    t0 = time.monotonic()
    first = next(gen)
    assert first == 0 and time.monotonic() - t0 < 0.45
    assert list(gen) == [1, 2]
    cli.close()


def test_rpc_streaming_error_surfaces(server):
    cli = _client(server)
    gen = cli.call_stream("stream_fail")
    assert next(gen) == 1
    with pytest.raises(RuntimeError, match="mid-stream"):
        next(gen)
    cli.close()


def test_rpc_streaming_early_close(server):
    cli = _client(server)
    gen = cli.call_stream("count", 1000, delay=0.01)
    assert next(gen) == 0
    gen.close()  # client walks away mid-stream; server must survive
    assert cli.call("echo", "still alive") == "still alive"
    cli.close()


def test_rpc_plain_call_on_streaming_handler(server):
    cli = _client(server)
    assert cli.call("count", 4) == [0, 1, 2, 3]
    cli.close()


def test_rpc_malformed_frame_gets_error_not_crash(server):
    """A well-framed but undecodable request draws an error response and
    the server keeps serving (socket-level fuzz, VERDICT r4 #1)."""
    import hashlib
    import hmac

    host, port = server.address.rsplit(":", 1)
    s = socket.create_connection((host, int(port)), timeout=5)
    hello = s.recv(38)
    digest = hmac.new(b"t0k", hello[6:], hashlib.sha256).digest()
    s.sendall(digest + b"N" * 32)
    s.recv(33)
    rng = random.Random(7)
    for _ in range(50):
        blob = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 40)))
        s.sendall(struct.pack(">I", len(blob)) + blob)
        # One response per request: read the length-prefixed reply.
        hdr = s.recv(4)
        if not hdr:
            break
        (n,) = struct.unpack(">I", hdr)
        body = b""
        while len(body) < n:
            chunk = s.recv(n - len(body))
            assert chunk
            body += chunk
    s.close()
    # Server is still healthy for real clients.
    cli = _client(server)
    assert cli.call("echo", "ok") == "ok"
    cli.close()


def test_rpc_oversize_frame_drops_connection(server):
    import hashlib
    import hmac

    host, port = server.address.rsplit(":", 1)
    s = socket.create_connection((host, int(port)), timeout=5)
    hello = s.recv(38)
    digest = hmac.new(b"t0k", hello[6:], hashlib.sha256).digest()
    s.sendall(digest + b"N" * 32)
    s.recv(33)
    s.sendall(struct.pack(">I", (1 << 30) + 1))  # over MAX_FRAME_BYTES
    assert s.recv(1) == b""  # dropped without allocation
    s.close()


def test_no_token_strict_wire():
    """Explicit auth-off clusters get the strict codec: rich objects are
    refused at the encoder, pickle frames refused at the decoder."""

    class Rich:
        pass

    srv = RpcServer(_Handler(), token=b"")
    try:
        cli = RpcClient(srv.address, token=b"")
        assert cli.call("echo", {"x": (1, 2)}) == {"x": (1, 2)}
        with pytest.raises(WireError, match="not wire-encodable"):
            cli.call("echo", Rich())
        cli.close()
    finally:
        srv.stop()


def test_auto_token_generation(monkeypatch):
    from ray_tpu.cluster.rpc import ensure_cluster_token
    from ray_tpu.core.config import config

    monkeypatch.delenv("RAY_TPU_CLUSTER_TOKEN", raising=False)
    config.override("cluster_token", "")
    tok = ensure_cluster_token()
    try:
        assert tok and len(tok) == 32
        assert os.environ["RAY_TPU_CLUSTER_TOKEN"] == tok
        assert config.cluster_token == tok
        # Idempotent: a second cluster in-process keeps the same token.
        assert ensure_cluster_token() == tok
        # Explicit auth-off is respected.
        monkeypatch.setenv("RAY_TPU_CLUSTER_TOKEN", "")
        assert ensure_cluster_token() == ""
    finally:
        monkeypatch.delenv("RAY_TPU_CLUSTER_TOKEN", raising=False)
        config.reset("cluster_token")
