"""Autoscaler tests: demand-driven scale-up on a live simulated cluster and
pure-unit reconciler behavior (reference: ``test_autoscaler.py``,
``test_autoscaler_fake_multinode.py``)."""

import sys
import time

import cloudpickle
import pytest

import ray_tpu
from ray_tpu.autoscaler import LocalNodeProvider, NodeProvider, StandardAutoscaler
from ray_tpu.cluster import Cluster

cloudpickle.register_pickle_by_value(sys.modules[__name__])


class MockProvider(NodeProvider):
    def __init__(self):
        self.nodes = {}
        self.counter = 0

    def create_node(self, node_type, node_config):
        self.counter += 1
        node_id = f"mock-{self.counter}"
        self.nodes[node_id] = node_type
        return node_id

    def terminate_node(self, node_id):
        self.nodes.pop(node_id, None)

    def non_terminated_nodes(self):
        return list(self.nodes)


def test_nodes_to_launch_bin_packing():
    autoscaler = StandardAutoscaler.__new__(StandardAutoscaler)
    autoscaler.max_workers = 8
    autoscaler.node_types = {
        "small": {"num_cpus": 2},
        "tpu_host": {"num_cpus": 8, "resources": {"TPU": 4}},
    }
    # The TPU demand forces a tpu_host; the 1-CPU demands then pack into
    # its remaining headroom -> a single launch covers everything.
    launches = autoscaler._nodes_to_launch(
        [{"CPU": 1}, {"CPU": 1}, {"TPU": 4}], n_current=0
    )
    assert launches == ["tpu_host"]
    # CPU demands exceeding the tpu host's headroom need a second node.
    launches = autoscaler._nodes_to_launch(
        [{"TPU": 4}] + [{"CPU": 2}] * 5, n_current=0
    )
    assert sorted(launches) == ["small", "tpu_host"]
    # Budget cap respected.
    autoscaler.max_workers = 1
    launches = autoscaler._nodes_to_launch(
        [{"CPU": 2}, {"TPU": 4}], n_current=1
    )
    assert launches == []


def test_scale_up_makes_pending_task_runnable():
    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    autoscaler = StandardAutoscaler(
        cluster.address,
        LocalNodeProvider(cluster),
        node_types={"big": {"num_cpus": 4}},
        max_workers=2,
        idle_timeout_s=9999,
    )
    try:
        @ray_tpu.remote(num_cpus=4)
        def needs_big_node():
            return "ran"

        ref = needs_big_node.remote()  # no node can fit -> pending demand
        time.sleep(0.5)
        report = autoscaler.update()
        assert len(report["launched"]) == 1
        assert ray_tpu.get(ref, timeout=60) == "ran"
    finally:
        autoscaler.stop()
        ray_tpu.shutdown()
        cluster.shutdown()


def test_scale_down_idle_nodes():
    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    provider = LocalNodeProvider(cluster)
    autoscaler = StandardAutoscaler(
        cluster.address,
        provider,
        node_types={"big": {"num_cpus": 2}},
        max_workers=2,
        idle_timeout_s=0.5,
        launch_cooldown_s=0.0,
    )
    try:
        node_id = provider.create_node("big", {"num_cpus": 2})
        cluster.wait_for_nodes()
        assert provider.non_terminated_nodes() == [node_id]
        autoscaler.update()  # first observation starts the idle clock
        time.sleep(0.8)  # exceed idle timeout
        report = autoscaler.update()
        assert node_id in report["terminated"]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            alive = [n for n in ray_tpu.nodes() if n["Alive"]]
            if len(alive) == 1:
                break
            time.sleep(0.1)
        assert len([n for n in ray_tpu.nodes() if n["Alive"]]) == 1
    finally:
        autoscaler.stop()
        ray_tpu.shutdown()
        cluster.shutdown()


def test_cluster_launcher_from_yaml(tmp_path):
    """YAML config -> running cluster (ray up/down analog): head shape,
    min_workers per node type, provider registry, teardown."""
    import yaml

    from ray_tpu.autoscaler import launcher

    config = {
        "cluster_name": "yaml-demo",
        "max_workers": 4,
        "provider": {"type": "local"},
        "head_node_type": "head",
        "available_node_types": {
            "head": {"num_cpus": 2, "min_workers": 0},
            "tpu_worker": {"num_cpus": 1, "resources": {"TPU": 4},
                           "min_workers": 1},
        },
    }
    path = tmp_path / "cluster.yaml"
    path.write_text(yaml.safe_dump(config))

    ray_tpu.shutdown()
    handle = launcher.create_or_update_cluster(str(path),
                                               start_autoscaler=False)
    try:
        ray_tpu.init(address=handle.address)
        nodes = ray_tpu.nodes()
        assert len(nodes) == 2  # head + 1 min tpu_worker
        total = ray_tpu.cluster_resources()
        assert total["CPU"] == 3.0 and total.get("TPU") == 4.0
    finally:
        ray_tpu.shutdown()
        handle.teardown()


def test_launcher_provider_registry_and_validation(tmp_path):
    from ray_tpu.autoscaler import launcher

    with pytest.raises(ValueError, match="available_node_types"):
        launcher.load_cluster_config({"head_node_type": "x"})
    with pytest.raises(ValueError, match="head_node_type"):
        launcher.load_cluster_config(
            {"available_node_types": {"a": {}}, "head_node_type": "b"})

    created = []

    class FakeCloud(launcher.NodeProvider):
        def __init__(self, provider_cfg, cluster):
            self.cfg = provider_cfg

        def create_node(self, node_type, node_config):
            created.append(node_type)
            return f"fake-{len(created)}"

        def terminate_node(self, node_id):
            pass

        def non_terminated_nodes(self):
            return [f"fake-{i+1}" for i in range(len(created))]

    launcher.register_node_provider("fake_cloud", FakeCloud)
    ray_tpu.shutdown()
    handle = launcher.create_or_update_cluster(
        {
            "provider": {"type": "fake_cloud", "region": "tpu-west"},
            "head_node_type": "head",
            "available_node_types": {
                "head": {"num_cpus": 1},
                "pod": {"num_cpus": 8, "min_workers": 2},
            },
        },
        start_autoscaler=False,
    )
    try:
        assert created == ["pod", "pod"]
        assert handle.provider.cfg["region"] == "tpu-west"
    finally:
        handle.teardown()
