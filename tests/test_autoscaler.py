"""Autoscaler tests: demand-driven scale-up on a live simulated cluster and
pure-unit reconciler behavior (reference: ``test_autoscaler.py``,
``test_autoscaler_fake_multinode.py``).

Round 17 adds the execution half: heterogeneous bin-packing (STRICT_SPREAD
needs N distinct nodes; ``spot: false`` gangs only count on-demand types),
the launch-failure -> backoff -> quarantine -> fall-through boot loop,
SLO-burn-triggered scale-up, occupancy-coldest idle scale-down, and the
drain-before-terminate ordering guarantee."""

import sys
import time

import cloudpickle
import pytest

import ray_tpu
from ray_tpu.autoscaler import LocalNodeProvider, NodeProvider, StandardAutoscaler
from ray_tpu.cluster import Cluster
from ray_tpu.util import failpoints

cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


def wait_for(cond, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {msg}")


class MockProvider(NodeProvider):
    def __init__(self):
        self.nodes = {}
        self.counter = 0

    def create_node(self, node_type, node_config):
        self.counter += 1
        node_id = f"mock-{self.counter}"
        self.nodes[node_id] = node_type
        return node_id

    def terminate_node(self, node_id):
        self.nodes.pop(node_id, None)

    def non_terminated_nodes(self):
        return list(self.nodes)


class FakeHead:
    """Stand-in for the head RPC client in pure-unit reconciler tests:
    canned demand snapshot / node table / pubsub batches / occupancy, and
    records of drains, terminate acks, and status reports."""

    def __init__(self):
        self.snapshot = {"tasks": [], "actors": [], "pg_bundles": []}
        self.nodes = {}  # node_id -> node-table dict
        self.poll_batches = []  # list of message lists, popped per poll
        self.occupancy = {}  # node_id -> cpu percent
        self.drained = []
        self.acks = []
        self.reports = []

    def call(self, method, *args, **kwargs):
        if method == "demand_snapshot":
            return self.snapshot
        if method == "nodes":
            return [dict(n) for n in self.nodes.values()]
        if method == "pubsub_subscribe":
            return args[0]
        if method == "pubsub_poll":
            if self.poll_batches:
                return (self.poll_batches.pop(0), 0)
            return ([], 0)
        if method == "query_metrics":
            return {"ok": True, "op": "gauge_avg",
                    "value": dict(self.occupancy)}
        if method == "drain_node":
            node_id, reason = args[0], args[1]
            self.drained.append(node_id)
            n = self.nodes.get(node_id)
            if n is not None:  # instant drain: node goes DEAD
                n["Alive"] = False
                n["State"] = "DEAD"
                n["DeathCause"] = f"drained: {reason}"
            return {"ok": True}
        if method == "autoscaler_report":
            self.reports.append(args[0])
            return True
        if method == "terminate_ack":
            self.acks.append((args[0], args[1]))
            return {"ok": True, "node_id": args[0]}
        raise AssertionError(f"unexpected head call {method!r}")


def _node(node_id, cpus, *, alive=True, state="ALIVE", used=0.0):
    return {
        "NodeID": node_id,
        "Alive": alive,
        "State": state,
        "Resources": {"CPU": float(cpus)},
        "Available": {"CPU": float(cpus) - used},
        "Labels": {},
    }


def mk(provider, node_types, **kw):
    """Real constructor (RpcClient is lazy — no dial until .call), head
    swapped for a FakeHead."""
    kw.setdefault("max_workers", 8)
    kw.setdefault("idle_timeout_s", 9999.0)
    kw.setdefault("launch_cooldown_s", 0.0)
    a = StandardAutoscaler("127.0.0.1:1", provider,
                           node_types=node_types, **kw)
    fh = FakeHead()
    a.head = fh
    return a, fh


def test_nodes_to_launch_bin_packing():
    autoscaler, _ = mk(MockProvider(), {
        "small": {"num_cpus": 2},
        "tpu_host": {"num_cpus": 8, "resources": {"TPU": 4}},
    })
    # The TPU demand forces a tpu_host; the 1-CPU demands then pack into
    # its remaining headroom -> a single launch covers everything.
    launches = autoscaler._nodes_to_launch(
        [{"CPU": 1}, {"CPU": 1}, {"TPU": 4}], n_current=0
    )
    assert launches == ["tpu_host"]
    # CPU demands exceeding the tpu host's headroom need a second node.
    launches = autoscaler._nodes_to_launch(
        [{"TPU": 4}] + [{"CPU": 2}] * 5, n_current=0
    )
    assert sorted(launches) == ["small", "tpu_host"]
    # Budget cap respected.
    autoscaler.max_workers = 1
    launches = autoscaler._nodes_to_launch(
        [{"CPU": 2}, {"TPU": 4}], n_current=1
    )
    assert launches == []


def test_strict_spread_bundles_need_distinct_nodes():
    autoscaler, _ = mk(MockProvider(), {"big": {"num_cpus": 8}})
    spread = {"tasks": [], "actors": [], "pg_bundles": [{
        "pg_id": "pg-1", "strategy": "STRICT_SPREAD",
        "bundles": [{"CPU": 1}, {"CPU": 1}, {"CPU": 1}], "spot": True,
    }]}
    # 3 bundles-worth of CPU fits one node, but STRICT_SPREAD constrains
    # node COUNT: three distinct hosts.
    assert autoscaler._nodes_to_launch(spread, n_current=0) == [
        "big", "big", "big"]
    packed = {"tasks": [], "actors": [], "pg_bundles": [{
        "pg_id": "pg-2", "strategy": "PACK",
        "bundles": [{"CPU": 1}, {"CPU": 1}, {"CPU": 1}], "spot": True,
    }]}
    assert autoscaler._nodes_to_launch(packed, n_current=0) == ["big"]


def test_spot_false_gang_only_sizes_on_demand_types():
    # Spot type is cheaper (listed first) but a spot:false gang must
    # land on the on-demand type; plain task demand takes the spot type.
    autoscaler, _ = mk(MockProvider(), {
        "cheap_spot": {"num_cpus": 4, "spot": True},
        "ondemand": {"num_cpus": 4},
    })
    gang = {"tasks": [], "actors": [], "pg_bundles": [{
        "pg_id": "pg-crit", "strategy": "PACK",
        "bundles": [{"CPU": 2}], "spot": False,
    }]}
    assert autoscaler._nodes_to_launch(gang, n_current=0) == ["ondemand"]
    tasks = {"tasks": [{"CPU": 2}], "actors": [], "pg_bundles": []}
    assert autoscaler._nodes_to_launch(tasks, n_current=0) == ["cheap_spot"]


def test_launch_failure_backoff_quarantine_fallthrough():
    class FlakyProvider(MockProvider):
        def __init__(self):
            super().__init__()
            self.attempts = {}

        def create_node(self, node_type, node_config):
            self.attempts[node_type] = self.attempts.get(node_type, 0) + 1
            if node_type == "flaky":
                raise RuntimeError("boot failed")
            return super().create_node(node_type, node_config)

    provider = FlakyProvider()
    autoscaler, fh = mk(provider, {
        "flaky": {"num_cpus": 4},
        "fallback": {"num_cpus": 4},
    }, backoff_base_s=0.01, backoff_max_s=0.05,
        quarantine_failures=3, quarantine_cooldown_s=60.0)
    fh.snapshot = {"tasks": [{"CPU": 2}], "actors": [], "pg_bundles": []}
    for _ in range(80):
        autoscaler.update()
        if provider.attempts.get("fallback"):
            break
        time.sleep(0.02)
    # Exactly quarantine_failures create attempts on the flaky type
    # (backoff gates retries; quarantine then benches it for 60s), after
    # which demand falls through to the next feasible type.
    assert provider.attempts["flaky"] == 3
    assert provider.attempts["fallback"] == 1
    assert autoscaler._quarantined("flaky", time.monotonic())
    assert list(provider.nodes.values()) == ["fallback"]
    # The head-facing status report shows the bench.
    types = fh.reports[-1]["types"]
    assert types["flaky"]["quarantined"] is True
    assert types["flaky"]["quarantine_remaining_s"] > 0


def test_slo_burn_event_triggers_scale_up():
    provider = MockProvider()
    autoscaler, fh = mk(provider, {"small": {"num_cpus": 2}})
    fh.poll_batches = [[{"channel": "SLO", "key": "ttft_p50",
                         "message": {"slo": "ttft_p50",
                                     "state": "burning"}}]]
    report = autoscaler.update()  # burn transition -> one boost launch
    assert len(report["launched"]) == 1
    assert provider.nodes  # capacity added ahead of pending work
    # Still burning but already boosted: no launch storm.
    report = autoscaler.update()
    assert report["launched"] == []
    # Recovery clears the burn state.
    fh.poll_batches = [[{"channel": "SLO", "key": "ttft_p50",
                         "message": {"slo": "ttft_p50", "state": "ok"}}]]
    autoscaler.update()
    assert autoscaler._slo_burn == {}


def test_idle_scale_down_picks_occupancy_coldest_first():
    provider = MockProvider()
    autoscaler, fh = mk(provider, {"small": {"num_cpus": 2}},
                        idle_timeout_s=0.0)
    hot = provider.create_node("small", {"num_cpus": 2})
    cold = provider.create_node("small", {"num_cpus": 2})
    fh.nodes = {hot: _node(hot, 2), cold: _node(cold, 2)}
    fh.occupancy = {hot: 85.0, cold: 1.0}
    report = autoscaler.update()
    # Both are idle NOW, but the windowed signal ring says `cold` had
    # less recent load: it drains first.
    assert fh.drained == [cold, hot]
    # FakeHead drains instantly, so the settle pass terminates both —
    # and only AFTER the drain, with the ledger acked as planned.
    assert sorted(report["terminated"]) == sorted([hot, cold])
    assert fh.acks == [(cold, "drain:autoscaler_idle"),
                       (hot, "drain:autoscaler_idle")]
    assert provider.non_terminated_nodes() == []


def test_externally_dead_nodes_reclaimed_with_attributed_cause():
    """A spot preemption or operator drain lands as a head-side death
    the provider never hears about: the next reconcile pass terminates
    the stale provider slot and closes the goodput ledger with the
    attributed cause (preemption / drain:<reason> / failure:<cause>)."""
    provider = MockProvider()
    autoscaler, fh = mk(provider, {
        "spot_small": {"num_cpus": 2, "spot": True},
        "small": {"num_cpus": 2},
    })
    preempted = provider.create_node("spot_small", {"num_cpus": 2})
    drained = provider.create_node("small", {"num_cpus": 2})
    crashed = provider.create_node("small", {"num_cpus": 2})
    autoscaler._node_type_of.update({preempted: "spot_small",
                                     drained: "small", crashed: "small"})
    n1 = _node(preempted, 2, alive=False, state="DEAD")
    n1["DeathCause"] = "drained: preemption"
    n2 = _node(drained, 2, alive=False, state="DEAD")
    n2["DeathCause"] = "drained: maintenance"
    n3 = _node(crashed, 2, alive=False, state="DEAD")
    n3["DeathCause"] = "heartbeat timeout"
    fh.nodes = {preempted: n1, drained: n2, crashed: n3}
    report = autoscaler.update()
    assert sorted(report["terminated"]) == sorted(
        [preempted, drained, crashed])
    assert provider.non_terminated_nodes() == []
    causes = dict(fh.acks)
    assert causes[preempted] == "preemption"
    assert causes[drained] == "drain:maintenance"
    assert causes[crashed] == "failure:heartbeat timeout"


def test_scale_up_makes_pending_task_runnable():
    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    autoscaler = StandardAutoscaler(
        cluster.address,
        LocalNodeProvider(cluster),
        node_types={"big": {"num_cpus": 4}},
        max_workers=2,
        idle_timeout_s=9999,
    )
    try:
        @ray_tpu.remote(num_cpus=4)
        def needs_big_node():
            return "ran"

        ref = needs_big_node.remote()  # no node can fit -> pending demand
        time.sleep(0.5)
        report = autoscaler.update()
        assert len(report["launched"]) == 1
        assert ray_tpu.get(ref, timeout=60) == "ran"
    finally:
        autoscaler.stop()
        ray_tpu.shutdown()
        cluster.shutdown()


def test_scale_down_drains_before_terminate():
    """Idle scale-down is drain-first even under a terminate failpoint:
    the provider hook only ever fires on a node the head already reports
    DEAD with a ``drained:`` cause, and a failed terminate retries on a
    later pass instead of leaking the node."""
    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    provider = LocalNodeProvider(cluster)
    autoscaler = StandardAutoscaler(
        cluster.address,
        provider,
        node_types={"big": {"num_cpus": 2}},
        max_workers=2,
        idle_timeout_s=0.5,
        launch_cooldown_s=0.0,
    )
    observed = []
    real_terminate = provider.terminate_node

    def spy(node_id):
        info = {n["NodeID"]: n
                for n in cluster.head.rpc_nodes()}.get(node_id)
        observed.append((info["Alive"], info["DeathCause"]))
        real_terminate(node_id)

    provider.terminate_node = spy
    # First terminate attempt dies before the provider hook.
    failpoints.set_failpoints(
        {"autoscaler.before_terminate": "raise:chaos,once"})
    try:
        node_id = provider.create_node("big", {"num_cpus": 2})
        cluster.wait_for_nodes()
        autoscaler.update()  # first observation starts the idle clock
        time.sleep(0.8)  # exceed idle timeout
        terminated = []
        for _ in range(100):
            terminated += autoscaler.update()["terminated"]
            if node_id in terminated:
                break
            time.sleep(0.05)
        assert node_id in terminated  # retried past the chaos raise
        # The provider hook only ever saw a drained-dead node.
        assert observed and all(
            alive is False and cause.startswith("drained:")
            for alive, cause in observed)
        assert len([n for n in ray_tpu.nodes() if n["Alive"]]) == 1
        # The ledger got the planned-removal attribution.
        assert cluster.head.rpc_terminate_ack(node_id, "x")["ok"]
    finally:
        autoscaler.stop()
        ray_tpu.shutdown()
        cluster.shutdown()


def test_spot_preemption_reschedules_actor_without_budget_burn():
    """A spot node's preemption notice drains it; the restartable actor
    on it migrates budget-free (planned removal is not a crash)."""
    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    provider = LocalNodeProvider(cluster)
    try:
        spot_id = provider.create_node(
            "spot_tpu", {"num_cpus": 2, "spot": True})
        cluster.wait_for_nodes()
        labels = {n["NodeID"]: n["Labels"] for n in ray_tpu.nodes()}
        assert labels[spot_id] == {"node_type": "spot_tpu", "spot": True}

        @ray_tpu.remote(num_cpus=2, max_restarts=1)
        class Worker:
            def ping(self):
                return "ok"

        actor = Worker.remote()  # only fits the 2-CPU spot node
        assert ray_tpu.get(actor.ping.remote(), timeout=30) == "ok"
        cluster.add_node(num_cpus=2)  # on-demand fallback capacity
        cluster.wait_for_nodes()
        # Preemption signal -> drain plane (what the provider's
        # preemption watcher feeds).
        cluster.head.rpc_drain_node(spot_id, "preemption", 15.0,
                                    wait=True)
        assert ray_tpu.get(actor.ping.remote(), timeout=60) == "ok"
        # Budget-free migration: max_restarts untouched.
        rec = cluster.head._actor_specs[actor._actor_id]
        assert rec["restarts_left"] == 1
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_demand_snapshot_and_terminate_ack():
    """The head's demand snapshot carries queued-task shapes and the
    unplaced bundles of pending PGs (with their spot marker); the
    terminate ack refuses live nodes and absorbs duplicates."""
    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    try:
        @ray_tpu.remote(num_cpus=4)
        def too_big():
            return 1

        too_big.remote()  # infeasible on a 2-CPU fleet -> demand miss
        wait_for(
            lambda: any(d.get("CPU") == 4.0 for d in
                        cluster.head.rpc_demand_snapshot(30.0)["tasks"]),
            timeout=10, msg="queued task demand in snapshot")

        @ray_tpu.remote(num_cpus=2)
        class Hog:
            def ping(self):
                return "ok"

        hog = Hog.remote()  # holds the node's CPUs
        assert ray_tpu.get(hog.ping.remote(), timeout=30) == "ok"
        from ray_tpu.util.placement_group import placement_group

        pg = placement_group([{"CPU": 2}], strategy="PACK", spot=False)

        def pg_demand():
            snap = cluster.head.rpc_demand_snapshot(30.0)
            return [p for p in snap["pg_bundles"] if p["pg_id"] == pg.id]

        wait_for(lambda: bool(pg_demand()), timeout=10,
                 msg="pending PG bundles in snapshot")
        entry = pg_demand()[0]
        assert entry["strategy"] == "PACK"
        assert entry["spot"] is False
        assert entry["bundles"] == [{"CPU": 2}]

        # Ack protocol: refuse while the node is alive ...
        node_id = [n["NodeID"] for n in ray_tpu.nodes() if n["Alive"]][0]
        res = cluster.head.rpc_terminate_ack(node_id, "drain:test")
        assert res["ok"] is False
        # ... accept after a drain, idempotently on replay.
        agent = cluster.add_node(num_cpus=1)
        cluster.wait_for_nodes()
        cluster.head.rpc_drain_node(agent.node_id, "scale_down", 10.0,
                                    wait=True)
        assert cluster.head.rpc_terminate_ack(
            agent.node_id, "drain:scale_down")["ok"] is True
        assert cluster.head.rpc_terminate_ack(
            agent.node_id, "drain:scale_down")["ok"] is True
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_cluster_launcher_from_yaml(tmp_path):
    """YAML config -> running cluster (ray up/down analog): head shape,
    min_workers per node type, provider registry, teardown."""
    import yaml

    from ray_tpu.autoscaler import launcher

    config = {
        "cluster_name": "yaml-demo",
        "max_workers": 4,
        "provider": {"type": "local"},
        "head_node_type": "head",
        "available_node_types": {
            "head": {"num_cpus": 2, "min_workers": 0},
            "tpu_worker": {"num_cpus": 1, "resources": {"TPU": 4},
                           "min_workers": 1},
        },
    }
    path = tmp_path / "cluster.yaml"
    path.write_text(yaml.safe_dump(config))

    ray_tpu.shutdown()
    handle = launcher.create_or_update_cluster(str(path),
                                               start_autoscaler=False)
    try:
        ray_tpu.init(address=handle.address)
        nodes = ray_tpu.nodes()
        assert len(nodes) == 2  # head + 1 min tpu_worker
        total = ray_tpu.cluster_resources()
        assert total["CPU"] == 3.0 and total.get("TPU") == 4.0
    finally:
        ray_tpu.shutdown()
        handle.teardown()


def test_launcher_provider_registry_and_validation(tmp_path):
    from ray_tpu.autoscaler import launcher

    with pytest.raises(ValueError, match="available_node_types"):
        launcher.load_cluster_config({"head_node_type": "x"})
    with pytest.raises(ValueError, match="head_node_type"):
        launcher.load_cluster_config(
            {"available_node_types": {"a": {}}, "head_node_type": "b"})

    created = []

    class FakeCloud(launcher.NodeProvider):
        def __init__(self, provider_cfg, cluster):
            self.cfg = provider_cfg

        def create_node(self, node_type, node_config):
            created.append(node_type)
            return f"fake-{len(created)}"

        def terminate_node(self, node_id):
            pass

        def non_terminated_nodes(self):
            return [f"fake-{i+1}" for i in range(len(created))]

    launcher.register_node_provider("fake_cloud", FakeCloud)
    ray_tpu.shutdown()
    handle = launcher.create_or_update_cluster(
        {
            "provider": {"type": "fake_cloud", "region": "tpu-west"},
            "head_node_type": "head",
            "available_node_types": {
                "head": {"num_cpus": 1},
                "pod": {"num_cpus": 8, "min_workers": 2},
            },
        },
        start_autoscaler=False,
    )
    try:
        assert created == ["pod", "pod"]
        assert handle.provider.cfg["region"] == "tpu-west"
    finally:
        handle.teardown()
