"""Native shm object store tests (plasma-equivalent; SURVEY.md §2.1).

Covers the behaviors the reference tests in
``src/ray/object_manager/plasma/test/``: lifecycle, refcount pinning,
LRU eviction, cross-process visibility, zero-copy reads.
"""

import multiprocessing as mp
import os

import numpy as np
import pytest

from ray_tpu._native.shm_store import (
    ObjectExistsError,
    ShmStore,
    StoreFullError,
)
from ray_tpu.core import serialization


@pytest.fixture
def store(tmp_path):
    path = str(tmp_path / "segment")
    s = ShmStore(path, capacity=8 << 20, create=True)
    yield s
    s.close(unlink=True)


def test_put_get_roundtrip(store):
    store.put("obj1", b"hello world", meta=b"M")
    out = store.get("obj1")
    assert out is not None
    data, meta = out
    assert bytes(data) == b"hello world"
    assert meta == b"M"
    store.release("obj1")


def test_get_missing_returns_none(store):
    assert store.get("nope") is None
    assert not store.contains("nope")


def test_unsealed_not_visible(store):
    buf = store.create("obj2", 4)
    assert store.get("obj2") is None
    assert not store.contains("obj2")
    buf[:] = b"abcd"
    store.seal("obj2")
    assert store.contains("obj2")
    data, _ = store.get("obj2")
    assert bytes(data) == b"abcd"
    store.release("obj2")


def test_duplicate_create_raises(store):
    store.put("dup", b"x")
    with pytest.raises(ObjectExistsError):
        store.create("dup", 1)


def test_delete_and_abort(store):
    store.put("d1", b"x")
    assert store.delete("d1")
    assert store.get("d1") is None
    store.create("a1", 4)
    assert store.abort("a1")
    # after abort the id is reusable
    store.put("a1", b"yy")
    assert bytes(store.get("a1")[0]) == b"yy"
    store.release("a1")


def test_pinned_objects_not_deletable(store):
    store.put("p1", b"x" * 100)
    data, _ = store.get("p1")  # pin
    assert not store.delete("p1")
    store.release("p1")
    assert store.delete("p1")


def test_lru_eviction_under_pressure(store):
    # Fill with 1MB objects in an 8MB segment; old unpinned ones get evicted.
    blob = b"z" * (1 << 20)
    for i in range(20):
        store.put(f"evict{i}", blob)
    stats = store.stats()
    assert stats["num_evictions"] > 0
    # Most recent object must still be present.
    assert store.contains("evict19")
    # Oldest must be gone.
    assert not store.contains("evict0")


def test_pinned_survive_eviction(store):
    blob = b"z" * (1 << 20)
    store.put("keep", blob)
    assert store.get("keep") is not None  # pin it
    for i in range(20):
        store.put(f"fill{i}", blob)
    assert store.contains("keep")
    store.release("keep")


def test_object_larger_than_segment(store):
    with pytest.raises(StoreFullError):
        store.put("huge", b"x" * (64 << 20))


def _child_reader(path, q):
    s = ShmStore(path)
    out = s.get("shared")
    q.put(bytes(out[0]) if out else None)
    s.release("shared")
    s.close()


def _child_writer(path):
    s = ShmStore(path)
    s.put("from_child", b"child wrote this")
    s.close()


def test_cross_process_visibility(tmp_path):
    path = str(tmp_path / "seg2")
    s = ShmStore(path, capacity=4 << 20, create=True)
    s.put("shared", b"visible across processes")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_child_reader, args=(path, q))
    p.start()
    assert q.get(timeout=30) == b"visible across processes"
    p.join()

    p2 = ctx.Process(target=_child_writer, args=(path,))
    p2.start()
    p2.join()
    out = s.get("from_child")
    assert out is not None and bytes(out[0]) == b"child wrote this"
    s.release("from_child")
    s.close(unlink=True)


def test_zero_copy_numpy_via_serialization(store):
    arr = np.arange(100_000, dtype=np.float32)
    meta, chunks = serialization.serialize(arr)
    store.put("np1", chunks, meta=meta)
    data, meta2 = store.get("np1")
    out = serialization.deserialize(meta2, data)
    np.testing.assert_array_equal(out, arr)
    # Zero-copy: the array's buffer must live inside the segment mmap,
    # not a heap copy.
    assert not out.flags.owndata
    store.release("np1")


def test_serialization_roundtrip_structures():
    value = {"a": [1, 2, 3], "b": np.ones((4, 5)), "c": ("x", bytearray(b"yz"))}
    meta, chunks = serialization.serialize(value)
    blob = b"".join(bytes(c) for c in chunks)
    out = serialization.deserialize(meta, blob)
    assert out["a"] == [1, 2, 3]
    np.testing.assert_array_equal(out["b"], np.ones((4, 5)))
    assert out["c"] == ("x", bytearray(b"yz"))


def test_stats(store):
    store.put("s1", b"x" * 1000)
    st = store.stats()
    assert st["num_objects"] == 1
    assert st["used"] >= 1000
    assert st["capacity"] > 0
    assert len(store.list_keys()) == 1
