"""Owner-distributed object directory.

Reference parity: ``src/ray/core_worker/reference_count.h:61`` (per-object
state lives on the owning worker) and
``src/ray/core_worker/ownership_based_object_directory.h`` (locations are
resolved from owners, not the GCS). Here: every client hosts an owner
directory server (``cluster/client.py`` ``_OwnerService``); executing
workers report result locations straight to the submitting client; get()
on self-owned refs blocks on the local table with no head RPC; borrowers
long-poll the owner; the head keeps object->owner routing plus an
asynchronously-batched location view as the FT fallback.
"""

import time

import pytest

import ray_tpu
from ray_tpu.cluster.cluster_utils import Cluster


def wait_for(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {msg}")


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2, store_capacity=64 << 20)
    c.add_node(num_cpus=2, store_capacity=64 << 20)
    c.wait_for_nodes()
    ray_tpu.init(c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _backend():
    from ray_tpu._private import worker as worker_mod

    return worker_mod._backend


def _head_stat(cluster, method):
    stats = cluster.head._server.handler_stats()
    return stats.get(method, {}).get("count", 0)


def test_refs_carry_owner_address(cluster):
    b = _backend()
    ref = ray_tpu.put(1)
    assert ref._owner == b.owner_addr
    host, port = b.owner_addr.rsplit(":", 1)
    assert int(port) > 0


def test_self_owned_get_skips_head_wait(cluster):
    """The hot path: a driver getting its own tasks' results resolves
    from the LOCAL owner table — zero head wait_locations RPCs."""

    @ray_tpu.remote
    def f(x):
        return x + 1

    # Warm up (function export, worker start).
    assert ray_tpu.get(f.remote(0), timeout=60) == 1

    before = _head_stat(cluster, "wait_locations")
    refs = [f.remote(i) for i in range(40)]
    assert ray_tpu.get(refs, timeout=60) == [i + 1 for i in range(40)]
    after = _head_stat(cluster, "wait_locations")
    # The straggler sweep (every 4th poll round) may fire a couple of
    # times under load; O(tasks) would be >= 40.
    assert after - before <= 6, (
        f"expected near-zero head wait_locations, got {after - before}")


def test_worker_reports_result_to_owner(cluster):
    b = _backend()

    @ray_tpu.remote
    def g():
        return "hello"

    ref = g.remote()
    assert ray_tpu.get(ref, timeout=60) == "hello"
    # The executing worker reported the location to this driver's table.
    entry = b._owned.get(ref.id)
    assert entry is not None and entry["nodes"], entry


def test_borrower_resolves_via_owner(cluster):
    """A worker that receives a ref as a task arg (borrower) resolves the
    location from the owner's directory server."""

    @ray_tpu.remote
    def produce():
        return {"payload": list(range(100))}

    @ray_tpu.remote
    def consume(d):
        return len(d["payload"])

    ref = produce.remote()
    # Pass the REF (not the value): consume's worker borrows + resolves.
    out = consume.remote(ref)
    assert ray_tpu.get(out, timeout=60) == 100


def test_error_results_reach_owner_table(cluster):
    b = _backend()

    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    ref = boom.remote()
    with pytest.raises(Exception, match="kaboom"):
        ray_tpu.get(ref, timeout=60)
    entry = b._owned.get(ref.id)
    assert entry is not None and entry["error"] is True


def test_head_keeps_owner_routing(cluster):
    """The head's batched view records object->owner routing."""
    b = _backend()
    ref = ray_tpu.put("routed")
    b.flush_refs()  # push the batched add_locations now
    wait_for(
        lambda: b.head.call("owner_of", [ref.id]).get(ref.id)
        == b.owner_addr,
        msg="owner routing at head",
    )


def test_owner_death_falls_back_to_head(cluster):
    """A borrower whose owner process died resolves through the head's
    FT view (owner death degrades, not breaks, already-stored objects)."""
    import ray_tpu.cluster.client as client_mod
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    b = _backend()
    # Place the object on node 1 so the resolver (attached to node 0's
    # store) cannot short-circuit through a local read.
    node1 = cluster.nodes[1].node_id

    @ray_tpu.remote
    def produce():
        return [1, 2, 3]

    ref = produce.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node1, soft=False)).remote()
    assert ray_tpu.get(ref, timeout=60) == [1, 2, 3]
    b.flush_refs()  # head's batched view must know the location
    # Simulate owner death for the RESOLVER: point the ref at a dead
    # owner address and resolve through a fresh backend (a different
    # client process in spirit).
    other = client_mod.ClusterBackend(cluster.address)
    try:
        dead_ref = other.make_ref(ref.id, owner="127.0.0.1:1")
        vals = other.get([dead_ref], timeout=30)
        assert vals == [[1, 2, 3]]
        assert "127.0.0.1:1" in other._dead_owners
    finally:
        other.shutdown()


def test_forgotten_oid_redirects_borrower_to_head(cluster):
    """An owner that dropped its handle answers 'forgotten'; the borrower
    falls over to the head (which still tracks the pinned copy while the
    borrower holds a ref)."""
    b = _backend()
    ref = ray_tpu.put("keepsake")
    oid = ref.id
    b.flush_refs()
    # Owner drops its table entry (as _deref would) while the head still
    # has the location.
    with b._owned_cv:
        b._owned.pop(oid, None)
    got = b.owner_wait_locations([oid], timeout=0)
    assert got.get(oid, {}).get("forgotten") is True
    # A borrower-side get still resolves via head fallback.
    import ray_tpu.cluster.client as client_mod

    other = client_mod.ClusterBackend(cluster.address)
    try:
        bref = other.make_ref(oid, owner=b.owner_addr)
        assert other.get([bref], timeout=30) == ["keepsake"]
    finally:
        other.shutdown()


def test_wait_uses_owner_table(cluster):
    @ray_tpu.remote
    def slow():
        time.sleep(0.4)
        return 7

    before = _head_stat(cluster, "wait_locations")
    refs = [slow.remote() for _ in range(4)]
    ready, pending = ray_tpu.wait(refs, num_returns=4, timeout=30)
    assert len(ready) == 4 and not pending
    after = _head_stat(cluster, "wait_locations")
    # Old behavior: one head poll per pending ref per 5 ms round
    # (hundreds). Now: local table + occasional sweep.
    assert after - before <= 8, f"head polls in wait: {after - before}"
