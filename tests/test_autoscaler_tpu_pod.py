"""TPU-pod node provider: YAML-driven scale-up/down in dry-run mode
(reference: cloud NodeProvider plugins + command_runner,
``python/ray/autoscaler/node_provider.py:23``; SURVEY §7 build-plan 12)."""

import sys
import time

import cloudpickle
import pytest

import ray_tpu
from ray_tpu.autoscaler.launcher import (
    create_or_update_cluster,
    teardown_cluster,
)
from ray_tpu.autoscaler.tpu_pod import (
    DryRunCommandRunner,
    TPUPodNodeProvider,
)

cloudpickle.register_pickle_by_value(sys.modules[__name__])


CONFIG = {
    "cluster_name": "tpu-dry",
    "max_workers": 3,
    "idle_timeout_minutes": 0.03,  # ~2s: scale-down observable in-test
    "provider": {
        "type": "tpu_pod",
        "project": "proj-x",
        "zone": "us-central2-b",
        "runtime_version": "tpu-ubuntu2204-base",
        "name_prefix": "graft",
        "dry_run": True,
    },
    "head_node_type": "head",
    "available_node_types": {
        "head": {"num_cpus": 2, "min_workers": 0},
        "v5e_host": {
            "num_cpus": 2,
            "resources": {"TPU": 4},
            "accelerator_type": "v5litepod-4",
            "min_workers": 0,
            "max_workers": 3,
        },
    },
}


def test_provider_command_lines():
    runner = DryRunCommandRunner()
    provider = TPUPodNodeProvider(
        dict(CONFIG["provider"]), cluster=None, runner=runner)
    provider.dry_run = False  # no cluster simulation; just the commands
    name = provider.create_node(
        "v5e_host", CONFIG["available_node_types"]["v5e_host"])
    assert name == "graft-v5e_host-1"
    create = runner.commands[0]
    assert create[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "create"]
    assert "graft-v5e_host-1" in create
    assert "v5litepod-4" in create
    assert "proj-x" in create and "us-central2-b" in create
    assert provider.non_terminated_nodes() == [name]
    provider.terminate_node(name)
    deletes = [c for c in runner.commands if c[:5] == [
        "gcloud", "compute", "tpus", "tpu-vm", "delete"]]
    assert len(deletes) == 1 and name in deletes[0]
    assert provider.non_terminated_nodes() == []


def test_real_mode_adopts_listed_pods():
    """A restarted launcher reconciles against the cloud's list output
    instead of double-provisioning (and can terminate adopted pods)."""

    class ListingRunner(DryRunCommandRunner):
        def run(self, argv):
            super().run(argv)
            if "list" in argv:
                return "graft-v5e_host-7\nother-cluster-pod\n"
            return ""

    runner = ListingRunner()
    provider = TPUPodNodeProvider(
        {**CONFIG["provider"], "dry_run": False}, cluster=None,
        runner=runner)
    assert provider.non_terminated_nodes() == ["graft-v5e_host-7"]
    provider.terminate_node("graft-v5e_host-7")
    assert any("delete" in c and "graft-v5e_host-7" in c
               for c in runner.commands)
    # The foreign pod was never adopted.
    assert all("other-cluster-pod" not in c for c in runner.commands
               if "delete" in c)


def test_custom_command_templates():
    runner = DryRunCommandRunner()
    cfg = dict(CONFIG["provider"])
    cfg["commands"] = {
        "create": "kubectl scale nodepool {name} --replicas 1",
        "delete": "kubectl scale nodepool {name} --replicas 0",
    }
    provider = TPUPodNodeProvider(cfg, cluster=None, runner=runner)
    provider.dry_run = False
    name = provider.create_node("v5e_host", {})
    provider.terminate_node(name)
    assert runner.commands[0][0] == "kubectl"
    assert runner.commands[1][:2] == ["kubectl", "scale"]


def test_yaml_dryrun_scale_up_and_down():
    """End-to-end: pending TPU demand -> provider 'creates' a pod (gcloud
    command recorded + simulated host joins) -> task runs -> idle pod is
    scaled down (delete command recorded)."""
    ray_tpu.shutdown()
    handle = create_or_update_cluster(CONFIG)
    try:
        ray_tpu.init(address=handle.address)
        runner = handle.provider.runner
        assert isinstance(runner, DryRunCommandRunner)

        @ray_tpu.remote(num_tpus=4)
        def tpu_task():
            return "ok"

        # No TPU capacity yet: the task parks as pending demand; the
        # autoscaler reconciles, dry-"creates" a v5e host, the simulated
        # node joins, and the task becomes runnable.
        assert ray_tpu.get(tpu_task.remote(), timeout=120) == "ok"
        creates = [c for c in runner.commands if "create" in c]
        assert len(creates) >= 1
        assert any("v5litepod-4" in c for c in creates)

        # Scale-down: the pod idles past idle_timeout -> delete command.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if any("delete" in c for c in runner.commands):
                break
            time.sleep(0.5)
        assert any("delete" in c for c in runner.commands), runner.commands
    finally:
        ray_tpu.shutdown()
        teardown_cluster(handle)
